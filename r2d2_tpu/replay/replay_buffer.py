"""Central sequence-prioritized replay with vectorized batch assembly
(host data plane).

Capability parity with the reference ReplayBuffer (reference
worker.py:69-310): circular store of fixed-size blocks, a sum tree over all
sequence slots, stratified prioritized sampling with IS weights, and
stale-priority rejection via pointer-window masking (the control logic
lives in replay/control_plane.py, shared with the HBM-resident variant).

TPU-first redesign: the reference assembles each batch with a 64-iteration
Python loop of per-sequence tensor slices plus `pad_sequence`
(worker.py:210-288). Here every block field lives in ONE preallocated numpy
array, and a batch is assembled with a single fancy-index gather per field —
(batch, seq_len) windows come out fixed-shape (jit-stable) in a handful of
vectorized ops.

When host->device bandwidth is the binding constraint, prefer
replay/device_store.DeviceReplayBuffer, which keeps the data plane in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.block import Block
from r2d2_tpu.replay.control_plane import ReplayControlPlane


@dataclasses.dataclass
class SampledBatch:
    """Fixed-shape training batch (host numpy, ready for device_put)."""

    obs: np.ndarray            # (B, seq_len, *obs_shape) uint8
    last_action: np.ndarray    # (B, seq_len) uint8 scalar actions
    last_reward: np.ndarray    # (B, seq_len) float32
    hidden: np.ndarray         # (B, 2, H) cfg.state_dtype (f32 | bf16)
    action: np.ndarray         # (B, L) int32
    n_step_reward: np.ndarray  # (B, L) float32
    gamma: np.ndarray          # (B, L) float32
    burn_in_steps: np.ndarray  # (B,) int32
    learning_steps: np.ndarray # (B,) int32
    forward_steps: np.ndarray  # (B,) int32
    is_weights: np.ndarray     # (B,) float32
    idxes: np.ndarray          # (B,) int64 — sequence slots, for priority updates
    old_ptr: int               # block pointer at sample time (staleness check)
    env_steps: int             # total env steps stored so far
    # ptr_advances stamp (full-lap detection); None = no lap check
    old_advances: Optional[int] = None
    # (B,) int32 per-sequence task ids on multi-task configs; None on the
    # single-task golden path (keeps DeviceBatch.from_sampled's pytree —
    # and thus every donation/jaxpr contract over it — unchanged)
    task: Optional[np.ndarray] = None


class ReplayBuffer(ReplayControlPlane):
    def __init__(self, cfg: R2D2Config, native: Optional[object] = None):
        super().__init__(cfg, native=native)
        S = cfg.seqs_per_block
        nb, slot = cfg.num_blocks, cfg.block_slot_len

        self.obs_store = np.zeros((nb, slot, *cfg.obs_shape), dtype=np.uint8)
        self.last_action_store = np.zeros((nb, slot), dtype=np.uint8)
        self.last_reward_store = np.zeros((nb, slot), dtype=np.float32)
        self.action_store = np.zeros((nb, cfg.block_length), dtype=np.uint8)
        self.n_step_reward_store = np.zeros((nb, cfg.block_length), dtype=np.float32)
        self.gamma_store = np.zeros((nb, cfg.block_length), dtype=np.float32)
        # cfg.state_dtype: float32, or bfloat16 under precision="bf16" —
        # halves the carry slab and every sampled batch's hidden bytes
        # (block.hidden arrives float32; the slab assignment downcasts)
        self.hidden_store = np.zeros((nb, S, 2, cfg.hidden_dim), dtype=cfg.state_dtype)
        self.burn_in_store = np.zeros((nb, S), dtype=np.int32)
        self.learning_store = np.zeros((nb, S), dtype=np.int32)
        self.forward_store = np.zeros((nb, S), dtype=np.int32)
        # scalar per block (one actor collects one task); (nb,) is cheap
        # enough to keep unconditionally — sampling only SURFACES it on
        # multi-task configs (SampledBatch.task stays None otherwise)
        self.task_store = np.zeros((nb,), dtype=np.int32)

    # ------------------------------------------------------------------ add

    def _write_block_locked(self, block: Block, ptr: int) -> None:
        """Write one block's data-plane fields into slab slot `ptr`.
        Caller holds self.lock and owns the accounting that follows.
        (Factored so the tiered store's disk-demotion overrides can reuse
        the exact slab-write byte behavior without re-entering the lock —
        threading.Lock is not reentrant.)"""
        S = self.cfg.seqs_per_block
        steps = block.stored_steps
        self.obs_store[ptr, :steps] = block.obs
        self.last_action_store[ptr, :steps] = block.last_action
        self.last_reward_store[ptr, :steps] = block.last_reward
        T = len(block.action)
        self.action_store[ptr, :T] = block.action
        self.n_step_reward_store[ptr, :T] = block.n_step_reward
        self.gamma_store[ptr, :T] = block.gamma
        ns = block.num_sequences
        self.hidden_store[ptr, :ns] = block.hidden
        self.burn_in_store[ptr, :S] = 0
        self.learning_store[ptr, :S] = 0
        self.forward_store[ptr, :S] = 0
        self.burn_in_store[ptr, :ns] = block.burn_in_steps
        self.learning_store[ptr, :ns] = block.learning_steps
        self.forward_store[ptr, :ns] = block.forward_steps
        self.task_store[ptr] = block.task

    def add_block(
        self, block: Block, priorities: np.ndarray, episode_reward: Optional[float]
    ) -> None:
        """Write one block into the circular store and refresh its leaves
        (reference worker.py:178-208). `priorities` must already be padded
        to seqs_per_block (zeros for absent sequences)."""
        with self.lock:
            # data writes FIRST, accounting last: a malformed block (flaky
            # env shapes) raises here before the tree/pointer mutate, so a
            # supervised-restart run can never train on a slot whose
            # priorities describe data that was never written
            self._write_block_locked(block, self.block_ptr)
            self._account_add(
                block.num_sequences, int(block.learning_steps.sum()), priorities, episode_reward
            )

    def add_blocks_batch(self, items) -> None:
        """Write a list of (block, priorities, episode_reward) triples in
        one pass. The live-loop ingestion bridge's entry point: draining a
        burst under a single lock acquisition instead of one per block
        keeps the learner's sample path from interleaving tree refreshes
        with every store write. Semantically identical to calling
        add_block per item, in order."""
        with self.lock:
            for block, priorities, episode_reward in items:
                self._write_block_locked(block, self.block_ptr)
                self._account_add(
                    block.num_sequences, int(block.learning_steps.sum()),
                    priorities, episode_reward,
                )

    # --------------------------------------------------------------- sample

    def sample_batch(self, rng: np.random.Generator) -> SampledBatch:
        """Draw a fixed-shape batch via stratified prioritized sampling.

        All per-field gathers are single vectorized fancy-index reads over
        the preallocated stores — the TPU-feeding rewrite of reference
        worker.py:210-288.
        """
        cfg = self.cfg
        L = cfg.learning_steps
        with self.lock:
            b, s, idxes, is_weights = self._draw(rng)

            burn = self.burn_in_store[b, s]
            learn = self.learning_store[b, s]
            fwd = self.forward_store[b, s]
            first_burn = self.burn_in_store[b, 0]
            start = first_burn + s * L          # buffer coords of learning start
            win_start = start - burn

            if self.native is not None:
                # C++ memcpy gather (clamped-window batch assembly,
                # _native/replay_core.cpp) — one call per field.
                g = self.native.gather_windows
                T = cfg.seq_len
                obs = g(self.obs_store, b, win_start, T)
                last_action = g(self.last_action_store, b, win_start, T)
                last_reward = g(self.last_reward_store, b, win_start, T)
                lstart = s * L
                action = g(self.action_store, b, lstart, L).astype(np.int32)
                n_step_reward = g(self.n_step_reward_store, b, lstart, L)
                gamma = g(self.gamma_store, b, lstart, L)
            else:
                t = np.arange(cfg.seq_len)
                rows = win_start[:, None] + t[None, :]
                np.clip(rows, 0, cfg.block_slot_len - 1, out=rows)
                bcol = b[:, None]
                obs = self.obs_store[bcol, rows]
                last_action = self.last_action_store[bcol, rows]
                last_reward = self.last_reward_store[bcol, rows]

                tl = np.arange(L)
                lrows = s[:, None] * L + tl[None, :]
                np.clip(lrows, 0, cfg.block_length - 1, out=lrows)
                action = self.action_store[bcol, lrows].astype(np.int32)
                n_step_reward = self.n_step_reward_store[bcol, lrows]
                gamma = self.gamma_store[bcol, lrows]

            hidden = self.hidden_store[b, s]

            batch = SampledBatch(
                obs=obs,
                last_action=last_action,
                last_reward=last_reward,
                hidden=hidden,
                action=action,
                n_step_reward=n_step_reward,
                gamma=gamma,
                burn_in_steps=burn.astype(np.int32),
                learning_steps=learn.astype(np.int32),
                forward_steps=fwd.astype(np.int32),
                is_weights=is_weights,
                idxes=idxes,
                old_ptr=self.block_ptr,
                env_steps=self.env_steps,
                old_advances=self.ptr_advances,
                task=self.task_store[b] if cfg.num_tasks > 1 else None,
            )
        return batch
