"""Fused Pallas LSTM unroll — the TPU kernel for the framework's hot op.

The learner's sequence unroll (reference model.py:59,133-139 leans on a
cuDNN packed-sequence LSTM) is the latency-bound part of the jitted update:
T=85 strictly sequential recurrent steps whose per-step matmul
(B, H) x (H, 4H) is far too small to amortize HBM traffic if the loop body
re-fetches operands. This kernel runs the WHOLE unroll as one `pallas_call`
with a sequential grid over time:

- the recurrent weights `wh` (H, 4H) are fetched into VMEM once and stay
  resident for all T steps (the index_map pins the same block every
  iteration, so the pipeline does not re-copy it),
- the (h, c) carry lives in VMEM scratch across grid steps (TPU grid
  iterations execute sequentially, scratch persists),
- per step: one MXU matmul (B,H)x(H,4H) + VPU gate math, fused — nothing
  touches HBM except streaming in proj_t and streaming out h_t/c_t.

The input projection x @ Wi + b for ALL timesteps is deliberately NOT in
the kernel: it is one big (B*T, D) x (D, 4H) matmul that XLA already maps
perfectly onto the MXU (models/lstm.py does it), and keeping it outside
lets autodiff handle dWi/db for free.

Backward is a second Pallas kernel walking the grid in reverse time order,
carrying (dh, dc) in scratch and emitting per-step pre-activation grads dz;
the weight gradient dWh = h_prev^T @ dz then falls out as one big MXU
matmul outside the kernel (same trick as forward). Residuals saved: the
h_t and c_t sequences — gates are recomputed in the backward kernel (one
extra matmul per step, cheaper than storing 4H activations).

Numerics: gate math and the carry accumulate in float32 regardless of the
compute dtype; matmuls run in the weights' dtype with
preferred_element_type=float32 (bfloat16 feeds the MXU at double rate).

On non-TPU backends the kernels run in Pallas interpret mode, which is how
the CPU test suite pins forward/gradient parity against the lax.scan
reference implementation (models/lstm.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _split_gates(z: jnp.ndarray, H: int):
    i = jax.nn.sigmoid(z[..., :H])
    f = jax.nn.sigmoid(z[..., H : 2 * H])
    g = jnp.tanh(z[..., 2 * H : 3 * H])
    o = jax.nn.sigmoid(z[..., 3 * H :])
    return i, f, g, o


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(proj_ref, wh_ref, h0_ref, c0_ref, outs_ref, cs_ref, h_s, c_s):
    H = h_s.shape[-1]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0_ref[:].astype(jnp.float32)
        c_s[:] = c0_ref[:].astype(jnp.float32)

    wh = wh_ref[:]
    z = proj_ref[0].astype(jnp.float32) + jnp.dot(
        h_s[:].astype(wh.dtype), wh, preferred_element_type=jnp.float32
    )
    i, f, g, o = _split_gates(z, H)
    c_new = f * c_s[:] + i * g
    h_new = o * jnp.tanh(c_new)
    h_s[:] = h_new
    c_s[:] = c_new
    outs_ref[0] = h_new.astype(outs_ref.dtype)
    cs_ref[0] = c_new


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lstm_fwd_call(proj_t, wh, h0, c0, *, interpret: bool):
    T, B, fourH = proj_t.shape
    H = fourH // 4
    outs, cs = pl.pallas_call(
        _fwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, 4 * H), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((H, 4 * H), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), proj_t.dtype),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(proj_t, wh, h0, c0)
    return outs, cs


# --------------------------------------------------------------------------
# backward kernel (reverse time order via index_map t -> T-1-t)
# --------------------------------------------------------------------------


def _bwd_kernel(
    dout_ref, proj_ref, hprev_ref, cprev_ref, cs_ref, wh_ref, dcT_ref,
    dz_ref, dh0_ref, dc0_ref, dh_s, dc_s,
):
    H = dh_s.shape[-1]
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        # dh seed (the h_T cotangent) is folded into dout[-1] by the caller;
        # the c_T cotangent seeds the cell-grad carry here.
        dh_s[:] = jnp.zeros_like(dh_s)
        dc_s[:] = dcT_ref[:]

    wh = wh_ref[:]
    # recompute this step's gates from saved h_{t-1}, c_{t-1}
    z = proj_ref[0].astype(jnp.float32) + jnp.dot(
        hprev_ref[0].astype(wh.dtype), wh, preferred_element_type=jnp.float32
    )
    i, f, g, o = _split_gates(z, H)
    tanh_c = jnp.tanh(cs_ref[0])

    dh = dout_ref[0].astype(jnp.float32) + dh_s[:]
    do = dh * tanh_c
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
    di = dc * g
    df = dc * cprev_ref[0]
    dg = dc * i
    dz = jnp.concatenate(
        [
            di * i * (1.0 - i),
            df * f * (1.0 - f),
            dg * (1.0 - g * g),
            do * o * (1.0 - o),
        ],
        axis=-1,
    )
    dz_ref[0] = dz
    # carry to step t-1
    dh_s[:] = jnp.dot(dz.astype(wh.dtype), wh.T, preferred_element_type=jnp.float32)
    dc_s[:] = dc * f
    # after the last grid step (real t=0) these hold d h0 / d c0
    dh0_ref[:] = dh_s[:]
    dc0_ref[:] = dc_s[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lstm_bwd_call(dout, proj_t, hprev, cprev, cs, wh, dcT, *, interpret: bool):
    T, B, H = cs.shape
    rev3 = lambda t: (T - 1 - t, 0, 0)
    pinned = lambda t: (0, 0)
    dz, dh0, dc0 = pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, 4 * H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((H, 4 * H), pinned, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), pinned, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, B, 4 * H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), pinned, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), pinned, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, 4 * H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(dout, proj_t, hprev, cprev, cs, wh, dcT)
    return dz, dh0, dc0


# --------------------------------------------------------------------------
# custom-VJP public op
# --------------------------------------------------------------------------


@jax.custom_vjp
def lstm_unroll(
    proj_t: jnp.ndarray,  # (T, B, 4H) time-major input projections x@Wi+b
    wh: jnp.ndarray,      # (H, 4H) recurrent weights
    h0: jnp.ndarray,      # (B, H)
    c0: jnp.ndarray,      # (B, H)
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Fused LSTM unroll: returns (outs (T, B, H), (h_T, c_T))."""
    outs, cs = _lstm_fwd_call(proj_t, wh, h0, c0, interpret=_interpret())
    return outs, (outs[-1].astype(jnp.float32), cs[-1])


def _vjp_fwd(proj_t, wh, h0, c0):
    outs, cs = _lstm_fwd_call(proj_t, wh, h0, c0, interpret=_interpret())
    return (outs, (outs[-1].astype(jnp.float32), cs[-1])), (proj_t, wh, h0, c0, outs, cs)


def _vjp_bwd(res, grads):
    proj_t, wh, h0, c0, outs, cs = res
    douts, (dhT, dcT) = grads
    T, B, H = cs.shape
    # h_T IS outs[-1], so its cotangent folds into dout[-1]; the c_T
    # cotangent seeds the backward kernel's cell-grad carry at step T-1.
    douts = douts.astype(jnp.float32).at[-1].add(dhT.astype(jnp.float32))
    hprev = jnp.concatenate([h0.astype(outs.dtype)[None], outs[:-1]], axis=0)
    cprev = jnp.concatenate([c0.astype(jnp.float32)[None], cs[:-1]], axis=0)
    dz, dh0, dc0 = _lstm_bwd_call(
        douts, proj_t, hprev, cprev, cs, wh, dcT.astype(jnp.float32),
        interpret=_interpret(),
    )
    dproj = dz.astype(proj_t.dtype)
    # weight grad as ONE big MXU matmul: (H, T*B) x (T*B, 4H)
    dwh = jnp.dot(
        hprev.reshape(T * B, H).astype(jnp.float32).T, dz.reshape(T * B, 4 * H),
        preferred_element_type=jnp.float32,
    ).astype(wh.dtype)
    return dproj, dwh, dh0.astype(h0.dtype), dc0.astype(c0.dtype)


lstm_unroll.defvjp(_vjp_fwd, _vjp_bwd)


# --------------------------------------------------------------------------
# fused SEQUENCE op: burn-in + train segment in one launch, stop-gradient
# seam handled inside the backward kernel
# --------------------------------------------------------------------------
#
# R2D2 replays (burn-in ‖ learning ‖ forward) windows as ONE T-step sequence
# and stops gradients at the burn-in/train seam: burn-in steps refresh the
# recurrent state from stale-policy data but must not train the core.
#
# The seam position is PER ROW, not static: collect.py packs overlapping
# windows where window 0 of a block gets burn_in=0 and later windows get the
# full Bn, so a (B,) vector of seam indices rides along with every batch.
# That rules out splitting the launch at the seam; instead the forward runs
# the whole sequence as the one fused launch above (bit-identical to
# lstm_unroll — stop_gradient is the identity on values) and the backward
# kernel walks the full T-step reverse grid applying two per-row masks:
#
#   keep       = t >= burn   zeroes the pre-activation grad dz for burn-in
#                            steps (their outputs carry no cotangent),
#   carry_keep = t >  burn   cuts the (dh, dc) carry crossing the seam, so
#                            nothing flows from the train segment into
#                            burn-in steps.
#
# Rows below their seam therefore contribute exact zeros to dproj and to the
# big dWh matmul outside the kernel, and d h0 / d c0 are STRUCTURALLY zero
# for every row (the carry is cut at t == burn >= 0 before it can reach the
# initial state), so the VJP returns zeros without reading kernel outputs.
# Burn-in steps do no gate-recompute work that survives: their lanes are
# masked to zero and the only residual read the seam needs is h/c at the
# seam row itself (already part of the forward outputs; no extra residuals
# are saved for the burn-in segment).


def _seq_bwd_kernel(
    dout_ref, proj_ref, hprev_ref, cprev_ref, cs_ref, wh_ref, dcT_ref, burn_ref,
    dz_ref, dh_s, dc_s,
):
    H = dh_s.shape[-1]
    t = pl.program_id(0)
    # the grid streams blocks in reverse time order; recover the real index
    t_real = pl.num_programs(0) - 1 - t

    @pl.when(t == 0)
    def _():
        dh_s[:] = jnp.zeros_like(dh_s)
        dc_s[:] = dcT_ref[:]

    burn = burn_ref[:]  # (B, 1) int32 per-row seam
    keep = t_real >= burn
    carry_keep = t_real > burn

    wh = wh_ref[:]
    z = proj_ref[0].astype(jnp.float32) + jnp.dot(
        hprev_ref[0].astype(wh.dtype), wh, preferred_element_type=jnp.float32
    )
    i, f, g, o = _split_gates(z, H)
    tanh_c = jnp.tanh(cs_ref[0])

    dh = jnp.where(keep, dout_ref[0].astype(jnp.float32), 0.0) + dh_s[:]
    do = dh * tanh_c
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
    di = dc * g
    df = dc * cprev_ref[0]
    dg = dc * i
    dz = jnp.concatenate(
        [
            di * i * (1.0 - i),
            df * f * (1.0 - f),
            dg * (1.0 - g * g),
            do * o * (1.0 - o),
        ],
        axis=-1,
    )
    dz_ref[0] = dz
    # carry to step t_real-1, cut at the seam (and already-zero below it)
    dh_s[:] = jnp.where(
        carry_keep,
        jnp.dot(dz.astype(wh.dtype), wh.T, preferred_element_type=jnp.float32),
        0.0,
    )
    dc_s[:] = jnp.where(carry_keep, dc * f, 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lstm_seq_bwd_call(dout, proj_t, hprev, cprev, cs, wh, dcT, burn, *, interpret: bool):
    T, B, H = cs.shape
    rev3 = lambda t: (T - 1 - t, 0, 0)
    pinned = lambda t: (0, 0)
    (dz,) = pl.pallas_call(
        _seq_bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, 4 * H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((H, 4 * H), pinned, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), pinned, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 1), pinned, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, B, 4 * H), rev3, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, 4 * H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(dout, proj_t, hprev, cprev, cs, wh, dcT, burn)
    return dz


@jax.custom_vjp
def lstm_seq_unroll(
    proj_t: jnp.ndarray,   # (T, B, 4H) time-major input projections x@Wi+b
    wh: jnp.ndarray,       # (H, 4H) recurrent weights
    h0: jnp.ndarray,       # (B, H)
    c0: jnp.ndarray,       # (B, H)
    burn_in: jnp.ndarray,  # (B,) int32 per-row stop-gradient seam position
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Fused burn-in + train sequence unroll with a stop-gradient seam.

    Forward values are bit-identical to :func:`lstm_unroll` (one launch,
    carry pinned in VMEM scratch for all T steps). The VJP implements the
    R2D2 seam: gradients do not flow into steps t < burn_in[b] of row b,
    and d h0 / d c0 are exact zeros.

    Contract: 0 <= burn_in[b] < T. The replay pipeline guarantees this
    (burn_in + learning + forward == T with learning >= 1); a seam at or
    past T would mean "no train segment", which the masks above do not
    define (every collect/learner caller satisfies the contract by
    construction).
    """
    outs, cs = _lstm_fwd_call(proj_t, wh, h0, c0, interpret=_interpret())
    return outs, (outs[-1].astype(jnp.float32), cs[-1])


def _seq_vjp_fwd(proj_t, wh, h0, c0, burn_in):
    outs, cs = _lstm_fwd_call(proj_t, wh, h0, c0, interpret=_interpret())
    out = (outs, (outs[-1].astype(jnp.float32), cs[-1]))
    return out, (proj_t, wh, h0, c0, burn_in, outs, cs)


def _seq_vjp_bwd(res, grads):
    proj_t, wh, h0, c0, burn_in, outs, cs = res
    douts, (dhT, dcT) = grads
    T, B, H = cs.shape
    douts = douts.astype(jnp.float32).at[-1].add(dhT.astype(jnp.float32))
    hprev = jnp.concatenate([h0.astype(outs.dtype)[None], outs[:-1]], axis=0)
    cprev = jnp.concatenate([c0.astype(jnp.float32)[None], cs[:-1]], axis=0)
    burn = burn_in.astype(jnp.int32).reshape(B, 1)
    dz = _lstm_seq_bwd_call(
        douts, proj_t, hprev, cprev, cs, wh, dcT.astype(jnp.float32), burn,
        interpret=_interpret(),
    )
    dproj = dz.astype(proj_t.dtype)
    # dz is exactly zero for burn-in steps, so they drop out of dWh too
    dwh = jnp.dot(
        hprev.reshape(T * B, H).astype(jnp.float32).T, dz.reshape(T * B, 4 * H),
        preferred_element_type=jnp.float32,
    ).astype(wh.dtype)
    # the seam cut makes initial-state grads structurally zero; the int32
    # seam vector is non-differentiable (float0 cotangent)
    dburn = np.zeros(burn_in.shape, dtype=jax.dtypes.float0)
    return dproj, dwh, jnp.zeros_like(h0), jnp.zeros_like(c0), dburn


lstm_seq_unroll.defvjp(_seq_vjp_fwd, _seq_vjp_bwd)
