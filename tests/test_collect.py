"""On-device collector (collect.py) — equivalence with the host actor path.

The strongest possible pin: the DeviceCollector's in-jit packing must
reproduce the host VectorizedActor + SequenceAccumulator blocks
field-by-field on identical trajectories. The scripted env's host and
functional twins are deterministic and epsilon=0 makes the policy greedy,
so both paths see the same observations, take the same actions, and must
pack the same blocks (terminal AND truncation paths).
"""

import jax
import numpy as np
import pytest

from r2d2_tpu.actor import HostEnvPool, ParamStore, VectorizedActor
from r2d2_tpu.collect import DeviceCollector, make_collect_fn
from r2d2_tpu.config import tiny_test
from r2d2_tpu.envs.catch import CatchEnv
from r2d2_tpu.envs.fake import ScriptedEnv, ScriptedFnEnv
from r2d2_tpu.learner import init_train_state, make_fused_train_step
from r2d2_tpu.replay.device_store import DeviceReplayBuffer

E = 3


def _cfg(**kw):
    base = dict(
        block_length=12,
        buffer_capacity=624,
        learning_starts=24,
        num_actors=E,
        max_episode_steps=12,
    )
    base.update(kw)
    return tiny_test().replace(**base)


def _host_blocks(cfg, net, params, episode_len, steps):
    """Collect blocks via the host actor path on the scripted env."""
    store = ParamStore(params)
    pool = HostEnvPool([ScriptedEnv(episode_len=episode_len) for _ in range(cfg.num_actors)])
    pushed = []
    actor = VectorizedActor(
        cfg, net, store, pool, np.zeros(cfg.num_actors, np.float32),
        lambda b, p, r: pushed.append((b, p, r)), seed=7,
    )
    for _ in range(steps):
        actor.step()
    return pushed


def _device_out(cfg, net, params, episode_len, chunk):
    fn_env = ScriptedFnEnv(episode_len=episode_len)
    collect = make_collect_fn(cfg, net, fn_env, cfg.num_actors, chunk)
    key = jax.random.PRNGKey(3)
    env_state = jax.vmap(fn_env.reset)(jax.random.split(key, cfg.num_actors))
    eps = jax.numpy.zeros(cfg.num_actors)
    return collect(params, env_state, eps, jax.random.PRNGKey(11))


def _compare(cfg, fields, prios, num_seq, sizes, i, block, host_prios):
    size = int(sizes[i])
    assert size == len(block.action)
    ns = int(num_seq[i])
    assert ns == block.num_sequences
    np.testing.assert_array_equal(np.asarray(fields["obs"][i])[: size + 1], block.obs)
    # entries past size+1 are zeroed padding
    assert not np.asarray(fields["obs"][i])[size + 1 :].any()
    np.testing.assert_array_equal(
        np.asarray(fields["last_action"][i])[: size + 1], block.last_action.astype(np.int32)
    )
    np.testing.assert_allclose(
        np.asarray(fields["last_reward"][i])[: size + 1], block.last_reward, atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(fields["action"][i])[:size], block.action.astype(np.int32)
    )
    np.testing.assert_allclose(
        np.asarray(fields["n_step_reward"][i])[:size], block.n_step_reward, atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(fields["gamma"][i])[:size], block.gamma, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(fields["burn_in"][i])[:ns], block.burn_in_steps)
    np.testing.assert_array_equal(np.asarray(fields["learning"][i])[:ns], block.learning_steps)
    np.testing.assert_array_equal(np.asarray(fields["forward"][i])[:ns], block.forward_steps)
    np.testing.assert_allclose(np.asarray(fields["hidden"][i])[:ns], block.hidden, atol=1e-5)
    np.testing.assert_allclose(np.asarray(prios[i]), host_prios, atol=1e-4)


def test_terminal_chunk_matches_host_actor():
    """Episodes end inside the chunk: terminal encoding, stored hiddens,
    counters, and initial priorities all match the host path."""
    cfg = _cfg()
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    ep_len = 9
    pushed = _host_blocks(cfg, net, state.params, ep_len, steps=ep_len)
    assert len(pushed) == E
    fields, prios, num_seq, sizes, dones, ep_rewards, _, _ = _device_out(
        cfg, net, state.params, ep_len, chunk=cfg.block_length
    )
    assert np.asarray(dones).all()
    script_sum = sum(float(i % 3) for i in range(ep_len))
    np.testing.assert_allclose(np.asarray(ep_rewards), script_sum, atol=1e-6)
    for i in range(E):
        block, host_prios, ep_reward = pushed[i]
        assert ep_reward == pytest.approx(script_sum)
        _compare(cfg, fields, prios, num_seq, sizes, i, block, host_prios)


def test_truncation_chunk_matches_host_actor():
    """Episodes outlive the chunk: the truncation bootstrap (final policy
    eval) and shrinking gamma tail match the host actor's deferred cut."""
    chunk = 7
    cfg = _cfg(max_episode_steps=chunk)
    net, state = init_train_state(cfg, jax.random.PRNGKey(1))
    # host actor needs one extra step to flush the deferred truncation cut
    pushed = _host_blocks(cfg, net, state.params, episode_len=100, steps=chunk + 1)
    assert len(pushed) >= E
    fields, prios, num_seq, sizes, dones, _, _, _ = _device_out(
        cfg, net, state.params, episode_len=100, chunk=chunk
    )
    assert not np.asarray(dones).any()
    assert (np.asarray(sizes) == chunk).all()
    for i in range(E):
        block, host_prios, ep_reward = pushed[i]
        assert ep_reward is None
        _compare(cfg, fields, prios, num_seq, sizes, i, block, host_prios)
    # truncation keeps a live bootstrap: gamma tail is gamma^2, gamma^1
    g = np.asarray(fields["gamma"][0])
    assert g[chunk - 1] == pytest.approx(cfg.gamma)
    assert g[chunk - 2] == pytest.approx(cfg.gamma**2)


def test_collector_feeds_device_replay_end_to_end():
    """DeviceCollector -> HBM store -> fused train step: blocks land in the
    store, sampling opens, and one update returns finite loss/priorities."""
    cfg = _cfg()
    net, state = init_train_state(cfg, jax.random.PRNGKey(2))
    replay = DeviceReplayBuffer(cfg)
    collector = DeviceCollector(
        cfg, net, ParamStore(state.params), ScriptedFnEnv(episode_len=9), replay, seed=5
    )
    while not replay.can_sample():
        collector.step()
    assert collector.total_steps >= cfg.learning_starts
    n_ep, r_sum = replay.pop_episode_stats()
    assert n_ep > 0 and r_sum == pytest.approx(n_ep * sum(i % 3 for i in range(9)))

    si = replay.sample_indices(np.random.default_rng(0))
    step_fn = make_fused_train_step(cfg, net, donate=False)
    state2, metrics, priorities = replay.run_with_stores(
        lambda stores: step_fn(
            state, stores, jax.numpy.asarray(si.b), jax.numpy.asarray(si.s),
            jax.numpy.asarray(si.is_weights),
        )
    )
    assert np.isfinite(float(metrics["loss"]))
    assert np.asarray(priorities).shape == (cfg.batch_size,)
    assert np.isfinite(np.asarray(priorities)).all()
    replay.update_priorities(si.idxes, np.asarray(priorities), si.old_ptr)


def test_collector_on_catch_env():
    """Catch's functional core drives the collector: fixed-length episodes
    terminate inside the chunk and blocks account correctly."""
    env = CatchEnv(height=12, width=12)
    cfg = _cfg(max_episode_steps=12).replace(action_dim=env.NUM_ACTIONS)
    net, state = init_train_state(cfg, jax.random.PRNGKey(4))
    replay = DeviceReplayBuffer(cfg)
    collector = DeviceCollector(
        cfg, net, ParamStore(state.params), env, replay, seed=6
    )
    n = collector.step()
    # catch episodes last exactly height-2 steps
    assert n == E * (cfg.obs_shape[0] - 2)
    assert len(replay) == n
    totals = replay.episode_totals()
    assert totals[0] == E


def test_resync_restores_consistent_state():
    cfg = _cfg()
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    replay = DeviceReplayBuffer(cfg)
    collector = DeviceCollector(
        cfg, net, ParamStore(state.params), ScriptedFnEnv(episode_len=9), replay
    )
    collector.step()
    before = collector.total_steps
    collector.resync()
    collector.step()
    assert collector.total_steps == 2 * before


def test_carry_episodes_across_chunks():
    """Episodes longer than one chunk (carry_episodes): the episode
    CONTINUES into the next chunk's block — env state, recurrent state,
    and last action/reward carry across the seam; the continuation
    block's window-0 stored state is the carried state; episode stats
    report once, with the full return."""
    from r2d2_tpu.collect import initial_carry, make_collect_core

    cfg = _cfg(max_episode_steps=24)  # block/chunk 12 -> 2-chunk episodes
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    fn_env = ScriptedFnEnv(episode_len=24, action_dim=cfg.action_dim)
    collect = make_collect_fn(cfg, net, fn_env, E, 12, carry_episodes=True)

    carry0 = initial_carry(cfg, fn_env, E, jax.random.PRNGKey(5))
    eps = jax.numpy.zeros(E)
    out1 = collect(state.params, carry0, eps, jax.random.PRNGKey(8))
    f1, _, _, sizes1, dones1, ep1, carry1, _ = out1
    assert not np.asarray(dones1).any()          # mid-episode at the seam
    np.testing.assert_array_equal(np.asarray(sizes1), 12)
    # prefix reward = chunk-1 script sum (0,1,2 repeating over 12 steps)
    np.testing.assert_allclose(np.asarray(carry1.prefix_reward), 12.0)
    # carried env state resumes at t=12, not a fresh episode
    np.testing.assert_array_equal(np.asarray(carry1.env_state.t), 12)

    out2 = collect(state.params, carry1, eps, jax.random.PRNGKey(9))
    f2, _, _, sizes2, dones2, ep2, carry2, _ = out2
    assert np.asarray(dones2).all()              # episode ends in chunk 2
    np.testing.assert_array_equal(np.asarray(sizes2), 12)
    np.testing.assert_allclose(np.asarray(ep2), 24.0)  # FULL return
    np.testing.assert_allclose(np.asarray(carry2.prefix_reward), 0.0)

    # continuation block: first stored obs is the seam obs (t=12), the
    # window-0 stored state is the CARRIED recurrent state, and the first
    # stored last-action/reward are the carried values
    assert np.asarray(f2["obs"])[:, 0].max() == 12
    np.testing.assert_allclose(
        np.asarray(f2["hidden"])[:, 0],
        np.stack([np.asarray(carry1.h), np.asarray(carry1.c)], axis=1),
        atol=1e-6,
    )
    np.testing.assert_array_equal(
        np.asarray(f2["last_action"])[:, 0], np.asarray(carry1.last_action)
    )
    np.testing.assert_allclose(
        np.asarray(f2["last_reward"])[:, 0], np.asarray(carry1.last_reward)
    )


def test_device_collector_carry_mode_end_to_end():
    """DeviceCollector auto-enables the carry when max_episode_steps
    exceeds the chunk: transitions past the first chunk ARE collected and
    each multi-chunk episode is counted once with its full reward."""
    cfg = _cfg(max_episode_steps=24)
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    fn_env = ScriptedFnEnv(episode_len=24, action_dim=cfg.action_dim)
    replay = DeviceReplayBuffer(cfg)
    collector = DeviceCollector(
        cfg, net, ParamStore(state.params), fn_env, replay,
        epsilons=np.zeros(E, np.float32), seed=5,
    )
    assert collector.carry_episodes
    n1 = collector.step()
    assert n1 == E * 12
    n_ep, r_sum = replay.pop_episode_stats()
    assert n_ep == 0  # no episode finished at the seam
    n2 = collector.step()
    assert n2 == E * 12
    n_ep, r_sum = replay.pop_episode_stats()
    assert n_ep == E and r_sum == pytest.approx(24.0 * E)
    assert len(replay) == 2 * E * 12

    # resync restarts fresh episodes (carry rebuilt)
    collector.resync()
    np.testing.assert_array_equal(np.asarray(collector.env_state.env_state.t), 0)
    np.testing.assert_allclose(np.asarray(collector.env_state.prefix_reward), 0.0)
