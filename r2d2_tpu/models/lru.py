"""Time-parallel linear recurrent core (LRU) — the long-context option.

The reference framework has exactly one recurrent core, an LSTM
(reference model.py:59). An LSTM's recurrence is nonlinear, so its unroll
is inherently sequential — T steps cost T dependent iterations no matter
the hardware (models/lstm.py runs it as a remat-chunked lax.scan; that IS
the ceiling). This module adds the TPU-first alternative the literature
reached for the same reason: a DIAGONAL LINEAR complex recurrence

    h_t = lambda * h_{t-1} + gamma * (B x_t)        (elementwise in C^H)

per the Linear Recurrent Unit design (Orvieto et al. 2023, "Resurrecting
Recurrent Neural Networks for Long Sequences" — public literature;
pattern only, no code copied). Linearity makes the recurrence
ASSOCIATIVE, so the whole unroll runs as one `jax.lax.associative_scan`:
O(log T) dependent steps instead of O(T), mapping a 1024-step window onto
the VPU as ~10 parallel sweeps. Expressivity lost to linearity is bought
back the standard way: a nonlinear readout of the state plus an input
skip, with stability guaranteed by parameterizing |lambda| < 1 through
exp(-exp(nu_log)).

Drop-in contract (zero plumbing changes anywhere else):
- carry is a pair of (B, H) real arrays — here (Re h, Im h) instead of
  the LSTM's (h, c) — so the replay planes' stored (B, 2, H) hidden
  field, the actors' carries, burn-in, and zero-state ablation all work
  unchanged (models/r2d2.py `carry = (hidden[:, 0], hidden[:, 1])`).
- `__call__(xs (B,T,D), carry) -> (outs (B,T,H), carry)` and
  `step(x (B,D), carry) -> (out, carry)` mirror models/lstm.py.

Numerics: input/readout matmuls run in the configured compute dtype
(bf16 on TPU — MXU work); the elementwise recurrence and the scan run in
float32 (it is bandwidth-light, and f32 keeps 1000-step cumulative
products honest). Complex math is spelled out over (re, im) real pairs —
no complex dtypes, so XLA:TPU sees plain f32 elementwise ops.

Select with `recurrent_core="lru"` (config.py); params deliberately use
none of the Megatron-annotated names in parallel/mesh.train_state_shardings
(wi/wh/b), so under tp the LRU core stays replicated — its recurrence is
elementwise and its projections are (D, H): cheap relative to the encoder.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.models.lstm import _uniform_init

Carry = Tuple[jnp.ndarray, jnp.ndarray]  # (re, im), each (B, H) float32


def _ring_init(r_min: float, r_max: float):
    """nu_log such that |lambda| = exp(-exp(nu_log)) ~ U(r_min, r_max)."""

    def init(key, shape, dtype=jnp.float32):
        u = jax.random.uniform(key, shape, dtype)
        r = r_min + (r_max - r_min) * u
        return jnp.log(-jnp.log(r))

    return init


def _phase_init(max_phase: float):
    """theta_log such that theta = exp(theta_log) ~ U(~0, max_phase)."""

    def init(key, shape, dtype=jnp.float32):
        u = jax.random.uniform(key, shape, dtype, 1e-4, 1.0)
        return jnp.log(u * max_phase)

    return init


class LRU(nn.Module):
    hidden_dim: int
    in_dim: int
    dtype: jnp.dtype = jnp.float32
    r_min: float = 0.9          # eigenvalue ring: slowest-forgetting init
    r_max: float = 0.999
    max_phase: float = 6.283    # full circle of rotation frequencies

    def setup(self):
        H, D = self.hidden_dim, self.in_dim
        self.nu_log = self.param("nu_log", _ring_init(self.r_min, self.r_max), (H,))
        self.theta_log = self.param("theta_log", _phase_init(self.max_phase), (H,))
        s_in = 1.0 / np.sqrt(D)
        self.in_re = self.param("in_re", _uniform_init(s_in), (D, H))
        self.in_im = self.param("in_im", _uniform_init(s_in), (D, H))
        s_h = 1.0 / np.sqrt(H)
        self.out_re = self.param("out_re", _uniform_init(s_h), (H, H))
        self.out_im = self.param("out_im", _uniform_init(s_h), (H, H))
        self.skip = self.param("skip", _uniform_init(s_in), (D, H))

    def _decay(self):
        """lambda = exp(-exp(nu_log) + i exp(theta_log)), |lambda| < 1 by
        construction; gamma = sqrt(1 - |lambda|^2) normalizes the input so
        the state variance is O(1) at every decay rate."""
        mod = jnp.exp(-jnp.exp(self.nu_log))
        theta = jnp.exp(self.theta_log)
        lam_re = mod * jnp.cos(theta)
        lam_im = mod * jnp.sin(theta)
        gamma = jnp.sqrt(jnp.maximum(1.0 - mod * mod, 1e-8))
        return lam_re, lam_im, gamma

    def _project_in(self, xs: jnp.ndarray, gamma: jnp.ndarray):
        """(…, D) -> gamma-scaled complex input (re, im), f32."""
        xd = xs.astype(self.dtype)
        u_re = (xd @ self.in_re.astype(self.dtype)).astype(jnp.float32)
        u_im = (xd @ self.in_im.astype(self.dtype)).astype(jnp.float32)
        return u_re * gamma, u_im * gamma

    def _readout(self, h_re: jnp.ndarray, h_im: jnp.ndarray, xs: jnp.ndarray):
        """Nonlinear readout of the complex state + input skip: the
        standard recipe for buying back the expressivity the linear
        recurrence gives up. Re(h C) for complex C spelled out in reals."""
        hr = h_re.astype(self.dtype)
        hi = h_im.astype(self.dtype)
        y = hr @ self.out_re.astype(self.dtype) - hi @ self.out_im.astype(self.dtype)
        return nn.gelu(y) + xs.astype(self.dtype) @ self.skip.astype(self.dtype)

    def __call__(self, xs: jnp.ndarray, carry: Carry) -> Tuple[jnp.ndarray, Carry]:
        """Time-parallel unroll over (B, T, D) from carry via ONE
        associative scan; returns ((B, T, H), final carry)."""
        B, T, _ = xs.shape
        lam_re, lam_im, gamma = self._decay()
        u_re, u_im = self._project_in(xs, gamma)  # (B, T, H) f32

        # elements (a, b) of the recurrence h_t = a_t h_{t-1} + b_t with
        # a_t = lambda (constant), combined under
        #   (a1,b1) o (a2,b2) = (a2 a1, a2 b1 + b2)
        # the scan's prefix (A_t, B_t) satisfies h_t = A_t h0 + B_t
        a_re = jnp.broadcast_to(lam_re, (B, T, self.hidden_dim))
        a_im = jnp.broadcast_to(lam_im, (B, T, self.hidden_dim))

        def combine(e1, e2):
            a1r, a1i, b1r, b1i = e1
            a2r, a2i, b2r, b2i = e2
            ar = a2r * a1r - a2i * a1i
            ai = a2r * a1i + a2i * a1r
            br = a2r * b1r - a2i * b1i + b2r
            bi = a2r * b1i + a2i * b1r + b2i
            return ar, ai, br, bi

        A_re, A_im, B_re, B_im = jax.lax.associative_scan(
            combine, (a_re, a_im, u_re, u_im), axis=1
        )
        h0_re, h0_im = carry
        h0_re = h0_re.astype(jnp.float32)[:, None]
        h0_im = h0_im.astype(jnp.float32)[:, None]
        h_re = A_re * h0_re - A_im * h0_im + B_re
        h_im = A_re * h0_im + A_im * h0_re + B_im

        outs = self._readout(h_re, h_im, xs)
        return outs, (h_re[:, -1], h_im[:, -1])

    def step(self, x: jnp.ndarray, carry: Carry) -> Tuple[jnp.ndarray, Carry]:
        """Single acting step on (B, D): one elementwise complex
        multiply-add — the actor-side cost is O(H), cheaper than the
        LSTM's (B,H)x(H,4H) recurrent matmul."""
        lam_re, lam_im, gamma = self._decay()
        u_re, u_im = self._project_in(x, gamma)
        h_re, h_im = carry
        h_re = h_re.astype(jnp.float32)
        h_im = h_im.astype(jnp.float32)
        new_re = lam_re * h_re - lam_im * h_im + u_re
        new_im = lam_re * h_im + lam_im * h_re + u_im
        out = self._readout(new_re, new_im, x)
        return out, (new_re, new_im)
