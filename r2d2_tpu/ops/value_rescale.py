"""Value-function rescaling h and its closed-form inverse.

R2D2 trains Q in a squashed space to cope with Atari's raw-score reward
scale: targets are y = h(r_n + gamma_n * h^{-1}(Q_target)) (invariant from
reference worker.py:410,454-461; Pohlen et al. 2018, eq. 4-5):

    h(x)      = sign(x) * (sqrt(|x| + 1) - 1) + eps * x
    h^{-1}(x) = sign(x) * (((sqrt(1 + 4 eps (|x| + 1 + eps)) - 1) / (2 eps))^2 - 1)

Both are elementwise and jit/vmap/grad-safe.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def inverse_value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    t = (jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0) / (2.0 * eps)
    return jnp.sign(x) * (jnp.square(t) - 1.0)


# numpy twins for host-side code (actor initial priorities). The reference
# computes actor-side TDs on raw Q while the learner works in rescaled space
# (SURVEY.md quirk 6); this framework keeps both on the rescaled scale, so
# the host needs the same h / h^-1.

def value_rescale_np(x: np.ndarray, eps: float = 1e-3) -> np.ndarray:
    return np.sign(x) * (np.sqrt(np.abs(x) + 1.0) - 1.0) + eps * x


def inverse_value_rescale_np(x: np.ndarray, eps: float = 1e-3) -> np.ndarray:
    t = (np.sqrt(1.0 + 4.0 * eps * (np.abs(x) + 1.0 + eps)) - 1.0) / (2.0 * eps)
    return np.sign(x) * (np.square(t) - 1.0)
