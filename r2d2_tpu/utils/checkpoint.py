"""Orbax checkpointing with a full resume path.

The reference half-has this subsystem: it pickles (state_dict, num_updates,
env_steps, wall_minutes) every 500 updates but can never RESUME — optimizer
state, target net, and RNG state are never saved (reference worker.py:450-452;
SURVEY.md section 5.4). Here a checkpoint carries the complete TrainState
(params, target params, opt state, step) plus env_steps/wall_minutes, and
`restore_checkpoint` reconstructs the LEARNER exactly. Collection state
(replay contents, actor/sampler RNG streams) is not persisted: a resumed run
continues optimization from the identical learner state but refills replay
with freshly collected experience.

Layout: {dir}/step_{N}/ orbax trees — the evaluator walks the same series
the reference's test.py walks (test.py:26-30).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from r2d2_tpu.learner import TrainState
from r2d2_tpu.utils.faults import fault_point, with_retries


def _payload(state: TrainState, env_steps: int, wall_minutes: float) -> Dict[str, Any]:
    return {
        "params": state.params,
        "target_params": state.target_params,
        "opt_state": state.opt_state,
        "step": state.step,
        "env_steps": np.asarray(env_steps),
        "wall_minutes": np.asarray(wall_minutes),
    }


# The orbax finalize marker, written last inside a completed save. A
# step dir without it is partially written (crashed save, or a save still
# in flight on a fs without atomic rename) and must be invisible to
# readers: `ocp.utils.is_checkpoint_finalized` only inspects the directory
# NAME on a local fs, so a torn `step_N` would pass it.
_FINALIZED_MARKER = "_CHECKPOINT_METADATA"


def _barrier(name: str) -> None:
    """Multihost sync point: orbax saves distributed arrays collectively,
    so every process writes into the same (shared-fs) step dir and the
    rename must happen exactly once, after ALL hosts finished writing."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def save_checkpoint(
    ckpt_dir: str, state: TrainState, env_steps: int, wall_minutes: float
) -> str:
    """Atomic for concurrent readers (the serve-plane hot-reload watcher
    polls this series live): the tree is written to a deterministic temp
    dir, then renamed into `step_{N}` in one fs operation — a reader lists
    either the complete checkpoint or nothing, never a torn one."""
    step = int(state.step)
    base = os.path.abspath(ckpt_dir)
    final = os.path.join(base, f"step_{step}")
    # deterministic (not randomized) temp name: all hosts of a multihost
    # save must target the SAME directory on the shared fs
    tmp = os.path.join(base, f".tmp_step_{step}")
    if jax.process_index() == 0:
        os.makedirs(base, exist_ok=True)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)  # leftover from a crashed save
    _barrier(f"ckpt_clean_{step}")

    def write():
        # the flaky window is the orbax write itself (transient fs errors,
        # injected "checkpoint.save" faults); retried attempts rewrite the
        # SAME temp dir (force=True), so a half-written first attempt is
        # simply overwritten. Barriers stay OUTSIDE the retry: every host
        # retries locally the same bounded number of times at most, and
        # only the final outcome crosses the sync points.
        fault_point("checkpoint.save")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(tmp, _payload(state, env_steps, wall_minutes), force=True)
        ckptr.wait_until_finished()

    with_retries(write, "checkpoint.save")
    _barrier(f"ckpt_written_{step}")
    if jax.process_index() == 0:
        if os.path.isdir(final):
            shutil.rmtree(final)  # force=True semantics, atomically
        os.rename(tmp, final)
    _barrier(f"ckpt_renamed_{step}")
    return final


def list_checkpoint_steps(ckpt_dir: str) -> List[int]:
    """Completed checkpoints only: in-flight temp dirs (`.tmp_step_*`) and
    partially-written `step_*` dirs missing the orbax finalize marker are
    skipped, so a concurrent reader can never pick up a torn step."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    # sorted: fs enumeration order varies per host; the scan's order must
    # not leak into anything downstream of a resume decision
    for name in sorted(os.listdir(ckpt_dir)):
        if not name.startswith("step_"):
            continue
        try:
            step = int(name[5:])
        except ValueError:
            continue
        if os.path.exists(os.path.join(ckpt_dir, name, _FINALIZED_MARKER)):
            steps.append(step)
    return sorted(steps)


def latest_checkpoint_step(ckpt_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(ckpt_dir: str, template_state: TrainState, step: Optional[int] = None):
    """Returns (TrainState, env_steps, wall_minutes). `template_state` is an
    uninitialized state of the right structure (from init_train_state)."""
    if step is None:
        step = latest_checkpoint_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    abstract = jax.tree.map(
        ocp.utils.to_shape_dtype_struct, _payload(template_state, 0, 0.0)
    )

    def read():
        fault_point("checkpoint.restore")
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, abstract)

    restored = with_retries(read, "checkpoint.restore")
    state = TrainState(
        params=restored["params"],
        target_params=restored["target_params"],
        opt_state=restored["opt_state"],
        step=jnp.asarray(restored["step"], jnp.int32),
    )
    return state, int(restored["env_steps"]), float(restored["wall_minutes"])
