"""Structured metrics (SURVEY.md section 5.5 rebuild).

The reference logs via print() from the buffer process every 10 s
(reference worker.py:124-146). Here every record is a structured dict
written as one jsonl line (machine-readable learning curves) and mirrored
to stdout at a throttled cadence.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional

import numpy as np


def _json_default(v: Any) -> Any:
    """Serializer fallback for non-JSON values in metric records:
    numpy/jax scalars -> Python numbers, small arrays -> lists, big arrays
    -> a shape/dtype summary (a learning-curve line must never carry a
    multi-megabyte tensor), anything else -> str. `default=float` used to
    sit here and raised TypeError on all of these."""
    if isinstance(v, np.ndarray):
        if v.ndim == 0:
            return v.item()
        if v.size <= 32:
            return v.tolist()
        return f"<array shape={v.shape} dtype={v.dtype}>"
    if isinstance(v, (np.generic,)):
        return v.item()
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return v.item()  # jax scalar arrays
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        arr = np.asarray(v)
        return _json_default(arr)
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


class MetricsLogger:
    def __init__(self, path: Optional[str] = None, stdout_interval: float = 10.0):
        self.path = path
        self._fh = open(path, "a", buffering=1) if path else None
        self.stdout_interval = stdout_interval
        self._last_print = 0.0

    def log(self, record: Dict[str, Any], force_print: bool = False) -> None:
        record = {"ts": time.time(), **record}
        if self._fh:
            self._fh.write(json.dumps(record, default=_json_default) + "\n")
        now = time.time()
        if force_print or now - self._last_print >= self.stdout_interval:
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "ts"
            )
            print(parts, file=sys.stderr)
            self._last_print = now

    def close(self) -> None:
        """Idempotent: serve/train teardown paths may both close the same
        logger (supervised shutdown + atexit)."""
        fh, self._fh = self._fh, None
        if fh is not None and not fh.closed:
            fh.close()
