#!/bin/bash
# Round-7 kernel-pass bench chain: the measurement side of the raw-speed
# PR (fused Pallas sequence kernel, fused act tail, int8 serve arm).
# Four rungs, each one JSON line appended to runs/bench_kernels_r7.jsonl:
#
#   1. kernel-plane gate  — `pytest -m kernels` (interpret-mode parity +
#      launch counts) plus the static analysis CLI. A parity or
#      launch-count regression aborts the chain: a wrong kernel's
#      throughput number is noise.
#   2. breakdown          — per-phase step timing (unroll / head /
#      loss+grad / optimizer), the denominator map kernel rows cite.
#   3. learner headline   — best-of-matrix with vs_r05 (trajectory vs
#      BENCH_r05.json's 1004177.5) and the fused_seq sub-row (per-step
#      Pallas path re-run at the winning batch).
#   4. serve 3-arm        — fp32 -> bf16 -> int8; the serve_int8 sub-row
#      carries vs_fp32 and the q_drift_vs_fp32 bounded-parity column.
#
# PRE-REGISTERED read: rung 3's fused_seq.speedup_vs_per_step > 1.0 is
# the tentpole's claim on real hardware; vs_r05 is the honest round
# trajectory either way. Rung 4's q_drift_vs_fp32 staying ~1e-2 of the
# Q scale is the int8 arm's bounded-parity claim at full network size.
cd /root/repo

. runs/lib.sh

OUT=runs/bench_kernels_r7.jsonl
: > "$OUT"

echo "=== RUNG 1: kernel-plane gate ==="
python -m pytest tests/ -q -m kernels -p no:cacheprovider
RC=$?
echo "=== KERNELS_PYTEST EXIT: $RC ==="
python -m r2d2_tpu.analysis.cli --jaxpr
RCA=$?
echo "=== ANALYSIS EXIT: $RCA ==="
if [ $RC -ne 0 ] || [ $RCA -ne 0 ]; then
  echo "=== ABORT: kernel gate failed; bench rows would be noise ==="
  exit 1
fi

echo "=== RUNG 2: per-phase breakdown ==="
python bench.py --mode breakdown | tee -a "$OUT"
echo "=== BREAKDOWN EXIT: $? ==="

echo "=== RUNG 3: learner headline (vs_r05 + fused_seq row) ==="
python bench.py --mode learner --precision both | tee -a "$OUT"
echo "=== LEARNER EXIT: $? ==="

echo "=== RUNG 4: serve three-arm (fp32/bf16/int8) ==="
python bench.py --mode serve --precision both | tee -a "$OUT"
echo "=== SERVE EXIT: $? ==="

echo R7_KERNELS_ALL_DONE
