"""Live-loop plane tests (r2d2_tpu/liveloop): tap-vs-offline-accumulator
bit-parity (including epsilon/params_version audit stamps and the
reset/burn-in seams), ingestion-bridge backpressure accounting, mid-loop
snapshot/resume bit-exactness, the per-session epsilon serve protocol,
and a slow-marked end-to-end "return improves on catch under live load"
smoke. All CPU — tiny_test shapes."""

from __future__ import annotations

import io
import threading
import time

import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.liveloop import IngestBridge, TransitionTap
from r2d2_tpu.replay.accumulator import SequenceAccumulator

CFG = tiny_test()

BLOCK_FIELDS = (
    "obs", "last_action", "last_reward", "action", "n_step_reward",
    "gamma", "hidden", "burn_in_steps", "learning_steps", "forward_steps",
)


def _stream(cfg, T, seed=0, resets=()):
    """A synthetic single-session served request stream: row t carries the
    serve loop's facts at request t (obs_t, reward_{t-1}, reset_t) plus
    what the jitted step produced (action_t, q_t, post-step carry)."""
    rng = np.random.default_rng(seed)
    A, H = cfg.action_dim, cfg.hidden_dim
    rows = []
    for t in range(T):
        rows.append(dict(
            obs=rng.integers(0, 255, cfg.obs_shape, dtype=np.uint8),
            action=int(rng.integers(A)),
            q=rng.normal(size=A).astype(np.float32),
            # f32: rewards reach the tap through the serve loop's float32
            # batch row, and the offline reference must see the same bits
            reward=float(np.float32(rng.normal())),
            reset=t in resets,
            eps=float(rng.random() * 0.4),
            h=rng.normal(size=H).astype(np.float32),
            c=rng.normal(size=H).astype(np.float32),
            version=t // 7,
        ))
    return rows


def _feed(tap, rows, sid="s0", slot=1, store_rows=4):
    """Replay the stream through observe_batch as 1-row served batches,
    with the post-step carry living in a fake session store (the tap must
    gather the right slot, not row 0)."""
    H = len(rows[0]["h"])
    for r in rows:
        h_store = np.zeros((store_rows, H), np.float32)
        c_store = np.zeros((store_rows, H), np.float32)
        h_store[slot], c_store[slot] = r["h"], r["c"]
        tap.observe_batch(
            [sid], r["obs"][None], np.array([r["action"]]),
            r["q"][None], np.array([r["reward"]], np.float32),
            np.array([r["reset"]]), np.array([r["eps"]], np.float32),
            ckpt_step=r["version"], version=r["version"],
            h_store=h_store, c_store=c_store,
            slots=np.array([slot] * store_rows),
        )


def _offline(cfg, rows):
    """The actor-side reference: the same stream pushed through a bare
    SequenceAccumulator with the serving shift applied by hand — the
    transition for request t completes at request t+1, a full block cuts
    with q_{t+1} in hand, a reset row carries the terminal reward."""
    acc = SequenceAccumulator(cfg)
    blocks, stamps = [], []
    eps_s, ver_s = [], []
    pending = None

    def cut(last_qval):
        blocks.append(acc.finish(last_qval=last_qval))
        stamps.append((np.asarray(eps_s, np.float32),
                       np.asarray(ver_s, np.int64)))
        eps_s.clear()
        ver_s.clear()

    for t, r in enumerate(rows):
        hidden = np.stack([r["h"], r["c"]]).astype(np.float32)
        if t == 0:
            acc.reset(r["obs"])
        elif r["reset"]:
            a, q, hid, eps, ver = pending
            acc.add(a, r["reward"], r["obs"], q, hid)
            eps_s.append(eps)
            ver_s.append(ver)
            cut(None)
            acc.reset(r["obs"])
        else:
            a, q, hid, eps, ver = pending
            acc.add(a, r["reward"], r["obs"], q, hid)
            eps_s.append(eps)
            ver_s.append(ver)
            if acc.size == cfg.block_length:
                cut(r["q"])
        pending = (r["action"], r["q"], hidden, r["eps"], r["version"])
    if acc.size > 0:
        cut(pending[1])  # flush: bootstrap from the pending Q
    return blocks, stamps


def _collecting_tap(cfg, **kw):
    out = []
    tap = TransitionTap(cfg, **kw)
    tap.set_emit(lambda b, p, er: out.append((b, p, er)))
    return tap, out


def _assert_emissions_equal(got, want):
    assert len(got) == len(want)
    for (gb, gp, ger), (wb, wp, wer) in zip(got, want):
        for f in BLOCK_FIELDS:
            np.testing.assert_array_equal(
                getattr(gb, f), getattr(wb, f), err_msg=f"block field {f}"
            )
        assert gb.num_sequences == wb.num_sequences
        np.testing.assert_array_equal(gp, wp)
        assert ger == wer


# ----------------------------------------------------- tap/offline parity


def test_tap_matches_offline_accumulator():
    """Bit-parity of every emitted Block (mid-episode cuts with their
    q_{t+1} bootstrap, the terminal block a reset row closes, burn-in
    carried across block boundaries, the stop-time flush cut) AND of the
    per-transition (epsilon, params_version) audit stamps."""
    # T=40, reset at 17: block cut at t=16 (exactly block_length), a
    # 1-step terminal block at the reset row (burn-in seam from the cut),
    # a second full cut at t=33, and a partial flushed at the end
    rows = _stream(CFG, 40, seed=3, resets={17})
    tap, got = _collecting_tap(CFG)
    _feed(tap, rows)
    tap.process_pending()
    tap.flush()
    want, want_stamps = _offline(CFG, rows)
    _assert_emissions_equal(got, want)
    assert len(want) == 4  # the seam census above, not just "some blocks"

    stats = tap.stats()
    assert stats["tap_captured_steps"] == 39  # T-1: one pending per row
    assert stats["tap_emitted_blocks"] == 4
    assert stats["tap_dropped_batches"] == 0
    assert stats["tap_seam_breaks"] == 0
    assert stats["tap_open_sessions"] == 0

    audits = list(tap.audit_tail)
    assert len(audits) == len(want_stamps)
    for audit, (eps, ver) in zip(audits, want_stamps):
        assert audit["session"] == "s0"
        np.testing.assert_array_equal(audit["epsilon"], eps)
        np.testing.assert_array_equal(audit["params_version"], ver)


def test_tap_interleaved_sessions_match_per_session_offline():
    """Two sessions interleaved in shared batches emit exactly what each
    would alone — per-session streams are independent."""
    rows_a = _stream(CFG, 30, seed=11, resets={9})
    rows_b = _stream(CFG, 30, seed=12)
    tap, got = _collecting_tap(CFG)
    H = CFG.hidden_dim
    for ra, rb in zip(rows_a, rows_b):
        h_store = np.stack([ra["h"], rb["h"]] + [np.zeros(H, np.float32)] * 2)
        c_store = np.stack([ra["c"], rb["c"]] + [np.zeros(H, np.float32)] * 2)
        tap.observe_batch(
            ["a", "b"],
            np.stack([ra["obs"], rb["obs"]]),
            np.array([ra["action"], rb["action"]]),
            np.stack([ra["q"], rb["q"]]),
            np.array([ra["reward"], rb["reward"]], np.float32),
            np.array([ra["reset"], rb["reset"]]),
            np.array([ra["eps"], rb["eps"]], np.float32),
            ckpt_step=0, version=0,
            h_store=h_store, c_store=c_store, slots=np.arange(4),
        )
    tap.process_pending()
    tap.flush()
    want = []
    for rows in (rows_a, rows_b):
        solo, out = _collecting_tap(CFG)
        _feed(solo, rows)
        solo.process_pending()
        solo.flush()
        want.append(out)
    # emission order interleaves by time; compare per-session streams.
    # Session identity isn't on the Block, so split by matching: session
    # a's blocks are exactly the solo-a emissions in order.
    per = {"a": [], "b": []}
    audits = list(tap.audit_tail)
    assert len(audits) == len(got)
    for audit, emission in zip(audits, got):
        per[audit["session"]].append(emission)
    _assert_emissions_equal(per["a"], want[0])
    _assert_emissions_equal(per["b"], want[1])


def test_tap_eviction_cuts_partial_block():
    """A session eviction (queued from the client thread) cuts the partial
    block with the pending-Q bootstrap and drops the stream."""
    rows = _stream(CFG, 10, seed=5)
    tap, got = _collecting_tap(CFG)
    _feed(tap, rows)
    tap.observe_evict("s0")
    tap.process_pending()
    assert tap.stats()["tap_open_sessions"] == 0
    want, _ = _offline(CFG, rows)  # offline flush = same pending-Q cut
    _assert_emissions_equal(got, want)


def test_tap_drop_severs_and_reseeds():
    """Overflowing the record queue drops the OLDEST batch (counted); the
    severed session's partial is cut cleanly at next sight and the stream
    reseeds — emitted blocks stay internally consistent."""
    rows = _stream(CFG, 16, seed=7)
    tap, got = _collecting_tap(CFG, depth=6)
    _feed(tap, rows[:4])
    assert tap.process_pending() == 4  # stream established: 3 steps, pending
    _feed(tap, rows[4:])  # 12 records into a depth-6 queue: 6 dropped
    assert tap.process_pending() == 6
    tap.flush()
    stats = tap.stats()
    assert stats["tap_dropped_batches"] == 6
    # at next sight (row 10) the severed partial is cut with its pending-Q
    # bootstrap and the stream reseeds; rows 11..15 then add 5 steps
    assert stats["tap_seam_breaks"] == 1
    assert stats["tap_captured_steps"] == 3 + 5
    want_head, _ = _offline(CFG, rows[:4])   # the severance cut == a flush
    want_tail, _ = _offline(CFG, rows[10:])  # the reseeded stream
    _assert_emissions_equal(got, want_head + want_tail)


# ------------------------------------------------------- bridge backpressure


class _FakeReplay:
    def __init__(self):
        self.batches = []

    def add_blocks_batch(self, items):
        self.batches.append(list(items))


def test_bridge_backpressure_drops_oldest_counted():
    replay = _FakeReplay()
    bridge = IngestBridge(replay, depth=2)
    for i in range(5):
        bridge.offer(f"block{i}", f"prio{i}", None)
    stats = bridge.stats()
    assert stats["bridge_offered_blocks"] == 5
    assert stats["bridge_dropped_blocks"] == 3
    assert stats["bridge_queue_depth"] == 2
    assert bridge.drain_once() == 2
    # drop-oldest: the two NEWEST offers survive, in order
    assert replay.batches == [[("block3", "prio3", None),
                               ("block4", "prio4", None)]]
    stats = bridge.stats()
    assert stats["bridge_ingested_blocks"] == 2
    assert stats["bridge_queue_depth"] == 0
    # drain-granularity drop visibility: this drain observed the 3 sheds
    # since the previous one; a quiet follow-up drain reads 0 again
    assert stats["bridge_dropped_last_drain"] == 3
    bridge.offer("block5", "prio5", None)
    bridge.drain_once()
    assert bridge.stats()["bridge_dropped_last_drain"] == 0
    assert bridge.stats()["bridge_dropped_blocks"] == 3


def test_bridge_falls_back_to_add_block():
    """A replay plane without the batch entry point gets per-block adds."""

    class _OldReplay:
        def __init__(self):
            self.calls = []

        def add_block(self, block, priorities, episode_reward=None):
            self.calls.append((block, priorities, episode_reward))

    replay = _OldReplay()
    bridge = IngestBridge(replay, depth=8)
    bridge.offer("b0", "p0", 1.5)
    bridge.offer("b1", "p1", None)
    assert bridge.drain_once() == 2
    assert replay.calls == [("b0", "p0", 1.5), ("b1", "p1", None)]


# -------------------------------------------------- snapshot/resume parity


def test_tap_snapshot_resume_bit_exact():
    """Snapshot mid-stream (partial block accumulated, pending transition
    and audit stamps in flight), round-trip through npz arrays, restore
    into a FRESH tap, continue — emissions are bitwise identical to the
    uninterrupted run."""
    rows = _stream(CFG, 44, seed=9, resets={13})
    cut_at = 25  # mid-block, mid-episode, pending set

    tap_a, got_a = _collecting_tap(CFG)
    _feed(tap_a, rows)
    tap_a.process_pending()
    tap_a.flush()

    tap_b, got_b = _collecting_tap(CFG)
    _feed(tap_b, rows[:cut_at])
    tap_b.process_pending()
    snap = tap_b.carry_state()
    # the same npz round trip the replay snapshot applies
    restored = {}
    for sid, d in snap.items():
        buf = io.BytesIO()
        np.savez(buf, **d)
        buf.seek(0)
        with np.load(buf) as z:
            restored[sid] = {k: z[k] for k in z.files}
    tap_c, got_c = _collecting_tap(CFG)
    tap_c.restore_carry(restored)
    _feed(tap_c, rows[cut_at:])
    tap_c.process_pending()
    tap_c.flush()

    _assert_emissions_equal(got_b + got_c, got_a)
    # resumed audit stamps match the uninterrupted run's too
    audits_a = list(tap_a.audit_tail)
    audits_bc = list(tap_b.audit_tail) + list(tap_c.audit_tail)
    assert len(audits_a) == len(audits_bc)
    for x, y in zip(audits_a, audits_bc):
        np.testing.assert_array_equal(x["epsilon"], y["epsilon"])
        np.testing.assert_array_equal(x["params_version"], y["params_version"])


# ------------------------------------------- per-session epsilon protocol


@pytest.fixture(scope="module")
def eps_servers():
    """Two bit-identical warm servers for the override-parity test (same
    seed => same params, same action RNG stream)."""
    from r2d2_tpu.serve import PolicyServer, ServeConfig

    servers = []
    for _ in range(2):
        srv = PolicyServer(
            CFG, ServeConfig(buckets=(2,), max_wait_ms=1.0, cache_capacity=8)
        )
        srv.warmup()
        srv.start()
        servers.append(srv)
    yield servers
    for srv in servers:
        srv.stop()


def test_epsilon_none_and_zero_bitwise_identical(eps_servers):
    """An explicit epsilon=0.0 override takes the override code path but
    must leave the served stream bitwise identical to the default path —
    the satellite's 'default path unchanged' guarantee, strengthened to
    cover the plumbing itself."""
    from r2d2_tpu.serve import LocalClient

    rng = np.random.default_rng(0)
    obs_seq = [rng.integers(0, 255, CFG.obs_shape, dtype=np.uint8)
               for _ in range(12)]
    streams = []
    for srv, eps in zip(eps_servers, (None, 0.0)):
        client = LocalClient(srv)
        out = []
        for t, obs in enumerate(obs_seq):
            r = client.act("sess", obs, reward=0.5 * t, reset=(t == 0),
                           epsilon=eps)
            out.append((r.action, np.asarray(r.q).copy()))
        streams.append(out)
    for (a0, q0), (a1, q1) in zip(*streams):
        assert a0 == a1
        np.testing.assert_array_equal(q0, q1)


def test_epsilon_override_explores_and_assigner_surfaces_stats(eps_servers):
    from r2d2_tpu.liveloop import EpsilonAssigner
    from r2d2_tpu.serve import LocalClient

    srv = eps_servers[0]
    client = LocalClient(srv)
    rng = np.random.default_rng(1)
    # epsilon=1.0 forces uniform-random actions: some answer must deviate
    # from its own Q row's argmax (p(all greedy) = (1/A)^24)
    deviated = 0
    for t in range(24):
        obs = rng.integers(0, 255, CFG.obs_shape, dtype=np.uint8)
        r = client.act("explore", obs, reset=(t == 0), epsilon=1.0)
        deviated += int(r.action != int(np.argmax(np.asarray(r.q))))
    assert deviated > 0

    # install an always-explore assigner: new sessions draw a ladder rung,
    # the assignment is sticky, and stats() surfaces the census
    srv.eps_assigner = EpsilonAssigner(
        CFG.replace(liveloop_explore_fraction=1.0), seed=0
    )
    try:
        for t in range(4):
            obs = rng.integers(0, 255, CFG.obs_shape, dtype=np.uint8)
            client.act("assigned", obs, reset=(t == 0))
        stats = srv.stats()
        assert stats["eps_sessions_assigned"] == 1
        assert stats["eps_sessions_exploring"] == 1
        eps = srv.eps_assigner.epsilon_of("assigned")
        assert eps is not None and eps > 0.0
        # eviction releases the assignment (and the tap hook, if any)
        client.evict("assigned")
        assert srv.eps_assigner.epsilon_of("assigned") is None
    finally:
        srv.eps_assigner = None


# ------------------------------------------------------------ registration


def test_liveloop_fault_sites_registered():
    from r2d2_tpu.utils.faults import KNOWN_SITES

    assert "liveloop.tap" in KNOWN_SITES
    assert "liveloop.ingest" in KNOWN_SITES


# --------------------------------------------------------------- e2e smoke


@pytest.mark.slow
def test_liveloop_return_improves_on_catch(tmp_path):
    """The closed loop end-to-end under live load: a two-replica fleet
    serves catch sessions, the tap feeds replay, the live trainer's
    checkpoints hot-reload the fleet mid-run, and the served policy's
    episode return improves from the first half of the window to the
    second. Also asserts the acceptance invariants: >= 1 reload with
    params_version advancing, sessions_lost == 0."""
    import jax

    from r2d2_tpu.envs.catch import CatchHostEnv
    from r2d2_tpu.liveloop import LiveLoopPlane, LiveLoopTrainer
    from r2d2_tpu.serve import LocalClient, MultiDeviceServer, ServeConfig

    seconds, sessions, rate = 30.0, 6, 48.0
    cfg = tiny_test().replace(
        env_name="catch",
        action_dim=3,
        liveloop=True,
        checkpoint_dir=str(tmp_path / "ckpt"),
        save_interval=20,
        learning_starts=128,
        buffer_capacity=4096,
        training_steps=1_000_000,
        serve_spill=4 * sessions,
    ).validate()
    serve_cfg = ServeConfig(buckets=(2, 4, 8), max_wait_ms=2.0,
                            cache_capacity=16, poll_interval_s=0.25)
    trainer = LiveLoopTrainer(cfg)
    d0 = jax.local_devices()[0]
    server = MultiDeviceServer(cfg, serve_cfg,
                               checkpoint_dir=cfg.checkpoint_dir,
                               devices=[d0, d0])
    plane = LiveLoopPlane(cfg, server, trainer.replay, seed=0)
    server.warmup()
    server.start(watch_checkpoints=True)
    plane.start()
    version0 = server.stats()["params_version"]

    stop = threading.Event()
    lock = threading.Lock()
    episodes = []  # (t_rel, return)
    t0 = time.perf_counter()
    per_session_rate = rate / sessions

    def session_body(idx):
        rng = np.random.default_rng(100 + idx)
        env = CatchHostEnv(height=cfg.obs_shape[0], width=cfg.obs_shape[1],
                           seed=100 + idx)
        client = LocalClient(server)
        obs, reward, reset, ep_ret = env.reset(), 0.0, True, 0.0
        while not stop.is_set():
            try:
                res = client.act(f"s{idx}", obs, reward=reward, reset=reset)
            except Exception:
                obs, reward, reset, ep_ret = env.reset(), 0.0, True, 0.0
                time.sleep(rng.exponential(1.0 / per_session_rate))
                continue
            reset = False
            obs, reward, done, _ = env.step(res.action)
            ep_ret += reward
            if done:
                with lock:
                    episodes.append((time.perf_counter() - t0, ep_ret))
                obs, reset, ep_ret = env.reset(), True, 0.0
            time.sleep(rng.exponential(1.0 / per_session_rate))

    threads = [threading.Thread(target=session_body, args=(i,), daemon=True)
               for i in range(sessions)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + seconds
    try:
        while time.monotonic() < deadline:
            plane.check()
            if trainer.can_train():
                trainer.train(8, deadline=deadline)
            else:
                time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        plane.stop()
        trainer.finish()
        stats = server.stats()
        server.stop()

    assert stats["sessions_lost"] == 0
    assert stats["reloads"] >= 1
    assert stats["params_version"] > version0
    loop_stats = plane.stats()
    assert loop_stats["tap_captured_steps"] > 0
    assert loop_stats["bridge_ingested_blocks"] > 0
    half1 = [r for (t, r) in episodes if t < seconds / 2]
    half2 = [r for (t, r) in episodes if t >= seconds / 2]
    assert len(half1) >= 10 and len(half2) >= 10
    assert float(np.mean(half2)) > float(np.mean(half1))
