#!/bin/bash
# Round-3 chain E: runs after chain D drains. The 16x16 shaped maze
# hovered at its random-walk baseline through 30k updates, so this takes
# the difficulty ladder's next rung down: an 8x8 maze (procmaze_shaped:8,
# same 64x64x3 obs, same IMPALA preset) where the shaped signal plus a
# ~4x denser success rate under random play should be learnable — the
# BASELINE-config-4 positive, measured against ITS OWN random baseline.
cd /root/repo
while ! grep -q R3D_CHAIN_ALL_DONE runs/r3d_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

mkdir -p runs/procmaze_small
python runs/measure_random_baseline.py --env procmaze_shaped:8 --episodes 2048 \
  --out runs/procmaze_small/baseline.json
echo "=== PROCMAZE8_BASELINE EXIT: $? ==="
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:8 \
  --mode fused --steps 30000 --updates-per-dispatch 16 \
  --set checkpoint_dir=runs/procmaze_small/ckpt \
  --set metrics_path=runs/procmaze_small/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE8 TRAIN EXIT: $? ==="
python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:8 --episodes 4 \
  --out runs/procmaze_small/eval.jsonl --plot runs/procmaze_small/curve.jpg \
  --set checkpoint_dir=runs/procmaze_small/ckpt
echo "=== PROCMAZE8 EVAL EXIT: $? ==="

echo R3E_CHAIN_ALL_DONE
