"""dp-sharded device-resident replay: HBM capacity scales with the mesh.

The single-chip DeviceReplayBuffer (replay/device_store.py) caps replay at
one chip's HBM (~2M transitions of 84x84 obs fills 16 GB). This variant
shards every store's block axis over the mesh's dp axis, so a v4-8 holds
dp x that — the reference's full 2e6-transition capacity
(reference config.py:16) fits in HBM on a 4-way mesh with room to spare.

Design (mirrors the scaling-book recipe: pick a mesh, annotate shardings,
let collectives ride ICI):

- CONTROL PLANE: one host-side ReplayControlPlane PER SHARD (sum tree over
  that shard's sequence slots, its own circular pointer + staleness
  window). Blocks round-robin across shards, so every shard stays
  statistically identical to a 1/dp-sized uniform slice of the stream.
- DATA PLANE: one global jnp array per field with the block axis sharded
  NamedSharding(mesh, P("dp")). A block write is a donated
  dynamic_update_index_in_dim at the owning shard's global slot — XLA
  resolves it to a local update on the owning device.
- SAMPLING: each shard draws batch_size/dp sequences from its own tree;
  IS weights are renormalized across shards to the BATCH-global minimum
  priority, so weights match what a single global tree would produce for
  the same draws (min is over the sampled batch, replay/sum_tree.py).
- TRAINING: learner.make_sharded_fused_train_step runs under shard_map —
  each device gathers its sub-batch from its LOCAL shard (zero cross-device
  data-plane traffic) and gradients pmean over dp.

Priority round trip: update_priorities applies each shard's slice under
that shard's own pointer-window staleness mask (reference worker.py:290-307
invariant, per shard).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.block import Block, store_field_specs
from r2d2_tpu.replay.control_plane import ReplayControlPlane, shard_config
from r2d2_tpu.replay.device_store import DeviceReplayBuffer


@dataclasses.dataclass
class ShardedSampleIdx:
    """Per-shard stacked sample coordinates (host side)."""

    b: np.ndarray           # (dp, B/dp) block slot LOCAL to each shard
    s: np.ndarray           # (dp, B/dp) sequence-in-block
    is_weights: np.ndarray  # (dp, B/dp) float32, batch-globally normalized
    idxes: np.ndarray       # (dp, B/dp) sequence slots LOCAL to each shard
    old_ptrs: List[int]     # per-shard block pointer at sample time
    old_advances: List[int]  # per-shard ptr_advances stamp (lap detection)
    env_steps: int


class _ShardTreeMirror:
    """The per-shard face of the parent's stacked device tree: quacks like
    DeviceSumTree for the slice of its API the shard control plane
    (_tree_write) and snapshots (leaves/load_leaves) touch, routing every
    operation to the parent's (dp, tree_size) P("dp")-sharded array — one
    global array, row updates resolved to the owning device by XLA, same
    pattern as the block stores."""

    def __init__(self, parent: "ShardedDeviceReplay", sid: int):
        self.parent = parent
        self.sid = sid

    def update(self, idxes: np.ndarray, td_errors: np.ndarray) -> None:
        if len(idxes) == 0:
            return
        self.parent._dtree_row_update(self.sid, idxes, td_errors)

    def leaves(self) -> np.ndarray:
        p = self.parent
        off = 2 ** (p._dtree_layers - 1) - 1
        return np.asarray(p.dtree_stack[self.sid, off : off + p._dtree_cap])

    def load_leaves(self, values: np.ndarray) -> None:
        self.parent._dtree_row_load(self.sid, values)


class ShardedDeviceReplay:
    def __init__(self, cfg: R2D2Config, mesh: Mesh):
        dp = mesh.shape["dp"]
        if cfg.num_blocks % dp != 0:
            raise ValueError(f"num_blocks {cfg.num_blocks} not divisible by dp {dp}")
        if cfg.batch_size % dp != 0:
            raise ValueError(f"batch_size {cfg.batch_size} not divisible by dp {dp}")
        self.cfg = cfg
        self.mesh = mesh
        self.dp = dp
        self.blocks_per_shard = cfg.num_blocks // dp
        # per-shard view: 1/dp of capacity and batch; the shard config is
        # single-plane (its own control plane knows nothing of the mesh)
        shard_cfg = shard_config(cfg, dp)
        self.shards = [ReplayControlPlane(shard_cfg) for _ in range(dp)]
        self._rr = 0  # round-robin write cursor over shards

        nb = cfg.num_blocks
        shd = NamedSharding(mesh, P("dp"))
        self.stores: Dict[str, jnp.ndarray] = {
            k: jnp.zeros((nb, *shape), dt, device=shd)
            for k, (shape, dt) in store_field_specs(cfg).items()
        }

        def _write(stores, ptr, vals):
            return {
                k: jax.lax.dynamic_update_index_in_dim(arr, vals[k], ptr, axis=0)
                for k, arr in stores.items()
            }

        self._write = jax.jit(
            _write,
            donate_argnums=(0,),
            out_shardings={k: shd for k in self.stores},
        )

        # batched slab write for the on-device collector: the batch deals
        # round-robin starting at shard 0, so shard sid receives blocks
        # sid, sid+dp, ... as ONE contiguous slab in its own region. The
        # write runs under shard_map: each device applies a plain
        # dynamic_update_slice to its LOCAL (nb/dp, ...) store block at its
        # own start offset — no collectives, no GSPMD partitioning of a
        # sharded-axis update (which compiles/executes pathologically; a
        # dynamic-index scatter is just as bad, see
        # DeviceReplayBuffer._write_slab). vals must carry E % dp == 0
        # blocks (add_blocks_batch routes remainders through the
        # single-slot _write); starts: (dp,) LOCAL first slot per shard.
        from r2d2_tpu.parallel.jax_compat import shard_map

        def _slab_body(stores, starts, vals):
            # local views: stores (nb/dp, ...), starts (1,), vals (1, E/dp, ...)
            return {
                k: jax.lax.dynamic_update_slice_in_dim(
                    arr, vals[k][0], starts[0], axis=0
                )
                for k, arr in stores.items()
            }

        def _write_slabs(stores, starts, rr, vals):
            E = next(iter(vals.values())).shape[0]
            # block i -> shard (rr + i) % dp at consecutive local slots:
            # regroup (E, ...) as (dp, E/dp, ...) with [sid, j] = v[j*dp+sid]
            # for rr == 0, then roll the shard axis by the round-robin
            # cursor so the dealing continues where the last add stopped
            grouped = {
                k: jnp.roll(
                    jnp.swapaxes(v.reshape(E // dp, dp, *v.shape[1:]), 0, 1),
                    rr,
                    axis=0,
                )
                for k, v in vals.items()
            }
            specs = {k: P("dp") for k in stores}
            return shard_map(
                _slab_body,
                mesh=mesh,
                in_specs=(specs, P("dp"), {k: P("dp") for k in grouped}),
                out_specs=specs,
                check_vma=False,
            )(stores, starts, grouped)

        self._write_slabs = jax.jit(
            _write_slabs,
            donate_argnums=(0,),
            out_shardings={k: shd for k in self.stores},
        )

        # priority_plane="device": per-shard float32 trees stacked
        # (dp, tree_size) with the SAME P("dp") sharding as the stores —
        # each shard's tree lives next to its blocks. Host-side ingestion
        # mirrors through _ShardTreeMirror row updates; the sharded
        # superstep (megastep.make_sharded_priority_superstep) carries the
        # whole stack through its scan and hands it back via superstep_run.
        self.dtree_stack: Optional[jnp.ndarray] = None
        if cfg.priority_plane == "device":
            from r2d2_tpu.replay import device_sum_tree as dst

            self._dst = dst
            self._dtree_cap = shard_cfg.num_sequences
            self._dtree_layers = dst.tree_layers(self._dtree_cap)
            self._dtree_shd = shd
            tsize = dst.tree_size(self._dtree_layers)
            self.dtree_stack = jnp.zeros((dp, tsize), jnp.float32, device=shd)

            def _row_update(stack, sid, idxes, td):
                row = dst.tree_update(
                    stack[sid], self._dtree_layers, idxes, td, cfg.prio_exponent
                )
                return jax.lax.dynamic_update_index_in_dim(stack, row, sid, axis=0)

            self._row_update_fn = jax.jit(
                _row_update, donate_argnums=(0,), out_shardings=shd
            )
            for sid, sh in enumerate(self.shards):
                sh.attach_device_tree(_ShardTreeMirror(self, sid))
        self.lock = threading.Lock()

    # r2d2: guarded-by(lock)
    def _dtree_row_update(self, sid: int, idxes, td_errors) -> None:
        # callers (_tree_write via add_block/update_priorities) already hold
        # self.lock; the Lock is non-reentrant, so this must not re-acquire
        self.dtree_stack = self._row_update_fn(
            self.dtree_stack,
            jnp.int32(sid),
            jnp.asarray(np.asarray(idxes, np.int32)),
            jnp.asarray(np.asarray(td_errors, np.float32)),
        )

    def _dtree_row_load(self, sid: int, values: np.ndarray) -> None:
        """Snapshot-restore path: rebuild one shard's tree from raw leaves
        and re-deal the stack (host round trip; restore-time only)."""
        host = np.asarray(self.dtree_stack)
        host[sid] = np.asarray(self._dst.tree_from_leaves(values, self._dtree_cap))
        # restore runs before any worker thread starts (single-threaded
        # phase, snapshot.load_replay)  # r2d2: disable=lock-discipline
        self.dtree_stack = jax.device_put(host, self._dtree_shd)

    def superstep_run(self, fn: Callable):
        """Dispatch an in-jit sharded superstep under ONE buffer-lock hold:
        fn(stores, dtree_stack, num_seq_store (dp, nb/dp)) -> (stack',
        rest). Installing the output stack before the lock releases orders
        every later ingestion mirror write after the superstep on the
        device stream — the same serialization argument as
        DeviceReplayBuffer.superstep_run, per shard."""
        with self.lock:
            nss = np.stack([sh.num_seq_store for sh in self.shards])
            stack_out, rest = fn(self.stores, self.dtree_stack, nss)
            self.dtree_stack = stack_out
            return rest

    # ---------------------------------------------------------------- state

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    @property
    def env_steps(self) -> int:
        return sum(s.env_steps for s in self.shards)

    def can_sample(self) -> bool:
        return (
            len(self) >= self.cfg.learning_starts
            and all(s.tree.total > 0 for s in self.shards)
        )

    def pop_episode_stats(self):
        n = r = 0
        for sh in self.shards:
            ni, ri = sh.pop_episode_stats()
            n += ni
            r += ri
        return n, r

    def episode_totals(self):
        n = r = 0
        for sh in self.shards:
            ni, ri = sh.episode_totals()
            n += ni
            r += ri
        return n, r

    # ------------------------------------------------------------------ add

    def add_block(
        self, block: Block, priorities: np.ndarray, episode_reward: Optional[float]
    ) -> None:
        cfg = self.cfg
        vals = DeviceReplayBuffer.pad_block_fields(cfg, block)
        with self.lock:
            shard_id = self._rr
            shard = self.shards[shard_id]
            with shard.lock:
                # write first, account last (see replay_buffer.add_block)
                global_ptr = shard_id * self.blocks_per_shard + shard.block_ptr
                self.stores = self._write(self.stores, global_ptr, vals)
                shard._account_add(
                    block.num_sequences,
                    int(block.learning_steps.sum()),
                    priorities,
                    episode_reward,
                )
            self._rr = (self._rr + 1) % self.dp

    def add_blocks_batch(
        self,
        fields: Dict[str, jnp.ndarray],
        num_seq: np.ndarray,
        learning_totals: np.ndarray,
        priorities: np.ndarray,
        episode_rewards: np.ndarray,
        dones: np.ndarray,
    ) -> None:
        """Write E collector-packed blocks round-robin across shards
        (collect.DeviceCollector contract, mirroring
        DeviceReplayBuffer.add_blocks_batch). The first floor(E/dp)*dp
        blocks land as one shard_map slab write — each device updates its
        local store region, no collectives; the remainder goes through the
        single-slot write. Fields stay on device end to end; only the
        per-block accounting scalars are host-side. Dealing continues the
        round-robin cursor from the previous add, like E sequential
        add_block calls (pinned by test) — UNTIL a shard's local ring
        wraps: from then on the batched path retires tail slots via
        _reserve_contiguous to keep each slab contiguous, so slot
        placement (and the retired blocks' tree state) deliberately
        diverges from the sequential path, which never retires."""
        E = len(num_seq)
        bps = self.blocks_per_shard
        dp = self.dp
        if E > dp * bps:
            raise ValueError(f"{E} blocks per batch exceeds {dp * bps} slots")
        per = E // dp
        Em = per * dp  # slab-written prefix; blocks Em..E-1 write singly
        with self.lock:
            rr = self._rr  # block i -> shard (rr + i) % dp
            # hold EVERY shard's lock across write + account (ascending
            # order; other paths only ever hold one at a time): a sampler
            # draw between the slab write and the accounting would pair new
            # slot data with the evicted blocks' tree state — add_block's
            # single-shard lock gives the same guarantee
            locks = [sh.lock for sh in self.shards]
            for lk in locks:
                lk.acquire()
            try:
                if Em:
                    # destination slots BEFORE accounting mutates the
                    # pointers (write first, account last — same contract
                    # as add_block)
                    starts = np.asarray(
                        [sh._reserve_contiguous(per) for sh in self.shards],
                        np.int64,
                    )
                    slab_fields = {k: v[:Em] for k, v in fields.items()}
                    self.stores = self._write_slabs(
                        self.stores, jnp.asarray(starts, jnp.int32),
                        jnp.int32(rr), slab_fields,
                    )
                    # block i lands at local slot starts[(rr+i)%dp] + i//dp;
                    # accounting in ascending i matches that order per shard
                    for i in range(Em):
                        self.shards[(rr + i) % dp]._account_add(
                            int(num_seq[i]),
                            int(learning_totals[i]),
                            priorities[i],
                            float(episode_rewards[i]) if dones[i] else None,
                        )
                for j in range(E - Em):
                    i = Em + j
                    sid = (rr + j) % dp  # Em is a multiple of dp
                    shard = self.shards[sid]
                    gptr = sid * bps + shard.block_ptr
                    self.stores = self._write(
                        self.stores, gptr, {k: v[i] for k, v in fields.items()}
                    )
                    shard._account_add(
                        int(num_seq[i]),
                        int(learning_totals[i]),
                        priorities[i],
                        float(episode_rewards[i]) if dones[i] else None,
                    )
                self._rr = (rr + E) % dp
            finally:
                for lk in reversed(locks):
                    lk.release()

    # --------------------------------------------------------------- sample

    def sample_indices(
        self, rng: np.random.Generator, locked: bool = False
    ) -> ShardedSampleIdx:
        """Each shard draws B/dp sequences; IS weights renormalized to the
        batch-global minimum priority so the sharded draw matches the
        single-tree semantics. locked=True: the caller already holds every
        shard's lock (the fused runner's draw-under-reservation path)."""
        import contextlib

        bs, ss, idxs, prios = [], [], [], []
        old_ptrs, old_advances = [], []
        for shard in self.shards:
            with shard.lock if not locked else contextlib.nullcontext():
                b, s, idxes, _w = shard._draw(rng)
                old_ptrs.append(shard.block_ptr)
                old_advances.append(shard.ptr_advances)
                # read priorities under the SAME lock as the draw — an
                # interleaved add_block would rewrite these leaves and the
                # weights would no longer describe the drawn sample
                p = shard.tree.priorities_of(idxes)
            bs.append(b)
            ss.append(s)
            idxs.append(idxes)
            prios.append(p)
        p = np.stack(prios)  # (dp, B/dp) raw tree priorities
        positive = p[p > 0.0]
        min_p = positive.min() if positive.size else 1.0
        w = np.power(np.maximum(p, min_p) / min_p, -self.cfg.is_exponent)
        return ShardedSampleIdx(
            b=np.stack(bs).astype(np.int32),
            s=np.stack(ss).astype(np.int32),
            is_weights=w.astype(np.float32),
            idxes=np.stack(idxs),
            old_ptrs=old_ptrs,
            old_advances=old_advances,
            env_steps=self.env_steps,
        )

    # ------------------------------------------------------------ round trip

    def update_priorities(
        self,
        idxes: np.ndarray,
        td_errors: np.ndarray,
        old_ptrs: List[int],
        old_advances: Optional[List[int]] = None,
    ) -> None:
        """idxes/td_errors: (dp, B/dp) as returned by sample/train."""
        advances = old_advances if old_advances is not None else [None] * self.dp
        for shard, idx_row, td_row, old_ptr, old_adv in zip(
            self.shards, idxes, np.asarray(td_errors), old_ptrs, advances
        ):
            shard.update_priorities(idx_row, td_row, old_ptr, old_adv)

    def sample_and_run(self, rng: np.random.Generator, k: int, fn: Callable):
        """Draw k per-shard coordinate sets and dispatch fn(stores, draws)
        under ONE buffer-lock hold (multi-update path,
        learner.make_sharded_fused_multi_train_step) — the sharded
        counterpart of DeviceReplayBuffer.sample_and_run. Holding
        self.lock excludes add paths (they take it first), so the in-jit
        gathers read exactly the data the coordinates were drawn
        against."""
        with self.lock:
            draws = [self.sample_indices(rng) for _ in range(k)]
            return draws, fn(self.stores, draws)

    # ------------------------------------------------------------- dispatch

    def run_with_stores(self, fn: Callable):
        """Dispatch fn(stores) under the buffer lock (same contract as
        DeviceReplayBuffer.run_with_stores: the donated write invalidates
        prior store references)."""
        with self.lock:
            return fn(self.stores)
