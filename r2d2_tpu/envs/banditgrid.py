"""BanditGrid — a stochastic-reward grid of noisy arms (pure JAX).

The reward-variance probe of the multi-task family: the agent walks a g x g
grid whose cells pay out like bandit arms — a FIXED mean surface (rising
toward the far corner) plus fresh Gaussian noise every step. The optimal
policy is trivial spatially (walk to the high corner and sit), but the
return signal is buried in per-step noise whose sigma rivals the mean
spread, so TD errors stay large and noisy long after the policy is right.
That is exactly the load profile that stresses prioritized replay (PR 9's
device priority plane): priorities driven by reward noise rather than by
learnable error must not starve the rest of the buffer.

Same functional protocol as envs/catch.py (reset/step/render + NUM_ACTIONS).
Actions: 0 NOOP, 1 up, 2 down, 3 left, 4 right (procmaze's convention);
out-of-range actions (a padded multi-task union action space) degrade to
NOOP.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

BANDITGRID_DEFAULTS = dict(grid=4, horizon=16)
BANDIT_NOISE_SIGMA = 0.5


def banditgrid_params(name: str) -> dict:
    """Variant parameters encoded in an env name: 'banditgrid[:G[:H]]'
    (grid side, episode horizon). Raises on non-banditgrid names (gate on
    is_banditgrid_name) and degenerate values."""
    n = name.lower()
    base, _, suffix = n.partition(":")
    if base != "banditgrid":
        raise ValueError(f"not a banditgrid family env name: {name!r}")
    out = dict(BANDITGRID_DEFAULTS)
    if suffix:
        parts = suffix.split(":")
        if len(parts) > 2:
            raise ValueError(f"banditgrid takes at most :G:H, got {name!r}")
        for k, v in zip(("grid", "horizon"), parts):
            out[k] = int(v)
    if out["grid"] < 2:
        raise ValueError(f"banditgrid grid must be >= 2, got {out['grid']}")
    if out["horizon"] < 2:
        raise ValueError(f"banditgrid horizon must be >= 2, got {out['horizon']}")
    return out


def is_banditgrid_name(name: str) -> bool:
    return name.lower().partition(":")[0] == "banditgrid"


def build_banditgrid_env(obs_shape, max_episode_steps: int, name: str) -> "BanditGridEnv":
    """ONE factory for every 'banditgrid[:G[:H]]' name; the name-encoded
    horizon is capped by the config's episode budget."""
    p = banditgrid_params(name)
    h, w, c = obs_shape
    return BanditGridEnv(
        height=h, width=w, grid=p["grid"],
        horizon=min(max_episode_steps, p["horizon"]),
    )


class BanditGridState(NamedTuple):
    pos: jnp.ndarray  # (2,) int32 row, col
    t: jnp.ndarray    # int32 step counter
    key: jnp.ndarray  # PRNG key (consumed every step by the payout draw)


class BanditGridEnv:
    """Functional single-env core; every method is jit/vmap-safe."""

    NUM_ACTIONS = 5  # 0 = NOOP, 1 = up, 2 = down, 3 = left, 4 = right

    def __init__(
        self,
        height: int = 6,
        width: int = 6,
        grid: int = 4,
        horizon: int = 16,
        noise: float = BANDIT_NOISE_SIGMA,
    ):
        if grid < 2:
            raise ValueError(f"banditgrid grid must be >= 2, got {grid}")
        if height < grid or width < grid:
            raise ValueError(
                f"banditgrid obs canvas {height}x{width} cannot render a "
                f"{grid}x{grid} grid"
            )
        if horizon < 2:
            raise ValueError(f"banditgrid horizon must be >= 2, got {horizon}")
        self.h, self.w = height, width
        self.g = grid
        self.horizon = horizon
        self.noise = noise

    def _means(self) -> jnp.ndarray:
        """(g, g) f32 arm means in [0, 1], rising toward (g-1, g-1)."""
        idx = jnp.arange(self.g, dtype=jnp.float32)
        return (idx[:, None] + idx[None, :]) / (2.0 * (self.g - 1))

    def reset(self, key: jax.Array) -> BanditGridState:
        # fixed start at the LOW corner: the mean gradient must be climbed,
        # not spawned onto
        return BanditGridState(
            jnp.zeros((2,), jnp.int32), jnp.zeros((), jnp.int32), key
        )

    def render(self, s: BanditGridState) -> jnp.ndarray:
        """(H, W, 1) uint8: the static mean surface at half intensity
        (payout structure is observable — the hard part is the noise, not
        hidden state) with the agent cell at 255."""
        ys = jnp.arange(self.h)[:, None]
        xs = jnp.arange(self.w)[None, :]
        in_grid = (ys < self.g) & (xs < self.g)
        means = jnp.zeros((self.h, self.w), jnp.float32)
        means = means.at[: self.g, : self.g].set(self._means())
        surface = jnp.where(in_grid, means * 128.0, 0.0)
        agent = (ys == s.pos[0]) & (xs == s.pos[1])
        frame = jnp.where(agent, 255.0, surface).astype(jnp.uint8)
        return frame[:, :, None]

    def step(self, s: BanditGridState, action: jnp.ndarray):
        """Returns (state', reward, done): reward = mean(cell') + noise,
        terminal at the horizon."""
        dr = jnp.where(action == 1, -1, jnp.where(action == 2, 1, 0))
        dc = jnp.where(action == 3, -1, jnp.where(action == 4, 1, 0))
        pos = jnp.clip(
            s.pos + jnp.stack([dr, dc]), 0, self.g - 1
        ).astype(jnp.int32)
        t = s.t + 1
        key, kn = jax.random.split(s.key)
        mu = self._means()[pos[0], pos[1]]
        reward = mu + self.noise * jax.random.normal(kn)
        done = t >= self.horizon
        return BanditGridState(pos, t, key), reward, done
