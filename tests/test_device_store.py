"""DeviceReplayBuffer + fused train step: must be numerically equivalent to
the host-assembled path on identical data and sampling streams."""

import jax
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.learner import DeviceBatch, init_train_state, make_fused_train_step, make_train_step
from r2d2_tpu.replay.device_store import DeviceReplayBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from tests.test_replay_buffer import make_block, small_cfg


@pytest.fixture(scope="module")
def both_buffers():
    cfg = small_cfg(batch_size=6, hidden_dim=4)
    host = ReplayBuffer(cfg)
    dev = DeviceReplayBuffer(cfg)
    for k in range(4):
        block, prios, ep = make_block(cfg, seed=k, terminal=(k % 2 == 0))
        host.add_block(block, prios, ep)
        dev.add_block(block, prios, ep)
    return cfg, host, dev


def test_same_sampling_stream(both_buffers):
    cfg, host, dev = both_buffers
    hb = host.sample_batch(np.random.default_rng(7))
    di = dev.sample_indices(np.random.default_rng(7))
    np.testing.assert_array_equal(hb.idxes, di.idxes)
    np.testing.assert_allclose(hb.is_weights, di.is_weights, rtol=1e-6)
    assert hb.old_ptr == di.old_ptr
    assert hb.env_steps == di.env_steps


def test_fused_step_matches_host_step():
    cfg = tiny_test()
    host = ReplayBuffer(cfg)
    dev = DeviceReplayBuffer(cfg)
    rng = np.random.default_rng(0)
    from r2d2_tpu.replay.accumulator import SequenceAccumulator

    acc = SequenceAccumulator(cfg)
    for ep in range(12):
        acc.reset(rng.integers(0, 255, size=cfg.obs_shape, dtype=np.uint8))
        n = int(rng.integers(5, 30))
        for t in range(n):
            acc.add(
                int(rng.integers(cfg.action_dim)),
                float(rng.normal()),
                rng.integers(0, 255, size=cfg.obs_shape, dtype=np.uint8),
                rng.normal(size=cfg.action_dim).astype(np.float32),
                rng.normal(size=(2, cfg.hidden_dim)).astype(np.float32),
            )
            if len(acc) == cfg.block_length or t == n - 1:
                block, prios, r = acc.finish(
                    None if t == n - 1 else rng.normal(size=cfg.action_dim).astype(np.float32)
                )
                host.add_block(block, prios, r)
                dev.add_block(block, prios, r)

    net, state0 = init_train_state(cfg, jax.random.PRNGKey(0))
    host_step = make_train_step(cfg, net, donate=False)
    fused_step = make_fused_train_step(cfg, net, donate=False)

    hb = host.sample_batch(np.random.default_rng(3))
    di = dev.sample_indices(np.random.default_rng(3))
    np.testing.assert_array_equal(hb.idxes, di.idxes)

    s_host, m_host, p_host = host_step(state0, DeviceBatch.from_sampled(hb))
    s_dev, m_dev, p_dev = fused_step(
        state0, dev.stores, np.asarray(di.b), np.asarray(di.s), np.asarray(di.is_weights)
    )

    np.testing.assert_allclose(float(m_host["loss"]), float(m_dev["loss"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p_host), np.asarray(p_dev), rtol=1e-4, atol=1e-6)
    for a, b in zip(jax.tree.leaves(s_host.params), jax.tree.leaves(s_dev.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_device_store_eviction_and_staleness(both_buffers):
    cfg, host, dev = both_buffers
    assert len(dev) == len(host)
    di = dev.sample_indices(np.random.default_rng(1))
    old_ptr = di.old_ptr
    for k in range(2):
        block, prios, ep = make_block(cfg, seed=20 + k)
        dev.add_block(block, prios, ep)
    before = dev.tree.priorities_of(np.arange(12)).copy()
    dev.update_priorities(np.arange(12, dtype=np.int64), np.full(12, 9.0), old_ptr)
    after = dev.tree.priorities_of(np.arange(12))
    np.testing.assert_allclose(after[:6], before[:6])  # overwritten slots masked
    np.testing.assert_allclose(after[6:], 9.0**cfg.prio_exponent)


def test_multi_step_matches_sequential_fused():
    """K updates folded into one dispatch == K sequential fused steps on
    the same pre-drawn coordinates: same final params, same priorities."""
    import jax.numpy as jnp

    from r2d2_tpu.learner import make_fused_multi_train_step, make_fused_train_step

    cfg = tiny_test().replace(target_net_update_interval=2)  # sync mid-chunk
    net, state0 = init_train_state(cfg, jax.random.PRNGKey(0))
    replay = DeviceReplayBuffer(cfg)
    rng = np.random.default_rng(0)
    from bench import synth_block

    for _ in range(6):
        replay.add_block(
            synth_block(cfg, rng),
            rng.uniform(0.5, 2.0, cfg.seqs_per_block).astype(np.float32),
            None,
        )
    K = 3
    draws = [replay.sample_indices(np.random.default_rng(i)) for i in range(K)]

    single = make_fused_train_step(cfg, net, donate=False)
    state = state0
    prios_seq = []
    for si in draws:
        state, m, p = replay.run_with_stores(
            lambda stores, si=si: single(
                state, stores, jnp.asarray(si.b), jnp.asarray(si.s), jnp.asarray(si.is_weights)
            )
        )
        prios_seq.append(np.asarray(p))

    multi = make_fused_multi_train_step(cfg, net, K, donate=False)
    b = jnp.stack([jnp.asarray(si.b) for si in draws])
    s = jnp.stack([jnp.asarray(si.s) for si in draws])
    w = jnp.stack([jnp.asarray(si.is_weights) for si in draws])
    state_m, m_m, p_m = replay.run_with_stores(lambda stores: multi(state0, stores, b, s, w))

    assert int(state_m.step) == int(state.step) == K
    for a, bb in zip(jax.tree.leaves(state_m.params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=1e-6)
    for a, bb in zip(jax.tree.leaves(state_m.target_params), jax.tree.leaves(state.target_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_m), np.stack(prios_seq), atol=1e-5)
