"""Elastic resume: topology manifests, structured mismatch reporting, and
reshard_replay across every plane-family move the scheduler can force —
sharded->device, device->sharded at a different dp, device->host (dtype
cast across the family boundary), and the exact path, which must be
indistinguishable from a plain restore_replay."""

import json
import os

import jax
import numpy as np
import pytest

from bench import synth_block
from r2d2_tpu.config import tiny_test
from r2d2_tpu.parallel.mesh import make_mesh, slab_partition_map
from r2d2_tpu.replay.device_store import DeviceReplayBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.replay.reshard import (
    gather_logical,
    main as reshard_main,
    reshard_replay,
    snapshot_paths,
)
from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay
from r2d2_tpu.replay.snapshot import (
    TopologyMismatch,
    read_manifest,
    restore_replay,
    save_replay,
    snapshot_topology,
)
from r2d2_tpu.utils.faults import FaultPlane, InjectedFault, install, uninstall

NB = 40  # tiny_test: buffer_capacity 640 / block_length 16


def _fill(cfg, replay, n=10, seed=0):
    rng = np.random.default_rng(seed)
    for i in range(n):
        block = synth_block(cfg, rng)
        prios = rng.random(cfg.seqs_per_block).astype(np.float32) + 0.5
        replay.add_block(block, prios, float(i) if i % 3 == 0 else None)


def _fingerprint(replay):
    """Layout-independent content fingerprint: global counters, total tree
    mass, and the multiset of per-occupied-block obs sums."""
    if isinstance(replay, ShardedDeviceReplay):
        obs = np.asarray(replay.stores["obs"])
        bps = replay.blocks_per_shard
        sums, mass = [], 0.0
        for i, p in enumerate(replay.shards):
            mass += float(p.tree.leaves().sum())
            sums += [
                int(obs[i * bps + s].astype(np.int64).sum())
                for s in range(bps)
                if p.occupied[s]
            ]
        return (
            sum(p.env_steps for p in replay.shards),
            sum(p.size for p in replay.shards),
            sum(p.num_episodes for p in replay.shards),
            round(sum(float(p.episode_reward_sum) for p in replay.shards), 4),
            round(mass, 4),
            sorted(sums),
        )
    if isinstance(replay, DeviceReplayBuffer):
        obs = np.asarray(replay.stores["obs"])
    else:
        obs = np.asarray(replay.obs_store)
    sums = [
        int(obs[s].astype(np.int64).sum()) for s in range(NB) if replay.occupied[s]
    ]
    return (
        replay.env_steps,
        replay.size,
        replay.num_episodes,
        round(float(replay.episode_reward_sum), 4),
        round(float(replay.tree.leaves().sum()), 4),
        sorted(sums),
    )


@pytest.fixture(scope="module")
def saved_sharded(tmp_path_factory):
    """A filled sharded dp=4 replay snapshotted to disk, plus its
    fingerprint — the source for every cross-topology move below."""
    cfg = tiny_test()
    mesh = make_mesh(dp=4, tp=1, devices=jax.devices()[:4])
    replay = ShardedDeviceReplay(cfg, mesh)
    _fill(cfg, replay)
    d = tmp_path_factory.mktemp("sharded4")
    save_replay(
        replay,
        str(d / "replay_snapshot.npz"),
        extra={"carry_step": np.int64(7), "pend_idxes": np.arange(3)},
    )
    return cfg, str(d), _fingerprint(replay)


def test_manifest_contents(saved_sharded):
    cfg, d, _ = saved_sharded
    m = read_manifest(os.path.join(d, "replay_snapshot.npz"))
    assert m["plane"] == "sharded"
    assert m["dp"] == 4 and m["tp"] == 1 and m["process_count"] == 1
    assert m["num_blocks"] == NB and m["blocks_per_shard"] == NB // 4
    assert m["seqs_per_block"] == cfg.seqs_per_block
    assert m["local_ids"] == [0, 1, 2, 3]
    assert m["slab_ranges"] == [[g * 10, (g + 1) * 10] for g in range(4)]
    assert m["rng_streams"] == [0, 1, 2, 3]
    # the partition map helper agrees with what the manifest recorded
    mesh = make_mesh(dp=4, tp=1, devices=jax.devices()[:4])
    pmap = slab_partition_map(mesh, NB)
    assert m["slab_ranges"] == [list(pmap[g]) for g in range(4)]


def test_topology_mismatch_is_structured(saved_sharded):
    cfg, d, _ = saved_sharded
    dev = DeviceReplayBuffer(cfg)
    with pytest.raises(TopologyMismatch) as ei:
        restore_replay(dev, os.path.join(d, "replay_snapshot.npz"))
    e = ei.value
    assert isinstance(e, ValueError)  # callers catching ValueError still work
    assert e.saved["plane"] == "sharded" and e.saved["dp"] == 4
    assert e.current["plane"] == "device" and e.current["dp"] == 1
    assert "--reshard" in str(e)
    for frag in ("dp=4", "dp=1", "process_count=1"):
        assert frag in str(e)


def test_sharded_dp_mismatch_is_structured(saved_sharded):
    cfg, d, _ = saved_sharded
    mesh2 = make_mesh(dp=2, tp=1, devices=jax.devices()[:2])
    sh2 = ShardedDeviceReplay(cfg, mesh2)
    with pytest.raises(TopologyMismatch) as ei:
        restore_replay(sh2, os.path.join(d, "replay_snapshot.npz"))
    assert ei.value.saved["dp"] == 4 and ei.value.current["dp"] == 2


def test_reshard_sharded_to_device(saved_sharded):
    cfg, d, fp = saved_sharded
    dev = DeviceReplayBuffer(cfg)
    extras = reshard_replay(dev, snapshot_paths(d))
    assert _fingerprint(dev) == fp
    # layout-free carry survives, layout-bound (pend_*) is dropped
    assert int(extras["carry_step"]) == 7
    assert not any(k.startswith("pend_") for k in extras)
    # the re-dealt buffer samples
    dev.sample_indices(np.random.default_rng(0))


def test_reshard_device_to_sharded_dp2(saved_sharded, tmp_path):
    cfg, d, fp = saved_sharded
    dev = DeviceReplayBuffer(cfg)
    reshard_replay(dev, snapshot_paths(d))
    save_replay(dev, str(tmp_path / "replay_snapshot.npz"))
    mesh2 = make_mesh(dp=2, tp=1, devices=jax.devices()[:2])
    sh2 = ShardedDeviceReplay(cfg, mesh2)
    reshard_replay(sh2, snapshot_paths(str(tmp_path)))
    assert _fingerprint(sh2) == fp
    sh2.sample_indices(np.random.default_rng(0))


def test_reshard_device_to_host_casts_actions(saved_sharded, tmp_path):
    cfg, d, fp = saved_sharded
    dev = DeviceReplayBuffer(cfg)
    reshard_replay(dev, snapshot_paths(d))
    save_replay(dev, str(tmp_path / "replay_snapshot.npz"))
    host = ReplayBuffer(cfg)
    reshard_replay(host, snapshot_paths(str(tmp_path)))
    assert _fingerprint(host) == fp
    # device stores actions as int32; the host plane keeps uint8
    assert host.action_store.dtype == np.uint8
    assert host.last_action_store.dtype == np.uint8
    np.testing.assert_array_equal(
        np.asarray(host.action_store), np.asarray(dev.stores["action"])
    )


def test_exact_path_matches_plain_restore(saved_sharded):
    """Same logical shard set => reshard is bit-identical to restore: the
    sampling stream (and hence the learner loss) cannot tell them apart."""
    cfg, d, _ = saved_sharded
    path = os.path.join(d, "replay_snapshot.npz")
    mesh = make_mesh(dp=4, tp=1, devices=jax.devices()[:4])
    a = ShardedDeviceReplay(cfg, mesh)
    reshard_replay(a, [path])
    b = ShardedDeviceReplay(cfg, mesh)
    restore_replay(b, path)
    for k in a.stores:
        np.testing.assert_array_equal(np.asarray(a.stores[k]), np.asarray(b.stores[k]))
    for pa, pb in zip(a.shards, b.shards):
        np.testing.assert_array_equal(pa.tree.leaves(), pb.tree.leaves())
        assert pa.block_ptr == pb.block_ptr and pa.ptr_advances == pb.ptr_advances
    ra = a.sample_indices(np.random.default_rng(5))
    rb = b.sample_indices(np.random.default_rng(5))
    np.testing.assert_array_equal(np.asarray(ra.idxes), np.asarray(rb.idxes))
    np.testing.assert_allclose(np.asarray(ra.is_weights), np.asarray(rb.is_weights))


def test_gather_is_retry_safe(saved_sharded):
    """A crash mid-gather leaves the files untouched; the retry gathers the
    same logical state."""
    cfg, d, fp = saved_sharded
    plane = install(FaultPlane(schedule={"reshard.gather": {1: "error"}}))
    try:
        dev = DeviceReplayBuffer(cfg)
        with pytest.raises(InjectedFault):
            reshard_replay(dev, snapshot_paths(d))
        # nothing was mutated before the gather fault
        assert dev.size == 0 and not dev.occupied.any()
        reshard_replay(dev, snapshot_paths(d))  # call 2: passes through
        assert _fingerprint(dev) == fp
    finally:
        uninstall()
    assert ("reshard.gather", 1, "error") in plane.fired


def test_manifest_cli(saved_sharded, tmp_path, capsys):
    cfg, d, _ = saved_sharded
    assert reshard_main([d]) == 0
    out = json.loads(capsys.readouterr().out)
    (m,) = out["manifests"].values()
    assert m["plane"] == "sharded" and m["dp"] == 4
    assert reshard_main([d, "--expect-dp", "4", "--expect-process-count", "1"]) == 0
    capsys.readouterr()
    assert reshard_main([d, "--expect-dp", "2"]) == 2
    err = capsys.readouterr().err
    assert "dp=4" in err and "expected 2" in err
    # empty dir: nothing to assert, resume refills from scratch
    assert reshard_main([str(tmp_path), "--expect-dp", "8"]) == 0


def test_gather_rejects_duplicate_shards(saved_sharded):
    cfg, d, _ = saved_sharded
    path = os.path.join(d, "replay_snapshot.npz")
    with pytest.raises(ValueError, match="more than one"):
        gather_logical([path, path])


def test_capacity_shrink_drops_oldest(saved_sharded, tmp_path):
    """Re-deal into a smaller buffer keeps the newest blocks — the same
    eviction order a live run would have applied."""
    cfg, d, fp = saved_sharded
    import dataclasses

    small = dataclasses.replace(cfg, buffer_capacity=cfg.block_length * 8)
    dev = DeviceReplayBuffer(small)
    reshard_replay(dev, snapshot_paths(d))
    assert int(dev.occupied.sum()) == 8  # 10 saved, capacity 8
    # global totals still preserved exactly
    assert dev.env_steps == fp[0]
    assert dev.num_episodes == fp[2]
