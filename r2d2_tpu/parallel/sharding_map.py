"""Data-driven per-param sharding: wildcard name patterns -> mesh axes.

Replaces the hardcoded Megatron name-sets that used to live inside
`parallel/mesh.train_state_shardings` with an explicit, inspectable map
from wildcarded dotted param names to partition-axis tuples — the idiom
large-model serving stacks use for per-weight sharding tables, adapted to
this repo's tp×fsdp mesh.

Pattern grammar
---------------
A leaf's *name* is its pytree path joined with dots, with every integer
path component collapsed to ``*``:

    TrainState.params['params']['core']['wi']        -> params.params.core.wi
    opt_state[1][0].mu['params']['core']['wi']       -> opt_state.*.*.mu.params.core.wi

Rules are an ordered sequence of ``(pattern, axes)`` pairs matched with
fnmatch semantics (``*`` crosses dots); the FIRST match wins and anything
unmatched is replicated. ``axes`` is a PartitionSpec-style tuple over the
leaf's dims using mesh axis names ("tp", "fsdp") or None.

Axis semantics
--------------
tp    Megatron tensor parallelism, exactly the rules the old name-sets
      encoded: column-parallel (None, "tp") for the LSTM gate kernels /
      encoder Dense_0 / dueling hiddens (+ their biases on the sharded
      output axis), row-parallel ("tp", None) for the head outs, convs
      replicated (see DEFAULT_RULES below for the per-layer rationale,
      inherited from the old docstring).
fsdp  optimizer-state sharding (ZeRO-1 style): when the mesh carries an
      fsdp axis of size > 1, the Adam mu/nu moment leaves — the
      next-largest HBM residents after backward residuals — additionally
      shard their first still-unsharded, divisible dim over "fsdp".
      Params and target_params stay REPLICATED over fsdp: gradients are
      computed from whole params (no gather in the backward); only the
      moment update runs sharded. The rule is positional (``.mu.`` /
      ``.nu.`` in the name), so it composes with any param-level rules
      without per-layer duplication.

int8 serve weights flow through the same table: `quantize_tree` replaces a
kernel leaf with a ``{"q8", "scale"}`` dict, so the q8 leaf's name is the
kernel's name plus a suffix — the ``kernel*`` wildcards below cover both,
and the per-output-channel scale of the ROW-parallel heads gets an
explicit replicated entry (its (1, out) shape has no input dim to shard).

Topology note: the fsdp axis shards *state*, never the replay layout —
snapshot topology manifests record (plane, dp, tp, process_count) only
(replay/snapshot.py), so changing --fsdp across --resume/--reshard never
trips TopologyMismatch (pinned by tests/test_sharding_map.py).
"""

from __future__ import annotations

import fnmatch
from typing import Optional, Sequence, Tuple

import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (pattern, axes) in priority order — first match wins. Rationale for the
# tp choices (inherited from the old hardcoded sets): the LSTM gate
# kernels and encoder Dense_0 are the wide matmuls worth splitting;
# hidden/out head pairs form column/row Megatron pairs costing one
# all-reduce each; conv kernels stay replicated because 16-64 output
# channels shard into slivers whose collective cost exceeds the saved
# FLOPs (dp already covers the conv's batch-dominated FLOPs).
DEFAULT_RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    # ROW-parallel head-out scales first: (1, out) has no input dim, the
    # generic kernel* row rule below must not claim it
    ("*.adv_out.kernel.scale", ()),
    ("*.val_out.kernel.scale", ()),
    # LSTM core: column-parallel gates + bias on the sharded 4H axis
    ("*.core.wi", (None, "tp")),
    ("*.core.wh", (None, "tp")),
    ("*.core.b", ("tp",)),
    # encoder Dense_0 (the largest single matmul): column-parallel
    ("*.Dense_0.kernel*", (None, "tp")),
    ("*.Dense_0.bias", ("tp",)),
    # dueling hiddens: column-parallel, paired with row-parallel outs
    ("*.adv_hidden.kernel*", (None, "tp")),
    ("*.adv_hidden.bias", ("tp",)),
    ("*.val_hidden.kernel*", (None, "tp")),
    ("*.val_hidden.bias", ("tp",)),
    ("*.adv_out.kernel*", ("tp", None)),
    ("*.val_out.kernel*", ("tp", None)),
)

# name markers of the Adam moment subtrees the fsdp axis shards
_MOMENT_MARKERS = (".mu.", ".nu.")


def process_name(path) -> str:
    """Pytree path -> dotted name with integer components collapsed to *.

    Accepts the key objects jax.tree_util emits (GetAttrKey / DictKey /
    SequenceKey / FlattenedIndexKey); integer keys — tuple positions in
    the optax chain, list indices — become ``*`` so one pattern covers
    every stacked/replicated instance (SNIPPETS idiom)."""
    parts = []
    for k in path:
        v = getattr(k, "name", None)
        if v is None:
            v = getattr(k, "key", None)
        if v is None:
            v = getattr(k, "idx", None)
        if isinstance(v, int) or (isinstance(v, str) and v.isdigit()):
            parts.append("*")
        else:
            parts.append(str(v))
    return ".".join(parts)


def match_axes(
    name: str, rules: Sequence[Tuple[str, Tuple[Optional[str], ...]]]
) -> Tuple[Optional[str], ...]:
    """First-match lookup of a processed name against the rule table."""
    for pattern, axes in rules:
        if fnmatch.fnmatchcase(name, pattern):
            return tuple(axes)
    return ()


def spec_for(name: str, leaf, mesh: Mesh, rules=None) -> P:
    """PartitionSpec for one leaf: tp rules from the table, then the
    positional fsdp rule for optimizer-moment leaves."""
    rules = DEFAULT_RULES if rules is None else rules
    axes = list(match_axes(name, rules))
    ndim = getattr(leaf, "ndim", 0)
    # drop axes the mesh does not carry (a tp-only mesh ignores fsdp
    # entries and vice versa) and anything past the leaf's rank
    axes = [
        a if (a is None or a in mesh.axis_names) else None for a in axes
    ][:ndim]
    if (
        "fsdp" in mesh.axis_names
        and mesh.shape["fsdp"] > 1
        and any(m in name for m in _MOMENT_MARKERS)
    ):
        fsdp = mesh.shape["fsdp"]
        axes = axes + [None] * (ndim - len(axes))
        for d in range(ndim):
            if axes[d] is None and leaf.shape[d] % fsdp == 0 and leaf.shape[d] > 0:
                axes[d] = "fsdp"
                break
    # emit the rule's axes verbatim (trailing Nones included) so the
    # table reads back exactly as the old hardcoded layout spelled it
    return P(*axes)


def tree_pspecs(tree, mesh: Mesh, rules=None):
    """Per-leaf bare PartitionSpecs (no device placement) for ANY pytree
    via the same wildcard table — the in/out specs of the manual-
    partition train step's shard_map (learner.make_manual_train_step).
    One table drives BOTH the GSPMD placement (tree_shardings below) and
    the manual partitioning, so the two paths cannot disagree about
    where a leaf lives."""
    return jtu.tree_map_with_path(
        lambda p, l: spec_for(process_name(p), l, mesh, rules), tree
    )


def moment_spec_for(param_name: str, leaf, mesh: Mesh, rules=None) -> P:
    """The spec a param's Adam mu/nu mirror gets: its table axes plus the
    positional fsdp dim. `param_name` is the processed name within the
    variables tree (e.g. "params.core.wh"). The manual step's ZeRO-2
    reduce-scatter reads each gradient leaf's scatter dimension from
    here, so gradient shards land exactly on the moment shards."""
    return spec_for(f"opt_state.*.*.mu.{param_name}", leaf, mesh, rules)


def tree_shardings(tree, mesh: Mesh, rules=None):
    """Per-leaf NamedShardings for ANY pytree (params, a full TrainState,
    a quantized serve tree) via the wildcard table."""
    return jtu.tree_map_with_path(
        lambda p, l: NamedSharding(mesh, spec_for(process_name(p), l, mesh, rules)),
        tree,
    )


def train_state_shardings(state, mesh: Mesh, rules=None):
    """Per-leaf NamedShardings for a TrainState over the wildcard table.

    Drop-in successor of the old hardcoded implementation: on a (dp, tp)
    mesh the DEFAULT_RULES reproduce its Megatron column/row layout
    exactly (pinned by tests/test_sharding_map.py), and with tp=1 it
    degenerates to fully-replicated, so it is safe on any mesh. On a mesh
    carrying an fsdp axis, the Adam mu/nu trees additionally shard over
    it (see module docstring)."""
    return tree_shardings(state, mesh, rules)


def serve_param_shardings(params, mesh: Mesh, rules=None):
    """Shardings for a serve-plane param tree — possibly int8-quantized
    (ops/quantize.py): q8/scale leaves inherit the kernel's rules through
    the ``kernel*`` wildcards, so one table drives train AND serve
    placement."""
    return tree_shardings(params, mesh, rules)
