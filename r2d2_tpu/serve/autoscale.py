"""Elastic autoscaler: the control loop that grows and drains the fleet
from its own SLO signals (ROADMAP item 1).

Every elasticity VERB already exists as a manual call — `add_replica`
spawns/warms/publishes/activates a replica, `kill_replica` migrates its
sessions through the spill tier with `sessions_lost == 0`, and the
degradation ladder (serve/degrade.py) absorbs millisecond-scale overload.
This module is the NOUN that drives them: a supervised control loop
watching the fleet's sliding-window signals — queue fraction, windowed
p99, SLO attainment over the shared `SignalWindow` — with the same
dwell-count hysteresis + dead band the ladder uses, so an oscillating
signal parks the fleet size instead of flapping it.

Decision rules, per evaluation tick:

- PRESSURED (queue_frac >= queue_high, or windowed p99 past
  `pressure_margin * slo_ms` — capacity is bought while the SLO budget
  still has headroom, NOT after misses start, because a scale-up takes
  seconds to land — or attainment < attain_low) for `dwell_up`
  consecutive ticks, below `max_replicas`, outside the post-event
  cooldown -> SCALE UP: one
  `server.add_replica()` — constructed, warmed, and published under the
  fleet's shared params version before its router slot activates.
- HEALTHY (queue_frac <= queue_low and latency signals clean) for
  `dwell_down` consecutive ticks, above `min_replicas`, outside the
  cooldown -> SCALE DOWN: drain the best victim through the existing
  `kill_replica` migration path — its sessions spill-migrate to the
  survivors, zero loss. By default (`drain_requires_idle`) the drain
  additionally HOLDS until some replica is truly idle (no in-flight
  work, no request for `idle_age_s`): the fleet's health signals
  describe the fleet at its CURRENT size and are blind to what the
  smaller fleet would feel, so "2 replicas are comfortable" at a
  traffic crest must not drain one into that crest and pay the
  migration wave at peak — a replica nobody has talked to is the only
  signal-level proof the fleet is oversized. With the flag off, the
  dwell alone decides and the least-loaded replica by session affinity
  count drains.
- After any event: the latency window resets (pre-event samples must not
  judge the new fleet size) and a `cooldown_s` quiet period holds both
  dwells' decisions, bounding the event rate.

Timescale split (the scale-vs-degrade interlock): scaling takes SECONDS
(a replica warmup compiles every bucket), the ladder takes MILLISECONDS.
The autoscaler therefore installs `degrade.rung_up_gate`: quality-
degrading rung steps fire only while a scale-up is IN FLIGHT, or when
the fleet is pinned at `max_replicas` and capacity cannot answer. In
steady state, sustained pressure buys a replica, not a quality dip;
inside the warmup window the ladder is the shock absorber it was built
to be; the moment the replica lands the gate closes again, so the
ladder never ratchets into the quality arms against a backlog the new
capacity is already draining. Recovery steps are never gated.

Threading: `_iteration()` runs under the autoscaler's OWN supervised
root ("autoscaler") — scale events block on warmup/migration for whole
seconds and must not share a worker with the sub-second watch/degrade
ticks. All controller state lives under one lock; scale ACTIONS run
strictly outside it (blocking-under-lock rule). Lock order is
degrade._lock -> autoscale._lock (the gate probe) and
autoscale._lock -> router._lock (replica counts); neither reverses
anywhere, so no cycle.

Fault sites: `autoscale.evaluate` (top of every tick — supervised
restart drill), `autoscale.scale_up` / `autoscale.scale_down` (the
scheduled-chaos hooks: fail a scale event at its exact decision).

Default-off: with `config.serve_autoscale` False no Autoscaler object or
thread exists, no gate is installed, and the fleet is byte-for-byte the
static-size behavior.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from r2d2_tpu.serve.degrade import SignalWindow
from r2d2_tpu.utils.faults import fault_point
from r2d2_tpu.utils.supervision import Supervisor


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Scale thresholds. Same hysteresis vocabulary as DegradeConfig:
    enter/exit bands deliberately apart, dwell counts in consecutive
    evaluation ticks, plus the event-rate bounds (cooldown) scaling needs
    and the ladder doesn't."""

    min_replicas: int = 1
    max_replicas: int = 2
    eval_interval_s: float = 0.25
    window: int = 512           # latency samples (own window only; with a
    min_samples: int = 8        # degrade ladder the ladder's window is shared)
    slo_ms: float = 50.0
    pressure_margin: float = 0.8  # scale-up pressure judges p99 against
                                # margin * slo_ms: buy the replica while
                                # the budget still has headroom (warmup
                                # takes seconds). Healthy/recovery still
                                # judge the FULL SLO.
    queue_high: float = 0.25    # pressured when depth >= high * queue bound
    queue_low: float = 0.05     # healthy requires depth <= low * queue bound
    attain_low: float = 0.95    # pressured when SLO attainment < low
    attain_high: float = 0.98   # healthy requires attainment >= high
    dwell_up: int = 2
    dwell_down: int = 12
    cooldown_s: float = 2.0     # quiet period after any scale event
    idle_age_s: float = 1.0     # drain candidate's request-free threshold
    drain_requires_idle: bool = True  # a drain HOLDS until some replica
                                # is truly idle: fleet health signals
                                # describe the CURRENT size, not the
                                # smaller one, so a comfortable fleet
                                # mid-crest must not drain into the
                                # crest. Off: the dwell alone decides.
    stale_after_s: float = 5.0  # latency signals abstain past this sample
                                # age (an idle fleet's last crest must not
                                # hold a verdict forever)

    @classmethod
    def from_system(cls, cfg) -> "AutoscaleConfig":
        """Derive from the R2D2Config knob block (config.serve_autoscale
        and friends); the SLO target is shared with the degrade ladder."""
        return cls(
            min_replicas=cfg.autoscale_min_replicas,
            max_replicas=cfg.autoscale_max_replicas,
            eval_interval_s=cfg.autoscale_interval_s,
            slo_ms=cfg.serve_degrade_slo_ms,
            pressure_margin=cfg.autoscale_pressure_margin,
            dwell_up=cfg.autoscale_dwell_up,
            dwell_down=cfg.autoscale_dwell_down,
            cooldown_s=cfg.autoscale_cooldown_s,
            idle_age_s=cfg.autoscale_idle_age_s,
            drain_requires_idle=cfg.autoscale_drain_requires_idle,
        )


class Autoscaler:
    """Watches a fleet's overload signals and scales its replica set.

    `server` is a MultiDeviceServer (or a test double exposing the same
    surface): `queue_depth()` / `queue_bound`, `active_replicas()`,
    `add_replica()`, `kill_replica(idx)`, `stats()` with the per-replica
    idle triplet (`replica_active`, `replica_inflight`,
    `replica_last_request_age_s`) and `router_counts`, and optionally
    `.degrade` (whose SignalWindow is then shared and whose
    `rung_up_gate` gets the interlock)."""

    def __init__(self, server, cfg: Optional[AutoscaleConfig] = None):
        self.server = server
        self.cfg = cfg if cfg is not None else AutoscaleConfig.from_system(
            server.cfg
        )
        self._lock = threading.Lock()
        self._up_evals = 0
        self._down_evals = 0
        self._scaling = False          # an add_replica is in flight
        self._cooldown_until = 0.0     # monotonic deadline
        self._t0 = time.monotonic()
        # (monotonic t, active replica count) transition points; seeded at
        # start() so chip_seconds() integrates the whole served interval
        self._trace: List[Tuple[float, int]] = []
        self.evaluations = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_errors = 0
        self.drain_holds = 0
        # per-drain provenance: {replica, idle_age_s, inflight,
        # affinities} for every victim actually killed (guarded by _lock)
        self._drain_log: List[Dict[str, object]] = []
        self._victim_info: Optional[Dict[str, object]] = None
        self.supervisor: Optional[Supervisor] = None
        degrade = getattr(server, "degrade", None)
        if degrade is not None:
            # ONE window for both controllers: the ladder's observe path
            # already feeds it from every replica, and both judge the
            # same latencies — the shared-SignalWindow contract
            self.window = degrade.window
            self._own_window = False
            degrade.rung_up_gate = self._quality_gate
        else:
            self.window = SignalWindow(
                self.cfg.window, self.cfg.slo_ms, self.cfg.min_samples
            )
            self._own_window = True
            for r in getattr(server, "replicas", ()):
                self.attach(r)

    def attach(self, replica) -> None:
        """Wire a replica's completion latencies into the autoscaler's own
        window (no-op when the window is the degrade ladder's — the
        replica's shared `.degrade` already feeds it). add_replica calls
        this for replicas born after the autoscaler."""
        if self._own_window:
            replica._latency_sinks = tuple(replica._latency_sinks) + (
                self.window,
            )

    # ------------------------------------------------------------- interlock

    def _quality_gate(self) -> bool:
        """degrade.rung_up_gate: quality-degrading rung steps are allowed
        only while capacity is mid-answer (a scale-up in flight — the
        ladder is the shock absorber inside the warmup window) or cannot
        answer at all (fleet pinned at max). Deliberately NOT open during
        the post-event cooldown: once the replica lands, added capacity
        is draining the backlog, and an open gate there lets the ladder
        ratchet into the quality arms against a receding queue — a shed
        equilibrium the recovery dwell then has to climb out of."""
        with self._lock:
            scaling = self._scaling
        if scaling:
            return True
        return self.server.active_replicas() >= self.cfg.max_replicas

    # --------------------------------------------------------------- signals

    def signals(self) -> Dict[str, float]:
        out = {"queue_frac": self.server.queue_depth()
               / max(self.server.queue_bound, 1)}
        out.update(self.window.signals())
        return out

    # -------------------------------------------------------------- decision

    def evaluate_once(self) -> Optional[str]:
        """One bounded evaluation tick: read the signals, advance the
        hysteresis dwells, fire at most one scale event. Returns "up" /
        "down" on an event, else None."""
        fault_point("autoscale.evaluate")
        sig = self.signals()
        cfg = self.cfg
        have_lat = (
            sig["samples"] >= cfg.min_samples
            and sig.get("age_s", 0.0) <= cfg.stale_after_s
        )
        pressured = sig["queue_frac"] >= cfg.queue_high or (
            have_lat
            and (sig["p99_ms"] > cfg.slo_ms * cfg.pressure_margin
                 or sig["attainment"] < cfg.attain_low)
        )
        healthy = sig["queue_frac"] <= cfg.queue_low and (
            not have_lat or (sig["p99_ms"] <= cfg.slo_ms
                             and sig["attainment"] >= cfg.attain_high)
        )
        now = time.monotonic()
        decision = None
        with self._lock:
            self.evaluations += 1
            if pressured:
                self._up_evals += 1
                self._down_evals = 0
            elif healthy:
                self._down_evals += 1
                self._up_evals = 0
            # between the bands: hold both dwells (the dead band — an
            # oscillating signal parks the fleet size, never flaps it)
            if now >= self._cooldown_until and not self._scaling:
                n = self.server.active_replicas()
                if self._up_evals >= cfg.dwell_up and n < cfg.max_replicas:
                    self._up_evals = 0
                    self._scaling = True  # opens the quality gate NOW —
                    decision = "up"       # the ladder absorbs the warmup
                elif (
                    self._down_evals >= cfg.dwell_down
                    and n > cfg.min_replicas
                ):
                    # the dwell is NOT reset here: _scale_down may hold
                    # (drain_requires_idle and nobody idle) and must stay
                    # armed for the next tick; a drain that fires resets
                    # it there
                    decision = "down"
        if decision == "up":
            return self._scale_up()
        if decision == "down":
            return self._scale_down()
        return None

    def _scale_up(self) -> str:
        fault_point("autoscale.scale_up")
        try:
            self.server.add_replica()
        except BaseException:
            with self._lock:
                self.scale_errors += 1
                self._scaling = False
            raise  # supervised restart; the dwell re-accumulates
        self._settle("up")
        return "up"

    def _scale_down(self) -> Optional[str]:
        fault_point("autoscale.scale_down")
        victim = self._pick_drain_victim()
        if victim is not None and self._victim_info is not None:
            # drain provenance: WHICH replica went and how quiet it
            # actually was (idle-age straight from the fleet's stats
            # triplet) — the audit trail for "we never drained a replica
            # that was mid-conversation"
            with self._lock:
                self._drain_log.append(dict(self._victim_info))
        if victim is None:
            # drain_requires_idle and every replica is still talking:
            # hold — the armed dwell retries next tick (drain_holds
            # counts the waits)
            with self._lock:
                self.drain_holds += 1
            return None
        with self._lock:
            self._down_evals = 0
        try:
            self.server.kill_replica(victim)
        except BaseException:
            with self._lock:
                self.scale_errors += 1
            raise
        self._settle("down")
        return "down"

    def _settle(self, event: str) -> None:
        now = time.monotonic()
        n = self.server.active_replicas()
        with self._lock:
            self._scaling = False
            self._cooldown_until = now + self.cfg.cooldown_s
            if event == "up":
                self.scale_ups += 1
            else:
                self.scale_downs += 1
            self._trace.append((now, n))
        # pre-event latencies must not judge the new fleet size (and a
        # stale pressured window must not fire a second event the instant
        # the cooldown expires)
        self.window.reset()

    def _pick_drain_victim(self) -> Optional[int]:
        """The drain choice, from the fleet's per-replica idle triplet: a
        truly idle replica (nothing in flight, no request for idle_age_s)
        beats everything; ties and non-idle fleets drain the least-loaded
        by affinity count. Under `drain_requires_idle` (default) a
        non-idle fleet returns None instead — the drain holds until some
        replica has demonstrably nothing to say. Returns a replica index
        or None."""
        st = self.server.stats()
        active = st["replica_active"]
        inflight = st["replica_inflight"]
        ages = st["replica_last_request_age_s"]
        counts = st["router_counts"]
        best = None
        for i, a in enumerate(active):
            if not a:
                continue
            idle = 0 if (inflight[i] == 0 and ages[i] >= self.cfg.idle_age_s) \
                else 1
            key = (idle, counts[i], inflight[i], -ages[i], i)
            if best is None or key < best[0]:
                best = (key, i)
        if best is None:
            raise RuntimeError("no active replica to drain")
        if self.cfg.drain_requires_idle and best[0][0] != 0:
            # single-writer (the autoscaler's own worker) — see below
            # r2d2: disable=cross-thread-unguarded-write
            self._victim_info = None
            return None
        i = best[1]
        # single-writer (the autoscaler's own worker); _scale_down copies
        # it into the locked drain log
        self._victim_info = {  # r2d2: disable=cross-thread-unguarded-write
            "replica": i,
            "idle_age_s": round(float(ages[i]), 3),
            "inflight": int(inflight[i]),
            "affinities": int(counts[i]),
        }
        return i

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self.supervisor is not None:
            raise RuntimeError("autoscaler already started")
        now = time.monotonic()
        n = self.server.active_replicas()
        with self._lock:
            self._t0 = now
            self._trace = [(now, n)]
        self.supervisor = Supervisor()
        self.supervisor.spawn(
            "autoscaler",
            lambda: self._iteration(),
            max_restarts=self.server.serve_cfg.max_restarts,
        )

    def _iteration(self) -> None:
        # supervised worker body: one bounded tick, then a stoppable wait
        self.evaluate_once()
        if self.supervisor is not None:
            self.supervisor.stop.wait(self.cfg.eval_interval_s)
        else:
            time.sleep(self.cfg.eval_interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        if self.supervisor is not None:
            self.supervisor.shutdown(timeout)
            self.supervisor = None

    # --------------------------------------------------------------- metrics

    def chip_seconds(self, until: Optional[float] = None) -> float:
        """Integral of the active replica count over time since start(),
        in replica-seconds — the cost-of-traffic number the bench compares
        against a peak-sized static fleet."""
        end = time.monotonic() if until is None else until
        with self._lock:
            pts = list(self._trace)
        total = 0.0
        for (t, n), (t_next, _) in zip(pts, pts[1:] + [(end, 0)]):
            total += n * max(t_next - t, 0.0)
        return total

    def replica_trace(self) -> List[Dict[str, float]]:
        with self._lock:
            t0 = self._t0
            return [
                {"t": round(t - t0, 3), "replicas": n}
                for t, n in self._trace
            ]

    def stats(self) -> Dict[str, object]:
        now = time.monotonic()
        with self._lock:
            return {
                "autoscale_evaluations": self.evaluations,
                "autoscale_scale_ups": self.scale_ups,
                "autoscale_scale_downs": self.scale_downs,
                "autoscale_scale_errors": self.scale_errors,
                "autoscale_drain_holds": self.drain_holds,
                "autoscale_drain_log": [dict(d) for d in self._drain_log],
                "autoscale_in_flight": self._scaling,
                "autoscale_cooldown_active": now < self._cooldown_until,
                "autoscale_trace": [
                    {"t": round(t - self._t0, 3), "replicas": n}
                    for t, n in self._trace[-64:]
                ],
            }
