"""Integration: the minimum end-to-end slice (SURVEY.md section 7.2) on the
pure-JAX Catch env — env -> block packing -> PER sample -> jitted double-Q
update -> checkpoint -> resume -> eval. Exercises the stale-priority path
implicitly via continuous collection during training."""

import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.envs.catch import CatchVecEnv
from r2d2_tpu.evaluate import evaluate_params, evaluate_series
from r2d2_tpu.train import Trainer
from r2d2_tpu.utils.checkpoint import list_checkpoint_steps


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("e2e")
    cfg = tiny_test().replace(
        env_name="catch",
        checkpoint_dir=str(tmp / "ckpt"),
        metrics_path=str(tmp / "metrics.jsonl"),
        training_steps=30,
        save_interval=15,
        learning_starts=48,
    )
    vec_env = CatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=0)
    trainer = Trainer(cfg, vec_env=vec_env)
    trainer.run_inline(env_steps_per_update=4)
    return trainer


def test_training_reaches_step_target(trained):
    assert int(trained.state.step) == 30
    assert trained.replay.env_steps > 48


def test_metrics_written(trained):
    lines = open(trained.cfg.metrics_path).read().strip().splitlines()
    assert len(lines) == 30
    import json

    rec = json.loads(lines[-1])
    assert np.isfinite(rec["loss"]) and rec["step"] == 30


def test_checkpoint_series_and_resume(trained):
    cfg = trained.cfg
    assert list_checkpoint_steps(cfg.checkpoint_dir) == [15, 30]
    resumed = Trainer(cfg, vec_env=trained.vec_env, resume=True)
    assert int(resumed.state.step) == 30
    # resumed state matches the live one exactly (full TrainState payload)
    import jax

    for a, b in zip(jax.tree.leaves(resumed.state.params), jax.tree.leaves(trained.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(resumed.state.opt_state), jax.tree.leaves(trained.state.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_evaluate_runs(trained):
    vec = CatchVecEnv(num_envs=4, height=12, width=12, seed=7)
    reward = evaluate_params(trained.cfg, trained.net, trained.state.params, vec, seed=1)
    assert -1.0 <= reward <= 1.0


def test_evaluate_series(trained):
    vec = CatchVecEnv(num_envs=2, height=12, width=12, seed=9)
    rows = evaluate_series(trained.cfg, vec)
    assert [r["step"] for r in rows] == [15, 30]
    assert all(np.isfinite(r["mean_reward"]) for r in rows)
    assert all(r["env_frames"] == r["env_steps"] * 4 for r in rows)


def test_device_collector_training(tmp_path):
    """The all-device pipeline: jitted chunk collection -> HBM store ->
    fused update, driven inline and threaded through the Trainer."""
    cfg = tiny_test().replace(
        env_name="catch",
        collector="device",
        replay_plane="device",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=10,
        save_interval=5,
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    trainer.run_inline()
    assert int(trainer.state.step) == 10
    assert trainer.replay.env_steps >= 48
    assert trainer.actor.total_steps == trainer.replay.env_steps
    n_ep, _ = trainer.replay.pop_episode_stats()  # drained by _log already
    totals = trainer.replay.episode_totals()
    assert totals[0] > 0


def test_device_collector_threaded(tmp_path):
    cfg = tiny_test().replace(
        env_name="catch",
        collector="device",
        replay_plane="device",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=6,
        save_interval=100,
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    trainer.run_threaded()
    assert int(trainer.state.step) == 6


@pytest.mark.parametrize("mode", ["inline", "threaded"])
def test_multi_update_dispatch_training(tmp_path, mode):
    """updates_per_dispatch > 1: K updates per dispatch through the real
    Trainer — cadence crossings (publish/save) still fire and training
    reaches the step target."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="device",
        updates_per_dispatch=4,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=12,
        save_interval=5,  # crossings at 5 and 10 land mid-chunk
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    if mode == "inline":
        trainer.run_inline(env_steps_per_update=4)
    else:
        trainer.run_threaded()
    assert trainer._step == 12
    assert int(trainer.state.step) == 12
    # save_interval crossings 5 and 10 both produced checkpoints
    assert len(list_checkpoint_steps(cfg.checkpoint_dir)) == 2


def test_evaluate_plot(trained, tmp_path):
    from r2d2_tpu.evaluate import plot_series

    vec = CatchVecEnv(num_envs=2, height=12, width=12, seed=9)
    rows = evaluate_series(trained.cfg, vec)
    out = plot_series(rows, str(tmp_path / "curve.jpg"))
    import os

    assert os.path.getsize(out) > 1000


def test_long_context_training(tmp_path):
    """Scaled-down long_context preset shape (SURVEY.md section 5.7): long
    learning span, remat-chunked LSTM scan (seq 74 = 2 chunks of 37),
    trained end to end through the device plane."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="device",
        burn_in_steps=8,
        learning_steps=64,
        forward_steps=2,
        block_length=64,
        buffer_capacity=640,
        scan_chunk=37,  # 8+64+2 = 74 -> two remat chunks
        lstm_backend="scan",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=4,
        save_interval=100,
        learning_starts=96,
        max_episode_steps=64,
    )
    assert cfg.seq_len == 74
    trainer = Trainer(cfg)
    trainer.run_inline(env_steps_per_update=8)
    assert trainer._step == 4


def test_device_collector_with_sharded_plane(tmp_path):
    """On-device collection feeding the dp-sharded HBM replay: blocks
    round-robin across shards in one scatter, shard_map learner trains."""
    cfg = tiny_test().replace(
        env_name="catch",
        collector="device",
        replay_plane="sharded",
        dp_size=4,
        batch_size=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=6,
        save_interval=100,
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    trainer.run_inline()
    assert trainer._step == 6
    assert all(len(s) > 0 for s in trainer.replay.shards)


def test_impala_encoder_training(tmp_path):
    """IMPALA-ResNet encoder variant (BASELINE.json config 4 shape, scaled
    down) trained end to end on the device plane."""
    cfg = tiny_test().replace(
        env_name="catch",
        encoder="impala",
        impala_channels=(4, 8),
        replay_plane="device",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=3,
        save_interval=100,
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    trainer.run_inline(env_steps_per_update=4)
    assert trainer._step == 3


def test_device_side_evaluation(trained):
    """Device eval (one jitted chunk) returns a sane, deterministic score
    and plugs into the series evaluator."""
    from r2d2_tpu.envs.catch import CatchEnv
    from r2d2_tpu.evaluate import evaluate_params_device, make_eval_collect_fn

    cfg = trained.cfg
    env = CatchEnv(height=cfg.obs_shape[0], width=cfg.obs_shape[1])
    fn = make_eval_collect_fn(cfg, trained.net, env, num_envs=8)
    r1 = evaluate_params_device(cfg, trained.net, trained.state.params, env,
                                num_envs=8, seed=5, collect_fn=fn)
    r2 = evaluate_params_device(cfg, trained.net, trained.state.params, env,
                                num_envs=8, seed=5, collect_fn=fn)
    assert -1.0 <= r1 <= 1.0 and r1 == r2

    rows = evaluate_series(
        cfg, None, reward_fn=lambda net, p: evaluate_params_device(
            cfg, net, p, env, num_envs=8, seed=5, collect_fn=fn)
    )
    assert len(rows) == 2 and all(np.isfinite(r["mean_reward"]) for r in rows)

    # device rows must be distinguishable from host ones in the JSONL:
    # evaluator label + truncated-partial count (ADVICE r4)
    def reward_fn(net, p):
        mean, truncated = evaluate_params_device(
            cfg, net, p, env, num_envs=8, seed=5, collect_fn=fn,
            return_stats=True)
        return {"mean_reward": mean, "truncated_episodes": truncated}

    rows = evaluate_series(cfg, None, reward_fn=reward_fn,
                           evaluator_label="device")
    assert all(r["evaluator"] == "device" for r in rows)
    assert all(r["truncated_episodes"] == 0 for r in rows)  # episodes fit
    assert all(np.isfinite(r["mean_reward"]) for r in rows)


def test_samples_per_insert_throttles_collection(tmp_path):
    """With a samples-per-insert target, free-running actors yield once
    data outpaces optimization: the final consumed/inserted ratio stays
    near the target instead of collapsing toward zero."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="device",
        collector="device",
        samples_per_insert=2.0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=30,
        save_interval=1000,
        learning_starts=48,
        max_episode_steps=16,
    )
    trainer = Trainer(cfg)
    trainer.run_threaded()
    consumed = trainer._step * cfg.batch_size * cfg.learning_steps
    ratio = consumed / trainer.replay.env_steps
    # throttling keeps collection within ~2 chunks of the target band
    assert ratio > 0.5, f"actors free-ran: ratio {ratio:.2f}"


def test_evaluate_cli_walks_series(trained, tmp_path):
    """python -m r2d2_tpu.evaluate end to end: preset + --set overrides
    reach the checkpoint series and emit rows + plot."""
    from r2d2_tpu.evaluate import main as eval_main

    out = tmp_path / "rows.jsonl"
    plot = tmp_path / "curve.jpg"
    eval_main([
        "--preset", "tiny_test", "--env", "catch",
        "--set", f"checkpoint_dir={trained.cfg.checkpoint_dir}",
        "--out", str(out), "--plot", str(plot),
    ])
    import json

    rows = [json.loads(l) for l in open(out)]
    assert [r["step"] for r in rows] == [15, 30]
    assert all(np.isfinite(r["mean_reward"]) for r in rows)
    assert plot.exists() and plot.stat().st_size > 0


def test_train_cli_fused_mode(tmp_path):
    """python -m r2d2_tpu.train --mode fused end to end (CLI dispatch,
    collector defaulting, metrics)."""
    from r2d2_tpu.train import main as train_main

    train_main([
        "--preset", "tiny_test", "--env", "catch", "--mode", "fused",
        "--steps", "6", "--updates-per-dispatch", "3",
        # fused mode requires an ACCURATE episode bound <= the chunk
        # (megastep refuses loose caps that would truncate episode
        # tails); 12x12 catch episodes land in exactly 10 steps
        "--set", "max_episode_steps=10",
        "--set", f"checkpoint_dir={tmp_path}/ckpt",
        "--set", "save_interval=1000",
        "--metrics", f"{tmp_path}/m.jsonl",
    ])
    import json

    rows = [json.loads(l) for l in open(f"{tmp_path}/m.jsonl")]
    assert rows[-1]["step"] == 6


def test_evaluate_params_multi_episode_auto_reset():
    """episodes_per_slot > 1: slots roll into fresh episodes via the vec
    env's auto-reset, per-slot recurrent state re-zeroes at boundaries,
    and the mean covers exactly the completed episodes."""
    import jax

    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.envs.catch import CatchVecEnv
    from r2d2_tpu.evaluate import evaluate_params
    from r2d2_tpu.learner import init_train_state

    cfg = tiny_test().replace(env_name="catch", obs_shape=(12, 12, 1), action_dim=3)
    vec = CatchVecEnv(num_envs=4, height=12, width=12, seed=0)
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    r3 = evaluate_params(
        cfg, net, state.params, vec, seed=1, episodes_per_slot=3, max_steps=12
    )
    assert -1.0 <= r3 <= 1.0
    # catch episodes pay exactly +-1: a mean over 12 completed episodes
    # must be a multiple of 1/12 (it is NOT guaranteed for partial sums)
    assert abs(r3 * 12 - round(r3 * 12)) < 1e-9


def test_pick_device_eval_env_gate():
    """--evaluator resolution (evaluate.pick_device_eval_env): device for
    pure-JAX envs whose episodes fit one collector chunk; host fallback
    (None) when truncation would corrupt full-episode means or the env
    has no functional core; explicit 'device' raises on the latter."""
    import pytest

    from r2d2_tpu.collect import default_chunk_len
    from r2d2_tpu.config import default_atari, long_context, procgen_impala
    from r2d2_tpu.evaluate import pick_device_eval_env

    cfg = procgen_impala().replace(env_name="procmaze_shaped:8")
    assert pick_device_eval_env(cfg, "auto") is not None
    assert pick_device_eval_env(cfg, "host") is None

    # slow-fall episodes (984) exceed the atari chunk (400): auto -> host
    long_ep = default_atari().replace(
        env_name="memory_catch:8:12", max_episode_steps=984
    )
    assert long_ep.max_episode_steps > default_chunk_len(long_ep)
    assert pick_device_eval_env(long_ep, "auto") is None
    assert pick_device_eval_env(long_ep, "device") is not None  # knowing opt-in

    # the long_context preset sizes blocks to hold a full episode: device ok
    lc = long_context()
    assert pick_device_eval_env(lc, "auto") is not None

    # no functional core: auto falls back, explicit device raises
    ale = default_atari()  # env_name MsPacman, host-protocol only
    assert pick_device_eval_env(ale, "auto") is None
    with pytest.raises(ValueError):
        pick_device_eval_env(ale, "device")
