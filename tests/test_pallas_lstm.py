"""Parity of the fused Pallas LSTM unroll (ops/pallas_lstm.py) against the
lax.scan reference implementation (models/lstm.py), values AND gradients.

Runs in Pallas interpret mode on the CPU test backend — the same kernel
code path that compiles on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.models.lstm import LSTM
from r2d2_tpu.ops.pallas_lstm import (
    lstm_seq_unroll,
    lstm_seq_unroll_ckpt,
    lstm_seq_unroll_fused_dwh,
    lstm_unroll,
    seq_backward_residual_bytes,
)

pytestmark = pytest.mark.kernels


def _scan_reference(proj_t, wh, h0, c0):
    """Plain-JAX unroll over time-major projections (the scan semantics)."""
    H = h0.shape[-1]

    def step(carry, p):
        h, c = carry
        z = p + h @ wh
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H : 2 * H])
        g = jnp.tanh(z[..., 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[..., 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (h, c), outs = jax.lax.scan(step, (h0, c0), proj_t)
    return outs, (h, c)


def _rand_inputs(rng, T=6, B=8, H=16):
    proj_t = jnp.asarray(rng.normal(size=(T, B, 4 * H)).astype(np.float32))
    wh = jnp.asarray((rng.normal(size=(H, 4 * H)) / np.sqrt(H)).astype(np.float32))
    h0 = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.3)
    c0 = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.3)
    return proj_t, wh, h0, c0


def test_forward_matches_scan():
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(0))
    outs_p, (hT_p, cT_p) = lstm_unroll(proj_t, wh, h0, c0)
    outs_s, (hT_s, cT_s) = _scan_reference(proj_t, wh, h0, c0)
    np.testing.assert_allclose(np.asarray(outs_p), np.asarray(outs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT_p), np.asarray(hT_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT_p), np.asarray(cT_s), atol=1e-5)


@pytest.mark.parametrize("wrt", [0, 1, 2, 3])  # proj, wh, h0, c0
def test_grads_match_scan(wrt):
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(1))
    rng = np.random.default_rng(2)
    # random cotangent over outputs only (the learner's real use: the final
    # carry is discarded by R2D2Network.unroll)
    ct = jnp.asarray(rng.normal(size=(6, 8, 16)).astype(np.float32))

    def loss_pallas(*args):
        outs, _ = lstm_unroll(*args)
        return jnp.sum(outs * ct)

    def loss_scan(*args):
        outs, _ = _scan_reference(*args)
        return jnp.sum(outs * ct)

    g_p = jax.grad(loss_pallas, argnums=wrt)(proj_t, wh, h0, c0)
    g_s = jax.grad(loss_scan, argnums=wrt)(proj_t, wh, h0, c0)
    np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_s), rtol=1e-4, atol=1e-5)


def test_final_carry_grads_match_scan():
    """Cotangents through (h_T, c_T) too — exercises the dcT seed path."""
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(3))

    def loss(fn, *args):
        outs, (hT, cT) = fn(*args)
        return jnp.sum(outs) * 0.1 + jnp.sum(hT * cT)

    for wrt in range(4):
        g_p = jax.grad(lambda *a: loss(lstm_unroll, *a), argnums=wrt)(proj_t, wh, h0, c0)
        g_s = jax.grad(lambda *a: loss(_scan_reference, *a), argnums=wrt)(proj_t, wh, h0, c0)
        np.testing.assert_allclose(
            np.asarray(g_p), np.asarray(g_s), rtol=1e-4, atol=1e-5,
        )


def test_lstm_module_backend_parity():
    """The full flax LSTM module agrees between backend='scan' and
    backend='pallas' (same params), values and input grads."""
    cfg = tiny_test()
    B, T, D, H = 4, 6, 24, cfg.hidden_dim
    scan_mod = LSTM(hidden_dim=H, in_dim=D, backend="scan")
    pallas_mod = LSTM(hidden_dim=H, in_dim=D, backend="pallas")
    rng = np.random.default_rng(4)
    xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    carry = (
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
    )
    params = scan_mod.init(jax.random.PRNGKey(0), xs, carry)

    outs_s, carry_s = scan_mod.apply(params, xs, carry)
    outs_p, carry_p = pallas_mod.apply(params, xs, carry)
    np.testing.assert_allclose(np.asarray(outs_p), np.asarray(outs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(carry_p[0]), np.asarray(carry_s[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(carry_p[1]), np.asarray(carry_s[1]), atol=1e-5)

    def loss(mod, p, xs):
        outs, _ = mod.apply(p, xs, carry)
        return jnp.sum(jnp.tanh(outs))

    g_s = jax.grad(lambda p: loss(scan_mod, p, xs))(params)
    g_p = jax.grad(lambda p: loss(pallas_mod, p, xs))(params)
    flat_s = jax.tree.leaves(g_s)
    flat_p = jax.tree.leaves(g_p)
    for a, b in zip(flat_p, flat_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------------
# fused sequence kernel (lstm_seq_unroll): per-row stop-gradient seam
# --------------------------------------------------------------------------


def _seam_scan_reference(proj_t, wh, h0, c0, burn):
    """Scan with the R2D2 seam: per-row stop_gradient cut at t == burn[b]
    entering the step, plus a no-cotangent mask on burn-in outputs — the
    operator-equivalent of the kernel's backward masks."""
    H = h0.shape[-1]

    def step(carry, inp):
        t, p = inp
        h, c = carry
        cut = (t == burn)[:, None]
        h = jnp.where(cut, jax.lax.stop_gradient(h), h)
        c = jnp.where(cut, jax.lax.stop_gradient(c), c)
        z = p + h @ wh
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H : 2 * H])
        g = jnp.tanh(z[..., 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[..., 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        keep = (t >= burn)[:, None]
        out = jnp.where(keep, h, jax.lax.stop_gradient(h))
        return (h, c), out

    T = proj_t.shape[0]
    (h, c), outs = jax.lax.scan(step, (h0, c0), (jnp.arange(T, dtype=jnp.int32), proj_t))
    return outs, (h, c)


# one seam per batch row, spanning the contract range [0, T-1] for T=6
_BURN = np.array([0, 2, 5, 3, 5, 1, 0, 4], np.int32)


class TestFusedSequence:
    def test_forward_bit_identical_to_per_step_path(self):
        """The seam only gates gradients: forward values must match the
        existing Pallas path BIT FOR BIT (fp32 acceptance criterion)."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(10))
        burn = jnp.asarray(_BURN)
        outs_a, (hT_a, cT_a) = lstm_unroll(proj_t, wh, h0, c0)
        outs_b, (hT_b, cT_b) = lstm_seq_unroll(proj_t, wh, h0, c0, burn)
        assert np.array_equal(np.asarray(outs_a), np.asarray(outs_b))
        assert np.array_equal(np.asarray(hT_a), np.asarray(hT_b))
        assert np.array_equal(np.asarray(cT_a), np.asarray(cT_b))

    @pytest.mark.parametrize("wrt", [0, 1])  # proj, wh (h0/c0 are exact zeros)
    def test_grads_match_seam_scan(self, wrt):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(11))
        burn = jnp.asarray(_BURN)
        rng = np.random.default_rng(12)
        ct = jnp.asarray(rng.normal(size=(6, 8, 16)).astype(np.float32))
        cth = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
        ctc = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))

        def loss(fn, *args):
            outs, (hT, cT) = fn(*args)
            return jnp.sum(outs * ct) + jnp.sum(hT * cth) + jnp.sum(cT * ctc)

        g_k = jax.grad(lambda *a: loss(lstm_seq_unroll, *a, burn), argnums=wrt)(
            proj_t, wh, h0, c0
        )
        g_s = jax.grad(lambda *a: loss(_seam_scan_reference, *a, burn), argnums=wrt)(
            proj_t, wh, h0, c0
        )
        np.testing.assert_allclose(np.asarray(g_k), np.asarray(g_s), rtol=1e-4, atol=1e-5)

    def test_burn_in_boundary_grads_exactly_zero(self):
        """dproj rows strictly below each row's seam are EXACT zeros, and
        the initial-state grads are exact zeros for every row — the seam
        is a hard cut, not a small number."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(13))
        burn = jnp.asarray(_BURN)

        def loss(proj_t, wh, h0, c0):
            outs, (hT, cT) = lstm_seq_unroll(proj_t, wh, h0, c0, burn)
            return jnp.sum(outs**2) + jnp.sum(hT * cT)

        dproj, dwh, dh0, dc0 = jax.grad(loss, argnums=(0, 1, 2, 3))(proj_t, wh, h0, c0)
        dproj = np.asarray(dproj)
        for b, bi in enumerate(_BURN):
            assert not dproj[:bi, b, :].any(), f"row {b}: grads leak below seam {bi}"
            if bi < dproj.shape[0]:
                assert dproj[bi:, b, :].any(), f"row {b}: train segment got no grads"
        assert not np.asarray(dh0).any() and not np.asarray(dc0).any()
        assert np.asarray(dwh).any()

    def test_zero_burn_matches_full_backprop(self):
        """burn_in == 0 everywhere reduces the seam op to lstm_unroll's
        gradients exactly (the cut only removes the h0/c0 path, which the
        all-zero seam also cuts — checked against plain scan)."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(14))
        zero = jnp.zeros(8, jnp.int32)

        def loss(fn, *args):
            outs, _ = fn(*args)
            return jnp.sum(jnp.tanh(outs))

        g_k = jax.grad(lambda p, w: loss(lstm_seq_unroll, p, w, h0, c0, zero), argnums=(0, 1))(proj_t, wh)
        g_u = jax.grad(lambda p, w: loss(lstm_unroll, p, w, h0, c0), argnums=(0, 1))(proj_t, wh)
        for a, b in zip(g_k, g_u):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_module_backend_parity_with_seam(self, dtype):
        """Full LSTM module, scan vs pallas backends, seam active: fp32 is
        tight, bf16 drift-bounded (the precision plane's parity class)."""
        B, T, D, H = 8, 6, 24, tiny_test().hidden_dim
        scan_mod = LSTM(hidden_dim=H, in_dim=D, dtype=dtype, backend="scan")
        pallas_mod = LSTM(hidden_dim=H, in_dim=D, dtype=dtype, backend="pallas")
        rng = np.random.default_rng(15)
        xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
        carry = (
            jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
            jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
        )
        burn = jnp.asarray(np.minimum(_BURN, T - 1))
        params = scan_mod.init(jax.random.PRNGKey(1), xs, carry)

        outs_s, _ = scan_mod.apply(params, xs, carry, burn_in=burn)
        outs_p, _ = pallas_mod.apply(params, xs, carry, burn_in=burn)
        fwd_tol = 1e-5 if dtype == jnp.float32 else 3e-2
        np.testing.assert_allclose(
            np.asarray(outs_p, np.float32), np.asarray(outs_s, np.float32), atol=fwd_tol
        )

        def loss(mod, p):
            outs, _ = mod.apply(p, xs, carry, burn_in=burn)
            return jnp.sum(jnp.tanh(outs.astype(jnp.float32)))

        g_s = jax.tree.leaves(jax.grad(lambda p: loss(scan_mod, p))(params))
        g_p = jax.tree.leaves(jax.grad(lambda p: loss(pallas_mod, p))(params))
        for a, b in zip(g_p, g_s):
            a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
            if dtype == jnp.float32:
                np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
            else:
                # bf16: bounded relative L2 drift, not elementwise equality
                denom = np.linalg.norm(b) + 1e-6
                assert np.linalg.norm(a - b) / denom < 0.05

    def test_scan_chunk_seam_parity(self):
        """The remat'd chunked scan threads the global t through chunks:
        same function as the unchunked seam scan, values and grads."""
        B, T, D, H = 4, 8, 12, 16
        plain = LSTM(hidden_dim=H, in_dim=D, backend="scan")
        chunked = LSTM(hidden_dim=H, in_dim=D, backend="scan", scan_chunk=2)
        rng = np.random.default_rng(16)
        xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
        carry = (jnp.zeros((B, H), jnp.float32), jnp.zeros((B, H), jnp.float32))
        burn = jnp.asarray([0, 3, 5, 7], jnp.int32)
        params = plain.init(jax.random.PRNGKey(2), xs, carry)

        def loss(mod, p):
            outs, _ = mod.apply(p, xs, carry, burn_in=burn)
            return jnp.sum(outs**2)

        np.testing.assert_allclose(
            np.asarray(plain.apply(params, xs, carry, burn_in=burn)[0]),
            np.asarray(chunked.apply(params, xs, carry, burn_in=burn)[0]),
            atol=1e-6,
        )
        g_a = jax.tree.leaves(jax.grad(lambda p: loss(plain, p))(params))
        g_b = jax.tree.leaves(jax.grad(lambda p: loss(chunked, p))(params))
        for a, b in zip(g_a, g_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    def test_one_launch_per_train_step(self):
        """Compile-count gate, shared with the analysis plane: ONE
        pallas_call per sequence unroll, exactly three (online fwd +
        target fwd + backward) per train step — never O(T) launches."""
        from r2d2_tpu.analysis.jaxpr_rules import (
            fused_train_step_jaxpr,
            fused_unroll_jaxpr,
            scan_fused_unroll,
        )

        assert scan_fused_unroll("fp32") == []
        assert fused_unroll_jaxpr("fp32").count("pallas_call") == 1
        assert fused_train_step_jaxpr("fp32").count("pallas_call") == 3


# --------------------------------------------------------------------------
# alternative backward arms (ISSUE 14): fused-dWh and checkpointed kernels
# --------------------------------------------------------------------------


def _seam_loss(fn, proj_t, wh, h0, c0, burn):
    outs, (hT, cT) = fn(proj_t, wh, h0, c0, burn)
    return jnp.sum(outs.astype(jnp.float32) ** 2) + jnp.sum(
        hT.astype(jnp.float32) * cT.astype(jnp.float32)
    )


class TestFusedDwhArm:
    """lstm_seq_unroll_fused_dwh: dWh accumulated in VMEM scratch inside
    the reversed backward kernel — no outside (T·B,H)ᵀ@(T·B,4H) matmul,
    no full-size f32 dz in HBM. Forward and dproj are the SAME program as
    the default arm, so those are bitwise; dWh differs only in summation
    order (per-step scratch += vs one big matmul)."""

    def test_forward_bit_identical_to_default_arm(self):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(20))
        burn = jnp.asarray(_BURN)
        outs_a, (hT_a, cT_a) = lstm_seq_unroll(proj_t, wh, h0, c0, burn)
        outs_b, (hT_b, cT_b) = lstm_seq_unroll_fused_dwh(proj_t, wh, h0, c0, burn)
        assert np.array_equal(np.asarray(outs_a), np.asarray(outs_b))
        assert np.array_equal(np.asarray(hT_a), np.asarray(hT_b))
        assert np.array_equal(np.asarray(cT_a), np.asarray(cT_b))

    def test_grads_match_default_arm_fp32(self):
        """dproj is bitwise (identical dz program); dWh within a few ulp
        (summation order only); dh0/dc0 exact zeros on both arms."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(21))
        burn = jnp.asarray(_BURN)
        g_d = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll, *a, burn), argnums=(0, 1, 2, 3)
        )(proj_t, wh, h0, c0)
        g_f = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll_fused_dwh, *a, burn),
            argnums=(0, 1, 2, 3),
        )(proj_t, wh, h0, c0)
        assert np.array_equal(np.asarray(g_d[0]), np.asarray(g_f[0]))  # dproj
        np.testing.assert_allclose(
            np.asarray(g_d[1]), np.asarray(g_f[1]), rtol=1e-5, atol=1e-6
        )
        assert not np.asarray(g_f[2]).any() and not np.asarray(g_f[3]).any()

    def test_exact_zero_below_seam(self):
        """The seam contract carries over verbatim: dproj rows strictly
        below each row's burn are EXACT zeros (the masked dz contributes
        exact zeros to the scratch dWh too)."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(22))
        burn = jnp.asarray(_BURN)
        dproj = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll_fused_dwh, *a, burn)
        )(proj_t, wh, h0, c0)
        dproj = np.asarray(dproj)
        for b, bi in enumerate(_BURN):
            assert not dproj[:bi, b, :].any(), f"row {b}: leak below seam {bi}"
            if bi < dproj.shape[0]:
                assert dproj[bi:, b, :].any()

    def test_grads_match_seam_scan_reference(self):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(23))
        burn = jnp.asarray(_BURN)
        for wrt in (0, 1):
            g_k = jax.grad(
                lambda *a: _seam_loss(lstm_seq_unroll_fused_dwh, *a, burn),
                argnums=wrt,
            )(proj_t, wh, h0, c0)
            g_s = jax.grad(
                lambda *a: _seam_loss(_seam_scan_reference, *a, burn), argnums=wrt
            )(proj_t, wh, h0, c0)
            np.testing.assert_allclose(
                np.asarray(g_k), np.asarray(g_s), rtol=1e-4, atol=1e-5
            )


class TestCheckpointedArm:
    """lstm_seq_unroll_ckpt(S): residuals are every-S-step (h, c) carries
    only — O((T/S)·B·H) instead of O(T·B·H) — and the backward kernel
    recomputes each segment's gates from its checkpoint before walking it
    in reverse. dWh is inherently fused (the full h sequence never exists
    in HBM)."""

    def test_forward_bit_identical_to_default_arm(self):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(30))
        burn = jnp.asarray(_BURN)
        outs_a, (hT_a, cT_a) = lstm_seq_unroll(proj_t, wh, h0, c0, burn)
        outs_b, (hT_b, cT_b) = lstm_seq_unroll_ckpt(2)(proj_t, wh, h0, c0, burn)
        assert np.array_equal(np.asarray(outs_a), np.asarray(outs_b))
        assert np.array_equal(np.asarray(hT_a), np.asarray(hT_b))
        assert np.array_equal(np.asarray(cT_a), np.asarray(cT_b))

    @pytest.mark.parametrize("S", [1, 2, 3, 6])
    def test_grads_match_default_arm_fp32(self, S):
        """Every divisor segment length, including the degenerate S=1
        (checkpoint every step — pure recompute overhead, same math) and
        S=T (one segment — the whole unroll recomputed from h0/c0). The
        recompute replays identical f32 ops, but XLA fuses the two
        programs differently, so parity is one-ulp-tight, not bitwise."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(31))
        burn = jnp.asarray(_BURN)
        g_d = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll, *a, burn), argnums=(0, 1, 2, 3)
        )(proj_t, wh, h0, c0)
        g_c = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll_ckpt(S), *a, burn),
            argnums=(0, 1, 2, 3),
        )(proj_t, wh, h0, c0)
        np.testing.assert_allclose(
            np.asarray(g_d[0]), np.asarray(g_c[0]), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(g_d[1]), np.asarray(g_c[1]), rtol=1e-5, atol=1e-6
        )
        assert not np.asarray(g_c[2]).any() and not np.asarray(g_c[3]).any()

    @pytest.mark.parametrize(
        "burn_vec",
        [
            # seams ON segment boundaries (S=2 over T=6: boundaries 0/2/4)
            np.array([0, 2, 4, 2, 4, 0, 2, 4], np.int32),
            # seams strictly INSIDE recomputed segments
            np.array([1, 3, 5, 1, 3, 5, 1, 3], np.int32),
            # mixed, plus the all-learn and nearly-all-burn extremes
            np.array([0, 5, 1, 4, 2, 3, 0, 5], np.int32),
        ],
    )
    def test_seam_exact_zero_at_and_inside_segment_boundaries(self, burn_vec):
        """The hard case the segment recompute must not soften: a seam
        landing exactly on an S-boundary (the carry cut coincides with a
        checkpoint reload) or mid-segment (the cut applies inside the
        recomputed walk). Below-seam dproj must be EXACT zeros either
        way."""
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(32))
        burn = jnp.asarray(burn_vec)
        dproj, dwh, dh0, dc0 = jax.grad(
            lambda *a: _seam_loss(lstm_seq_unroll_ckpt(2), *a, burn),
            argnums=(0, 1, 2, 3),
        )(proj_t, wh, h0, c0)
        dproj = np.asarray(dproj)
        for b, bi in enumerate(burn_vec):
            assert not dproj[:bi, b, :].any(), f"row {b}: leak below seam {bi}"
            if bi < dproj.shape[0]:
                assert dproj[bi:, b, :].any(), f"row {b}: train segment empty"
        assert not np.asarray(dh0).any() and not np.asarray(dc0).any()
        assert np.asarray(dwh).any()

    def test_grads_match_seam_scan_reference(self):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(33))
        burn = jnp.asarray(_BURN)
        for wrt in (0, 1):
            g_k = jax.grad(
                lambda *a: _seam_loss(lstm_seq_unroll_ckpt(3), *a, burn),
                argnums=wrt,
            )(proj_t, wh, h0, c0)
            g_s = jax.grad(
                lambda *a: _seam_loss(_seam_scan_reference, *a, burn), argnums=wrt
            )(proj_t, wh, h0, c0)
            np.testing.assert_allclose(
                np.asarray(g_k), np.asarray(g_s), rtol=1e-4, atol=1e-5
            )

    def test_rejects_non_divisor_segment(self):
        proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(34))
        burn = jnp.asarray(_BURN)
        with pytest.raises(ValueError, match="not divisible"):
            jax.grad(
                lambda *a: _seam_loss(lstm_seq_unroll_ckpt(4), *a, burn)
            )(proj_t, wh, h0, c0)

    def test_residual_bytes_scale_with_segment_length(self):
        """The measurable claim behind the arm: carry residuals shrink by
        exactly T/S (h at proj dtype + c at f32, per the vjp_fwd's
        concatenated checkpoint tensors)."""
        T, B, H = 80, 32, 512
        full = seq_backward_residual_bytes(T, B, H, jnp.bfloat16)
        ck = seq_backward_residual_bytes(T, B, H, jnp.bfloat16, ckpt_every=5)
        assert full["carry_residual_bytes"] == T * B * H * (2 + 4)
        assert ck["carry_residual_bytes"] == (T // 5) * B * H * (2 + 4)
        assert full["carry_residual_bytes"] == 5 * ck["carry_residual_bytes"]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("arm", ["fused_dwh", "ckpt"])
def test_backward_arm_module_parity(arm, dtype):
    """Full LSTM module with an arm enabled vs the default pallas path:
    identical params, seam active, both precisions. fp32 is one-ulp
    tight; bf16 recompute parity holds by construction (bf16 h round-trip
    is identity, c checkpoints are f32-exact), so bf16 is ALSO tight
    against the default arm — the drift-vs-scan class does not widen."""
    B, T, D, H = 8, 6, 24, tiny_test().hidden_dim
    kw = dict(hidden_dim=H, in_dim=D, dtype=dtype, backend="pallas")
    default_mod = LSTM(**kw)
    arm_mod = LSTM(**kw, fused_dwh=True) if arm == "fused_dwh" else LSTM(
        **kw, grad_checkpoint=3
    )
    rng = np.random.default_rng(40)
    xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    carry = (
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
    )
    burn = jnp.asarray(np.minimum(_BURN, T - 1))
    params = default_mod.init(jax.random.PRNGKey(3), xs, carry)

    outs_d, _ = default_mod.apply(params, xs, carry, burn_in=burn)
    outs_a, _ = arm_mod.apply(params, xs, carry, burn_in=burn)
    assert np.array_equal(np.asarray(outs_d), np.asarray(outs_a))  # fwd bitwise

    def loss(mod, p):
        outs, _ = mod.apply(p, xs, carry, burn_in=burn)
        return jnp.sum(jnp.tanh(outs.astype(jnp.float32)))

    g_d = jax.tree.leaves(jax.grad(lambda p: loss(default_mod, p))(params))
    g_a = jax.tree.leaves(jax.grad(lambda p: loss(arm_mod, p))(params))
    for a, b in zip(g_a, g_d):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=2e-6,
        )


def test_backward_arm_launch_budget():
    """Each armed train step holds the default path's exact 3-launch
    budget — the fused dWh and the segment recompute live INSIDE the one
    backward launch, they do not buy extra launches."""
    from r2d2_tpu.analysis.jaxpr_rules import (
        backward_arm_train_step_jaxpr,
        scan_backward_arms,
    )

    assert scan_backward_arms("fp32") == []
    for arm in ("fused_dwh", "ckpt"):
        assert backward_arm_train_step_jaxpr("fp32", arm).count("pallas_call") == 3


class TestScanChunkRemainder:
    """scan_chunk no longer requires chunk | T: the tail runs as one
    shorter remat'd chunk (models/lstm.py), so live-loop sequence lengths
    don't have to be multiples of the checkpoint chunk."""

    @pytest.mark.parametrize("chunk", [3, 4, 5, 7, 10, 11])
    def test_remainder_chunks_match_plain_scan(self, chunk):
        B, T, D, H = 4, 10, 12, 16
        plain = LSTM(hidden_dim=H, in_dim=D, backend="scan")
        chunked = LSTM(hidden_dim=H, in_dim=D, backend="scan", scan_chunk=chunk)
        rng = np.random.default_rng(50)
        xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
        carry = (jnp.zeros((B, H), jnp.float32), jnp.zeros((B, H), jnp.float32))
        burn = jnp.asarray([0, 3, 6, 9], jnp.int32)
        params = plain.init(jax.random.PRNGKey(4), xs, carry)

        def loss(mod, p):
            outs, _ = mod.apply(p, xs, carry, burn_in=burn)
            return jnp.sum(outs**2)

        np.testing.assert_allclose(
            np.asarray(plain.apply(params, xs, carry, burn_in=burn)[0]),
            np.asarray(chunked.apply(params, xs, carry, burn_in=burn)[0]),
            atol=1e-6,
        )
        g_a = jax.tree.leaves(jax.grad(lambda p: loss(plain, p))(params))
        g_b = jax.tree.leaves(jax.grad(lambda p: loss(chunked, p))(params))
        for a, b in zip(g_a, g_b):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_remainder_without_burn_in(self):
        B, T, D, H = 2, 7, 8, 16
        plain = LSTM(hidden_dim=H, in_dim=D, backend="scan")
        chunked = LSTM(hidden_dim=H, in_dim=D, backend="scan", scan_chunk=4)
        rng = np.random.default_rng(51)
        xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
        carry = (jnp.zeros((B, H), jnp.float32), jnp.zeros((B, H), jnp.float32))
        params = plain.init(jax.random.PRNGKey(5), xs, carry)
        outs_a, (h_a, c_a) = plain.apply(params, xs, carry)
        outs_b, (h_b, c_b) = chunked.apply(params, xs, carry)
        np.testing.assert_allclose(np.asarray(outs_a), np.asarray(outs_b), atol=1e-6)
        np.testing.assert_allclose(np.asarray(h_a), np.asarray(h_b), atol=1e-6)
        np.testing.assert_allclose(np.asarray(c_a), np.asarray(c_b), atol=1e-6)


class TestChooseBackwardArm:
    """choose_backward_arm (ops/pallas_lstm.py) + config.resolve_backward_arm:
    the auto-selector that picks the sequence backward from the peak-
    residual-bytes budget (ISSUE 16 satellite). Pure shape math — no
    kernel runs."""

    T, B, H = 84, 8, 512

    def _peaks(self, dtype):
        d = seq_backward_residual_bytes(self.T, self.B, self.H, dtype)
        dz_f32 = self.T * self.B * 4 * self.H * 4
        dz_proj = self.T * self.B * 4 * self.H * jnp.dtype(dtype).itemsize
        return d["carry_residual_bytes"], dz_f32, dz_proj

    def test_auto_prefers_default_when_budget_fits(self):
        from r2d2_tpu.ops.pallas_lstm import choose_backward_arm

        carry, dz_f32, _ = self._peaks(jnp.bfloat16)
        arm, stride = choose_backward_arm(
            self.T, self.B, self.H, jnp.bfloat16, carry + dz_f32
        )
        assert (arm, stride) == ("default", 0)

    def test_auto_steps_down_to_fused_dwh_then_ckpt(self):
        from r2d2_tpu.ops.pallas_lstm import choose_backward_arm

        carry, dz_f32, dz_proj = self._peaks(jnp.bfloat16)
        # budget excludes the f32 dz residual but fits the bf16 one
        arm, stride = choose_backward_arm(
            self.T, self.B, self.H, jnp.bfloat16, carry + dz_f32 - 1
        )
        assert (arm, stride) == ("fused_dwh", 0)
        # budget below even the fused arm: checkpointing, with the
        # SMALLEST divisor stride of T=84 whose peak fits
        arm, stride = choose_backward_arm(
            self.T, self.B, self.H, jnp.bfloat16, carry + dz_proj - 1
        )
        assert arm == "ckpt"
        assert stride >= 2 and self.T % stride == 0
        ck = seq_backward_residual_bytes(self.T, self.B, self.H, jnp.bfloat16, stride)
        assert ck["carry_residual_bytes"] + dz_proj <= carry + dz_proj - 1

    def test_explicit_modes_pass_through(self):
        from r2d2_tpu.ops.pallas_lstm import choose_backward_arm

        assert choose_backward_arm(10, 4, 16, jnp.float32, 1, "default") == ("default", 0)
        assert choose_backward_arm(10, 4, 16, jnp.float32, 1, "fused_dwh") == ("fused_dwh", 0)
        arm, stride = choose_backward_arm(10, 4, 16, jnp.float32, 1, "ckpt")
        assert arm == "ckpt" and 10 % stride == 0
        with pytest.raises(ValueError, match="backward-arm"):
            choose_backward_arm(10, 4, 16, jnp.float32, 1, "nope")

    def test_config_resolution_legacy_knobs_win(self):
        cfg = tiny_test().replace(lstm_backend="pallas", seq_fused_dwh=True)
        assert cfg.resolve_backward_arm() == ("fused_dwh", 0)
        cfg = tiny_test().replace(lstm_backend="pallas", seq_grad_checkpoint=5)
        assert cfg.resolve_backward_arm() == ("ckpt", 5)

    def test_config_resolution_non_pallas_is_default(self):
        # scan backend (and the CPU test backend's auto resolution) has no
        # Pallas sequence backward to pick between
        assert tiny_test().replace(lstm_backend="scan").resolve_backward_arm() == ("default", 0)
        assert tiny_test().resolve_backward_arm() == ("default", 0)
        lru = tiny_test().replace(recurrent_core="lru", lstm_backend="auto")
        assert lru.resolve_backward_arm() == ("default", 0)

    def test_config_resolution_budget_divides_by_data_shards(self):
        """The per-device residual budget sees B/(dp*fsdp) under manual
        partitioning — a model that needs ckpt on one chip can ride the
        default arm once the batch shards."""
        carry, dz_f32, _ = self._peaks(jnp.bfloat16)
        budget_mb = -(-(carry + dz_f32) // (1 << 20))  # ceil to MB: fits 1 shard
        base = dict(
            lstm_backend="pallas",
            precision="bf16",
            hidden_dim=self.H,
            batch_size=8 * self.B,
            burn_in_steps=40,
            learning_steps=40,
            block_length=40,
            forward_steps=4,  # seq_len = 84
            backward_residual_budget_mb=int(budget_mb),
        )
        crowded = tiny_test().replace(**base)
        arm_1chip, _ = crowded.resolve_backward_arm()
        assert arm_1chip != "default"  # 8x the batch per device
        sharded = tiny_test().replace(
            **base, dp_size=4, fsdp_size=2, replay_plane="host",
            partitioning="manual",
        )
        assert sharded.resolved_partitioning == "manual"
        assert sharded.resolve_backward_arm() == ("default", 0)
