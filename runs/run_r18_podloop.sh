#!/bin/bash
# Round-18 pod-loop transport chain: the measurement side of the
# block-stream transport PR (transport/framing|publisher|ingest, the
# podloop roles, the SIGKILL-one-host drill). Three rungs, the report
# written to BENCH_r18.json:
#
#   1. transport gate — the transport/chaos/fault/liveloop/autoscale
#      test files plus the full static-analysis CLI (AST lints, jaxpr
#      gates, AND the interprocedural concurrency pass over the new
#      publisher/ingest threads). A broken resume protocol or a racy
#      spool aborts the chain: pod economics measured over a stream
#      that duplicates or drops silently are noise.
#   2. parity anchor  — one single-process liveloop-off serve row, so
#      the default (transport-less) path is exercised the same day the
#      pod loop ships.
#   3. pod loop       — bench.py --mode podloop: 2 serve processes +
#      1 learner process on CPU, closed-loop catch traffic, a mid-run
#      SIGKILL of serve host 0, relaunch from its on-disk spool.
#
# PRE-REGISTERED read: the learner process rides through the SIGKILL
# uninterrupted AND keeps training (step advances after the kill), the
# killed host resumes from its spool with its per-host seq ADVANCING
# past the pre-kill high-water, duplicate_blocks == 0 on the learner,
# sessions_lost == 0 across every host, >= 1 checkpoint broadcast
# applied by >= 1 host (host_reloads), and ingest lag is reported as a
# first-class column (p50/p95/max ms) — the metric that decides
# whether the fleet learns from today's traffic today.
cd /root/repo

. runs/lib.sh

OUT=BENCH_r18.json

echo "=== RUNG 1: transport gate ==="
python -m pytest tests/test_transport.py tests/test_chaos.py \
  tests/test_faults.py tests/test_liveloop.py tests/test_autoscale.py \
  -q -p no:cacheprovider
RC=$?
echo "=== TRANSPORT_PYTEST EXIT: $RC ==="
python -m r2d2_tpu.analysis.cli --jaxpr --concurrency
RCA=$?
echo "=== ANALYSIS EXIT: $RCA ==="
if [ $RC -ne 0 ] || [ $RCA -ne 0 ]; then
  echo "=== ABORT: transport gate failed; pod economics would be noise ==="
  exit 1
fi

echo "=== RUNG 2: parity anchor (single process, transport-less default) ==="
python bench.py --mode serve --serve-seconds 10 --arrival-rate 60 \
  | tee runs/bench_serve_r18_anchor.jsonl
echo "=== SERVE_ANCHOR EXIT: $? ==="

echo "=== RUNG 3: pod loop (2 serve hosts + 1 learner, SIGKILL drill) ==="
python bench.py --mode podloop --podloop-out "$OUT"
RC=$?
echo "=== PODLOOP EXIT: $RC ==="
if [ $RC -ne 0 ]; then
  echo "=== ABORT: podloop bench failed ==="
  exit 1
fi

python - "$OUT" <<'PY'
import json, sys
r = json.load(open(sys.argv[1]))
d = r["sigkill_drill"]
assert d["learner_uninterrupted"], d
assert r["learner_step_final"] > d["learner_step_at_kill"], \
    (d["learner_step_at_kill"], r["learner_step_final"])
assert d["h0_seq_final"] > d["h0_seq_at_kill"], d
assert d["duplicate_blocks"] == 0, d["duplicate_blocks"]
assert d["sessions_lost"] == 0, d["sessions_lost"]
assert r["ckpts_broadcast"] >= 1 and sum(r["host_reloads"]) >= 1, \
    (r["ckpts_broadcast"], r["host_reloads"])
assert r["value"] is not None and r["value"] > 0, r["value"]
print(f"podloop: {r['agg_requests_per_s']:.0f} req/s aggregate, "
      f"return/session {r['return_per_session_2nd_half']}, "
      f"lag p50/p95 {r['ingest_lag_p50_ms']:.0f}/{r['value']:.0f} ms, "
      f"drill: learner {d['learner_step_at_kill']}->"
      f"{r['learner_step_final']}, h0 seq {d['h0_seq_at_kill']}->"
      f"{d['h0_seq_final']}, dupes 0, lost 0, "
      f"reloads {r['host_reloads']}")
PY
RC=$?
echo "=== PODLOOP_ASSERT EXIT: $RC ==="
[ $RC -ne 0 ] && exit 1

echo R18_PODLOOP_ALL_DONE
