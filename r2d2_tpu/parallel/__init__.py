"""Parallelism layer: device meshes, shardings, collectives.

The reference's only parallelism is OS processes + queues on one host
(SURVEY.md section 2.3). Here distribution is expressed the TPU way: a
`jax.sharding.Mesh` with named axes, sharding annotations on the jitted
learner step, and XLA-inserted collectives (psum all-reduce for gradients)
riding ICI — no NCCL/MPI analogue is needed because the compiler owns the
communication schedule.
"""

from r2d2_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    manual_batch_sharding,
    manual_data_axes,
    replicated_sharding,
    shard_batch,
)
from r2d2_tpu.parallel.sharding_map import (
    DEFAULT_RULES,
    moment_spec_for,
    serve_param_shardings,
    train_state_shardings,
    tree_pspecs,
    tree_shardings,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "manual_batch_sharding",
    "manual_data_axes",
    "replicated_sharding",
    "shard_batch",
    "DEFAULT_RULES",
    "moment_spec_for",
    "train_state_shardings",
    "tree_pspecs",
    "tree_shardings",
    "serve_param_shardings",
]
