"""Central sequence-prioritized replay with vectorized batch assembly.

Capability parity with the reference ReplayBuffer (reference
worker.py:69-310): circular store of fixed-size blocks, a sum tree over all
sequence slots, stratified prioritized sampling with IS weights, and
stale-priority rejection via pointer-window masking.

TPU-first redesign: the reference assembles each batch with a 64-iteration
Python loop of per-sequence tensor slices plus `pad_sequence`
(worker.py:210-288). Here every block field lives in ONE preallocated numpy
array, and a batch is assembled with a single fancy-index gather per field —
(batch, seq_len) windows come out fixed-shape (jit-stable) in a handful of
vectorized ops. This is what keeps a TPU learner fed from a host CPU.

Thread safety: one lock around add/sample/update, as in the reference
(worker.py:97), but the buffer is passive — service loops live in the
trainer so the same object works single- and multi-threaded.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.block import Block
from r2d2_tpu.replay.sum_tree import SumTree


@dataclasses.dataclass
class SampledBatch:
    """Fixed-shape training batch (host numpy, ready for device_put)."""

    obs: np.ndarray            # (B, seq_len, *obs_shape) uint8
    last_action: np.ndarray    # (B, seq_len) uint8 scalar actions
    last_reward: np.ndarray    # (B, seq_len) float32
    hidden: np.ndarray         # (B, 2, H) float32
    action: np.ndarray         # (B, L) int32
    n_step_reward: np.ndarray  # (B, L) float32
    gamma: np.ndarray          # (B, L) float32
    burn_in_steps: np.ndarray  # (B,) int32
    learning_steps: np.ndarray # (B,) int32
    forward_steps: np.ndarray  # (B,) int32
    is_weights: np.ndarray     # (B,) float32
    idxes: np.ndarray          # (B,) int64 — sequence slots, for priority updates
    old_ptr: int               # block pointer at sample time (staleness check)
    env_steps: int             # total env steps stored so far


class ReplayBuffer:
    def __init__(self, cfg: R2D2Config, native: Optional[object] = None):
        self.cfg = cfg
        S, L = cfg.seqs_per_block, cfg.learning_steps
        nb, slot = cfg.num_blocks, cfg.block_slot_len

        self.tree = SumTree(cfg.num_sequences, cfg.prio_exponent, cfg.is_exponent, native=native)
        self._native = native

        self.obs_store = np.zeros((nb, slot, *cfg.obs_shape), dtype=np.uint8)
        self.last_action_store = np.zeros((nb, slot), dtype=np.uint8)
        self.last_reward_store = np.zeros((nb, slot), dtype=np.float32)
        self.action_store = np.zeros((nb, cfg.block_length), dtype=np.uint8)
        self.n_step_reward_store = np.zeros((nb, cfg.block_length), dtype=np.float32)
        self.gamma_store = np.zeros((nb, cfg.block_length), dtype=np.float32)
        self.hidden_store = np.zeros((nb, S, 2, cfg.hidden_dim), dtype=np.float32)
        self.burn_in_store = np.zeros((nb, S), dtype=np.int32)
        self.learning_store = np.zeros((nb, S), dtype=np.int32)
        self.forward_store = np.zeros((nb, S), dtype=np.int32)
        self.num_seq_store = np.zeros(nb, dtype=np.int32)
        self.learning_sum = np.zeros(nb, dtype=np.int64)
        self.occupied = np.zeros(nb, dtype=bool)

        self.block_ptr = 0
        self.size = 0  # stored learning transitions
        self.env_steps = 0
        self.num_episodes = 0
        self.episode_reward_sum = 0.0
        self.lock = threading.Lock()

    def __len__(self) -> int:
        return self.size

    # ------------------------------------------------------------------ add

    def add_block(
        self, block: Block, priorities: np.ndarray, episode_reward: Optional[float]
    ) -> None:
        """Write one block into the circular store and refresh its leaves
        (reference worker.py:178-208). `priorities` must already be padded
        to seqs_per_block (zeros for absent sequences)."""
        cfg = self.cfg
        S = cfg.seqs_per_block
        with self.lock:
            ptr = self.block_ptr
            idxes = np.arange(ptr * S, (ptr + 1) * S, dtype=np.int64)
            self.tree.update(idxes, priorities)

            if self.occupied[ptr]:
                self.size -= int(self.learning_sum[ptr])

            steps = block.stored_steps
            self.obs_store[ptr, :steps] = block.obs
            self.last_action_store[ptr, :steps] = block.last_action
            self.last_reward_store[ptr, :steps] = block.last_reward
            T = len(block.action)
            self.action_store[ptr, :T] = block.action
            self.n_step_reward_store[ptr, :T] = block.n_step_reward
            self.gamma_store[ptr, :T] = block.gamma
            ns = block.num_sequences
            self.hidden_store[ptr, :ns] = block.hidden
            self.burn_in_store[ptr, :S] = 0
            self.learning_store[ptr, :S] = 0
            self.forward_store[ptr, :S] = 0
            self.burn_in_store[ptr, :ns] = block.burn_in_steps
            self.learning_store[ptr, :ns] = block.learning_steps
            self.forward_store[ptr, :ns] = block.forward_steps
            self.num_seq_store[ptr] = ns
            lsum = int(block.learning_steps.sum())
            self.learning_sum[ptr] = lsum
            self.occupied[ptr] = True

            self.size += lsum
            self.env_steps += lsum
            self.block_ptr = (ptr + 1) % cfg.num_blocks

            if episode_reward is not None:
                self.episode_reward_sum += episode_reward
                self.num_episodes += 1

    # --------------------------------------------------------------- sample

    def can_sample(self) -> bool:
        return self.size >= self.cfg.learning_starts

    def sample_batch(self, rng: np.random.Generator) -> SampledBatch:
        """Draw a fixed-shape batch via stratified prioritized sampling.

        All per-field gathers are single vectorized fancy-index reads over
        the preallocated stores — the TPU-feeding rewrite of reference
        worker.py:210-288.
        """
        cfg = self.cfg
        S, L, n = cfg.seqs_per_block, cfg.learning_steps, cfg.forward_steps
        bsz = cfg.batch_size
        with self.lock:
            idxes, is_weights = self.tree.sample(bsz, rng)
            b = idxes // S
            s = idxes % S
            # A stratum boundary can land on a zero-priority leaf of a
            # partially-filled block; clamp instead of crashing (the
            # reference asserts here, worker.py:228, against a misspelled
            # attribute — SURVEY.md quirk 2). Rewrite idxes to the clamped
            # slot so the learner's priority update lands on the sequence
            # that was actually trained on, not the empty slot.
            s = np.minimum(s, np.maximum(self.num_seq_store[b] - 1, 0))
            idxes = b * S + s

            burn = self.burn_in_store[b, s]
            learn = self.learning_store[b, s]
            fwd = self.forward_store[b, s]
            first_burn = self.burn_in_store[b, 0]
            start = first_burn + s * L          # buffer coords of learning start
            win_start = start - burn

            t = np.arange(cfg.seq_len)
            rows = win_start[:, None] + t[None, :]
            np.clip(rows, 0, cfg.block_slot_len - 1, out=rows)
            bcol = b[:, None]
            obs = self.obs_store[bcol, rows]
            last_action = self.last_action_store[bcol, rows]
            last_reward = self.last_reward_store[bcol, rows]

            tl = np.arange(L)
            lrows = s[:, None] * L + tl[None, :]
            np.clip(lrows, 0, cfg.block_length - 1, out=lrows)
            action = self.action_store[bcol, lrows].astype(np.int32)
            n_step_reward = self.n_step_reward_store[bcol, lrows]
            gamma = self.gamma_store[bcol, lrows]

            hidden = self.hidden_store[b, s]

            batch = SampledBatch(
                obs=obs,
                last_action=last_action,
                last_reward=last_reward,
                hidden=hidden,
                action=action,
                n_step_reward=n_step_reward,
                gamma=gamma,
                burn_in_steps=burn.astype(np.int32),
                learning_steps=learn.astype(np.int32),
                forward_steps=fwd.astype(np.int32),
                is_weights=is_weights,
                idxes=idxes,
                old_ptr=self.block_ptr,
                env_steps=self.env_steps,
            )
        return batch

    # ------------------------------------------------------------- priority

    def update_priorities(
        self, idxes: np.ndarray, td_errors: np.ndarray, old_ptr: int
    ) -> None:
        """Apply learner priorities, discarding any index whose block was
        overwritten during the sample->train round trip (the pointer-window
        invariant of reference worker.py:290-307)."""
        S = self.cfg.seqs_per_block
        with self.lock:
            ptr = self.block_ptr
            if ptr > old_ptr:
                mask = (idxes < old_ptr * S) | (idxes >= ptr * S)
            elif ptr < old_ptr:
                mask = (idxes < old_ptr * S) & (idxes >= ptr * S)
            else:
                mask = np.ones_like(idxes, dtype=bool)
            self.tree.update(idxes[mask], td_errors[mask])

    # -------------------------------------------------------------- metrics

    def pop_episode_stats(self):
        with self.lock:
            n, r = self.num_episodes, self.episode_reward_sum
            self.num_episodes = 0
            self.episode_reward_sum = 0.0
        return n, r
