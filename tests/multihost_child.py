"""Shared driver for the multi-host replay tests.

`build_and_run(mesh)` fills a MultiHostShardedReplay with per-shard
deterministic blocks and runs 3 collective train steps — called BOTH by the
in-process single-host reference (4 fake devices, all shards local) and by
the real 2-process children this file spawns as `python multihost_child.py
<pid> <nprocs> <port>`. Identical per-shard content + layout-independent
draw seeds mean the two topologies must produce the same losses.
"""

import json
import sys


def _seed_replay(replay, cfg):
    """Fill with per-GLOBAL-shard deterministic blocks: the same blocks
    land in the same shards regardless of how shards are spread over
    processes. Equal priorities -> IS weights exactly 1.0."""
    import numpy as np

    from bench import synth_block

    rngs = {g: np.random.default_rng(100 + g) for g in replay.local_ids}
    for _ in range(2):
        for g in replay.local_ids:
            block = synth_block(cfg, rngs[g])
            prios = np.full(cfg.seqs_per_block, 1.0, np.float32)
            replay.add_block(block, prios, None)
    assert replay.can_sample()


def _allgather_sum(x):
    """Sum a host-local float over all processes (identity single-host)."""
    import jax
    import numpy as np

    x = np.float64(x)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x).sum()
    return float(x)


def build_and_run(mesh):
    import jax
    import numpy as np

    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.learner import init_train_state, make_sharded_fused_train_step
    from r2d2_tpu.parallel.mesh import replicated_sharding
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay

    cfg = tiny_test().replace(batch_size=8)
    replay = MultiHostShardedReplay(cfg, mesh, seed=5)
    _seed_replay(replay, cfg)

    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_sharded_fused_train_step(
        cfg, net, mesh, donate=False, is_from_priorities=True
    )
    losses = []
    for _ in range(3):
        state, metrics = replay.run_step(step_fn, state)
        losses.append(float(metrics["loss"]))
    # K-dispatch phase: two K=2 collective scan dispatches (the second
    # also drains the first's deferred priorities), then the final drain —
    # the full run_step_k lifecycle on both process topologies
    from r2d2_tpu.learner import make_sharded_fused_multi_train_step

    multi_fn = make_sharded_fused_multi_train_step(
        cfg, net, mesh, 2, donate=False, is_from_priorities=True
    )
    for _ in range(2):
        state, metrics = replay.run_step_k(multi_fn, state, 2)
        losses.append(float(metrics["loss"]))
    replay.drain_pending()
    checksum = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(state.params))
    )
    # the trees saw every drained priority batch: fold the GLOBAL tree
    # mass into the cross-topology comparison too (each process only
    # holds its local shards' trees)
    checksum += _allgather_sum(
        sum(replay.shards[g].tree.total for g in replay.local_ids)
    )
    return losses, checksum


def build_elastic(mesh, shared_dir, phase):
    """Elastic-resume driver, both sides of a topology change.

    phase="save": seed the replay, run 3 collective steps, drain the
    deferred priorities, snapshot (per-process file + topology manifest +
    the replicated train state as layout-free carry extras), then run 3
    MORE steps and return their losses — the uninterrupted run's
    continuation, the reference a resumed run must reproduce.

    phase="resume": fresh replay on THIS mesh (any process layout),
    reshard_replay over whatever snapshot files the old layout left,
    rebuild the train state from the carry extras, run 3 steps. Because
    the logical shard set (dp=4) is unchanged and draw streams are keyed
    by (seed, GLOBAL shard id, epoch), the losses must be bit-identical
    to the save phase's continuation — across 2proc->1proc, 1proc->2proc,
    or any other regrouping of the same shards."""
    import os

    import jax
    import numpy as np

    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.learner import init_train_state, make_sharded_fused_train_step
    from r2d2_tpu.parallel.mesh import replicated_sharding
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay
    from r2d2_tpu.replay.reshard import reshard_replay, snapshot_paths
    from r2d2_tpu.replay.snapshot import save_replay

    cfg = tiny_test().replace(batch_size=8)
    replay = MultiHostShardedReplay(cfg, mesh, seed=5)
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    treedef = jax.tree.structure(state)
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_sharded_fused_train_step(
        cfg, net, mesh, donate=False, is_from_priorities=True
    )

    if phase == "save":
        _seed_replay(replay, cfg)
        for _ in range(3):
            state, _ = replay.run_step(step_fn, state)
        replay.drain_pending()  # snapshot post-drain: no pending write-backs lost
        extra = {
            f"st_{j}": np.asarray(v) for j, v in enumerate(jax.tree.leaves(state))
        }
        path = os.path.join(
            shared_dir, f"replay_snapshot_p{jax.process_index()}.npz"
        )
        save_replay(replay, path, extra=extra)
    else:
        extras = reshard_replay(replay, snapshot_paths(shared_dir))
        n_leaves = sum(1 for k in extras if k.startswith("st_"))
        state = jax.tree.unflatten(treedef, [extras[f"st_{j}"] for j in range(n_leaves)])
        state = jax.device_put(state, replicated_sharding(mesh))

    losses = []
    for _ in range(3):
        state, metrics = replay.run_step(step_fn, state)
        losses.append(float(metrics["loss"]))
    checksum = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(state.params))
    )
    checksum += _allgather_sum(
        sum(replay.shards[g].tree.total for g in replay.local_ids)
    )
    return losses, checksum


def fused_cfg():
    from r2d2_tpu.config import tiny_test

    # sized so the deferred-drain guard holds on a dp=4 mesh: E_local=2,
    # blocks_per_shard=32 >> the 6-slot aliasing bound; episodes (10)
    # fit one collection chunk (block_length=16)
    return tiny_test().replace(
        env_name="catch",
        action_dim=3,
        replay_plane="multihost",
        collector="device",
        num_actors=8,
        batch_size=8,
        updates_per_dispatch=2,
        block_length=16,
        buffer_capacity=16 * 16 * 8,
        learning_starts=64,
        max_episode_steps=10,
        training_steps=8,
    )


def build_and_run_fused(mesh):
    """MultiHostFusedRunner end to end: seed the replay with per-GLOBAL-
    shard deterministic blocks (so the first draws exist), then drive 4
    collective megastep dispatches — K=2 updates + a collection chunk +
    local slab writes each — through the runner's deferred-drain
    protocol, and finish(). Collection is layout-independent by
    construction (env slots and PRNG streams are keyed by GLOBAL shard
    id, draws by (seed, shard, epoch)), so the single-process 4-device
    run and the real 2-process run must produce identical losses, env
    accounting, and tree mass. This pins the runner's HOST-side per-
    process plumbing — slot reservation, addressable-piece chunk drain,
    stamped priority drain — which the single-process tests cannot
    distinguish from global reads."""
    import jax
    import numpy as np

    from r2d2_tpu.megastep import MultiHostFusedRunner
    from r2d2_tpu.envs.catch import CatchEnv
    from r2d2_tpu.learner import init_train_state
    from r2d2_tpu.ops.epsilon import epsilon_ladder
    from r2d2_tpu.parallel.mesh import replicated_sharding
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay

    cfg = fused_cfg()
    replay = MultiHostShardedReplay(cfg, mesh, seed=5)
    _seed_replay(replay, cfg)

    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated_sharding(mesh))
    fn_env = CatchEnv(height=cfg.obs_shape[0], width=cfg.obs_shape[1])
    runner = MultiHostFusedRunner(
        cfg, net, fn_env, replay,
        epsilon_ladder(cfg.num_actors), jax.random.PRNGKey(42), mesh,
        collect_every=1, sample_rng=np.random.default_rng(7),
    )
    losses, recorded_total = [], 0
    for _ in range(4):
        state, m, recorded = runner.step(state)
        losses.append(float(m["loss"]))
        recorded_total += recorded
    recorded_total += runner.finish()

    checksum = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(state.params))
    )
    # fold in the per-process-visible accounting: local tree mass and the
    # env steps this host recorded into its shards (allgathered so both
    # topologies compare the same global quantity)
    checksum += _allgather_sum(
        sum(replay.shards[g].tree.total for g in replay.local_ids)
    )
    return losses, checksum, _allgather_sum(recorded_total)


def main():
    import os

    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "basic"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", nprocs, pid)
    assert jax.process_count() == nprocs, jax.process_count()

    from r2d2_tpu.parallel.multihost import make_global_mesh

    mesh = make_global_mesh(tp=1)
    if mode == "fused":
        losses, checksum, steps = build_and_run_fused(mesh)
        payload = {"pid": pid, "losses": losses, "checksum": checksum,
                   "env_steps": steps}
    elif mode in ("elastic_save", "elastic_resume"):
        shared_dir = sys.argv[5]
        losses, checksum = build_elastic(
            mesh, shared_dir, "save" if mode == "elastic_save" else "resume"
        )
        payload = {"pid": pid, "losses": losses, "checksum": checksum}
    else:
        losses, checksum = build_and_run(mesh)
        payload = {"pid": pid, "losses": losses, "checksum": checksum}
    print("CHILD_RESULT " + json.dumps(payload), flush=True)


if __name__ == "__main__":
    main()
