"""Observation encoders.

All encoders take NHWC uint8-normalized float input (channels-last is the
TPU-native conv layout — no NCHW transpose before the MXU) and emit a flat
latent of `latent_dim` features.

- NatureEncoder: the Nature-DQN trunk used by the reference
  (reference model.py:47-57): Conv 32x8x8/4 -> 64x4x4/2 -> 64x3x3/1 ->
  Dense(512), ReLU, VALID padding. 84x84x1 -> 7x7x64 = 3136 -> 512.
- ImpalaEncoder: the IMPALA-ResNet stack (Espeholt et al. 2018) for the
  Procgen preset (BASELINE.json config 4).
- MLPEncoder: tiny trunk for unit tests.

Two growth/parallelism dials shared by every trunk (ISSUE 16):

depth    (config.encoder_depth) extra Dense(latent)+relu layers appended
         after the latent projection — auto-named Dense_1, Dense_2, ...
         by nn.compact, which the sharding table leaves REPLICATED (only
         Dense_0 has a column-parallel rule), so deeper trunks need no
         new sharding rules. depth=0 is the historical trunk, bit-exact.
tp_size  manual tensor parallelism (learner.make_manual_train_step's
         shard_map): > 1 builds the SHARD-LOCAL trunk — the latent
         Dense_0 goes column-parallel (features = latent/tp, matching
         the table's contiguous column slices; its bias shards with the
         output axis) and the latent is re-gathered over `tp_axis` after
         the relu (elementwise, so relu-then-gather == gather-then-relu
         bit-exactly). Convs stay replicated, exactly as the table says.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _latent_tail(x, latent_dim, dtype, depth, tp_size, tp_axis):
    """Shared latent projection: column-parallel Dense_0 (+gather under
    tp), then `depth` replicated Dense(latent)+relu layers."""
    x = nn.relu(nn.Dense(latent_dim // tp_size, dtype=dtype)(x))
    if tp_size > 1:
        x = jax.lax.all_gather(x, tp_axis, axis=x.ndim - 1, tiled=True)
    for _ in range(depth):
        x = nn.relu(nn.Dense(latent_dim, dtype=dtype)(x))
    return x


class NatureEncoder(nn.Module):
    latent_dim: int = 512
    dtype: jnp.dtype = jnp.float32
    depth: int = 0
    tp_size: int = 1
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), padding="VALID", dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), padding="VALID", dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), padding="VALID", dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        return _latent_tail(
            x, self.latent_dim, self.dtype, self.depth, self.tp_size, self.tp_axis
        )


class ResidualBlock(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype)(y)
        return x + y


class ImpalaEncoder(nn.Module):
    latent_dim: int = 512
    channels: Sequence[int] = (16, 32, 32)
    dtype: jnp.dtype = jnp.float32
    depth: int = 0
    tp_size: int = 1
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = ResidualBlock(ch, dtype=self.dtype)(x)
            x = ResidualBlock(ch, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        return _latent_tail(
            x, self.latent_dim, self.dtype, self.depth, self.tp_size, self.tp_axis
        )


class MLPEncoder(nn.Module):
    latent_dim: int = 32
    dtype: jnp.dtype = jnp.float32
    depth: int = 0
    tp_size: int = 1
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        return _latent_tail(
            x, self.latent_dim, self.dtype, self.depth, self.tp_size, self.tp_axis
        )


def make_encoder(
    name: str,
    latent_dim: int,
    dtype,
    impala_channels=(16, 32, 32),
    depth: int = 0,
    tp_size: int = 1,
    tp_axis: str = "tp",
):
    if tp_size > 1 and latent_dim % tp_size != 0:
        raise ValueError(
            f"latent_dim={latent_dim} must divide by tp_size={tp_size} "
            "(column-parallel latent projection)"
        )
    kw = dict(
        latent_dim=latent_dim, dtype=dtype, depth=depth,
        tp_size=tp_size, tp_axis=tp_axis,
    )
    if name == "nature":
        return NatureEncoder(**kw)
    if name == "impala":
        return ImpalaEncoder(**kw)
    if name == "mlp":
        return MLPEncoder(**kw)
    raise ValueError(f"unknown encoder {name!r}")
