"""Model tests.

The central one is act/unroll parity: the reference's single-step forward
and sequence forwards are an UNCHECKED consistency assumption (SURVEY.md
section 4 'Model'); here it is pinned by test — stepping the network one
frame at a time must reproduce exactly the Q values the scan-based unroll
gathers, including the bootstrap view's edge-repeat clamp semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import R2D2Config, tiny_test
from r2d2_tpu.models.lstm import LSTM
from r2d2_tpu.models.r2d2 import R2D2Network, init_params, initial_carry


def make_net(cfg):
    net, params = init_params(jax.random.PRNGKey(0), cfg)
    return net, params


def rollout_sequential(net, params, obs, la, lr, hidden0):
    """Step `act` over every frame of (1, T, ...) inputs; return (T, A) Qs."""
    T = obs.shape[1]
    carry = (hidden0[:, 0], hidden0[:, 1])
    qs = []
    for t in range(T):
        q, carry = net.apply(params, obs[:, t], la[:, t], lr[:, t], carry, method=net.act)
        qs.append(np.asarray(q[0]))
    return np.stack(qs)


@pytest.fixture(scope="module")
def cfg():
    return tiny_test()


@pytest.fixture(scope="module")
def net_params(cfg):
    return make_net(cfg)


def random_inputs(cfg, rng, B=1):
    T = cfg.seq_len
    obs = rng.integers(0, 255, size=(B, T, *cfg.obs_shape), dtype=np.uint8)
    la = rng.integers(0, cfg.action_dim, size=(B, T)).astype(np.int32)
    lr = rng.normal(size=(B, T)).astype(np.float32)
    hid = rng.normal(size=(B, 2, cfg.hidden_dim)).astype(np.float32)
    return jnp.asarray(obs), jnp.asarray(la), jnp.asarray(lr), jnp.asarray(hid)


def test_act_unroll_parity_learning_view(cfg, net_params):
    net, params = net_params
    rng = np.random.default_rng(0)
    obs, la, lr, hid = random_inputs(cfg, rng)
    burn, learn, fwd = cfg.burn_in_steps, cfg.learning_steps, cfg.forward_steps

    qs_seq = rollout_sequential(net, params, obs, la, lr, hid)
    q_learn, q_boot, mask = net.apply(
        params, obs, la, lr, hid,
        jnp.array([burn], jnp.int32), jnp.array([learn], jnp.int32), jnp.array([fwd], jnp.int32),
    )
    for t in range(learn):
        np.testing.assert_allclose(np.asarray(q_learn[0, t]), qs_seq[burn + t], atol=2e-3)
    np.testing.assert_array_equal(np.asarray(mask[0]), np.ones(learn))


def test_bootstrap_view_edge_repeat(cfg, net_params):
    """forward < F_max: the bootstrap gather must clamp at the sequence's
    last valid output — the reference's edge-repeat (model.py:141-150)."""
    net, params = net_params
    rng = np.random.default_rng(1)
    obs, la, lr, hid = random_inputs(cfg, rng)
    burn, learn = cfg.burn_in_steps, cfg.learning_steps
    fwd = 1  # tail sequence: only 1 forward step available

    qs_seq = rollout_sequential(net, params, obs, la, lr, hid)
    _, q_boot, _ = net.apply(
        params, obs, la, lr, hid,
        jnp.array([burn], jnp.int32), jnp.array([learn], jnp.int32), jnp.array([fwd], jnp.int32),
    )
    seq_end = burn + learn + fwd
    for t in range(learn):
        want_idx = min(burn + cfg.forward_steps + t, seq_end - 1)
        np.testing.assert_allclose(np.asarray(q_boot[0, t]), qs_seq[want_idx], atol=2e-3)


def test_short_sequence_mask(cfg, net_params):
    net, params = net_params
    rng = np.random.default_rng(2)
    obs, la, lr, hid = random_inputs(cfg, rng)
    learn = 2  # ragged tail
    _, _, mask = net.apply(
        params, obs, la, lr, hid,
        jnp.array([0], jnp.int32), jnp.array([learn], jnp.int32), jnp.array([1], jnp.int32),
    )
    np.testing.assert_array_equal(np.asarray(mask[0]), [1, 1, 0, 0])


def test_batched_heterogeneous_windows(cfg, net_params):
    """Rows with different burn-in/learning/forward in one batch must each
    match their own sequential rollout (pack_padded_sequence replacement)."""
    net, params = net_params
    rng = np.random.default_rng(3)
    obs, la, lr, hid = random_inputs(cfg, rng, B=3)
    burn = jnp.array([0, 2, 4], jnp.int32)
    learn = jnp.array([4, 4, 2], jnp.int32)
    fwd = jnp.array([2, 2, 1], jnp.int32)

    q_learn, q_boot, mask = net.apply(params, obs, la, lr, hid, burn, learn, fwd)
    for i in range(3):
        qs_seq = rollout_sequential(net, params, obs[i : i + 1], la[i : i + 1], lr[i : i + 1], hid[i : i + 1])
        for t in range(int(learn[i])):
            np.testing.assert_allclose(np.asarray(q_learn[i, t]), qs_seq[int(burn[i]) + t], atol=2e-3)
            want = min(int(burn[i]) + cfg.forward_steps + t, int(burn[i] + learn[i] + fwd[i]) - 1)
            np.testing.assert_allclose(np.asarray(q_boot[i, t]), qs_seq[want], atol=2e-3)
        np.testing.assert_array_equal(np.asarray(mask[i]), (np.arange(cfg.learning_steps) < int(learn[i])))


def test_lstm_scan_chunk_equivalence():
    """Remat-chunked long scan must be numerically identical to the plain
    scan (long-context preset machinery, SURVEY.md section 5.7)."""
    H, B, T, D = 8, 2, 16, 5
    xs = jnp.asarray(np.random.default_rng(0).normal(size=(B, T, D)).astype(np.float32))
    carry = (jnp.zeros((B, H)), jnp.zeros((B, H)))
    plain = LSTM(H, in_dim=D)
    params = plain.init(jax.random.PRNGKey(0), xs, carry)
    out1, (h1, c1) = plain.apply(params, xs, carry)
    chunked = LSTM(H, in_dim=D, scan_chunk=4)
    out2, (h2, c2) = chunked.apply(params, xs, carry)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-6)


def test_nature_encoder_reference_shapes():
    """84x84 trunk must flatten to 3136 features like the reference
    (model.py:55: Linear(3136, 512))."""
    from r2d2_tpu.models.encoders import NatureEncoder

    enc = NatureEncoder(latent_dim=512)
    x = jnp.zeros((2, 84, 84, 1))
    params = enc.init(jax.random.PRNGKey(0), x)
    # conv stack output before the dense: (2, 7, 7, 64) -> 3136
    dense_kernel = params["params"]["Dense_0"]["kernel"]
    assert dense_kernel.shape == (3136, 512)


def test_impala_encoder_runs():
    from r2d2_tpu.models.encoders import ImpalaEncoder

    enc = ImpalaEncoder(latent_dim=256)
    x = jnp.zeros((2, 64, 64, 3))
    params = enc.init(jax.random.PRNGKey(0), x)
    y = enc.apply(params, x)
    assert y.shape == (2, 256)


def test_bfloat16_compute_path():
    cfg = tiny_test().replace(compute_dtype="bfloat16")
    net, params = make_net(cfg)
    rng = np.random.default_rng(4)
    obs, la, lr, hid = random_inputs(cfg, rng)
    ones = jnp.ones((1,), jnp.int32)
    q_learn, q_boot, mask = net.apply(
        params, obs, la, lr, hid, ones * cfg.burn_in_steps, ones * cfg.learning_steps, ones * cfg.forward_steps
    )
    # heads must still emit float32 (loss math stays f32)
    assert q_learn.dtype == jnp.float32
    assert np.isfinite(np.asarray(q_learn)).all()


def test_model_presets_grow_the_brain():
    """config.MODEL_PRESETS: named sizes for the largest-model-that-fits
    probe (bench.py fits table). Applying one changes exactly the fields
    it names; encoder_depth grows real Dense layers."""
    from r2d2_tpu.config import MODEL_PRESETS, apply_model_preset

    base = tiny_test()
    assert apply_model_preset(base, "base") .hidden_dim == base.hidden_dim
    wide = apply_model_preset(base, "wide")
    assert wide.hidden_dim == 1024 and wide.model_preset == "wide"
    deep = apply_model_preset(base, "deep")
    assert deep.encoder_depth == 2 and deep.hidden_dim == base.hidden_dim
    assert set(MODEL_PRESETS) >= {"base", "wide", "xl", "deep", "deep_wide"}
    with pytest.raises(ValueError, match="model_preset"):
        base.replace(model_preset="nope")


def test_encoder_depth_adds_dense_layers():
    cfg = tiny_test().replace(encoder_depth=2)
    net, params = make_net(cfg)
    enc = params["params"]["enc"]
    assert {"Dense_0", "Dense_1", "Dense_2"} <= set(enc)
    # extra layers are square latent->latent and REPLICATED under tp (no
    # sharding rule claims Dense_1+ — pinned so the manual-tp step's
    # grad psum grouping stays correct)
    from r2d2_tpu.parallel.sharding_map import DEFAULT_RULES, match_axes

    assert enc["Dense_1"]["kernel"].shape == (cfg.hidden_dim, cfg.hidden_dim)
    assert match_axes("params.enc.Dense_1.kernel", DEFAULT_RULES) == ()
    rng = np.random.default_rng(7)
    obs, la, lr, hid = random_inputs(cfg, rng)
    ones = jnp.ones((1,), jnp.int32)
    q_learn, _, _ = net.apply(
        params, obs, la, lr, hid,
        ones * cfg.burn_in_steps, ones * cfg.learning_steps, ones * cfg.forward_steps,
    )
    assert np.isfinite(np.asarray(q_learn)).all()
