"""Render the long-context attack history: every arm on one axes.

BASELINE config 5's task class (memory_catch:10:12 at 26x26 — 288-step
episodes, seq 340, two learning windows per block, window 1 replayed
from the stored recurrent state). One line per run:

  lstm (const lr)      runs/long_context_mid       peak -0.19 @ 9k, regresses
  lru  (const lr)      runs/long_context_mid_lru   peak -0.19 @ 13.5k, regresses
  lru  (cosine)        runs/long_context_mid_lru2  above chance throughout, no breakout
  lru  (cosine+sync500)runs/long_context_mid_lru3  same shape as lru2
  lru  (cosine, 4x budget) runs/long_context_mid_lru4  the budget attack

  python runs/plot_long_context.py --out runs/long_context_attacks.jpg
"""

from __future__ import annotations

import argparse
import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

HERE = os.path.dirname(os.path.abspath(__file__))

SERIES = [
    ("LSTM, const lr (36k)", "long_context_mid/eval.jsonl", "tab:gray", "--"),
    ("LRU, const lr (36k)", "long_context_mid_lru/eval.jsonl", "tab:orange", "--"),
    ("LRU, cosine lr (36k)", "long_context_mid_lru2/eval.jsonl", "tab:red", "-"),
    ("LRU, cosine + sync500 (36k)", "long_context_mid_lru3/eval.jsonl", "tab:purple", "-"),
    ("LRU, cosine lr, 4x budget (144k)", "long_context_mid_lru4/eval.jsonl", "tab:green", "-"),
]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(HERE, "long_context_attacks.jpg"))
    p.add_argument("--chance", type=float, default=-0.504,
                   help="MEASURED random-policy mean reward at the run "
                        "geometry (long_context_mid/baseline.json, n=2048: "
                        "24.8%% catch — a random walk has ~270 blind steps "
                        "to diffuse across 24 columns, so the slow-fall "
                        "null is far above the fast task's)")
    args = p.parse_args()

    fig, ax = plt.subplots(figsize=(8, 4.5))
    for label, rel, color, ls in SERIES:
        path = os.path.join(HERE, rel)
        if not os.path.exists(path):
            print(f"skip {label}: {rel} absent")
            continue
        with open(path) as fh:
            rows = [json.loads(l) for l in fh if l.strip()]
        ax.plot(
            [r["step"] for r in rows], [r["mean_reward"] for r in rows],
            marker="o", ms=3, color=color, ls=ls, label=label,
        )
    ax.axhline(args.chance, color="black", lw=0.8, ls=":",
               label=f"chance ≈ {args.chance}")
    ax.set_xlabel("learner updates")
    ax.set_ylabel("eval mean reward (ε=0.001)")
    ax.set_title("Long-context memory catch (26×26 slow fall, seq 340, "
                 "window 1 from stored state)")
    ax.legend(loc="lower right", fontsize=7)
    ax.grid(alpha=0.25)
    fig.tight_layout()
    fig.savefig(args.out, dpi=140)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
