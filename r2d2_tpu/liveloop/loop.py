"""LiveLoopPlane — wire tap + bridge + exploration onto a running server.

One coordinator owns the liveloop side-threads and installs the capture
hooks on a serve stack (a single `PolicyServer` or every replica of a
`MultiDeviceServer` — the tap and assigner are shared; session affinity
means one session's records always come from one replica's serve loop,
and concurrent replicas only ever append to the tap's lock-guarded
queue). Two supervised workers run under the same supervision contract
as the serve plane (utils/supervision.py — bounded work per iteration,
crash restart, stall detection):

    liveloop-tap     drains batch records into per-session accumulators
                     (fault site "liveloop.tap")
    liveloop-ingest  drains finished Blocks into the replay plane
                     (fault site "liveloop.ingest")

`config.liveloop` off (the default) means none of this is constructed:
no tap is installed on any server, no threads exist, and the serve and
train paths are byte-for-byte their pre-liveloop behavior.
"""

from __future__ import annotations

from typing import List, Optional

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.liveloop.bridge import IngestBridge
from r2d2_tpu.liveloop.explore import EpsilonAssigner
from r2d2_tpu.liveloop.tap import TransitionTap
from r2d2_tpu.utils.faults import fault_point
from r2d2_tpu.utils.supervision import Supervisor


class LiveLoopPlane:
    def __init__(self, cfg: R2D2Config, server, replay, seed: int = 0):
        self.cfg = cfg
        self.tap = TransitionTap(cfg, depth=cfg.liveloop_tap_depth)
        self.bridge = IngestBridge(replay, depth=cfg.liveloop_queue_depth)
        self.tap.set_emit(self.bridge.offer)
        self.assigner = EpsilonAssigner(cfg, seed=seed)
        self.supervisor: Optional[Supervisor] = None
        # install the capture hooks on every serve loop in the stack
        self._servers: List = list(getattr(server, "replicas", None) or [server])
        for s in self._servers:
            s.tap = self.tap
            s.eps_assigner = self.assigner

    # --------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.supervisor = Supervisor()
        self.supervisor.spawn("liveloop-tap", self._tap_iteration)
        self.supervisor.spawn("liveloop-ingest", self._ingest_iteration)

    def _tap_iteration(self) -> None:
        # chaos drill: an "error" here exercises supervised restart; the
        # record queue is the crash boundary (un-drained records survive)
        fault_point("liveloop.tap")
        self.tap.process_pending(timeout=0.25)

    def _ingest_iteration(self) -> None:
        fault_point("liveloop.ingest")
        self.bridge.drain_once(timeout=0.25)

    def stop(self) -> None:
        """Detach the hooks, stop the workers, then run the final drains
        inline: queued records are accumulated, in-flight partial blocks
        are cut (bootstrapped from their pending Q), and everything
        emitted lands in replay before this returns."""
        for s in self._servers:
            s.tap = None
            s.eps_assigner = None
        if self.supervisor is not None:
            self.supervisor.stop.set()
            for w in self.supervisor.workers:
                w.join(timeout=5.0)
            self.supervisor = None
        self.tap.process_pending(timeout=0.0)
        self.tap.flush()
        self.bridge.drain_once(timeout=0.0)

    def check(self) -> dict:
        """Surface worker restart/stall counters (raises if a liveloop
        worker died for good — same loud-failure contract as the learner)."""
        return self.supervisor.check() if self.supervisor is not None else {}

    def stats(self) -> dict:
        out = {}
        out.update(self.tap.stats())
        out.update(self.bridge.stats())
        out.update(self.assigner.stats())
        return out
