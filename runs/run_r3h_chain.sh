#!/bin/bash
# Round-3 chain H: after chain G. The LRU core vs the 84x84 memory wall.
# Every LSTM attack on 84x84 memory catch failed (PARITY.md frontier
# table) while the LRU solved the 26x26 task 7x faster than the LSTM
# (runs/mc_mid_lru). Same discriminating-experiment setup as
# mc84_small_cue60 (cue 60 -> 22 blind steps, mid-scale recipe) with
# recurrent_core=lru. Learns => the flagship-scale memory positive at
# the round-2 bar (blind span >= 20, eval >= +0.5), and the zero-state
# ablation runs at the SAME scale to complete the "done" pair. Fails =>
# the 40x40 frontier point charts the LRU's own frontier.
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

while ! grep -q R3G_CHAIN_ALL_DONE runs/r3g_chain.log 2>/dev/null; do sleep 60; done

run_with_retry python examples/catch_demo.py --out runs/mc84_lru \
  --env memory_catch:60 --size 84 --steps 60000 --mode fused \
  --set recurrent_core=lru
echo "=== MC84_LRU EXIT: $? ==="
EV=$(last_eval runs/mc84_lru/eval.jsonl)
echo "=== MC84_LRU EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_lru_zerostate \
    --env memory_catch:60 --size 84 --steps 60000 --mode fused \
    --set recurrent_core=lru --ablate-zero-state
  echo "=== MC84_LRU_ZEROSTATE EXIT: $? ==="
else
  run_with_retry python examples/catch_demo.py --out runs/mc_frontier40_lru \
    --env memory_catch:16 --size 40 --steps 48000 --mode fused \
    --set recurrent_core=lru
  echo "=== FRONTIER40_LRU EXIT: $? ==="
fi

echo R3H_CHAIN_ALL_DONE
