"""Learner (L4): the jitted double-Q, value-rescaled, prioritized update.

Capability parity with the reference Learner (reference worker.py:330-461),
re-architected as ONE pure jitted function over a device mesh:

- double-Q target: a* = argmax_a Q_online(s_{t+n}, a) under stop_gradient,
  evaluated by the target net; y = h(R_n + gamma_n * h^-1(Q_target))
  (worker.py:402-410).
- IS-weighted per-step MSE over valid learning steps (worker.py:419); the
  reference repeats IS weights per step and takes a flat mean over the
  packed steps — identical here as sum(w * td^2 * mask) / sum(mask).
- mixed per-sequence TD priorities computed ON DEVICE in the same jit
  (worker.py:422-425 pays a device->host sync before priority math; here
  only the final (B,) priorities travel to the host).
- Adam(lr=1e-4, eps=1e-3) after global-norm clip 40 (worker.py:344,430).
- target sync folded into the jitted step as a where-select every
  `target_net_update_interval` updates (worker.py:445-447) — no separate
  host-side copy pass.

Per update this runs 2 conv + 2 LSTM evaluations (online, target) vs the
reference's 3 + 3, because `unroll` yields both gather views in one pass
(see models/r2d2.py).

Distribution: with the batch sharded over the mesh's dp axis and params
replicated, XLA inserts the gradient psum automatically — the test suite
asserts 8-fake-device equivalence with the single-device update.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import optax
from flax import struct

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.models.r2d2 import R2D2Network
from r2d2_tpu.ops.priority import mixed_td_priorities
from r2d2_tpu.ops.value_rescale import inverse_value_rescale, value_rescale
from r2d2_tpu.replay.replay_buffer import SampledBatch


class TrainState(struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jnp.ndarray  # scalar int32


class DeviceBatch(NamedTuple):
    """The device-side view of a SampledBatch (jnp arrays).

    `task` is the multi-task plane's per-sequence task id (B,) int32; it
    defaults to None so every single-task constructor, pytree, and
    donation contract is unchanged (a None leaf is absent from the tree)."""

    obs: jnp.ndarray
    last_action: jnp.ndarray
    last_reward: jnp.ndarray
    hidden: jnp.ndarray
    action: jnp.ndarray
    n_step_reward: jnp.ndarray
    gamma: jnp.ndarray
    burn_in_steps: jnp.ndarray
    learning_steps: jnp.ndarray
    forward_steps: jnp.ndarray
    is_weights: jnp.ndarray
    task: Optional[jnp.ndarray] = None

    @classmethod
    def from_sampled(cls, b: SampledBatch) -> "DeviceBatch":
        return cls(
            obs=jnp.asarray(b.obs),
            last_action=jnp.asarray(b.last_action, jnp.int32),
            last_reward=jnp.asarray(b.last_reward),
            hidden=jnp.asarray(b.hidden),
            action=jnp.asarray(b.action, jnp.int32),
            n_step_reward=jnp.asarray(b.n_step_reward),
            gamma=jnp.asarray(b.gamma),
            burn_in_steps=jnp.asarray(b.burn_in_steps),
            learning_steps=jnp.asarray(b.learning_steps),
            forward_steps=jnp.asarray(b.forward_steps),
            is_weights=jnp.asarray(b.is_weights),
            task=None if b.task is None else jnp.asarray(b.task, jnp.int32),
        )


def _adam(cfg: R2D2Config) -> optax.GradientTransformation:
    """The Adam tail of the optimizer chain — split out so the manual-
    partition step can run EXACTLY these numerics on moment SHARDS (its
    global-norm clip needs cross-shard psums, but Adam is elementwise, so
    the same transformation applies per-shard unchanged)."""
    if cfg.lr_schedule == "cosine":
        # decays over training_steps then HOLDS at lr*lr_final_frac (a
        # resumed run past the horizon keeps the floor, it does not
        # re-warm). Position comes from adam's own update count, which
        # is part of the checkpointed opt_state.
        lr = optax.cosine_decay_schedule(
            cfg.lr, max(cfg.training_steps, 1), alpha=cfg.lr_final_frac
        )
    else:
        lr = cfg.lr
    return optax.adam(lr, eps=cfg.adam_eps)


def make_optimizer(cfg: R2D2Config) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_norm),
        _adam(cfg),
    )


def init_train_state(cfg: R2D2Config, rng: jax.Array) -> Tuple[R2D2Network, TrainState]:
    from r2d2_tpu.models.r2d2 import init_params

    net, params = init_params(rng, cfg)
    opt_state = make_optimizer(cfg).init(params)
    return net, TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
    )


def make_loss_fn(cfg: R2D2Config, net: R2D2Network):
    """The per-batch loss closure (params, target_params, batch, denom) ->
    (loss, (priorities, aux)), shared by every train-step builder and by
    the bench's per-phase breakdown (which times it as its own jitted
    program to isolate loss+grad cost from the optimizer)."""
    eps = cfg.value_rescale_eps

    def loss_fn(params, target_params, b: DeviceBatch, denom):
        """denom is the GLOBAL valid-step count: under shard_map it has
        already been psum'd over dp, so per-shard losses are global-loss
        contributions and a grad psum reproduces the global-batch gradient
        exactly (per-shard mask sums differ, so pmean of local ratios would
        not)."""
        # b.task is None on the single-task golden path (a no-op input);
        # multi-task batches condition the dueling head per sequence
        q_learn, q_boot_online, mask = net.apply(
            params, b.obs, b.last_action, b.last_reward, b.hidden,
            b.burn_in_steps, b.learning_steps, b.forward_steps, b.task,
        )
        _, q_boot_target, _ = net.apply(
            target_params, b.obs, b.last_action, b.last_reward, b.hidden,
            b.burn_in_steps, b.learning_steps, b.forward_steps, b.task,
        )
        # fp32 island (precision policy, config.precision): Q-target math,
        # value rescaling, n-step folding, TD/priorities, IS weighting,
        # and the loss reduction stay float32 no matter the compute dtype.
        # The heads already emit f32 (models/r2d2.py _dueling); the casts
        # pin the contract so a future bf16 head cannot silently narrow
        # the target math (tests/test_precision.py asserts the island).
        # double-Q: online selects, target evaluates (worker.py:402-406)
        a_star = jnp.argmax(jax.lax.stop_gradient(q_boot_online), axis=-1)  # (B, L)
        q_tgt = jnp.take_along_axis(q_boot_target, a_star[..., None], axis=-1)[..., 0]
        q_tgt = q_tgt.astype(jnp.float32)
        y = value_rescale(
            b.n_step_reward.astype(jnp.float32)
            + b.gamma.astype(jnp.float32) * inverse_value_rescale(q_tgt, eps),
            eps,
        )
        y = jax.lax.stop_gradient(y)

        q_taken = jnp.take_along_axis(q_learn, b.action[..., None], axis=-1)[..., 0]
        q_taken = q_taken.astype(jnp.float32)
        td = y - q_taken
        w = b.is_weights.astype(jnp.float32)[:, None]
        loss = jnp.sum(w * jnp.square(td) * mask) / denom

        abs_td = jnp.abs(td) * mask
        priorities = mixed_td_priorities(abs_td, mask, cfg.td_mix_eta)
        aux = {
            "q_mean": jnp.sum(q_taken * mask) / denom,
            "target_mean": jnp.sum(y * mask) / denom,
            "td_abs_mean": jnp.sum(abs_td) / denom,
        }
        return loss, (priorities, aux)

    return loss_fn


def _raw_train_step(cfg: R2D2Config, net: R2D2Network, axis_name: Optional[str] = None):
    """The un-jitted (state, batch) -> (state, metrics, priorities) body,
    shared by the host-batch and device-store (fused) entry points.

    axis_name=None: pure single-program body — under plain jit with the
    batch sharded over a mesh, XLA inserts the gradient all-reduce itself.
    axis_name="dp": the body runs per-shard under shard_map and all-reduces
    gradients/metrics with an explicit lax.psum over the named axis (exact
    because the loss denominator is psum'd globally first; the collective
    rides ICI on a real slice)."""
    optimizer = make_optimizer(cfg)
    loss_fn = make_loss_fn(cfg, net)

    def train_step(state: TrainState, b: DeviceBatch):
        if cfg.zero_state_replay:
            # zero-state ablation (R2D2 paper's baseline replay strategy):
            # discard the stored recurrent state; one site covers every
            # plane because all step builders share this body
            b = b._replace(hidden=jnp.zeros_like(b.hidden))
        # valid learning steps: mask row i has exactly learning_steps[i] ones
        denom = jnp.sum(b.learning_steps).astype(jnp.float32)
        if axis_name is not None:
            denom = jax.lax.psum(denom, axis_name)
        denom = jnp.maximum(denom, 1.0)
        (loss, (priorities, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.target_params, b, denom
        )
        if axis_name is not None:
            grads = jax.lax.psum(grads, axis_name)
            loss = jax.lax.psum(loss, axis_name)
            aux = jax.tree.map(lambda x: jax.lax.psum(x, axis_name), aux)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        # target sync every interval, inside the compiled step
        sync = (step % cfg.target_net_update_interval) == 0
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), state.target_params, params
        )
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            **aux,
        }
        new_state = TrainState(
            params=params, target_params=target_params, opt_state=opt_state, step=step
        )
        return new_state, metrics, priorities

    return train_step


def make_train_step(cfg: R2D2Config, net: R2D2Network, donate: bool = True):
    """Jitted (state, batch) -> (state, metrics, priorities) over a
    host-assembled DeviceBatch."""
    raw = _raw_train_step(cfg, net)
    return jax.jit(raw, donate_argnums=(0,) if donate else ())


def make_store_gather(cfg: R2D2Config):
    """(stores, b, s, is_weights) -> DeviceBatch: in-jit clamped-window
    gather straight out of the HBM-resident stores. b is a block index
    LOCAL to whatever store shard the caller passes (the whole store under
    plain jit; one dp shard under shard_map)."""
    L, T = cfg.learning_steps, cfg.seq_len
    slot, bl = cfg.block_slot_len, cfg.block_length

    def gather_batch(stores, b, s, is_weights) -> DeviceBatch:
        burn = stores["burn_in"][b, s]
        learn = stores["learning"][b, s]
        fwd = stores["forward"][b, s]
        first_burn = stores["burn_in"][b, 0]
        start = first_burn + s * L
        win = start - burn
        t = jnp.arange(T, dtype=jnp.int32)
        rows = jnp.clip(win[:, None] + t[None, :], 0, slot - 1)
        bcol = b[:, None]
        lrow = jnp.clip(s[:, None] * L + jnp.arange(L, dtype=jnp.int32)[None, :], 0, bl - 1)
        return DeviceBatch(
            obs=stores["obs"][bcol, rows],
            last_action=stores["last_action"][bcol, rows],
            last_reward=stores["last_reward"][bcol, rows],
            hidden=stores["hidden"][b, s],
            action=stores["action"][bcol, lrow],
            n_step_reward=stores["n_step_reward"][bcol, lrow],
            gamma=stores["gamma"][bcol, lrow],
            burn_in_steps=burn,
            learning_steps=learn,
            forward_steps=fwd,
            is_weights=is_weights,
            # the task store exists only when the config runs multi-task
            # (replay/block.store_field_specs) — single-task stores keep
            # their exact field set and this stays a None leaf
            task=stores["task"][b, s] if "task" in stores else None,
        )

    return gather_batch


def make_fused_train_step(cfg: R2D2Config, net: R2D2Network, donate: bool = True):
    """Train step over a DEVICE-RESIDENT replay store.

    Signature: (state, stores, b, s, is_weights) -> (state, metrics,
    priorities). The batch windows are gathered in-jit straight from HBM
    (see replay/device_store.py), so only the (B,) sample coordinates cross
    the host->device boundary per update — the whole point on hardware
    where transfer, not compute, bounds the learner. Numerically identical
    to make_train_step on the equivalent host-assembled batch (pinned by
    test)."""
    raw = _raw_train_step(cfg, net)
    gather_batch = make_store_gather(cfg)

    def fused(state: TrainState, stores, b, s, is_weights):
        batch = gather_batch(stores, b, s, is_weights)
        return raw(state, batch)

    return jax.jit(fused, donate_argnums=(0,) if donate else ())


def make_fused_multi_train_step(
    cfg: R2D2Config, net: R2D2Network, num_steps: int, donate: bool = True
):
    """K train steps in ONE dispatch: lax.scan over stacked sample
    coordinates, each iteration gathering its batch from the HBM store and
    applying the full update (in-jit target sync included).

    Exactly equivalent to running the K single fused steps sequentially on
    the same pre-drawn coordinates (pinned by test) — the host simply was
    not involved between them. This is the dispatch-latency amortizer: on
    hardware where each jit call costs ~milliseconds of launch/tunnel
    latency, per-update overhead drops K-fold. The semantic trade is that
    priorities and new blocks apply to the tree at K-update granularity —
    the reference's own pipeline already tolerates a deeper lag (its batch
    queue + learner prefetch hold ~12 batches, reference worker.py:364-371).

    Signature: (state, stores, b, s, w) with b/s/w of shape (K, B);
    returns (state, metrics-of-last-step, priorities (K, B))."""
    return jax.jit(
        make_multi_update_core(cfg, net, num_steps),
        donate_argnums=(0,) if donate else (),
    )


def make_multi_update_core(
    cfg: R2D2Config, net: R2D2Network, num_steps: int,
    axis_name: Optional[str] = None,
    is_from_priorities: bool = False,
):
    """The un-jitted K-update scan body shared by
    make_fused_multi_train_step and megastep.make_megastep — one
    definition so the two dispatch paths cannot diverge.

    axis_name="dp": the body runs per-shard under shard_map — gathers hit
    the LOCAL store shard and gradients/denominators psum over the axis
    (same contract as make_sharded_fused_train_step); b/s/w are then the
    local (K, B/dp) coordinate stacks.

    is_from_priorities=True (needs axis_name): w carries RAW sampled tree
    priorities; each scan iteration normalizes ITS OWN batch against that
    update's batch-global minimum via a pmin over the axis — per-update
    semantics identical to K single is_from_priorities steps (the
    multihost K-dispatch contract, replay/multihost_store.py)."""
    if is_from_priorities and axis_name is None:
        raise ValueError("is_from_priorities needs an axis_name (pmin)")
    raw = _raw_train_step(cfg, net, axis_name=axis_name)
    gather_batch = make_store_gather(cfg)

    def multi(state: TrainState, stores, b, s, w):
        if b.shape[0] != num_steps:
            raise ValueError(
                f"coordinate stack has {b.shape[0]} steps, expected {num_steps}"
            )

        def body(state, xs):
            bb, ss, ww = xs
            if is_from_priorities:
                # same formula as make_sharded_fused_train_step's body
                p = ww
                pos_min = jnp.min(jnp.where(p > 0, p, jnp.inf))
                min_p = jax.lax.pmin(pos_min, axis_name)
                min_p = jnp.where(jnp.isfinite(min_p), min_p, 1.0)
                ww = jnp.power(jnp.maximum(p, min_p) / min_p, -cfg.is_exponent)
            batch = gather_batch(stores, bb, ss, ww)
            state, metrics, prios = raw(state, batch)
            return state, (metrics, prios)

        state, (metrics, prios) = jax.lax.scan(body, state, (b, s, w))
        return state, jax.tree.map(lambda x: x[-1], metrics), prios

    return multi


def make_sharded_fused_multi_train_step(
    cfg: R2D2Config, net: R2D2Network, mesh, num_steps: int, donate: bool = True,
    is_from_priorities: bool = False,
):
    """K updates in ONE shard_map dispatch over a dp-SHARDED replay store:
    the multi-chip form of make_fused_multi_train_step. Each device scans
    K updates gathering its (B/dp) sub-batches from its LOCAL store shard
    and psums gradients over dp per update (ICI).

    Signature: (state, stores, b, s, w) with b/s/w of shape (K, dp, B/dp)
    and b LOCAL to each shard; returns (state, metrics-of-last-step,
    priorities (K, dp, B/dp)). is_from_priorities: see
    make_multi_update_core — w carries raw priorities, normalized per
    update with a pmin over dp (the multihost K-dispatch path)."""
    from jax.sharding import PartitionSpec as P
    from r2d2_tpu.parallel.jax_compat import shard_map

    multi = make_multi_update_core(
        cfg, net, num_steps, axis_name="dp", is_from_priorities=is_from_priorities
    )

    def body(state: TrainState, stores, b, s, w):
        # local views: stores (nb/dp, ...), b/s/w (K, 1, B/dp)
        state, metrics, prios = multi(state, stores, b[:, 0], s[:, 0], w[:, 0])
        return state, metrics, prios[:, None]

    # P("dp") is a PREFIX spec for the stores dict: it applies to every
    # field array (same idiom as make_sharded_fused_train_step).
    # axis_names={"dp"}: the map is MANUAL over dp only — the mesh's tp
    # axis stays GSPMD-auto, so params arriving with tp NamedShardings
    # (parallel/mesh.train_state_shardings) are Megatron-partitioned
    # inside the per-dp-shard body by the compiler, composing dp×tp.
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P("dp"), P(None, "dp"), P(None, "dp"), P(None, "dp")),
        out_specs=(P(), P(), P(None, "dp")),
        axis_names={"dp"},
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_gather_step(cfg: R2D2Config):
    """Jitted (stores, b, s, is_weights) -> DeviceBatch: materialize the
    sampled windows into a fresh HBM batch AT SAMPLE TIME.

    This is the pipelined-mode counterpart of the fused step: a queued
    fused-step item holds only coordinates, so a store slot overwritten
    while the item waits would be gathered as DIFFERENT data than was
    sampled. Gathering under the store lock at sample time freezes the
    batch; the queue then carries ~4 MB of HBM per item instead of a
    staleness hazard."""
    return jax.jit(make_store_gather(cfg))


def make_batch_train_step(cfg: R2D2Config, net: R2D2Network, donate: bool = True):
    """Jitted (state, DeviceBatch) -> (state, metrics, priorities) over a
    pre-gathered device-resident batch (from make_gather_step). Donates the
    batch too: it was materialized for exactly one update."""
    raw = _raw_train_step(cfg, net)
    return jax.jit(raw, donate_argnums=(0, 1) if donate else ())


def make_stacked_batch_train_step(
    cfg: R2D2Config, net: R2D2Network, num_steps: int, donate: bool = True
):
    """K train steps in ONE dispatch over a PRE-GATHERED stacked batch: the
    tiered plane's consumer. make_fused_multi_train_step's scan gathers each
    iteration's batch from the HBM-resident store; here the gather already
    happened on host at stage time (replay/tiered_store.py), so the scan is
    re-pointed at the staging slab — a DeviceBatch whose leaves carry a
    leading (K, ...) axis — and each iteration just slices its batch off.

    Donating the batch (argnum 1) is what closes the staging ring: the
    consumed slab's HBM is recycled into the next device_put instead of
    accumulating a third live copy.

    Signature: (state, stacked DeviceBatch with (K, B, ...) leaves) ->
    (state, metrics-of-last-step, priorities (K, B))."""
    raw = _raw_train_step(cfg, net)

    def multi(state: TrainState, stacked: DeviceBatch):
        if stacked.obs.shape[0] != num_steps:
            raise ValueError(
                f"staged batch has {stacked.obs.shape[0]} steps, "
                f"expected {num_steps}"
            )

        def body(state, batch):
            state, metrics, prios = raw(state, batch)
            return state, (metrics, prios)

        state, (metrics, prios) = jax.lax.scan(body, state, stacked)
        return state, jax.tree.map(lambda x: x[-1], metrics), prios

    return jax.jit(multi, donate_argnums=(0, 1) if donate else ())


def make_sharded_gather_step(cfg: R2D2Config, mesh):
    """shard_map gather over the dp-sharded stores: each device gathers its
    (B/dp) sub-batch locally; the result is one global DeviceBatch with
    every leaf's batch axis sharded over dp — ready for the plain-jit train
    step (XLA inserts the gradient psum)."""
    from jax.sharding import PartitionSpec as P
    from r2d2_tpu.parallel.jax_compat import shard_map

    gather_batch = make_store_gather(cfg)

    def body(stores, b, s, is_weights):
        return gather_batch(stores, b[0], s[0], is_weights[0])

    out_specs = DeviceBatch(*([P("dp")] * len(DeviceBatch._fields)))
    if cfg.num_tasks <= 1:
        # single-task gathers return task=None; the spec tree must carry
        # the same empty subtree for the structures to match
        out_specs = out_specs._replace(task=None)
    gathered = shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
        out_specs=out_specs,
        axis_names={"dp"},
        check_vma=False,
    )
    return jax.jit(gathered)


def make_sharded_fused_train_step(
    cfg: R2D2Config,
    net: R2D2Network,
    mesh,
    donate: bool = True,
    is_from_priorities: bool = False,
):
    """Fused train step over a dp-SHARDED device replay store
    (replay/sharded_store.ShardedDeviceReplay).

    shard_map over the mesh's dp axis: each device gathers its local
    (B/dp)-sequence sub-batch from its OWN store shard — no cross-device
    data-plane traffic — computes local gradients, and all-reduces them
    with lax.psum over dp (ICI; exact thanks to the globally-psum'd loss
    denominator). Params/opt state replicated in and out.

    Signature: (state, stores, b, s, is_weights) -> (state, metrics,
    priorities) where b/s/is_weights are (dp, B/dp) stacked per-shard
    coordinates with b LOCAL to each shard, and priorities come back
    (dp, B/dp).

    is_from_priorities=True: the third coordinate array carries RAW sampled
    tree priorities instead of precomputed IS weights; the step normalizes
    them in-jit against the BATCH-GLOBAL minimum via a pmin collective over
    dp. This is how the multi-host replay gets exact single-tree IS
    semantics with zero cross-host control traffic (replay/
    multihost_store.py) — each host only knows its local priorities, the
    collective finds the global min."""
    from jax.sharding import PartitionSpec as P
    from r2d2_tpu.parallel.jax_compat import shard_map

    raw = _raw_train_step(cfg, net, axis_name="dp")
    gather_batch = make_store_gather(cfg)

    def body(state: TrainState, stores, b, s, is_weights):
        # local views: stores = this device's (nb/dp, ...) block shard;
        # b/s/is_weights arrive (1, B/dp) from their stacked (dp, B/dp) form
        w = is_weights[0]
        if is_from_priorities:
            p = w
            pos_min = jnp.min(jnp.where(p > 0, p, jnp.inf))
            min_p = jax.lax.pmin(pos_min, "dp")
            min_p = jnp.where(jnp.isfinite(min_p), min_p, 1.0)
            # same formula as SumTree.sample (zero-priority leaves clamp
            # to the min -> weight 1.0)
            w = jnp.power(jnp.maximum(p, min_p) / min_p, -cfg.is_exponent)
        batch = gather_batch(stores, b[0], s[0], w)
        new_state, metrics, priorities = raw(state, batch)
        return new_state, metrics, priorities[None, :]

    # manual over dp only; tp stays GSPMD-auto (see
    # make_sharded_fused_multi_train_step) so tp-sharded params compose
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P("dp"), P("dp"), P("dp"), P("dp")),
        out_specs=(P(), P(), P("dp")),
        axis_names={"dp"},
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_manual_train_step(cfg: R2D2Config, mesh, donate: bool = True):
    """Fully-manual shard_map train step over ALL mesh axes — the tp×fsdp
    path that GSPMD miscompiles (PR 14: tp-sharded params on a 3-axis mesh
    break the recurrent scan's forward; config.resolved_partitioning routes
    here instead of blocking).

    Partitioning (every spec read from parallel/sharding_map's table, so
    this step and the GSPMD planes cannot disagree about placement):

      tp    Megatron splits inside the per-shard network itself
            (R2D2Network.from_config(manual_tp=tp)): column-parallel gate
            kernels with an explicit per-step all-gather seam at the gate
            matmul (models/lstm._gates), column/row dueling heads with a
            psum seam (models/r2d2.RowDense), column-parallel encoder
            Dense_0. Params replicated over dp and fsdp.
      dp    batch data parallelism, explicit gradient psum.
      fsdp  ZeRO-2: the batch ALSO splits over fsdp (manual_data_axes), so
            each fsdp member owns gradients for a distinct batch slice and
            the gradient lands on the Adam moment shards via a TRUE
            reduce-scatter (psum_scatter); Adam runs on shards; updates
            all-gather back to replicated params.

    Gradient correctness under manual tp (validated bit-level against the
    unsharded reference): the per-device AD gradient equals the derivative
    of the SUM of all tp members' objectives w.r.t. the local shard, so
    with the loss scaled by 1/tp inside value_and_grad, tp-SHARDED leaves'
    local grads are already exact per-shard (no collective), while
    REPLICATED leaves (convs, row-parallel biases, deeper encoder Dense,
    LRU params) need an extra psum over tp to sum their members'
    contributions.

    The global-norm clip reproduces optax.clip_by_global_norm exactly:
    per-leaf shard sum-of-squares are psum'd over exactly the axes that
    leaf is sharded over (tp for table-sharded leaves, fsdp for scattered
    ones), summed, sqrt'd — the same global norm every device, then the
    identical where/scale formula. Adam itself is elementwise, so the
    _adam(cfg) tail runs unchanged on moment shards.

    Signature: jitted (state, batch) -> (state, metrics, priorities) where
    state leaves are placed per train_state_shardings(mesh) and batch
    leaves are sharded over (dp, fsdp) on their leading axis
    (parallel.manual_batch_sharding)."""
    from jax.sharding import PartitionSpec as P
    from r2d2_tpu.parallel.jax_compat import shard_map
    from r2d2_tpu.parallel.mesh import manual_data_axes
    from r2d2_tpu.parallel.sharding_map import (
        moment_spec_for,
        process_name,
        spec_for,
        tree_pspecs,
    )

    tp = int(mesh.shape.get("tp", 1))
    data_axes = manual_data_axes(mesh)
    n_data = 1
    for a in data_axes:
        n_data *= int(mesh.shape[a])
    if cfg.batch_size % n_data != 0:
        raise ValueError(
            f"batch_size {cfg.batch_size} not divisible by dp*fsdp={n_data}"
        )
    has_fsdp = "fsdp" in mesh.axis_names and int(mesh.shape["fsdp"]) > 1

    # the per-shard network: kernels declared at their LOCAL (1/tp) widths,
    # collective seams inside the module bodies
    local_net = R2D2Network.from_config(cfg, manual_tp=tp)
    loss_fn = make_loss_fn(cfg, local_net)
    adam = _adam(cfg)

    # abstract GLOBAL TrainState -> spec trees + per-param-leaf grad plan
    template = jax.eval_shape(
        lambda k: init_train_state(cfg, k)[1], jax.random.PRNGKey(0)
    )
    state_specs = tree_pspecs(template, mesh)
    params_treedef = jax.tree.structure(template.params)
    grad_plan = []  # aligned with jax.tree.leaves(params): (tp_sharded, fdim)
    for path, leaf in jtu.tree_flatten_with_path(template.params)[0]:
        name = process_name(path)
        pspec = tuple(spec_for(name, leaf, mesh))
        mspec = tuple(moment_spec_for(name, leaf, mesh))
        tp_sharded = tp > 1 and "tp" in pspec
        fdim = mspec.index("fsdp") if (has_fsdp and "fsdp" in mspec) else None
        grad_plan.append((tp_sharded, fdim))

    batch_spec = P(data_axes)
    in_batch = DeviceBatch(*([batch_spec] * len(DeviceBatch._fields)))
    if cfg.num_tasks <= 1:
        in_batch = in_batch._replace(task=None)

    def body(state: TrainState, b: DeviceBatch):
        if cfg.zero_state_replay:
            b = b._replace(hidden=jnp.zeros_like(b.hidden))
        denom = jnp.sum(b.learning_steps).astype(jnp.float32)
        denom = jnp.maximum(jax.lax.psum(denom, data_axes), 1.0)

        def objective(params, target_params, b, denom):
            loss, extras = loss_fn(params, target_params, b, denom)
            # 1/tp balances AD's accumulation across the tp group (see
            # docstring); exact no-op at tp=1
            return loss / tp, extras

        (loss, (priorities, aux)), grads = jax.value_and_grad(
            objective, has_aux=True
        )(state.params, state.target_params, b, denom)

        # summing the scaled per-member losses over every axis recovers the
        # global loss (tp members carry identical copies at weight 1/tp)
        loss = jax.lax.psum(loss, data_axes + ("tp",))
        aux = jax.tree.map(lambda x: jax.lax.psum(x, data_axes), aux)

        # gradient reduction per the plan: dp always; +tp for replicated
        # leaves; fsdp by reduce-scatter onto the moment shard's dim when
        # it has one (ZeRO-2), full psum otherwise
        def reduce_grad(g, tp_sharded, fdim):
            axes = ["dp"]
            if tp > 1 and not tp_sharded:
                axes.append("tp")
            if has_fsdp and fdim is None:
                axes.append("fsdp")
            g = jax.lax.psum(g, tuple(axes))
            if has_fsdp and fdim is not None:
                g = jax.lax.psum_scatter(
                    g, "fsdp", scatter_dimension=fdim, tiled=True
                )
            return g

        flat_g = [
            reduce_grad(g, tps, fd)
            for g, (tps, fd) in zip(jax.tree.leaves(grads), grad_plan)
        ]

        # global-norm clip == optax.clip_by_global_norm on the full grads:
        # group leaves by which axes still shard them after reduction
        partial_sq: Dict[tuple, jnp.ndarray] = {}
        for g, (tps, fd) in zip(flat_g, grad_plan):
            axes = []
            if tps:
                axes.append("tp")
            if fd is not None:
                axes.append("fsdp")
            key = tuple(axes)
            sq = jnp.sum(jnp.square(g))
            partial_sq[key] = partial_sq.get(key, 0.0) + sq
        total_sq = jnp.float32(0.0)
        for axes, sq in partial_sq.items():
            total_sq = total_sq + (jax.lax.psum(sq, axes) if axes else sq)
        gnorm = jnp.sqrt(total_sq)
        trigger = gnorm < cfg.grad_norm
        flat_g = [
            jnp.where(trigger, g, (g / gnorm.astype(g.dtype)) * cfg.grad_norm)
            for g in flat_g
        ]
        grads = jax.tree.unflatten(params_treedef, flat_g)

        # Adam on shards; updates gather back to replicated param layout
        clip_state, adam_state = state.opt_state
        updates, adam_state = adam.update(grads, adam_state)
        if has_fsdp:
            flat_u = [
                jax.lax.all_gather(u, "fsdp", axis=fd, tiled=True)
                if fd is not None
                else u
                for u, (_, fd) in zip(jax.tree.leaves(updates), grad_plan)
            ]
            updates = jax.tree.unflatten(params_treedef, flat_u)
        params = optax.apply_updates(state.params, updates)

        step = state.step + 1
        sync = (step % cfg.target_net_update_interval) == 0
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), state.target_params, params
        )
        metrics = {"loss": loss, "grad_norm": gnorm, **aux}
        new_state = TrainState(
            params=params,
            target_params=target_params,
            opt_state=(clip_state, adam_state),
            step=step,
        )
        return new_state, metrics, priorities

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, in_batch),
        out_specs=(state_specs, P(), batch_spec),
        axis_names=None,  # fully manual over EVERY mesh axis
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
