"""Model layer (L2): flax networks compiled by XLA for the TPU MXU.

The reference's Network (reference model.py:35-188) exposes three forwards:
single-step acting, full-sequence target Q, and burn-in+learning Q. Here one
flax module exposes `act` (batched single step) and `unroll` (lax.scan over
the padded fixed-length sequence) — and `unroll` returns BOTH gather views
(learning-window Q and bootstrap-window Q) from a single LSTM pass, because
they differ only in output indexing. That collapses the reference's
3 conv + 3 LSTM evaluations per update to 2 + 2.
"""

from r2d2_tpu.models.encoders import ImpalaEncoder, MLPEncoder, NatureEncoder
from r2d2_tpu.models.lstm import LSTM
from r2d2_tpu.models.r2d2 import R2D2Network

__all__ = ["NatureEncoder", "ImpalaEncoder", "MLPEncoder", "LSTM", "R2D2Network"]
