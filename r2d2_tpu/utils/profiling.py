"""Tracing / profiling hooks (SURVEY.md section 5.1 rebuild).

The reference has no profiler at all — its only timing is wall-clock
minutes stored in checkpoints (reference worker.py:378,452) and derived
rates printed every 10 s (worker.py:126,135). Here:

- `start_profiler_server(port)` exposes the live process to
  `xprof`/TensorBoard-profile capture at any time (device + host traces).
- `trace_to(dir)` context manager records a bounded trace programmatically
  (e.g. `--profile-dir` on the trainer CLI traces the first post-warmup
  updates, where the steady-state pipeline shape is visible).
- `span(name)` / `step_span(name, step)` annotate HOST-side phases (replay
  sample, block pack, priority update) so they line up against device
  activity in the trace viewer. They are no-ops costing one context-manager
  enter/exit when no trace is being captured, so the hot paths keep them
  permanently.
- `TransferTimer` is the tiered replay plane's staging accountant: it
  measures how much of the host->HBM tunnel time is hidden behind update
  compute (the plane's whole reason to exist), without needing a trace
  capture.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

import jax

_server = None


class TransferTimer:
    """Host->device staging overlap accountant (tiered replay plane).

    Two accumulators, fed from different threads:
    - `h2d(nbytes)` spans wrap the STAGING side of a chunk — host window
      gather + device_put + transfer completion — measured on the staging
      thread, off the critical path.
    - `wait()` spans wrap the CONSUMER side — the time the update loop
      actually stalled waiting for a staged chunk to be ready.

    overlap_fraction = 1 - wait/h2d, clamped to [0, 1]: 1.0 means every
    byte of tunnel time was hidden behind compute (the consumer never
    waited), 0.0 means staging was fully serialized ahead of the updates
    (the inline host plane's behavior). Thread-safe; `reset()` rebases the
    window so a bench can exclude compile/warmup chunks."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.h2d_seconds = 0.0
            self.wait_seconds = 0.0
            self.bytes_staged = 0
            self.chunks = 0

    @contextlib.contextmanager
    def h2d(self, nbytes: int = 0) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.h2d_seconds += dt
                self.bytes_staged += nbytes
                self.chunks += 1

    @contextlib.contextmanager
    def wait(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.wait_seconds += dt

    def overlap_fraction(self) -> float:
        with self._lock:
            if self.h2d_seconds <= 0.0:
                return 1.0
            return max(0.0, min(1.0, 1.0 - self.wait_seconds / self.h2d_seconds))

    def stats(self) -> dict:
        """One flat dict for metrics/bench JSON."""
        with self._lock:
            h2d, wait = self.h2d_seconds, self.wait_seconds
            chunks, staged = self.chunks, self.bytes_staged
        frac = 1.0 if h2d <= 0.0 else max(0.0, min(1.0, 1.0 - wait / h2d))
        return {
            "h2d_overlap_fraction": round(frac, 4),
            "h2d_seconds": round(h2d, 4),
            "h2d_wait_seconds": round(wait, 4),
            "h2d_chunks": chunks,
            "h2d_gbytes_staged": round(staged / 1e9, 3),
        }


def start_profiler_server(port: int = 9012) -> None:
    """Idempotent: starts the jax.profiler server once per process."""
    global _server
    if _server is None:
        _server = jax.profiler.start_server(port)


@contextlib.contextmanager
def trace_to(log_dir: Optional[str]) -> Iterator[None]:
    """Record a profiler trace into `log_dir` for the duration of the
    context; None disables (zero overhead)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def span(name: str):
    """Named host-span annotation visible in the trace viewer."""
    return jax.profiler.TraceAnnotation(name)


def step_span(name: str, step: int):
    """Step-correlated span: groups device work under learner step N."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)
