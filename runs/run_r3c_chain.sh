#!/bin/bash
# Round-3 chain C: runs after run_r3b_chain.sh drains. LRU-core evidence
# plus the core-unroll scaling microbench, then the round bench.
#   1. core-unroll microbench: LSTM(pallas/scan) vs LRU forward unroll
#      time at T=128..1024 on the real chip (the LRU's O(log T) claim)
#   2. LRU learning evidence: the solved mid-scale memory-catch recipe
#      with recurrent_core=lru — same task, same budget, different core;
#      memory is load-bearing (cue task), so a positive shows the
#      linear-recurrence state carries the cue end to end
cd /root/repo
while ! grep -q R3B_CHAIN_ALL_DONE runs/r3b_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

python runs/bench_core_unroll.py --out runs/core_unroll.jsonl
echo "=== CORE_UNROLL EXIT: $? ==="

run_with_retry python examples/catch_demo.py --out runs/mc_mid_lru \
  --env memory_catch:10 --steps 48000 --mode fused --eval-episodes 4 \
  --set recurrent_core=lru
echo "=== MC_MID_LRU EXIT: $? ==="

echo R3C_CHAIN_ALL_DONE
