#!/bin/bash
# Round-5 chain D: make the 16x16 procmaze rung decisive (VERDICT r4
# item 5) and land the multi-env sweep artifact (item 6).
#
# Procmaze: the round-4 warm-started run held +0.02..+0.038 over the
# 0.137 baseline across its final five n=256 checkpoints but was read as
# exploration-bound. This arm resumes from its step-60000 checkpoint
# with DOUBLE the fresh budget (60k updates) and the exploration lever
# pulled: eps_alpha 7 -> 3 flattens the Ape-X ladder so the actor fleet
# spends far more of its time at epsilon 0.05..0.4 instead of
# concentrating near the greedy floor. Verdict comes from
# runs/eval_stats.py: per-episode returns, stderr, and a z-score against
# the null distribution measured through the SAME device collector at
# epsilon=1 — "final checkpoints >= baseline + 3 sigma" is now a number.
#
# Sweep: one artifact per env family (obs geometries differ), both under
# runs/sweep_r5/: the catch family at 84x84 through the atari preset and
# procmaze through procgen_impala — converting sweep.py (BASELINE
# config 3's driver, unit-tested but never driven) into a driven tool.
cd /root/repo
while ! grep -q R5C_CHAIN_ALL_DONE runs/r5c_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

mkdir -p runs/procmaze16_warm2/ckpt
if [ ! -d runs/procmaze16_warm2/ckpt/step_60000 ]; then
  cp -r runs/procmaze16_warm/ckpt/step_60000 runs/procmaze16_warm2/ckpt/step_60000
fi
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
  --mode fused --steps 120000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze16_warm2/ckpt \
  --set metrics_path=runs/procmaze16_warm2/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=7500 \
  --set target_net_update_interval=500 --set forward_steps=20 \
  --set num_actors=16 --set eps_alpha=3.0
echo "=== PROCMAZE16_WARM2 TRAIN EXIT: $? ==="
python runs/eval_stats.py --preset procgen_impala --env procmaze_shaped:16 \
  --ckpt runs/procmaze16_warm2/ckpt --episodes 512 --null-episodes 2048 \
  --out runs/procmaze16_warm2/eval_stats.jsonl
echo "=== PROCMAZE16_WARM2 STATS EXIT: $? ==="

python -m r2d2_tpu.sweep --games catch memory_catch memory_catch:60 \
  --allow-any-env --preset atari --root runs/sweep_r5/catch_family \
  --steps 4000 --set learning_starts=20000 --set save_interval=2000
echo "=== SWEEP_CATCH EXIT: $? ==="
python -m r2d2_tpu.sweep --games procmaze_shaped procmaze_shaped:8 \
  --allow-any-env --preset procgen_impala --root runs/sweep_r5/procmaze \
  --steps 4000 --set learning_starts=20000 --set save_interval=2000
echo "=== SWEEP_PROCMAZE EXIT: $? ==="

echo R5D_CHAIN_ALL_DONE
