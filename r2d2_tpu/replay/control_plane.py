"""Shared host-side replay control plane.

Both replay buffers — host data plane (replay_buffer.ReplayBuffer) and HBM
data plane (device_store.DeviceReplayBuffer) — run the SAME control logic:
sum-tree priorities, circular block pointer, eviction/size accounting,
clamped stratified sampling of sequence coordinates, and the stale-priority
pointer-window rejection of reference worker.py:290-307. It lives here once
so a fix to any of the subtle parts (wrap-around masking, zero-leaf clamp)
cannot diverge between the two data planes.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.sum_tree import SumTree


def shard_config(cfg: R2D2Config, dp: int) -> R2D2Config:
    """The per-shard (1/dp) view of a config, for dp-sharded replay planes:
    each shard's control plane sees its slice of capacity/batch and knows
    nothing of the mesh."""
    return cfg.replace(
        buffer_capacity=cfg.buffer_capacity // dp,
        learning_starts=max(cfg.learning_starts // dp, 1),
        batch_size=cfg.batch_size // dp,
        dp_size=1,
        tp_size=1,
        replay_plane="host",
        collector="host",  # collection is the PARENT plane's concern
        updates_per_dispatch=1,
    )


class ReplayControlPlane:
    def __init__(self, cfg: R2D2Config, native: Optional[object] = None):
        self.cfg = cfg
        if native is None and cfg.use_native_replay:
            from r2d2_tpu._native import load_native

            native = load_native()  # None if the toolchain is unavailable
        self.native = native
        self.tree = SumTree(
            cfg.num_sequences, cfg.prio_exponent, cfg.is_exponent, native=native
        )
        self.block_ptr = 0
        self.size = 0
        self.env_steps = 0
        self.num_episodes = 0
        self.episode_reward_sum = 0.0
        # run-lifetime totals (never reset by pop_episode_stats)
        self.total_episodes = 0
        self.total_reward_sum = 0.0
        self.learning_sum = np.zeros(cfg.num_blocks, np.int64)
        self.occupied = np.zeros(cfg.num_blocks, bool)
        self.num_seq_store = np.zeros(cfg.num_blocks, np.int32)
        self.lock = threading.Lock()

    def __len__(self) -> int:
        return self.size

    def can_sample(self) -> bool:
        return self.size >= self.cfg.learning_starts

    # --- accounting (call with self.lock held) ----------------------------

    def _account_add(
        self, num_sequences: int, learning_total: int, priorities: np.ndarray,
        episode_reward: Optional[float],
    ) -> int:
        """Update tree + counters for a block landing at block_ptr; returns
        the slot index written. Caller holds the lock and writes the data
        plane for the same slot."""
        ptr = self.block_ptr
        S = self.cfg.seqs_per_block
        idxes = np.arange(ptr * S, (ptr + 1) * S, dtype=np.int64)
        self.tree.update(idxes, priorities)
        if self.occupied[ptr]:
            self.size -= int(self.learning_sum[ptr])
        self.learning_sum[ptr] = learning_total
        self.occupied[ptr] = True
        self.num_seq_store[ptr] = num_sequences
        self.size += learning_total
        self.env_steps += learning_total
        self.block_ptr = (ptr + 1) % self.cfg.num_blocks
        if episode_reward is not None:
            self.episode_reward_sum += episode_reward
            self.num_episodes += 1
            self.total_episodes += 1
            self.total_reward_sum += episode_reward
        return ptr

    def _draw(self, rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Stratified draw of batch_size sequence coordinates (with the
        zero-leaf clamp reflected into the returned global idxes). Caller
        holds the lock. Returns (b, s, idxes, is_weights)."""
        S = self.cfg.seqs_per_block
        idxes, is_weights = self.tree.sample(self.cfg.batch_size, rng)
        b = idxes // S
        s = np.minimum(idxes % S, np.maximum(self.num_seq_store[b] - 1, 0))
        return b, s, b * S + s, is_weights

    # --- priorities -------------------------------------------------------

    def update_priorities(self, idxes: np.ndarray, td_errors: np.ndarray, old_ptr: int) -> None:
        """Apply learner priorities, discarding any index overwritten during
        the sample->train round trip (worker.py:290-307 invariant)."""
        S = self.cfg.seqs_per_block
        with self.lock:
            ptr = self.block_ptr
            if ptr > old_ptr:
                mask = (idxes < old_ptr * S) | (idxes >= ptr * S)
            elif ptr < old_ptr:
                mask = (idxes < old_ptr * S) & (idxes >= ptr * S)
            else:
                mask = np.ones_like(idxes, dtype=bool)
            self.tree.update(idxes[mask], td_errors[mask])

    def pop_episode_stats(self):
        with self.lock:
            n, r = self.num_episodes, self.episode_reward_sum
            self.num_episodes = 0
            self.episode_reward_sum = 0.0
        return n, r

    def episode_totals(self):
        """Run-lifetime (episodes, reward_sum) — unaffected by the
        pop-and-reset logging stream."""
        with self.lock:
            return self.total_episodes, self.total_reward_sum
