#!/bin/bash
# Round-4 chain D: the long-context BUDGET attack, after chain C.
# Episode accounting across the three long_context_mid runs: 36k updates
# over 288-step episodes sees ~17k episodes — 13x fewer than the ~230k
# episodes the solved fast-task runs consumed (same spatial task, 24-step
# episodes). Every n=64 checkpoint of the cosine-lr run sits above
# chance (-0.28..-0.75 vs ~-0.9) without breaking out, which reads as
# under-trained, not unstable. This arm runs 4x the budget (144k
# updates, cosine horizon matched) with the otherwise-best-known recipe
# (lru core, sync 250). Solves (>= +0.5) => run the zero-state control
# at the same budget: window 1 of each block replays from the stored
# state, so the ablation isolates exactly the long-context machinery.
cd /root/repo
while ! grep -q R4C_CHAIN_ALL_DONE runs/r4c_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid_lru4 \
  --env memory_catch:10:12 --steps 144000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=288 \
  --set learning_steps=256 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID_LRU4 EXIT: $? ==="
EV=$(last_eval runs/long_context_mid_lru4/eval.jsonl)
echo "=== LONG_CONTEXT_MID_LRU4 EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/long_context_demo.py --out runs/long_context_mid_lru4_zs \
    --env memory_catch:10:12 --steps 144000 --eval-episodes 4 \
    --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
    --set hidden_dim=128 --set max_episode_steps=288 \
    --set learning_steps=256 --set block_length=512 \
    --set buffer_capacity=102400 --set learning_starts=40000 \
    --set recurrent_core=lru --set lr_schedule=cosine \
    --ablate-zero-state
  echo "=== LONG_CONTEXT_MID_LRU4_ZS EXIT: $? ==="
fi

echo R4D_CHAIN_ALL_DONE
