"""AST lint rules over the package source.

Each rule encodes an invariant that Python cannot enforce at runtime until
it is too late on hardware: a host sync inside a hot loop stalls the
dispatch pipeline for a full device round trip, a silent recompile costs
seconds per occurrence, a float64 op doubles memory and falls off the MXU,
an unregistered fault site silently drops out of the chaos sweep, and an
unlocked write to lock-guarded state is a data race waiting for a thread
interleaving. The rules are deliberately conservative approximations —
they flag the syntactic patterns that produce those failures, and a
deliberate exception is silenced in place with

    # r2d2: disable=<rule>[,<rule>...]          (same line or line above)

so every suppression is visible in the diff it rides in on.

Lock-discipline exceptions have a PRECISE variant: instead of muting the
rule, an annotation asserts WHICH lock protects the write —

    self.count += 1  # r2d2: guarded-by(lock)   (this write: caller holds
                                                 self.lock)
    def _account(self):  # r2d2: guarded-by(lock)
        ...                                     (whole function runs with
                                                 self.lock held — the
                                                 caller-holds-lock contract)

A guarded-by annotation silences `lock-discipline` for the covered lines
exactly like a disable comment would, but unlike a disable it feeds the
interprocedural concurrency pass (analysis/concurrency.py), which treats
the named lock as held there and CHECKS the assertion's consequences
(lock-order edges, cross-thread guard consistency) instead of going blind.

Rule catalog (ids, severities — the table in ARCHITECTURE.md mirrors this):

- host-sync-in-hot-path  (warning)  `.item()` / `jax.device_get` /
  `np.asarray` / `np.array` / `float(x)` / `bool(x)` inside a for/while
  body in the hot-path modules (learner.py, collect.py, megastep.py):
  each call can force a device->host sync per iteration. The serving
  plane graduated to its own rule (below).
- blocking-host-sync-in-serve-step (warning)  the serve-pipeline variant,
  covering serve/* files: the same loop-body flags as
  host-sync-in-hot-path, PLUS function-wide (not just loop-body) coverage
  of `np.asarray` / `np.array` / `jax.device_get` / `.item()` /
  `.block_until_ready()` inside the pipeline's stage/dispatch bodies
  (`_run_batch`, `_serve_iteration`, `_stage*`, `_dispatch*`) — one
  blocking materialization there stalls the whole depth-2 overlap, so the
  serve thread must never wait on the device. Completion-side functions
  (`_complete*`) and `warmup*` are exempt: materializing is their job.
- jit-in-loop            (error)    `jax.jit(...)` called inside a
  for/while body — a fresh jit wrapper per iteration retraces every call.
- unhashable-static-arg  (error)    a jit static parameter whose default
  is a mutable literal (list/dict/set): jit's cache key hashes static
  args, so the first call raises (or, with a custom __hash__, silently
  retraces).
- shape-branch-in-jit    (warning)  an `if` on `.shape` inside a jitted
  function whose body does real work (not just a guard `raise`): each new
  shape traces a new program variant. Guard-raises are exempt — shape
  validation at trace time is the idiom.
- float64-op             (error)    device-plane float64: `jnp.float64`,
  a float64 dtype passed to a jnp/jax constructor, or enabling
  jax_enable_x64. Host-side numpy float64 (sum-tree prefix sums, env
  reward accumulators) is fine and not flagged.
- unknown-fault-site     (error)    `fault_point("site")` whose literal is
  not registered in faults.KNOWN_SITES — the site would be invisible to
  chaos sweeps and the R2D2_FAULTS operator surface.
- dynamic-fault-site     (warning)  `fault_point(expr)` with a non-literal
  argument — statically uncheckable, and sweeps cannot enumerate it.
- snapshot-missing-topology (error) a `save_replay(...)` call site in the
  package without an explicit `topology=` manifest: the writer relies on
  the callee's default, and a snapshot written without a manifest cannot
  be resharded onto a changed device/host layout (replay/reshard.py) or
  asserted by the runs/ chain guards.
- lock-discipline        (warning)  a class that guards attribute writes
  with `with self.<lock>:` in one method but writes the same attributes
  bare in another (non-__init__) method — the trainer/serve/watcher
  threads share these objects, so the bare write races the guarded one.
- host-tree-in-hot-loop  (warning)  a host `SumTree` method call
  (`.tree.sample(...)`, `.tree.update(...)`, ...) inside a for/while body
  in the learner hot-path modules: under priority_plane='device' the sum
  tree lives in HBM and sampling/write-back run in-jit inside the
  superstep (megastep.make_priority_superstep), so a host-tree call here
  both stalls the dispatch pipeline per iteration and silently forks the
  host tree away from the device tree. The in-jit device ops
  (replay/device_sum_tree.py module functions) are not flagged.
- raw-shard-map-import   (error)    a `jax.experimental.shard_map` import
  anywhere outside parallel/jax_compat.py: every shard_map must come
  through the version shim (check_rep/auto vs check_vma/axis_names), and
  the manual tp×fsdp train step depends on the shim's axis_names=None ->
  fully-manual defaulting.
- codec-decode-in-hot-loop (warning) a block-codec decode
  (`decode_field` / `decode_block` / `read_block`) or an mmap page-in
  (`np.memmap` / `mmap.mmap`) inside a for/while body in the learner
  hot-path modules or serve/*: the disk replay tier's contract is that
  decompression and first-touch page faults happen on the replay staging
  thread (tiered_store._fill_disk_rows), never on the learner or serve
  step — one zlib inflate per iteration there erases the overlap the
  three-tier design buys.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from r2d2_tpu.analysis.findings import Finding
from r2d2_tpu.utils.faults import KNOWN_SITES

ALL_RULES = (
    "host-sync-in-hot-path",
    "blocking-host-sync-in-serve-step",
    "jit-in-loop",
    "unhashable-static-arg",
    "shape-branch-in-jit",
    "float64-op",
    "unknown-fault-site",
    "dynamic-fault-site",
    "snapshot-missing-topology",
    "lock-discipline",
    "host-tree-in-hot-loop",
    "raw-shard-map-import",
    "codec-decode-in-hot-loop",
)

# hot-path modules for the host-sync rule: the learner/collection dispatch
# loops. The serving plane moved to blocking-host-sync-in-serve-step,
# which adds function-wide stage/dispatch coverage on top of the same
# loop-body checks.
HOT_BASENAMES = {"learner.py", "collect.py", "megastep.py"}
HOT_DIRNAMES: Set[str] = set()

# the serve rule's scope + its pipeline-role name conventions
# (serve/server.py): stage/dispatch bodies must never block on the
# device; completion/warmup bodies exist to block on it
SERVE_DIRNAMES = {"serve"}
_SERVE_STEP_NAMES = {"_run_batch", "_serve_iteration"}
_SERVE_STEP_PREFIXES = ("_stage", "_dispatch")
_SERVE_EXEMPT_PREFIXES = ("_complete", "warmup")

_SYNC_CALLS = {
    "np.asarray": "np.asarray",
    "np.array": "np.array",
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.device_get": "jax.device_get",
}

_DISABLE_RE = re.compile(r"#\s*r2d2:\s*disable=([A-Za-z0-9_,\s-]+)")
_GUARDED_BY_RE = re.compile(r"#\s*r2d2:\s*guarded-by\(([A-Za-z0-9_.\s,]+)\)")

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


def is_hot_path(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return parts[-1] in HOT_BASENAMES or bool(HOT_DIRNAMES & set(parts[:-1]))


def is_serve_path(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return bool(SERVE_DIRNAMES & set(parts[:-1]))


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.numpy.float64' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _suppressions(src_lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Line -> suppressed rule set. A trailing `# r2d2: disable=` comment
    covers its own line; a comment-ONLY line covers itself and the line
    below (so it can sit above a long statement without leaking onto
    unrelated neighbors)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src_lines, start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        targets = (i, i + 1) if line.lstrip().startswith("#") else (i,)
        for target in targets:
            out.setdefault(target, set()).update(rules)
    return out


def _guarded_by_comments(src_lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Line -> lock names asserted held there by `# r2d2: guarded-by(X)`
    annotations. Same placement rules as _suppressions: a trailing comment
    covers its own line, a comment-only line covers itself and the line
    below."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src_lines, start=1):
        m = _GUARDED_BY_RE.search(line)
        if not m:
            continue
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        targets = (i, i + 1) if line.lstrip().startswith("#") else (i,)
        for target in targets:
            out.setdefault(target, set()).update(names)
    return out


def guarded_by_map(tree: ast.AST, src_lines: Sequence[str]) -> Dict[int, Set[str]]:
    """The full guarded-by map for one file: per-line annotations, with a
    def-line annotation expanded over the whole function body (the
    caller-holds-lock contract — every statement in the function runs
    with the named lock held)."""
    out = _guarded_by_comments(src_lines)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names = out.get(node.lineno)
        if names:
            for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                out.setdefault(ln, set()).update(names)
    return out


def _is_float64(node: ast.AST) -> bool:
    d = _dotted(node)
    if d in ("np.float64", "numpy.float64", "jnp.float64", "jax.numpy.float64"):
        return True
    return isinstance(node, ast.Constant) and node.value == "float64"


# ---------------------------------------------------------------- the rules


def _rule_host_sync(tree: ast.AST, path: str) -> List[Finding]:
    if not is_hot_path(path):
        return []
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()

    def flag(node: ast.AST, what: str) -> None:
        key = (node.lineno, node.col_offset)
        if key in seen:
            return
        seen.add(key)
        out.append(
            Finding(
                rule="host-sync-in-hot-path",
                severity="warning",
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=f"{what} inside a hot-path loop body forces a "
                "device->host sync per iteration",
                hint="hoist the transfer out of the loop (batch it), or "
                "mark a deliberate readback with "
                "`# r2d2: disable=host-sync-in-hot-path`",
            )
        )

    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for stmt in list(loop.body) + list(loop.orelse):
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d in _SYNC_CALLS:
                    flag(node, f"{_SYNC_CALLS[d]}(...)")
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    flag(node, ".item()")
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "bool")
                    and len(node.args) == 1
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    flag(node, f"{node.func.id}(...) on a possible device value")
    return out


def _own_nodes(root: ast.AST) -> List[ast.AST]:
    """All descendant nodes of `root` that belong to ITS scope — nested
    function/class definitions are skipped (they get their own scope
    decision when the caller iterates over them directly)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _rule_serve_step_host_sync(tree: ast.AST, path: str) -> List[Finding]:
    """Serve-plane host-sync discipline (the depth-2 pipeline's contract):

    - everywhere in serve/* except completion/warmup bodies, the classic
      loop-body checks apply (a sync per iteration stalls the batch);
    - inside stage/dispatch bodies (`_run_batch`, `_serve_iteration`,
      `_stage*`, `_dispatch*`) the blocking calls are banned FUNCTION-WIDE
      — np.asarray / np.array / jax.device_get / `.item()` /
      `.block_until_ready()` anywhere there serializes the serve thread
      against the device and collapses the stage/step overlap. float()/
      bool() stay loop-only (scalar host math at stage time is fine).
    """
    if not is_serve_path(path):
        return []
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()

    def flag(node: ast.AST, what: str, where: str) -> None:
        key = (node.lineno, node.col_offset)
        if key in seen:
            return
        seen.add(key)
        out.append(
            Finding(
                rule="blocking-host-sync-in-serve-step",
                severity="warning",
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=f"{what} {where} blocks the serve thread on the "
                "device and stalls the stage/dispatch pipeline",
                hint="materialize on the completion side (_complete*), or "
                "mark a deliberate sync with "
                "`# r2d2: disable=blocking-host-sync-in-serve-step`",
            )
        )

    def _blocking(node: ast.Call) -> Optional[str]:
        d = _dotted(node.func)
        if d in _SYNC_CALLS:
            return f"{_SYNC_CALLS[d]}(...)"
        if d == "jax.block_until_ready":
            return "jax.block_until_ready(...)"
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                return ".item()"
            if node.func.attr == "block_until_ready":
                return ".block_until_ready()"
        return None

    def check_loops(scope: ast.AST) -> None:
        own = _own_nodes(scope)
        own_set = set(map(id, own))
        for loop in own:
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for stmt in list(loop.body) + list(loop.orelse):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or id(node) not in own_set:
                        continue
                    what = _blocking(node)
                    if what is not None:
                        flag(node, what, "inside a serve loop body")
                    elif (
                        isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "bool")
                        and len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)
                    ):
                        flag(
                            node,
                            f"{node.func.id}(...) on a possible device value",
                            "inside a serve loop body",
                        )

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name.startswith(_SERVE_EXEMPT_PREFIXES):
            continue
        if fn.name in _SERVE_STEP_NAMES or fn.name.startswith(_SERVE_STEP_PREFIXES):
            for node in _own_nodes(fn):
                if isinstance(node, ast.Call):
                    what = _blocking(node)
                    if what is not None:
                        flag(node, what, f"in stage/dispatch body {fn.name}()")
        check_loops(fn)
    check_loops(tree)
    return out


# host SumTree API surface (replay/sum_tree.py + the control plane's tree
# attribute) and the receiver names that conventionally hold a HOST tree.
# The device plane's ops are module functions (dst.tree_update(...)) so
# their receiver chain never matches.
_HOST_TREE_METHODS = {
    "sample", "update", "sample_indices", "update_priorities",
    "priorities_of", "leaves",
}
_HOST_TREE_NAMES = {"tree", "sum_tree", "host_tree"}


def _rule_host_tree_in_hot_loop(tree: ast.AST, path: str) -> List[Finding]:
    if not is_hot_path(path):
        return []
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for stmt in list(loop.body) + list(loop.orelse):
            for node in ast.walk(stmt):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_TREE_METHODS
                ):
                    continue
                recv = node.func.value
                recv_d = _dotted(recv) or ""
                # jax.tree.leaves / jax.tree_util & friends are pytree ops
                if recv_d.startswith(("jax.", "jnp.", "tree_util.")):
                    continue
                last = (
                    recv.attr
                    if isinstance(recv, ast.Attribute)
                    else recv.id if isinstance(recv, ast.Name) else ""
                )
                if last not in _HOST_TREE_NAMES:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                out.append(
                    Finding(
                        rule="host-tree-in-hot-loop",
                        severity="warning",
                        path=path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=f"host SumTree call {recv_d or last}."
                        f"{node.func.attr}(...) inside a hot-loop body: "
                        "under priority_plane='device' sampling and "
                        "priority write-back run in-jit over the HBM tree "
                        "(megastep superstep); a host-tree call here syncs "
                        "per iteration and forks the host tree from the "
                        "device tree",
                        hint="use the device ops "
                        "(replay/device_sum_tree.py) or the control "
                        "plane's _tree_write funnel; mark a deliberate "
                        "host-plane path with "
                        "`# r2d2: disable=host-tree-in-hot-loop`",
                    )
                )
    return out


# several rules ask the same pure questions of the same module tree; the
# one-entry memo (keyed on tree identity, holding a strong ref so ids are
# never reused under it) makes each question one walk per module instead
# of one per rule
_TREE_MEMO: Dict[str, Tuple[ast.AST, object]] = {}


def _memo_per_tree(name: str, tree: ast.AST, build):
    ent = _TREE_MEMO.get(name)
    if ent is not None and ent[0] is tree:
        return ent[1]
    res = build()
    _TREE_MEMO[name] = (tree, res)
    return res


def _jit_calls(tree: ast.AST) -> List[ast.Call]:
    """Every `jax.jit(...)` call, including the `functools.partial(jax.jit,
    ...)` decorator form (the partial call itself is returned)."""

    def build() -> List[ast.Call]:
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d == "jax.jit":
                out.append(node)
            elif d in ("functools.partial", "partial") and node.args:
                if _dotted(node.args[0]) == "jax.jit":
                    out.append(node)
        return out

    return _memo_per_tree("jit_calls", tree, build)


def _rule_jit_in_loop(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    jit_positions = {(c.lineno, c.col_offset) for c in _jit_calls(tree)}
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for stmt in list(loop.body) + list(loop.orelse):
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Call)
                    and (node.lineno, node.col_offset) in jit_positions
                ):
                    out.append(
                        Finding(
                            rule="jit-in-loop",
                            severity="error",
                            path=path,
                            line=node.lineno,
                            col=node.col_offset,
                            message="jax.jit called inside a loop body: each "
                            "iteration builds a fresh wrapper with an empty "
                            "trace cache",
                            hint="build the jitted callable once outside the "
                            "loop and reuse it",
                        )
                    )
    return out


def _function_defs(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    def build() -> Dict[str, ast.FunctionDef]:
        defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        return defs

    return _memo_per_tree("function_defs", tree, build)


def _static_params(call: ast.Call, fn: ast.FunctionDef) -> List[ast.arg]:
    """Parameters of `fn` marked static by a jit call's static_argnames /
    static_argnums keywords (literal values only)."""
    params = list(fn.args.posonlyargs) + list(fn.args.args)
    out: List[ast.arg] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(kw.value, (ast.Tuple, ast.List)):
            names = {
                e.value
                for e in kw.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
            out.extend(p for p in params if p.arg in names)
        elif kw.arg == "static_argnames" and isinstance(kw.value, ast.Constant):
            out.extend(p for p in params if p.arg == kw.value.value)
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, int):
                nums = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                nums = [
                    e.value
                    for e in kw.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            out.extend(params[n] for n in nums if 0 <= n < len(params))
    return out


def _param_default(fn: ast.FunctionDef, param: ast.arg) -> Optional[ast.AST]:
    params = list(fn.args.posonlyargs) + list(fn.args.args)
    defaults = list(fn.args.defaults)
    offset = len(params) - len(defaults)
    for i, p in enumerate(params):
        if p is param and i >= offset:
            return defaults[i - offset]
    for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if p is param and d is not None:
            return d
    return None


def _jitted_defs(tree: ast.AST) -> List[Tuple[ast.Call, ast.FunctionDef]]:
    """(jit call, wrapped FunctionDef) pairs resolvable statically: a bare
    `jax.jit(name, ...)` over a same-module def, or a decorator (`@jax.jit`
    / `@functools.partial(jax.jit, ...)`)."""
    defs = _function_defs(tree)
    calls = _jit_calls(tree)
    pairs: List[Tuple[ast.Call, ast.FunctionDef]] = []
    for call in calls:
        target = None
        if _dotted(call.func) == "jax.jit" and call.args:
            if isinstance(call.args[0], ast.Name):
                target = defs.get(call.args[0].id)
        elif call.args and len(call.args) >= 1:
            # partial(jax.jit, ...) form: the decorated def is found below
            pass
        if target is not None:
            pairs.append((call, target))
    for fn in defs.values():
        for dec in fn.decorator_list:
            if _dotted(dec) == "jax.jit":
                pairs.append((ast.Call(func=dec, args=[], keywords=[]), fn))
            elif isinstance(dec, ast.Call) and dec in calls:
                pairs.append((dec, fn))
    return pairs


def _rule_unhashable_static_arg(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for call, fn in _jitted_defs(tree):
        for param in _static_params(call, fn):
            default = _param_default(fn, param)
            if default is not None and isinstance(default, _MUTABLE_LITERALS):
                out.append(
                    Finding(
                        rule="unhashable-static-arg",
                        severity="error",
                        path=path,
                        line=param.lineno,
                        col=param.col_offset,
                        message=f"static jit parameter {param.arg!r} defaults "
                        "to a mutable (unhashable) literal: jit hashes static "
                        "args for its cache key",
                        hint="use a tuple / frozen value, or drop the "
                        "parameter from static_argnames",
                    )
                )
    return out


def _rule_shape_branch_in_jit(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()
    for _, fn in _jitted_defs(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            has_shape = any(
                isinstance(sub, ast.Attribute) and sub.attr == "shape"
                for sub in ast.walk(node.test)
            )
            if not has_shape:
                continue
            # guard-raise idiom (shape validation at trace time) is exempt
            if all(isinstance(stmt, ast.Raise) for stmt in node.body) and not node.orelse:
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            seen.add(key)
            out.append(
                Finding(
                    rule="shape-branch-in-jit",
                    severity="warning",
                    path=path,
                    line=node.lineno,
                    col=node.col_offset,
                    message="shape-dependent branch inside a jitted function: "
                    "every distinct shape traces (and compiles) a new variant",
                    hint="pad to a fixed shape, lift the branch to the "
                    "builder, or keep only a guard `raise`",
                )
            )
    return out


def _rule_float64(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()

    def flag(node: ast.AST, message: str, hint: str) -> None:
        key = (node.lineno, node.col_offset)
        if key in seen:
            return
        seen.add(key)
        out.append(
            Finding(
                rule="float64-op",
                severity="error",
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=message,
                hint=hint,
            )
        )

    for node in ast.walk(tree):
        d = _dotted(node) if isinstance(node, ast.Attribute) else None
        if d in ("jnp.float64", "jax.numpy.float64"):
            flag(
                node,
                "jnp.float64 violates the precision policy (x64 is off; the "
                "op silently produces f32 or, with x64 on, doubles memory "
                "and falls off the MXU)",
                "use jnp.float32; host-side accumulation may use np.float64",
            )
        elif isinstance(node, ast.Call):
            cd = _dotted(node.func)
            if (
                cd == "jax.config.update"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_enable_x64"
                and len(node.args) > 1
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value is True
            ):
                flag(
                    node,
                    "enabling jax_enable_x64 turns every default float into "
                    "f64 device-wide",
                    "keep x64 off; widen individual host-side numpy arrays "
                    "instead",
                )
            elif cd is not None and cd.split(".")[0] in ("jnp", "jax"):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if _is_float64(arg):
                        flag(
                            arg,
                            f"float64 dtype passed to {cd}: device arrays "
                            "must stay <= 32-bit under the precision policy",
                            "use float32 (or bf16 via config.precision)",
                        )
    return out


def _rule_fault_sites(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None or d.split(".")[-1] != "fault_point":
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in KNOWN_SITES:
                out.append(
                    Finding(
                        rule="unknown-fault-site",
                        severity="error",
                        path=path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=f"fault site {arg.value!r} is not registered "
                        "in faults.KNOWN_SITES: chaos sweeps and the "
                        "R2D2_FAULTS operator surface cannot see it",
                        hint="add the site to KNOWN_SITES (utils/faults.py) "
                        "or fix the typo",
                    )
                )
        else:
            out.append(
                Finding(
                    rule="dynamic-fault-site",
                    severity="warning",
                    path=path,
                    line=node.lineno,
                    col=node.col_offset,
                    message="fault_point called with a non-literal site name: "
                    "statically uncheckable and unenumerable by sweeps",
                    hint="pass a string literal registered in KNOWN_SITES",
                )
            )
    return out


def _rule_snapshot_topology(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None or d.split(".")[-1] != "save_replay":
            continue
        # kw.arg is None for a **kwargs splat: statically unverifiable,
        # give it the benefit of the doubt rather than false-positive
        if any(kw.arg == "topology" or kw.arg is None for kw in node.keywords):
            continue
        out.append(
            Finding(
                rule="snapshot-missing-topology",
                severity="error",
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message="save_replay call without an explicit topology= "
                "manifest: a snapshot written without one cannot be "
                "resharded onto a changed device/host layout "
                "(replay/reshard.py) or asserted by the runs/ chain guards",
                hint="pass topology=snapshot_topology(replay, tp=cfg.tp_size)",
            )
        )
    return out


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Call)
            and _dotted(node.value.func) in ("threading.Lock", "threading.RLock")
        ):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                locks.add(t.attr)
    return locks


def _self_attr_writes(node: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(attr name, node) for every `self.X = / self.X op= / self.X[...] =`
    in the subtree, NOT descending into nested function defs."""
    out: List[Tuple[str, ast.AST]] = []

    def targets_of(stmt) -> List[ast.AST]:
        if isinstance(stmt, ast.Assign):
            return list(stmt.targets)
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            return [stmt.target]
        return []

    def visit(n: ast.AST) -> None:
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            for t in targets_of(child):
                base = t
                if isinstance(base, ast.Subscript):
                    base = base.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    out.append((base.attr, child))
            visit(child)

    visit(node)
    return out


def _rule_lock_discipline(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

        def lock_blocks(method) -> List[ast.With]:
            blocks = []
            for node in ast.walk(method):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call):  # e.g. lock.acquire-style wrappers
                        ctx = ctx.func
                    if (
                        isinstance(ctx, ast.Attribute)
                        and isinstance(ctx.value, ast.Name)
                        and ctx.value.id == "self"
                        and ctx.attr in locks
                    ):
                        blocks.append(node)
                        break
            return blocks

        guarded: Set[str] = set()
        per_method_blocks: Dict[str, List[ast.With]] = {}
        for m in methods:
            blocks = lock_blocks(m)
            per_method_blocks[m.name] = blocks
            for b in blocks:
                for attr, _ in _self_attr_writes(b):
                    guarded.add(attr)
        guarded -= locks
        if not guarded:
            continue

        for m in methods:
            if m.name == "__init__":
                continue
            locked_nodes: Set[int] = set()
            for b in per_method_blocks[m.name]:
                for sub in ast.walk(b):
                    locked_nodes.add(id(sub))
            for attr, node in _self_attr_writes(m):
                if attr in guarded and id(node) not in locked_nodes:
                    out.append(
                        Finding(
                            rule="lock-discipline",
                            severity="warning",
                            path=path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=f"self.{attr} is written under "
                            f"`with self.<lock>` elsewhere in "
                            f"{cls.name} but bare here: the write races "
                            "the guarded ones across threads",
                            hint="take the lock, or mark a single-threaded "
                            "phase with `# r2d2: disable=lock-discipline`",
                        )
                    )
    return out


def _rule_raw_shard_map_import(tree: ast.Module, path: str) -> List[Finding]:
    """Every shard_map must come through parallel/jax_compat.shard_map —
    the version shim that maps the old check_rep/auto API onto the new
    check_vma/axis_names one. A raw `jax.experimental.shard_map` import
    anywhere else would pin one jax era's signature and silently diverge
    from the shim's manual/auto-axis semantics (the tp×fsdp manual train
    step depends on axis_names=None meaning FULLY manual)."""
    norm = path.replace(os.sep, "/")
    if norm.endswith("parallel/jax_compat.py"):
        return []
    out: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        out.append(
            Finding(
                rule="raw-shard-map-import",
                severity="error",
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=f"{what} bypasses the parallel/jax_compat shim; "
                "raw jax.experimental.shard_map pins one jax era's "
                "signature (check_rep vs check_vma) and skips the shim's "
                "manual-axis defaulting",
                hint="from r2d2_tpu.parallel.jax_compat import shard_map",
            )
        )

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("jax.experimental.shard_map"):
                flag(node, f"`from {mod} import ...`")
            elif mod == "jax.experimental" and any(
                a.name == "shard_map" for a in node.names
            ):
                flag(node, "`from jax.experimental import shard_map`")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.experimental.shard_map"):
                    flag(node, f"`import {a.name}`")
    return out


# block-codec decode / disk page-in surface (replay/codec.py +
# replay/disk_tier.py). Method-style receivers (x.decode_field(...)) and
# bare names (decode_field(...)) both match: the contract is positional
# ("not on the learner/serve step"), not receiver-typed.
_DECODE_CALL_NAMES = {"decode_field", "decode_block", "read_block"}
_MMAP_CALLS = {"np.memmap", "numpy.memmap", "mmap.mmap"}


def _rule_codec_decode_in_hot_loop(tree: ast.AST, path: str) -> List[Finding]:
    if not (is_hot_path(path) or is_serve_path(path)):
        return []
    out: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for stmt in list(loop.body) + list(loop.orelse):
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func) or ""
                last = d.split(".")[-1]
                if d in _MMAP_CALLS:
                    what = f"{d}(...)"
                elif last in _DECODE_CALL_NAMES:
                    what = f"{d or last}(...)"
                else:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                out.append(
                    Finding(
                        rule="codec-decode-in-hot-loop",
                        severity="warning",
                        path=path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=f"{what} inside a hot-loop body: block-codec "
                        "inflate / mmap page-in belongs on the replay "
                        "staging thread (tiered_store._fill_disk_rows), not "
                        "the learner/serve step — a per-iteration decode "
                        "erases the three-tier overlap",
                        hint="sample through TieredReplayBuffer (the staging "
                        "thread decodes behind the prefetch queue), or mark "
                        "a deliberate cold-path decode with "
                        "`# r2d2: disable=codec-decode-in-hot-loop`",
                    )
                )
    return out


_RULES = (
    _rule_host_sync,
    _rule_serve_step_host_sync,
    _rule_jit_in_loop,
    _rule_unhashable_static_arg,
    _rule_shape_branch_in_jit,
    _rule_float64,
    _rule_fault_sites,
    _rule_snapshot_topology,
    _rule_lock_discipline,
    _rule_host_tree_in_hot_loop,
    _rule_raw_shard_map_import,
    _rule_codec_decode_in_hot_loop,
)


# ---------------------------------------------------------------- driver


def analyze_source(
    text: str, path: str
) -> Tuple[List[Finding], List[Finding]]:
    """Run every AST rule over one file's source. Returns
    (findings, suppressed) — suppressed findings matched a
    `# r2d2: disable=` comment and do not gate."""
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return (
            [
                Finding(
                    rule="syntax-error",
                    severity="error",
                    path=path,
                    line=e.lineno or 0,
                    col=e.offset or 0,
                    message=f"file does not parse: {e.msg}",
                )
            ],
            [],
        )
    src_lines = text.splitlines()
    suppress = _suppressions(src_lines)
    guards = guarded_by_map(tree, src_lines)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for rule_fn in _RULES:
        for f in rule_fn(tree, path):
            rules_here = suppress.get(f.line, set())
            if f.rule in rules_here or "all" in rules_here:
                suppressed.append(f)
            elif f.rule == "lock-discipline" and guards.get(f.line):
                # a guarded-by annotation asserts the named lock is held
                # at this write (caller-holds-lock contract); the
                # concurrency pass checks the assertion interprocedurally
                suppressed.append(f)
            else:
                findings.append(f)
    return findings, suppressed


def collect_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, f) for f in sorted(files) if f.endswith(".py")
                )
        elif p.endswith(".py") and os.path.exists(p):
            out.append(p)
    return sorted(dict.fromkeys(out))


def analyze_paths(
    paths: Iterable[str],
) -> Tuple[List[Finding], List[Finding]]:
    """AST-lint every .py file under `paths` (files or directories).
    Returns (findings, suppressed), stable-sorted."""
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for path in collect_py_files(paths):
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        f, s = analyze_source(text, path)
        findings.extend(f)
        suppressed.extend(s)
    findings.sort(key=Finding.sort_key)
    suppressed.sort(key=Finding.sort_key)
    return findings, suppressed
