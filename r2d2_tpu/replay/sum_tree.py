"""Array-backed sum tree with stratified sampling and IS weights.

Capabilities match the reference's PriorityTree (reference
priority_tree.py:5-46): priorities are td^alpha, sampling is stratified
(one uniform draw per equal probability stratum), descent is vectorized
layer-by-layer, and importance weights are (p / min_p)^-beta.

Differences from the reference, by design:

- Fixed stratum arithmetic: the reference builds strata with
  `np.arange(0, p_sum, interval)` whose float step can yield
  num_samples + 1 points and crash (SURVEY.md quirk 10). Here strata are
  `(arange(n) + U[0,1)) * p_sum / n` — exactly n draws, always in range.
- Explicit RNG: sampling takes a numpy Generator instead of the global
  stream, so runs are reproducible (SURVEY.md quirk 13).
- An optional C++ core (replay/_native) accelerates update/sample; the
  numpy path is the reference implementation for tests.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class SumTree:
    def __init__(
        self,
        capacity: int,
        prio_exponent: float = 0.9,
        is_exponent: float = 0.6,
        native: Optional[object] = None,
    ):
        self.capacity = capacity
        self.num_layers = 1
        while capacity > 2 ** (self.num_layers - 1):
            self.num_layers += 1
        self.leaf_offset = 2 ** (self.num_layers - 1) - 1
        self.tree = np.zeros(2**self.num_layers - 1, dtype=np.float64)
        self.prio_exponent = prio_exponent
        self.is_exponent = is_exponent
        self._native = native

    @property
    def total(self) -> float:
        return float(self.tree[0])

    def update(self, idxes: np.ndarray, td_errors: np.ndarray) -> None:
        """Set leaf priorities to td^alpha and resum ancestors bottom-up."""
        if len(idxes) == 0:
            return
        if self._native is not None:
            self._native.tree_update(self.tree, self.num_layers, idxes, td_errors, self.prio_exponent)
            return
        priorities = np.asarray(td_errors, dtype=np.float64) ** self.prio_exponent
        nodes = np.asarray(idxes, dtype=np.int64) + self.leaf_offset
        self.tree[nodes] = priorities
        for _ in range(self.num_layers - 1):
            nodes = np.unique((nodes - 1) // 2)
            self.tree[nodes] = self.tree[2 * nodes + 1] + self.tree[2 * nodes + 2]

    def sample(
        self, num_samples: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Stratified sample of `num_samples` leaves.

        Returns (leaf indices, IS weights). Requires total > 0.
        """
        p_sum = self.tree[0]
        if p_sum <= 0:
            raise ValueError("cannot sample from an empty sum tree")
        interval = p_sum / num_samples
        prefixsums = (
            np.arange(num_samples, dtype=np.float64) + rng.uniform(0.0, 1.0, num_samples)
        ) * interval
        # guard the right edge against float accumulation
        np.clip(prefixsums, 0.0, np.nextafter(p_sum, 0.0), out=prefixsums)

        if self._native is not None:
            nodes = self._native.tree_sample(self.tree, self.num_layers, prefixsums)
            is_weights = self._native.is_weights(
                self.tree, self.num_layers, nodes, self.is_exponent
            )
            return (nodes - self.leaf_offset).astype(np.int64), is_weights

        nodes = np.zeros(num_samples, dtype=np.int64)
        for _ in range(self.num_layers - 1):
            left = self.tree[nodes * 2 + 1]
            go_left = prefixsums < left
            nodes = np.where(go_left, nodes * 2 + 1, nodes * 2 + 2)
            prefixsums = np.where(go_left, prefixsums, prefixsums - left)

        priorities = self.tree[nodes]
        # Float roundoff in the descent can land a stratum on a zero-priority
        # leaf (empty slot of a partially-filled block). Treat those as
        # minimum-priority so the weight formula stays finite: they get the
        # max weight 1.0 instead of 0/0 = NaN poisoning the batch.
        positive = priorities[priorities > 0.0]
        min_p = positive.min() if positive.size else 1.0
        is_weights = np.power(np.maximum(priorities, min_p) / min_p, -self.is_exponent)
        return (nodes - self.leaf_offset).astype(np.int64), is_weights.astype(np.float32)

    def priorities_of(self, idxes: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idxes, dtype=np.int64) + self.leaf_offset]

    def set_raw(self, idxes: np.ndarray, raw: np.ndarray) -> None:
        """Set leaves to ALREADY-EXPONENTIATED priorities (as read back by
        priorities_of/leaves) and resum ancestors. The disk tier uses this
        to MOVE leaves between slots during demotion — going through
        update() would re-apply ^alpha to values that already carry it.
        Mutates self.tree in place, so it composes with the native core
        (which shares the same array)."""
        idxes = np.asarray(idxes, dtype=np.int64)
        if len(idxes) == 0:
            return
        nodes = idxes + self.leaf_offset
        self.tree[nodes] = np.asarray(raw, dtype=np.float64)
        for _ in range(self.num_layers - 1):
            nodes = np.unique((nodes - 1) // 2)
            self.tree[nodes] = self.tree[2 * nodes + 1] + self.tree[2 * nodes + 2]

    # ------------------------------------------------------- snapshot support

    def leaves(self) -> np.ndarray:
        """Raw leaf priorities (already ^alpha), for replay snapshots."""
        return self.tree[self.leaf_offset : self.leaf_offset + self.capacity].copy()

    def load_leaves(self, values: np.ndarray) -> None:
        """Restore raw leaf priorities (as returned by leaves()) and rebuild
        every internal sum bottom-up."""
        if len(values) != self.capacity:
            raise ValueError(f"expected {self.capacity} leaves, got {len(values)}")
        self.tree[:] = 0.0
        self.tree[self.leaf_offset : self.leaf_offset + self.capacity] = values
        for k in range(self.num_layers - 1, 0, -1):
            p = np.arange(2 ** (k - 1) - 1, 2**k - 1)
            self.tree[p] = self.tree[2 * p + 1] + self.tree[2 * p + 2]
