"""Sum-tree unit tests: exactness vs brute force, stratified edge cases."""

import numpy as np
import pytest

from r2d2_tpu.replay.sum_tree import SumTree


def test_update_totals_match_brute_force():
    rng = np.random.default_rng(0)
    tree = SumTree(100, prio_exponent=0.9, is_exponent=0.6)
    leaves = np.zeros(100)
    for _ in range(20):
        idxes = rng.choice(100, size=17, replace=False)
        tds = rng.uniform(0.0, 5.0, size=17)
        tree.update(idxes, tds)
        leaves[idxes] = tds**0.9
        np.testing.assert_allclose(tree.total, leaves.sum(), rtol=1e-9)
        np.testing.assert_allclose(tree.priorities_of(np.arange(100)), leaves, rtol=1e-9)


def test_sample_distribution():
    rng = np.random.default_rng(1)
    tree = SumTree(64, prio_exponent=1.0, is_exponent=0.5)
    tds = rng.uniform(0.1, 2.0, size=64)
    tree.update(np.arange(64), tds)
    counts = np.zeros(64)
    n_rounds, bsz = 2000, 32
    for _ in range(n_rounds):
        idxes, _ = tree.sample(bsz, rng)
        np.add.at(counts, idxes, 1)
    freq = counts / (n_rounds * bsz)
    want = tds / tds.sum()
    np.testing.assert_allclose(freq, want, atol=0.01)


def test_is_weights_formula():
    rng = np.random.default_rng(2)
    tree = SumTree(16, prio_exponent=1.0, is_exponent=0.6)
    tds = np.linspace(0.5, 4.0, 16)
    tree.update(np.arange(16), tds)
    idxes, w = tree.sample(8, rng)
    p = tree.priorities_of(idxes)
    np.testing.assert_allclose(w, (p / p.min()) ** -0.6, rtol=1e-5)


def test_exact_sample_count_quirk10_regression():
    """The reference's arange-based strata can emit num+1 samples for
    adversarial float sums (SURVEY.md quirk 10); ours must always emit
    exactly num samples and stay in range."""
    rng = np.random.default_rng(3)
    tree = SumTree(1000, prio_exponent=1.0, is_exponent=0.6)
    # sums engineered to give a p_sum/num interval with accumulating error
    tree.update(np.arange(1000), np.full(1000, 0.1 + 1e-9))
    for _ in range(50):
        idxes, w = tree.sample(64, rng)
        assert idxes.shape == (64,)
        assert (idxes >= 0).all() and (idxes < 1000).all()
        assert np.isfinite(w).all()


def test_empty_tree_raises():
    tree = SumTree(8)
    with pytest.raises(ValueError):
        tree.sample(4, np.random.default_rng(0))


def test_capacity_not_power_of_two():
    tree = SumTree(50_000, prio_exponent=0.9, is_exponent=0.6)
    # 17 layers / 131071 nodes at the reference's leaf count (SURVEY.md #11)
    assert tree.num_layers == 17
    assert tree.tree.shape == (131071,)


def test_zero_priority_leaf_gives_finite_weights():
    """Regression: a sampled zero-priority leaf must yield max-weight 1.0,
    not NaN/inf (0/0 in the IS formula)."""
    tree = SumTree(8, prio_exponent=1.0, is_exponent=0.6)
    tree.update(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))
    # force the degenerate case directly: weights over a mix incl. a 0 leaf
    nodes = np.array([0, 1, 4, 7]) + tree.leaf_offset
    priorities = tree.tree[nodes]
    assert priorities[-1] == 0.0
    positive = priorities[priorities > 0.0]
    min_p = positive.min()
    w = np.power(np.maximum(priorities, min_p) / min_p, -tree.is_exponent)
    assert np.isfinite(w).all() and w[-1] == 1.0
