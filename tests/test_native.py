"""Parity tests: C++ replay core (r2d2_tpu/_native) vs the numpy reference
implementations in replay/sum_tree.py and replay/replay_buffer.py.

The numpy path is the executable spec; the native path must agree exactly
(same dtypes, same clamp semantics). If the toolchain is missing the whole
module skips — native is a performance layer, never a correctness layer.
"""

import numpy as np
import pytest

from r2d2_tpu._native import load_native
from r2d2_tpu.replay.sum_tree import SumTree

native = load_native()
# the `native` marker lets `pytest -m native` target exactly this layer;
# load_native() returns None (never raises) on a missing toolchain or a
# stale .so, so collection always succeeds and the module skips cleanly
pytestmark = [
    pytest.mark.native,
    pytest.mark.skipif(native is None, reason="native core unavailable"),
]


def test_tree_update_matches_numpy():
    rng = np.random.default_rng(0)
    a, b = SumTree(1000, prio_exponent=0.9), SumTree(1000, prio_exponent=0.9, native=native)
    for _ in range(20):
        idxes = rng.integers(0, 1000, size=64)
        tds = rng.uniform(0.0, 5.0, size=64)
        a.update(idxes, tds)
        b.update(idxes, tds)
        np.testing.assert_allclose(a.tree, b.tree, rtol=1e-12)


def test_tree_update_duplicate_idxes():
    tree_np, tree_cc = SumTree(64), SumTree(64, native=native)
    idxes = np.array([3, 3, 3, 7], np.int64)
    tds = np.array([1.0, 2.0, 3.0, 4.0])
    tree_np.update(idxes, tds)
    tree_cc.update(idxes, tds)
    np.testing.assert_allclose(tree_np.tree, tree_cc.tree, rtol=1e-12)
    # last write wins on the duplicated leaf
    assert tree_cc.priorities_of(np.array([3]))[0] == pytest.approx(3.0**0.9)


def test_tree_sample_matches_numpy():
    rng = np.random.default_rng(1)
    tree_np, tree_cc = SumTree(512), SumTree(512, native=native)
    idxes = np.arange(512)
    tds = rng.uniform(0.01, 3.0, size=512)
    tree_np.update(idxes, tds)
    tree_cc.update(idxes, tds)
    for seed in range(10):
        i1, w1 = tree_np.sample(64, np.random.default_rng(seed))
        i2, w2 = tree_cc.sample(64, np.random.default_rng(seed))
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(w1, w2, rtol=1e-6)


def test_gather_windows_clamped_parity():
    rng = np.random.default_rng(2)
    nb, slot, T = 7, 21, 14
    for dtype, shape in [(np.uint8, (5, 3)), (np.float32, ()), (np.uint8, ())]:
        store = rng.integers(0, 255, size=(nb, slot, *shape)).astype(dtype)
        b = rng.integers(0, nb, size=9).astype(np.int64)
        # include negative starts and starts that overrun the slot
        win = rng.integers(-5, slot, size=9).astype(np.int64)
        out = native.gather_windows(store, b, win, T)
        rows = np.clip(win[:, None] + np.arange(T)[None, :], 0, slot - 1)
        expect = store[b[:, None], rows]
        np.testing.assert_array_equal(out, expect)


def test_gather_windows_multi_matches_per_field():
    """The grouped multi-field gather is bit-identical to per-field
    gather_windows calls on the same coordinates — mixed dtypes and row
    shapes in one group, negative and overrunning window starts."""
    rng = np.random.default_rng(3)
    nb, slot, T = 7, 21, 14
    stores = [
        rng.integers(0, 255, size=(nb, slot, 5, 3)).astype(np.uint8),
        rng.integers(0, 255, size=(nb, slot)).astype(np.uint8),
        rng.normal(size=(nb, slot)).astype(np.float32),
    ]
    b = rng.integers(0, nb, size=9).astype(np.int64)
    win = rng.integers(-5, slot, size=9).astype(np.int64)
    outs = native.gather_windows_multi(stores, b, win, T)
    assert len(outs) == len(stores)
    for store, out in zip(stores, outs):
        assert out.dtype == store.dtype
        np.testing.assert_array_equal(out, native.gather_windows(store, b, win, T))


def test_replay_buffer_native_vs_numpy_batches():
    """End-to-end: the two ReplayBuffer data paths assemble identical
    batches from identical contents and RNG streams."""
    from r2d2_tpu.replay.replay_buffer import ReplayBuffer
    from tests.test_replay_buffer import make_block, small_cfg

    cfg = small_cfg()
    buf_np = ReplayBuffer(cfg.replace(use_native_replay=False))
    buf_cc = ReplayBuffer(cfg, native=native)
    assert buf_cc.native is not None

    for i in range(6):
        # mix of full, short, and terminal blocks exercises the clamp paths
        steps = [12, 12, 7, 12, 5, 12][i]
        block, prios, ep = make_block(
            cfg, steps=steps, start_step=13 * i, terminal=(i % 3 == 2), seed=i
        )
        buf_np.add_block(block, prios, ep)
        buf_cc.add_block(block, prios, ep)

    for seed in range(5):
        b1 = buf_np.sample_batch(np.random.default_rng(seed))
        b2 = buf_cc.sample_batch(np.random.default_rng(seed))
        np.testing.assert_array_equal(b1.obs, b2.obs)
        np.testing.assert_array_equal(b1.last_action, b2.last_action)
        np.testing.assert_allclose(b1.last_reward, b2.last_reward)
        np.testing.assert_array_equal(b1.action, b2.action)
        np.testing.assert_allclose(b1.n_step_reward, b2.n_step_reward)
        np.testing.assert_allclose(b1.gamma, b2.gamma)
        np.testing.assert_allclose(b1.hidden, b2.hidden)
        np.testing.assert_array_equal(b1.idxes, b2.idxes)
        np.testing.assert_allclose(b1.is_weights, b2.is_weights, rtol=1e-6)
