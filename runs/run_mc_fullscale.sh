#!/bin/bash
# Waits for the midscale pair, then runs the 84x84 memory-catch proof pair.
# cue=40 aligns the cue phase exactly with seq0/burn-in windows: seq1+ is
# fully blind, so the zero-state ablation has no path to the ball column.
cd /root/repo
while ! grep -q MID_ALL_DONE runs/mc_mid_driver.log 2>/dev/null; do sleep 60; done
run_with_retry() {
  local out=$1; shift
  local tries=0
  python examples/catch_demo.py --out "$out" "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1))
    echo "=== stall exit 86; resuming $out (try $tries) ==="
    python examples/catch_demo.py --out "$out" "$@" --resume
    rc=$?
  done
  return $rc
}
run_with_retry runs/memcatch84_main --env memory_catch:40 --full --steps 100000 --mode fused
echo "=== FULL MAIN EXIT: $? ==="
run_with_retry runs/memcatch84_zerostate --env memory_catch:40 --full --steps 100000 --mode fused --ablate-zero-state
echo "=== FULL ABLATION EXIT: $? ==="
echo FULL_ALL_DONE
