"""End-to-end Trainer runs over the device and sharded replay planes
(the host plane is covered by test_end_to_end.py). Both run the same
minimum slice on Catch: collection -> HBM block writes -> coordinate-only
sampling -> fused/jitted update -> priority round trip."""

import jax
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.envs.catch import CatchVecEnv
from r2d2_tpu.train import Trainer


def run_trainer(cfg, steps=10):
    vec_env = CatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=0)
    trainer = Trainer(cfg, vec_env=vec_env)
    trainer.run_inline(env_steps_per_update=4)
    return trainer


def test_device_plane_end_to_end(tmp_path):
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="device",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=10,
        save_interval=10,
        learning_starts=48,
    )
    tr = run_trainer(cfg)
    assert int(tr.state.step) == 10
    assert tr.replay.env_steps > 0
    # priorities actually landed in the tree (round trip exercised)
    assert tr.replay.tree.total > 0


def test_tiered_plane_end_to_end(tmp_path):
    """The tiered plane's full loop: collection -> host store -> staged
    K-batch chunks through the prefetch pipeline -> stacked K-update scan
    -> deferred priority round trip, with the overlap metric populated."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="tiered",
        updates_per_dispatch=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=10,
        save_interval=10,
        learning_starts=48,
    )
    tr = run_trainer(cfg)
    assert int(tr.state.step) == 10
    assert tr.replay.env_steps > 0
    # priorities actually landed in the tree (deferred round trip drained)
    assert tr.replay.tree.total > 0
    # the staging pipeline ran and the overlap accountant saw its chunks
    assert tr.plane.xfer.chunks > 0
    stats = tr.plane.xfer.stats()
    assert 0.0 <= stats["h2d_overlap_fraction"] <= 1.0
    # run_inline's finish_updates stopped the staging thread
    assert tr.plane._pipe is None
    assert tr.plane._pending is None


def test_tiered_plane_torn_shutdown_drain(tmp_path):
    """Stopping mid-pipeline with a priority readback still in flight:
    drain_pending applies the pending chunk under its staleness stamps and
    leaves the sum tree CONSISTENT (root == sum of leaves, all finite);
    a second drain and a dropped undelivered staged chunk are no-ops."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="tiered",
        updates_per_dispatch=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=50,
        save_interval=50,
        learning_starts=48,
    )
    vec_env = CatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=0)
    tr = Trainer(cfg, vec_env=vec_env)
    tr.warmup()
    # one update leaves its priority readback pending (deferred one
    # dispatch) and the pipeline's next staged chunk in flight
    tr.state, _ = tr.plane.update(tr.state, tr.plane.sample())
    assert tr.plane._pending is not None
    assert tr.plane._pipe is not None

    tr.finish_updates()  # the torn shutdown
    assert tr.plane._pending is None
    assert tr.plane._pipe is None

    tree = tr.replay.tree
    leaves = tree.tree[tree.leaf_offset : tree.leaf_offset + tree.capacity]
    assert np.all(np.isfinite(leaves)) and np.all(leaves >= 0)
    np.testing.assert_allclose(tree.total, leaves.sum(), rtol=1e-9)
    assert tree.total > 0
    tr.finish_updates()  # idempotent


def test_sharded_plane_end_to_end(tmp_path):
    assert len(jax.devices()) >= 8
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="sharded",
        dp_size=4,
        tp_size=2,
        batch_size=8,  # 2 per dp shard
        buffer_capacity=16 * 40,  # 40 blocks -> 10 per shard
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=10,
        save_interval=10,
        learning_starts=48,
    )
    tr = run_trainer(cfg)
    assert tr.mesh is not None and tr.mesh.shape == {"dp": 4, "tp": 2}
    assert int(tr.state.step) == 10
    assert all(s.tree.total > 0 for s in tr.replay.shards)
    # tp=2 on the sharded plane is REAL tensor parallelism now: the
    # core-agnostic probe kernel (tp_probe_kernel — resolves to core/wi
    # here since tiny_test uses the default LSTM core; it falls back to
    # enc/Dense_0 only for the LRU core, whose params are tp-replicated)
    # keeps its Megatron column sharding through 10 updates (manual-dp
    # shard_map with the tp axis GSPMD-auto), while the params stay
    # dp-replicated
    from r2d2_tpu.parallel.mesh import tp_probe_kernel

    wi = tp_probe_kernel(tr.state.params)
    assert wi.sharding.spec[-1] == "tp"
    assert all(
        "dp" not in str(l.sharding.spec) for l in jax.tree.leaves(tr.state.params)
    )


def test_device_plane_threaded_pipelined(tmp_path):
    """Threaded mode gathers at sample time (make_gather_step): queued
    items carry materialized batches, immune to store overwrites."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="device",
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=6,
        save_interval=6,
        learning_starts=48,
    )
    vec_env = CatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=0)
    trainer = Trainer(cfg, vec_env=vec_env)
    trainer.run_threaded()
    assert int(trainer.state.step) == 6


def test_sharded_pipelined_gather_matches_fused(tmp_path):
    """The pipelined path (sharded gather -> plain-jit batch step with
    XLA-inserted psum) must equal the fused shard_map step numerically."""
    import jax.numpy as jnp
    from r2d2_tpu.learner import (
        init_train_state,
        make_batch_train_step,
        make_sharded_fused_train_step,
        make_sharded_gather_step,
    )
    from r2d2_tpu.parallel.mesh import make_mesh
    from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay
    from tests.test_sharded_replay import fill, sharded_cfg

    mesh = make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
    cfg = sharded_cfg()
    replay = ShardedDeviceReplay(cfg, mesh)
    fill(replay, cfg)
    net, state0 = init_train_state(cfg, jax.random.PRNGKey(5))
    si = replay.sample_indices(np.random.default_rng(4))
    coords = (jnp.asarray(si.b), jnp.asarray(si.s), jnp.asarray(si.is_weights))

    fused = make_sharded_fused_train_step(cfg, net, mesh, donate=False)
    _, m_fused, p_fused = replay.run_with_stores(
        lambda st: fused(state0, st, *coords)
    )
    gather = make_sharded_gather_step(cfg, mesh)
    batch = replay.run_with_stores(lambda st: gather(st, *coords))
    step = make_batch_train_step(cfg, net, donate=False)
    _, m_piped, p_piped = step(state0, batch)

    np.testing.assert_allclose(float(m_fused["loss"]), float(m_piped["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p_fused).reshape(-1), np.asarray(p_piped), rtol=1e-5
    )


def test_sharded_plane_requires_mesh():
    with pytest.raises(ValueError, match="sharded"):
        tiny_test().replace(replay_plane="sharded")


def test_host_plane_with_mesh_auto_psum(tmp_path):
    """dp>1 on the HOST plane: batches shard over dp under plain jit and
    XLA inserts the gradient all-reduce (no shard_map)."""
    cfg = tiny_test().replace(
        env_name="catch",
        dp_size=8,
        batch_size=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=6,
        save_interval=6,
        learning_starts=48,
    )
    tr = run_trainer(cfg, steps=6)
    assert int(tr.state.step) == 6
    leaf = jax.tree.leaves(tr.state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_sharded_plane_tp_resume(tmp_path):
    """Checkpoint -> resume on the dp x tp sharded plane: the restored
    state must carry the SAME tp shardings as a fresh placement (restore
    templates from the already-placed state), and training must continue
    from the saved step."""
    cfg = tiny_test().replace(
        env_name="catch",
        replay_plane="sharded",
        dp_size=4,
        tp_size=2,
        batch_size=8,
        buffer_capacity=16 * 40,
        checkpoint_dir=str(tmp_path / "ckpt"),
        training_steps=10,
        save_interval=5,
        learning_starts=48,
    )
    tr = run_trainer(cfg)
    assert int(tr.state.step) == 10

    resumed = Trainer(
        cfg.replace(training_steps=12),
        vec_env=CatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=1),
        resume=True,
    )
    assert int(resumed.state.step) == 10
    from r2d2_tpu.parallel.mesh import tp_probe_kernel

    wi = tp_probe_kernel(resumed.state.params)
    assert wi.sharding.spec[-1] == "tp", wi.sharding
    for a, b in zip(
        jax.tree.leaves(resumed.state.params), jax.tree.leaves(tr.state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    resumed.run_inline(env_steps_per_update=4)
    assert int(resumed.state.step) == 12
