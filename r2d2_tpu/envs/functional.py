"""Generic host-protocol adapters over any functional (jit/vmap-safe) env.

A functional env exposes reset(key) -> state, step(state, action) ->
(state', reward, done), render(state) -> uint8 obs, plus NUM_ACTIONS
(envs/catch.py, envs/procmaze.py). These adapters lift that core into the
two host-facing protocols the framework speaks, so a new pure-JAX env gets
the whole stack — HostEnvPool actor, vectorized actor, evaluator — by
writing only the core. (The on-device collector consumes the core
directly; no adapter needed.)

The adapters mirror envs/catch.py's CatchHostEnv/CatchVecEnv shape; the
jitted functions are cached per core-config so a pool of N envs compiles
once, not N times.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _jitted_fns(make_env: Callable, env_args: tuple, env_kw: tuple = ()):
    env = make_env(*env_args, **dict(env_kw))
    return jax.jit(env.reset), jax.jit(env.step), jax.jit(env.render)


class FnHostEnv:
    """Single-env host protocol (reset()/step(int)) over a functional core.
    `make_env(*env_args, **kwargs)` must be hashable/cacheable (a class +
    scalar args) so jitted functions are shared across instances."""

    def __init__(
        self, make_env: Callable, env_args: tuple = (), seed: int = 0,
        kwargs: dict | None = None,
    ):
        kw = tuple(sorted((kwargs or {}).items()))
        self.env = make_env(*env_args, **dict(kw))
        self.action_dim = self.env.NUM_ACTIONS
        self._key = jax.random.PRNGKey(seed)
        self._reset, self._step, self._render = _jitted_fns(make_env, env_args, kw)
        self._state = None
        self.obs_shape = tuple(
            jax.eval_shape(
                self._render, jax.eval_shape(self._reset, jax.random.PRNGKey(0))
            ).shape
        )

    def reset(self) -> np.ndarray:
        self._key, sub = jax.random.split(self._key)
        self._state = self._reset(sub)
        return np.asarray(self._render(self._state))

    def step(self, action: int):
        self._state, reward, done = self._step(self._state, jnp.int32(action))
        return np.asarray(self._render(self._state)), float(reward), bool(done), {}


class FnVecEnv:
    """Vectorized host-protocol adapter: E functional envs stepped in one
    jitted call with device-side auto-reset. step() returns the terminal
    frame (for replay parity with the reference) plus the fresh-episode
    frame to seed the next accumulator window — the same contract as
    envs/catch.CatchVecEnv / actor.HostEnvPool."""

    def __init__(self, fn_env, num_envs: int = 1, seed: int = 0):
        self.env = fn_env
        self.num_envs = num_envs
        self.action_dim = fn_env.NUM_ACTIONS
        self._seed = seed
        self._reset_count = 0
        self._vreset = jax.jit(jax.vmap(fn_env.reset))
        self._state = self._vreset(jax.random.split(jax.random.PRNGKey(seed), num_envs))
        self.obs_shape = tuple(
            jax.eval_shape(
                fn_env.render, jax.tree.map(lambda x: x[0], self._state)
            ).shape
        )

        @jax.jit
        def _vstep(state, actions: jnp.ndarray):
            def one(s, a):
                s2, reward, done = fn_env.step(s, a)
                term_obs = fn_env.render(s2)
                key, sub = jax.random.split(s2.key)
                fresh = fn_env.reset(sub)
                fresh = fresh._replace(key=key)
                nxt = jax.tree.map(lambda f, o: jnp.where(done, f, o), fresh, s2)
                return nxt, term_obs, reward, done, fn_env.render(nxt)

            return jax.vmap(one)(state, actions)

        self._vstep = _vstep
        self._vrender = jax.jit(jax.vmap(fn_env.render))

    def reset_all(self) -> np.ndarray:
        """Start fresh episodes in every slot (mid-episode state is
        discarded — HostEnvPool.reset_all contract)."""
        self._reset_count += 1
        keys = jax.random.split(
            jax.random.PRNGKey(self._seed + self._reset_count * 1_000_003), self.num_envs
        )
        self._state = self._vreset(keys)
        return np.asarray(self._vrender(self._state))

    def step(self, actions: np.ndarray):
        self._state, term_obs, reward, done, next_obs = self._vstep(
            self._state, jnp.asarray(actions, jnp.int32)
        )
        return (
            np.asarray(term_obs),
            np.asarray(reward, np.float64),
            np.asarray(done),
            np.asarray(next_obs),
        )
