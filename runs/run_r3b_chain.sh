#!/bin/bash
# Round-3 session-B serialized TPU queue (one v5e chip). Value order:
#   1. DISCRIMINATING EXPERIMENT: 84x84 memory catch (blind span 22)
#      with the mid-scale recipe (IMPALA-small, 128-LSTM) that solves the
#      26x26 task. Learns => binding factor at flagship was the big
#      net's optimization, and we run the zero-state ablation at the
#      same scale (the verdict's "done" pair). Fails => factor is
#      spatial scale; extend once, then the frontier points decide.
#   2. Scale frontier: the same recipe at 40x40 and 52x52 (blind
#      fraction ~0.58 throughout) to bracket where it breaks.
#   3. procmaze_shaped (potential-based shaping) vs measured
#      random-walk baseline under the IMPALA preset.
#   4. Long-context solvable span: memory_catch:8:4 (328-step episodes,
#      one 512-window covers the episode; training seq stays 581).
#   5. Re-run the mid-scale headline ablation pair with n=64
#      episodes/checkpoint (reference protocol: >=5; old ckpts are
#      gone with the container, so re-emit = re-run).
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

# --- 1. discriminating experiment: 84x84, blind span 22, mid-scale recipe
run_with_retry python examples/catch_demo.py --out runs/mc84_small_cue60 \
  --env memory_catch:60 --size 84 --steps 60000 --mode fused
echo "=== MC84_SMALL_CUE60 EXIT: $? ==="
EV=$(last_eval runs/mc84_small_cue60/eval.jsonl)
echo "=== MC84_SMALL_CUE60 EVAL: $EV ==="
if ! python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_small_cue60 \
    --env memory_catch:60 --size 84 --steps 120000 --mode fused --resume
  echo "=== MC84_SMALL_CUE60_EXT EXIT: $? ==="
  EV=$(last_eval runs/mc84_small_cue60/eval.jsonl)
  echo "=== MC84_SMALL_CUE60 EVAL2: $EV ==="
fi
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  # positive at 84x84: zero-state ablation at the SAME config/budget
  STEPS=$(python - runs/mc84_small_cue60/eval.jsonl <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["step"] if rows else 60000)
PY
)
  run_with_retry python examples/catch_demo.py --out runs/mc84_small_cue60_zerostate \
    --env memory_catch:60 --size 84 --steps "$STEPS" --mode fused --ablate-zero-state
  echo "=== MC84_SMALL_ZEROSTATE EXIT: $? ==="
fi

# --- 2. scale frontier (blind fraction ~0.58: cue 16/38 at 40, 21/50 at 52)
run_with_retry python examples/catch_demo.py --out runs/mc_frontier40 \
  --env memory_catch:16 --size 40 --steps 48000 --mode fused
echo "=== FRONTIER40 EXIT: $? ==="
run_with_retry python examples/catch_demo.py --out runs/mc_frontier52 \
  --env memory_catch:21 --size 52 --steps 48000 --mode fused
echo "=== FRONTIER52 EXIT: $? ==="

# --- 3. shaped procmaze under the IMPALA preset
mkdir -p runs/procmaze_shaped
python runs/measure_random_baseline.py --env procmaze_shaped --episodes 2048 \
  --out runs/procmaze_shaped/baseline.json
echo "=== PROCMAZE_BASELINE EXIT: $? ==="
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped \
  --mode fused --steps 30000 --updates-per-dispatch 16 \
  --set checkpoint_dir=runs/procmaze_shaped/ckpt \
  --set metrics_path=runs/procmaze_shaped/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE_SHAPED TRAIN EXIT: $? ==="
python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped --episodes 4 \
  --out runs/procmaze_shaped/eval.jsonl --plot runs/procmaze_shaped/curve.jpg \
  --set checkpoint_dir=runs/procmaze_shaped/ckpt
echo "=== PROCMAZE_SHAPED EVAL EXIT: $? ==="

# --- 4. long-context solvable span
run_with_retry python examples/long_context_demo.py --out runs/long_context_solve \
  --env memory_catch:8:4 --steps 30000 \
  --set block_length=512 --set buffer_capacity=204800 --set learning_starts=40000
echo "=== LONG_CONTEXT_SOLVE EXIT: $? ==="

# --- 5. mid-scale headline ablation pair at n=64 episodes/checkpoint
#        (fresh dirs: the round-2 evidence in mc_mid_main/_zerostate is
#        kept; these are the re-emitted reference-protocol curves)
run_with_retry python examples/catch_demo.py --out runs/mc_mid_main_n64 \
  --env memory_catch:10 --steps 48000 --mode fused --eval-episodes 4
echo "=== MID MAIN EXIT: $? ==="
run_with_retry python examples/catch_demo.py --out runs/mc_mid_zerostate_n64 \
  --env memory_catch:10 --steps 48000 --mode fused --ablate-zero-state --eval-episodes 4
echo "=== MID ZEROSTATE EXIT: $? ==="

echo R3B_CHAIN_ALL_DONE
