"""Hand-rolled LSTM under lax.scan.

XLA has no cuDNN-style packed-sequence LSTM (the reference leans on
`pack_padded_sequence`, reference model.py:133); instead sequences are
fixed-shape and padded, the recurrence runs the full length, and output
gathers with clamped indices reproduce the variable-length semantics
(see models/r2d2.py).

TPU-first structure: the input projection x @ Wi for ALL timesteps is one
big (B*T, D) x (D, 4H) matmul — large, batched, MXU-friendly — so the
sequential scan body is only the (B, H) x (H, 4H) recurrent matmul plus
elementwise gates. For long-context configs the scan is chunked and each
chunk rematerialized (jax.checkpoint), trading FLOPs for HBM
(SURVEY.md section 5.7: an RNN recurrence parallelizes over batch, never
over time).

Gate order follows i, f, g, o. Weights use the same uniform(-1/sqrt(H),
1/sqrt(H)) scale family as the reference's recurrent core so Q-value
magnitudes start in a comparable regime.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Carry = Tuple[jnp.ndarray, jnp.ndarray]  # (h, c), each (B, H)


def _uniform_init(scale):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -scale, scale)

    return init


class LSTM(nn.Module):
    hidden_dim: int
    in_dim: int
    dtype: jnp.dtype = jnp.float32
    # remat chunk length for long unrolls; None = single un-remat'd scan
    scan_chunk: Optional[int] = None
    # "scan": lax.scan unroll. "pallas": fused Pallas kernel (ops/
    # pallas_lstm.py) — recurrent weights + carry stay VMEM-resident for
    # the whole unroll. "auto": pallas on TPU, scan elsewhere.
    backend: str = "auto"
    # Pallas-backend backward arms (config.seq_fused_dwh /
    # seq_grad_checkpoint; ops/pallas_lstm.py). Both default OFF — the
    # default backward path stays bit-identical. Applied only on the
    # fused-sequence (burn_in) path; the scan backend ignores them
    # (scan_chunk is its rematerialization knob).
    fused_dwh: bool = False
    grad_checkpoint: int = 0
    # Manual tensor parallelism (learner.make_manual_train_step's
    # shard_map): > 1 builds the SHARD-LOCAL module — wi/wh/b carry this
    # device's contiguous 4H/tp column slice, matching the sharding_map
    # table's column-parallel layout — and _gates re-gathers the
    # per-shard gate pre-activations over `tp_axis` before the
    # (replicated) gate/carry math. Scan backend only: the fused Pallas
    # kernel computes gates in-kernel and cannot host the seam.
    tp_size: int = 1
    tp_axis: str = "tp"

    def setup(self):
        H = self.hidden_dim
        scale = 1.0 / np.sqrt(H)
        if (4 * H) % self.tp_size != 0:
            raise ValueError(
                f"LSTM gate width 4*{H} must divide by tp_size={self.tp_size}"
            )
        cols = 4 * H // self.tp_size
        self.wi = self.param("wi", _uniform_init(scale), (self.in_dim, cols))
        self.wh = self.param("wh", _uniform_init(scale), (H, cols))
        self.b = self.param("b", _uniform_init(scale), (cols,))

    def _params(self):
        return self.wi, self.wh, self.b

    def _gates(self, proj: jnp.ndarray, h: jnp.ndarray, wh: jnp.ndarray, c: jnp.ndarray):
        H = self.hidden_dim
        z = proj + h @ wh
        if self.tp_size > 1:
            # tp seam: each shard holds a contiguous 4H/tp column slice
            # of the gate pre-activations (column-parallel wi/wh/b). One
            # tiled all-gather reconstructs the full z BIT-exactly — the
            # within-shard matmul reductions are untouched, the gather
            # only concatenates finished columns — after which gate math
            # and the (h, c) carry are replicated across tp.
            z = jax.lax.all_gather(z, self.tp_axis, axis=z.ndim - 1, tiled=True)
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H : 2 * H])
        g = jnp.tanh(z[..., 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[..., 3 * H :])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new

    def __call__(
        self,
        xs: jnp.ndarray,
        carry: Carry,
        burn_in: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Carry]:
        """Unroll over (B, T, D) inputs from carry; returns (B, T, H) + carry.

        `burn_in` (B,) int32, when given, places a per-row stop-gradient
        seam at step burn_in[b]: forward values are unchanged, but the
        backward pass treats steps t < burn_in[b] as state-refresh only
        (zero grads into the weights and into the initial carry). Both
        backends implement the same seam — the Pallas path inside its
        backward kernel (ops/pallas_lstm.py lstm_seq_unroll), the scan
        path via the operator-equivalent where/stop_gradient masks below —
        so the trained function is backend-independent.
        """
        B, T, D = xs.shape
        wi, wh, b = self._params()
        xs = xs.astype(self.dtype)
        wi, wh, b = wi.astype(self.dtype), wh.astype(self.dtype), b.astype(self.dtype)
        h, c = carry
        h, c = h.astype(self.dtype), c.astype(self.dtype)

        # one MXU-sized matmul for every timestep's input projection
        # (wi.shape[-1] = 4H/tp — the shard-local column count)
        proj = (xs.reshape(B * T, D) @ wi + b).reshape(B, T, wi.shape[-1])
        proj_t = jnp.swapaxes(proj, 0, 1)  # (T, B, 4H/tp) time-major for scan

        use_pallas = self.backend == "pallas" or (
            self.backend == "auto" and jax.default_backend() == "tpu"
        )
        if use_pallas and self.tp_size > 1:
            raise ValueError(
                "the shard-local (manual-tp) LSTM needs its all-gather "
                "seam inside the step body; use the scan backend "
                "(config.validate routes tp here via tp_shards_params)"
            )
        if use_pallas:
            from r2d2_tpu.ops.pallas_lstm import (
                lstm_seq_unroll,
                lstm_seq_unroll_ckpt,
                lstm_seq_unroll_fused_dwh,
                lstm_unroll,
            )

            if burn_in is None:
                outs_t, (hT, cT) = lstm_unroll(proj_t, wh, h, c)
            elif self.grad_checkpoint:
                outs_t, (hT, cT) = lstm_seq_unroll_ckpt(self.grad_checkpoint)(
                    proj_t, wh, h, c, burn_in.astype(jnp.int32)
                )
            elif self.fused_dwh:
                outs_t, (hT, cT) = lstm_seq_unroll_fused_dwh(
                    proj_t, wh, h, c, burn_in.astype(jnp.int32)
                )
            else:
                outs_t, (hT, cT) = lstm_seq_unroll(
                    proj_t, wh, h, c, burn_in.astype(jnp.int32)
                )
            return (
                jnp.swapaxes(outs_t, 0, 1),
                (hT.astype(self.dtype), cT.astype(self.dtype)),
            )

        if burn_in is None:

            def step(carry, p):
                h, c = carry
                h, c = self._gates(p, h, wh, c)
                return (h, c), h

            xs_scan = proj_t
        else:
            bi = burn_in.astype(jnp.int32)

            def step(carry, inp):
                t, p = inp
                h, c = carry
                # seam: the carry entering step burn_in[b] is state-refresh
                # only — identical values, no gradient across the boundary
                cut = (t == bi)[:, None]
                h = jnp.where(cut, jax.lax.stop_gradient(h), h)
                c = jnp.where(cut, jax.lax.stop_gradient(c), c)
                h, c = self._gates(p, h, wh, c)
                # burn-in outputs carry no cotangent into the weights
                keep = (t >= bi)[:, None]
                out = jnp.where(keep, h, jax.lax.stop_gradient(h))
                return (h, c), out

            xs_scan = (jnp.arange(T, dtype=jnp.int32), proj_t)

        if self.scan_chunk is None or T <= self.scan_chunk:
            (h, c), outs = jax.lax.scan(step, (h, c), xs_scan)
        else:
            # T > chunk: remat each full chunk; a non-divisible tail runs
            # as ONE shorter remat'd chunk (same step fn, same remat
            # boundary semantics), so burn-in/learning-window geometries
            # are not constrained to divisible sequence lengths.
            chunk = self.scan_chunk
            n_full = T // chunk
            main_len = n_full * chunk

            @jax.checkpoint
            def run_chunk(carry, chunk_xs):
                return jax.lax.scan(step, carry, chunk_xs)

            p_chunks = proj_t[:main_len].reshape(
                n_full, chunk, B, proj_t.shape[-1]
            )
            ts = jnp.arange(T, dtype=jnp.int32)
            if burn_in is None:
                chunk_xs = p_chunks
            else:
                chunk_xs = (ts[:main_len].reshape(n_full, chunk), p_chunks)
            (h, c), outs = jax.lax.scan(run_chunk, (h, c), chunk_xs)
            outs = outs.reshape(main_len, B, self.hidden_dim)
            if main_len < T:
                tail_xs = (
                    proj_t[main_len:]
                    if burn_in is None
                    else (ts[main_len:], proj_t[main_len:])
                )
                (h, c), tail_outs = run_chunk((h, c), tail_xs)
                outs = jnp.concatenate([outs, tail_outs], axis=0)

        return jnp.swapaxes(outs, 0, 1), (h, c)

    def step(self, x: jnp.ndarray, carry: Carry) -> Tuple[jnp.ndarray, Carry]:
        """Single acting step on (B, D) input (reference model.py:83)."""
        wi, wh, b = self._params()
        x = x.astype(self.dtype)
        wi, wh, b = wi.astype(self.dtype), wh.astype(self.dtype), b.astype(self.dtype)
        h, c = carry
        proj = x @ wi + b
        h_new, c_new = self._gates(proj, h.astype(self.dtype), wh, c.astype(self.dtype))
        return h_new, (h_new, c_new)
