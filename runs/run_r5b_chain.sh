#!/bin/bash
# Round-5 chain B: attack the blind-270 rung with the probe-backed lever,
# and localize the temporal break (VERDICT r4 items 3 and 8).
#
# The linear probe (runs/probe_state.py) settled the blind-270 diagnosis
# DIRECTLY: the cue is perfectly encoded at blinding (within-paddle-reach
# decode 1.0 on both the solved blind-194 rung and the failing blind-270
# rung) and decays over the blind fall — by end-of-blind the failing
# rung's state supports a catch only 53% of the time (mean column error
# 5.2) while the solved rung holds 100% (0.28). The state FORGETS: a
# memory-horizon failure, not credit assignment.
#
# 1) The designed counter: widen the LRU eigenvalue ring from the default
#    U(0.9, 0.999) (time constants ~10..1000 steps, most mass far below
#    the 270-step horizon) to U(0.98, 0.9999) (~50..10000) — exactly the
#    dial models/lru.py documents for this case (config.lru_r_min).
# 2+3) The two rungs between solved-194 and failing-270 (fall_every 10,
#    11 => blind ~216, ~243), same recipe as the solved mid9, to localize
#    the break to one rung — each verdict against its own measured
#    random-walk null (baseline.json, CPU-measured).
cd /root/repo
while ! grep -q R5A_CHAIN_ALL_DONE runs/r5a_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid12_ring \
  --env memory_catch:10:12 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=288 \
  --set learning_steps=128 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine \
  --set lru_r_min=0.98 --set lru_r_max=0.9999
echo "=== LONG_CONTEXT_MID12_RING EXIT: $? ==="

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid10 \
  --env memory_catch:10:10 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=240 \
  --set learning_steps=128 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID10 EXIT: $? ==="

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid11 \
  --env memory_catch:10:11 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=264 \
  --set learning_steps=128 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID11 EXIT: $? ==="

echo R5B_CHAIN_ALL_DONE
