"""ctypes loader for the native replay core (replay_core.cpp).

Builds the shared library with g++ on first import if it is missing or
older than the source (pybind11 is not in this image; plain C ABI +
ctypes needs no build-time Python dependency at all). Thread/process safe
via an atomic rename. `load_native()` returns a NativeReplayCore or None —
every caller must tolerate None and fall back to the numpy path, so a
missing toolchain degrades performance, never correctness.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "replay_core.cpp")
_LIB = os.path.join(_DIR, "libreplay_core.so")

_lock = threading.Lock()
_core: Optional["NativeReplayCore"] = None
_load_failed = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    """(Re)compile the .so if missing/stale. Returns True if usable."""
    try:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return True
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
            _SRC, "-o", tmp,
        ]
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            # retry without OpenMP (toolchains without libgomp)
            cmd = [c for c in cmd if c != "-fopenmp"]
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
            if r.returncode != 0:
                os.unlink(tmp)
                return False
        os.replace(tmp, _LIB)  # atomic: concurrent builders race benignly
        return True
    except (OSError, subprocess.SubprocessError):
        return False


class NativeReplayCore:
    """The interface replay/sum_tree.py's `native` hook expects, plus the
    window gatherer used by replay/replay_buffer.py batch assembly."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tree_update.argtypes = [_f64p, ctypes.c_int64, _i64p, _f64p,
                                    ctypes.c_int64, ctypes.c_double]
        lib.tree_update.restype = None
        lib.tree_sample.argtypes = [_f64p, ctypes.c_int64, _f64p,
                                    ctypes.c_int64, _i64p]
        lib.tree_sample.restype = None
        lib.gather_windows.argtypes = [_u8p, ctypes.c_int64, ctypes.c_int64,
                                       _i64p, _i64p, ctypes.c_int64,
                                       ctypes.c_int64, _u8p]
        lib.gather_windows.restype = None
        lib.gather_windows_multi.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), _i64p, ctypes.c_int64,
            ctypes.c_int64, _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.gather_windows_multi.restype = None
        lib.is_weights.argtypes = [_f64p, ctypes.c_int64, _i64p,
                                   ctypes.c_int64, ctypes.c_double, _f32p]
        lib.is_weights.restype = ctypes.c_int64

    # --- sum tree ---------------------------------------------------------

    def tree_update(self, tree: np.ndarray, num_layers: int,
                    idxes: np.ndarray, td_errors: np.ndarray,
                    alpha: float) -> None:
        idxes = np.ascontiguousarray(idxes, np.int64)
        td = np.ascontiguousarray(td_errors, np.float64)
        self._lib.tree_update(tree, num_layers, idxes, td, len(idxes), alpha)

    def tree_sample(self, tree: np.ndarray, num_layers: int,
                    prefixsums: np.ndarray) -> np.ndarray:
        prefixsums = np.ascontiguousarray(prefixsums, np.float64)
        out = np.empty(len(prefixsums), np.int64)
        self._lib.tree_sample(tree, num_layers, prefixsums, len(prefixsums), out)
        return out

    def is_weights(self, tree: np.ndarray, num_layers: int,
                   nodes: np.ndarray, beta: float) -> np.ndarray:
        nodes = np.ascontiguousarray(nodes, np.int64)
        out = np.empty(len(nodes), np.float32)
        self._lib.is_weights(tree, num_layers, nodes, len(nodes), beta, out)
        return out

    # --- batch assembly ---------------------------------------------------

    def gather_windows(self, store: np.ndarray, b: np.ndarray,
                       win_start: np.ndarray, T: int) -> np.ndarray:
        """store: (num_blocks, slot, *row_shape) C-contiguous; returns
        (B, T, *row_shape) with row indices clamped to [0, slot-1]."""
        assert store.flags["C_CONTIGUOUS"]
        slot = store.shape[1]
        row_shape = store.shape[2:]
        row_bytes = int(np.prod(row_shape, dtype=np.int64)) * store.itemsize
        b = np.ascontiguousarray(b, np.int64)
        win_start = np.ascontiguousarray(win_start, np.int64)
        B = len(b)
        out = np.empty((B, T, *row_shape), store.dtype)
        self._lib.gather_windows(
            store.view(np.uint8).reshape(-1),
            slot, row_bytes, b, win_start, B, T,
            out.view(np.uint8).reshape(-1),
        )
        return out

    def gather_windows_multi(self, stores, b: np.ndarray,
                             win_start: np.ndarray, T: int) -> list:
        """Gather the SAME (b, win_start) windows from several stores that
        share the slot axis, in ONE native call (one ctypes crossing + one
        OMP region for the whole field group). Returns one (B, T,
        *row_shape) array per store; clamp semantics identical to
        gather_windows (bit-identical outputs, pinned by test)."""
        b = np.ascontiguousarray(b, np.int64)
        win_start = np.ascontiguousarray(win_start, np.int64)
        B = len(b)
        slot = stores[0].shape[1]
        outs, row_bytes = [], np.empty(len(stores), np.int64)
        store_ptrs = (ctypes.c_void_p * len(stores))()
        out_ptrs = (ctypes.c_void_p * len(stores))()
        for f, store in enumerate(stores):
            assert store.flags["C_CONTIGUOUS"] and store.shape[1] == slot
            row_shape = store.shape[2:]
            row_bytes[f] = int(np.prod(row_shape, dtype=np.int64)) * store.itemsize
            out = np.empty((B, T, *row_shape), store.dtype)
            outs.append(out)
            store_ptrs[f] = store.ctypes.data
            out_ptrs[f] = out.ctypes.data
        self._lib.gather_windows_multi(
            store_ptrs, row_bytes, len(stores), slot, b, win_start, B, T,
            out_ptrs,
        )
        return outs


def load_native() -> Optional[NativeReplayCore]:
    """Build (if needed) and load the core; None if the toolchain or load
    fails. Result is cached process-wide."""
    global _core, _load_failed
    if _core is not None:
        return _core
    if _load_failed:
        return None
    with _lock:
        if _core is not None or _load_failed:
            return _core
        if not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
            _core = NativeReplayCore(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale .so missing a newer entry point (e.g.
            # hand-copied into an image whose mtime defeats the rebuild
            # check) — degrade to the numpy path instead of crashing every
            # importer, including pytest collection of the -m native tests
            _load_failed = True
            return None
        return _core
