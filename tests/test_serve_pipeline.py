"""Depth-2 serve pipeline tests (PR 15): pipelined-vs-serial bitwise
parity (actions, q, carries, RNG stream) at fp32 and bf16 including
mixed-task buckets, mid-pipeline hot-reload provenance, same-session
ordering across pipeline depth, and the zero-alloc staging contract.

The deterministic drives below build batches through the REAL batcher
(submit -> next_batch) and run the pipeline by hand: stage/dispatch batch
k+1 before completing batch k, exactly the overlap the serve-complete
worker produces in production, but with a batch composition that is
reproducible enough to compare bit-for-bit against the serial path.
All CPU tier-1 — tiny_test shapes."""

from __future__ import annotations

import copy
from collections import deque

import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.serve import PolicyServer, ServeConfig
from r2d2_tpu.serve.batcher import BucketStaging

CFG = tiny_test()


def _spec_stream(rng, cfg, n_batches, sessions, tasks=None, obs_shape=None):
    """Deterministic request schedule: each entry is one batch's worth of
    (sid, obs, reward, reset, task) tuples with varying composition —
    sessions recur across consecutive batches so the depth-2 overlap
    exercises same-session carry ordering."""
    shape = tuple(obs_shape if obs_shape is not None else cfg.obs_shape)
    out = []
    for b in range(n_batches):
        k = 1 + (b % min(4, len(sessions)))
        rows = []
        for i in range(k):
            sid = sessions[(b + i) % len(sessions)]
            rows.append((
                sid,
                rng.integers(0, 255, shape, dtype=np.uint8),
                float(rng.normal()),
                bool(b > 0 and i == 0 and b % 5 == 0),
                0 if tasks is None else tasks[sid],
            ))
        out.append(rows)
    return out


def _submit_batch(srv, rows):
    futures = [
        srv.submit(sid, obs, reward=reward, reset=reset, task=task)
        for sid, obs, reward, reset, task in rows
    ]
    batch = srv.batcher.next_batch(timeout=1.0)
    assert batch is not None and len(batch) == len(rows)
    return batch, futures


def _drive_serial(srv, specs):
    """The pre-pipeline loop: stage+dispatch+complete inline per batch."""
    results = []
    for rows in specs:
        batch, futures = _submit_batch(srv, rows)
        srv._run_batch(batch)
        results.append([f.result(timeout=5.0) for f in futures])
    return results


def _drive_pipelined(srv, specs, depth=2):
    """Hand-run the depth-2 pipeline: batch k+1 stages and dispatches
    BEFORE batch k completes (the serve-thread/completion-worker overlap,
    made deterministic)."""
    pending = deque()
    futures_all = []
    for rows in specs:
        batch, futures = _submit_batch(srv, rows)
        pending.append(srv._stage_and_dispatch(batch))
        futures_all.append(futures)
        if len(pending) == depth:
            srv._complete(pending.popleft())
    while pending:
        srv._complete(pending.popleft())
    return [[f.result(timeout=5.0) for f in futures] for futures in futures_all]


def _assert_bitwise_equal(res_a, res_b, srv_a, srv_b):
    for batch_a, batch_b in zip(res_a, res_b):
        for ra, rb in zip(batch_a, batch_b):
            assert ra.action == rb.action
            np.testing.assert_array_equal(np.asarray(ra.q), np.asarray(rb.q))
            assert ra.bucket == rb.bucket
    # the full RNG stream was consumed identically (same draw count, same
    # order) — not just the draws that happened to pick equal actions
    assert (srv_a._rng.bit_generator.state == srv_b._rng.bit_generator.state)
    # committed session carries are bitwise identical
    for a, b in zip(srv_a.cache.arrays(), srv_b.cache.arrays()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _pair(cfg, serve_cfg):
    """Two freshly initialized servers over the same seed: one serial
    (serve_pipeline=False), one pipelined. Same params, same RNG."""
    srv_ser = PolicyServer(cfg.replace(serve_pipeline=False), serve_cfg)
    srv_pipe = PolicyServer(cfg.replace(serve_pipeline=True), serve_cfg)
    srv_ser.warmup()
    srv_pipe.warmup()
    return srv_ser, srv_pipe


SCFG = ServeConfig(buckets=(2, 4, 8), max_wait_ms=3.0, cache_capacity=64,
                   epsilon=0.3)


@pytest.mark.parametrize("precision", ["fp32", "bf16"])
def test_pipelined_matches_serial_bitwise(precision):
    """The tentpole contract: with exploration ON (epsilon=0.3 — every
    batch consumes RNG), the pipelined path answers every request bitwise
    identically to the serial path: actions, q, the post-run RNG state,
    and the committed carries."""
    cfg = CFG.replace(precision=precision)
    srv_ser, srv_pipe = _pair(cfg, SCFG)
    rng_a = np.random.default_rng(7)
    rng_b = np.random.default_rng(7)
    sessions = [f"s{i}" for i in range(6)]
    res_ser = _drive_serial(srv_ser, _spec_stream(rng_a, cfg, 12, sessions))
    res_pipe = _drive_pipelined(srv_pipe, _spec_stream(rng_b, cfg, 12, sessions))
    _assert_bitwise_equal(res_ser, res_pipe, srv_ser, srv_pipe)


def test_pipelined_matches_serial_mixed_task_buckets():
    """Multi-task serving: mixed-task (and mixed-shape) buckets with
    task-native exploration draws — the task-conditioned randoms path
    must consume the RNG in the same arrival order pipelined."""
    from r2d2_tpu.multitask import build_registry

    cfg, specs = build_registry(CFG, ["drift", "banditgrid"])
    srv_ser, srv_pipe = _pair(cfg, SCFG)
    sessions = [f"mt{i}" for i in range(5)]
    tasks = {sid: i % len(specs) for i, sid in enumerate(sessions)}
    # one session submits at a smaller native rendering; the server pads
    # it to the union geometry at stage time (mixed-shape bucket)
    shapes = {sid: tuple(cfg.obs_shape) for sid in sessions}
    shapes[sessions[1]] = (8, 8, 1)

    def stream(seed):
        rng = np.random.default_rng(seed)
        out = []
        for b in range(10):
            k = 1 + (b % 4)
            rows = []
            for i in range(k):
                sid = sessions[(b + i) % len(sessions)]
                rows.append((
                    sid,
                    rng.integers(0, 255, shapes[sid], dtype=np.uint8),
                    float(rng.normal()),
                    False,
                    tasks[sid],
                ))
            out.append(rows)
        return out

    res_ser = _drive_serial(srv_ser, stream(3))
    res_pipe = _drive_pipelined(srv_pipe, stream(3))
    _assert_bitwise_equal(res_ser, res_pipe, srv_ser, srv_pipe)


def test_mid_pipeline_reload_keeps_staged_provenance():
    """A batch staged under version v must resolve stamped v even when a
    hot reload lands between its dispatch and its completion — and the
    NEXT staged batch picks up the new version."""
    cfg = CFG.replace(serve_pipeline=True)
    srv = PolicyServer(cfg, SCFG)
    srv.warmup()
    rng = np.random.default_rng(5)
    old_step, old_version = srv._published[1], srv._published[2]

    rows = [("pv-a", rng.integers(0, 255, cfg.obs_shape, dtype=np.uint8),
             0.0, False, 0)]
    batch, futures = _submit_batch(srv, rows)
    rec = srv._stage_and_dispatch(batch)
    # reload lands mid-pipeline (between this batch's dispatch and its
    # completion)
    new_params = copy.deepcopy(srv._params_raw)
    srv.publish(new_params, ckpt_step=old_step + 1)
    srv._complete(rec)
    res = futures[0].result(timeout=5.0)
    assert res.ckpt_step == old_step
    assert res.params_version == old_version

    batch2, futures2 = _submit_batch(srv, rows)
    srv._complete(srv._stage_and_dispatch(batch2))
    res2 = futures2[0].result(timeout=5.0)
    assert res2.ckpt_step == old_step + 1
    assert res2.params_version == old_version + 1


def test_same_session_back_to_back_across_pipeline_depth():
    """Two immediate submits for ONE session on a STARTED pipelined
    server: the batcher defers the duplicate into the next batch, which
    stages while the first is still completing — the second answer must
    still see the first's committed carry (bitwise equal to the serial
    server's sequential answers)."""
    scfg = ServeConfig(buckets=(2, 4), max_wait_ms=2.0, cache_capacity=16,
                       epsilon=0.3)
    srv_ser, srv_pipe = _pair(CFG, scfg)
    rng = np.random.default_rng(9)
    obs = [rng.integers(0, 255, CFG.obs_shape, dtype=np.uint8)
           for _ in range(4)]

    # serial reference, strictly sequential
    ref = []
    for t, o in enumerate(obs):
        b, fs = _submit_batch(srv_ser, [("bb", o, float(t), False, 0)])
        srv_ser._run_batch(b)
        ref.append(fs[0].result(timeout=5.0))

    srv_pipe.start(watch_checkpoints=False)
    try:
        futures = [
            srv_pipe.submit("bb", o, reward=float(t), reset=False)
            for t, o in enumerate(obs)
        ]
        got = [f.result(timeout=30.0) for f in futures]
    finally:
        srv_pipe.stop()
    for r_ref, r_got in zip(ref, got):
        assert r_ref.action == r_got.action
        np.testing.assert_array_equal(np.asarray(r_ref.q), np.asarray(r_got.q))
    assert srv_pipe.completed_batches == len(obs)
    assert (srv_ser._rng.bit_generator.state
            == srv_pipe._rng.bit_generator.state)


def test_staging_reuses_buffers_zero_alloc():
    """The zero-copy contract: for a warm bucket, assembly writes into the
    TWO preallocated buffer sets and allocates nothing new per batch —
    the StagedBatch arrays ARE the staging buffers, alternating."""
    staging = BucketStaging((2, 4), num_tasks=1)

    class _Req:
        def __init__(self, r):
            self.reward = r
            self.reset = False
            self.task = 0

    rng = np.random.default_rng(0)
    rows = [rng.integers(0, 255, (4, 4, 1), dtype=np.uint8) for _ in range(3)]
    reqs = [_Req(float(i)) for i in range(3)]
    ids = {"obs": set(), "rewards": set(), "slots": set()}
    staged_ids = []
    for _ in range(6):
        staged = staging.stage(reqs, 4, rows, 0.1)
        ids["obs"].add(id(staged.obs))
        ids["rewards"].add(id(staged.rewards))
        ids["slots"].add(id(staged.slots))
        staged_ids.append(id(staged.obs))
        assert staged.obs.shape == (4, 4, 4, 1)
        np.testing.assert_array_equal(staged.obs[:3], np.stack(rows))
        np.testing.assert_array_equal(staged.obs[3], 0)
        np.testing.assert_array_equal(
            staged.rewards, np.array([0.0, 1.0, 2.0, 0.0], np.float32))
        assert staged.reset_mask[3]  # pad rows reset
        assert not staged.explore.any() or True  # zeroed pre-draw
    # double-buffered: exactly two distinct buffers per field, used
    # alternately — no per-batch allocation for a warm bucket
    assert len(ids["obs"]) == 2
    assert len(ids["rewards"]) == 2
    assert len(ids["slots"]) == 2
    assert staged_ids[0] == staged_ids[2] == staged_ids[4]
    assert staged_ids[1] == staged_ids[3] == staged_ids[5]


def test_serve_log_interval_defers_metrics():
    """serve_log_interval > 0: the per-batch metrics dict is built only on
    the cadence (plus forced arm/version-change rows); skipped batches are
    counted so rates stay computable. interval=0.0 logs every batch (the
    pre-pipeline behavior)."""

    class _Sink:
        def __init__(self):
            self.rows = []

        def log(self, row):
            self.rows.append(row)

    cfg = CFG.replace(serve_pipeline=False, serve_log_interval=3600.0)
    sink = _Sink()
    srv = PolicyServer(cfg, SCFG, metrics=sink)
    srv.warmup()
    rng = np.random.default_rng(2)
    sessions = ["m0", "m1"]
    _drive_serial(srv, _spec_stream(rng, cfg, 5, sessions))
    # first batch logs (version edge from the init publish), the rest of
    # the hour-long window skips
    serve_rows = [r for r in sink.rows if r.get("plane") == "serve"]
    assert len(serve_rows) == 1
    assert srv.metrics_skipped == 4
    assert srv.stats()["metrics_skipped"] == 4
    assert serve_rows[0]["completed_batches"] == 1
    # a reload (version bump) forces a row even inside the window
    srv.publish(copy.deepcopy(srv._params_raw), ckpt_step=123)
    _drive_serial(srv, _spec_stream(rng, cfg, 1, sessions))
    serve_rows = [r for r in sink.rows if r.get("plane") == "serve"]
    assert len(serve_rows) == 2
    assert serve_rows[-1]["params_version"] > serve_rows[0]["params_version"]

    # interval 0.0 = legacy every-batch logging
    sink0 = _Sink()
    srv0 = PolicyServer(CFG.replace(serve_pipeline=False), SCFG, metrics=sink0)
    srv0.warmup()
    _drive_serial(srv0, _spec_stream(np.random.default_rng(2), CFG, 4, sessions))
    assert len([r for r in sink0.rows if r.get("plane") == "serve"]) == 4
    assert srv0.metrics_skipped == 0


def test_pipelined_e2e_parity_under_started_server():
    """End-to-end smoke over the real threads: a started pipelined server
    answers an interleaved multi-session stream bitwise identically to a
    started SERIAL server given the same single-submitter request order
    (one submitter thread -> deterministic batcher composition is not
    guaranteed, so sessions submit strictly round-robin and wait)."""
    scfg = ServeConfig(buckets=(2, 4), max_wait_ms=2.0, cache_capacity=16)
    srv_ser, srv_pipe = _pair(CFG, scfg)
    rng = np.random.default_rng(13)
    stream = [
        (f"e2e-{t % 3}", rng.integers(0, 255, CFG.obs_shape, dtype=np.uint8),
         float(rng.normal()))
        for t in range(9)
    ]
    out = {}
    for name, srv in (("ser", srv_ser), ("pipe", srv_pipe)):
        srv.start(watch_checkpoints=False)
        try:
            out[name] = [
                srv.submit(sid, obs, reward=rw).result(timeout=30.0)
                for sid, obs, rw in stream
            ]
        finally:
            srv.stop()
    for a, b in zip(out["ser"], out["pipe"]):
        assert a.action == b.action
        np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))
    assert srv_pipe.completed_batches == len(stream)
    assert srv_ser.completed_batches == len(stream)
