"""Sweep driver (r2d2_tpu/sweep.py): config construction for the full
Atari-57 suite, and a tiny end-to-end 2-game sweep on the catch env."""

import json
import os

import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.sweep import ATARI_57, run_sweep, sweep_config


def test_atari_57_is_57_games():
    assert len(ATARI_57) == 57
    assert len(set(ATARI_57)) == 57
    for g in ("MsPacman", "Breakout", "Seaquest", "Qbert", "MontezumaRevenge"):
        assert g in ATARI_57


def test_sweep_configs_validate_for_all_games(tmp_path):
    for game in ATARI_57:
        cfg = sweep_config(game, preset="atari", root=str(tmp_path))
        assert cfg.env_name == game
        assert game in cfg.checkpoint_dir
        assert cfg.metrics_path.endswith("metrics.jsonl")


def test_tiny_two_game_sweep(tmp_path):
    from r2d2_tpu.train import Trainer

    root = str(tmp_path / "sweep")

    def factory(cfg):
        # swap the Atari env for the fast catch env, keep everything else
        cfg = tiny_test().replace(
            env_name="catch",
            training_steps=3,
            checkpoint_dir=cfg.checkpoint_dir,
            metrics_path=cfg.metrics_path,
        )
        return Trainer(cfg)

    rows = run_sweep(
        ["Breakout", "Pong"], root=root, mode="inline", trainer_factory=factory
    )
    assert [r["game"] for r in rows] == ["Breakout", "Pong"]
    for r in rows:
        assert r["steps"] == 3
        assert r["env_steps"] > 0
    with open(os.path.join(root, "summary.jsonl")) as fh:
        lines = [json.loads(l) for l in fh]
    assert len(lines) == 2


def test_cli_rejects_unknown_game():
    from r2d2_tpu.sweep import main

    with pytest.raises(SystemExit):
        main(["--games", "NotAGame"])


def test_cli_allow_any_env_flag(tmp_path):
    from r2d2_tpu.sweep import main

    rows_path = tmp_path / "summary.jsonl"
    main(["--games", "catch", "--preset", "tiny_test", "--root", str(tmp_path),
          "--steps", "4", "--mode", "inline", "--allow-any-env"])
    assert rows_path.exists()
