"""Structured metrics (SURVEY.md section 5.5 rebuild).

The reference logs via print() from the buffer process every 10 s
(reference worker.py:124-146). Here every record is a structured dict
written as one jsonl line (machine-readable learning curves) and mirrored
to stdout at a throttled cadence.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str] = None, stdout_interval: float = 10.0):
        self.path = path
        self._fh = open(path, "a", buffering=1) if path else None
        self.stdout_interval = stdout_interval
        self._last_print = 0.0

    def log(self, record: Dict[str, Any], force_print: bool = False) -> None:
        record = {"ts": time.time(), **record}
        if self._fh:
            self._fh.write(json.dumps(record, default=float) + "\n")
        now = time.time()
        if force_print or now - self._last_print >= self.stdout_interval:
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "ts"
            )
            print(parts, file=sys.stderr)
            self._last_print = now

    def close(self) -> None:
        if self._fh:
            self._fh.close()
