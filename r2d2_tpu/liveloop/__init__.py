"""Live-loop learning plane: serve -> replay -> learn -> publish.

Served traffic feeds replay through a TransitionTap + IngestBridge, a
LiveLoopTrainer trains continuously against the live store, and the serve
plane's checkpoint watcher hot-reloads the improved params fleet-wide.
See ARCHITECTURE.md (live-loop section) for the dataflow and the
off-policy stamping / backpressure semantics.
"""

from r2d2_tpu.liveloop.bridge import IngestBridge
from r2d2_tpu.liveloop.explore import EpsilonAssigner
from r2d2_tpu.liveloop.loop import LiveLoopPlane
from r2d2_tpu.liveloop.tap import TransitionTap, gather_carry_rows
from r2d2_tpu.liveloop.trainer import LiveLoopTrainer

__all__ = [
    "EpsilonAssigner",
    "IngestBridge",
    "LiveLoopPlane",
    "LiveLoopTrainer",
    "TransitionTap",
    "gather_carry_rows",
]
