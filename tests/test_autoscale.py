"""Elastic autoscaler tests (serve/autoscale.py + Trainer.reshard_live).

Pins the PR's acceptance criteria: scale decisions are a deterministic
function of a seeded scenario trace, the hysteresis dead band parks the
fleet size on an oscillating signal instead of flapping it, a live fleet
survives one scale-up AND one scale-down with `sessions_lost == 0` and
BITWISE carry continuity for every session, quality-degrading rung steps
are gated behind an in-flight scale-up (the scale-vs-degrade interlock),
and the learner's in-process `reshard_live` resumes bit-exactly without a
process exit."""

from __future__ import annotations

import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.serve import (
    AutoscaleConfig,
    Autoscaler,
    DegradeConfig,
    DegradeController,
    LocalClient,
    MultiDeviceServer,
    ScenarioSpec,
    ServeConfig,
    SignalWindow,
)
from tests.test_scenarios import _StubServer
from tests.test_serve import SessionReference


# ------------------------------------------------------------- signal window


def test_signal_window_abstains_cold_then_judges():
    w = SignalWindow(window=16, slo_ms=50.0, min_samples=4)
    cold = w.signals()
    assert (cold["p99_ms"], cold["attainment"], cold["samples"]) \
        == (0.0, 1.0, 0.0)
    assert cold["age_s"] == float("inf")
    for lat in (0.01, 0.02, 0.01, 0.2):
        w.observe(lat)
    sig = w.signals()
    assert sig["samples"] == 4.0 and sig["age_s"] < 60.0
    assert sig["p99_ms"] > 50.0 and sig["attainment"] == 0.75
    w.reset()
    fresh = w.signals()
    assert fresh["samples"] == 0.0 and fresh["age_s"] == float("inf")


def test_stale_window_abstains_for_the_autoscaler():
    """An idle fleet stops producing latencies; the last crest's bad p99
    must not hold the drain decision hostage — past stale_after_s the
    latency signals abstain and the queue signal alone judges."""
    stub, auto = _autoscaler(dwell_down=2, stale_after_s=0.0)
    stub.n = 2
    for _ in range(8):  # a crest's worth of SLO-missing samples
        auto.window.observe(10.0)
    stub.depth = 0  # queue empty; samples stale (stale_after_s=0)
    evs = [auto.evaluate_once() for _ in range(3)]
    assert "down" in evs and stub.n == 1


# ------------------------------------------------------------ decision logic


class _ElasticStub(_StubServer):
    """Fleet double: the degrade surface plus the autoscaler's verbs and
    per-replica idle triplet. Replica 0 looks idle (old last-request age),
    later replicas look busy — the drain choice is observable."""

    def __init__(self, n: int = 1, queue_bound: int = 100):
        super().__init__(queue_bound=queue_bound)
        self.n = n
        self.replicas: list = []
        self.events: list = []

    def active_replicas(self) -> int:
        return self.n

    def add_replica(self) -> int:
        self.n += 1
        self.events.append("up")
        return self.n - 1

    def kill_replica(self, idx: int) -> dict:
        self.n -= 1
        self.events.append(("down", idx))
        return {"migrated": 0, "lost": 0, "restarted": 0}

    def stats(self) -> dict:
        return {
            "replica_active": [True] * self.n,
            "replica_inflight": [0] * self.n,
            "replica_last_request_age_s": [9.0] + [0.01] * (self.n - 1),
            "router_counts": [1] * self.n,
        }


def _autoscaler(stub=None, **kw) -> tuple:
    stub = stub if stub is not None else _ElasticStub()
    defaults = dict(min_replicas=1, max_replicas=2, dwell_up=2,
                    dwell_down=3, cooldown_s=0.0, idle_age_s=1.0,
                    min_samples=4)
    defaults.update(kw)
    return stub, Autoscaler(stub, AutoscaleConfig(**defaults))


def _diurnal_events(spec: ScenarioSpec, ticks: int = 64,
                    capacity_rate: float = None) -> list:
    """Drive one autoscaler through the seeded diurnal rate profile: each
    tick's queue depth is the offered-vs-capacity overhang at that point
    of the (pure, seeded) spec. Returns the scale-event sequence."""
    cap = capacity_rate if capacity_rate is not None else 1.5 * spec.base_rate
    stub, auto = _autoscaler()
    events = []
    for k in range(ticks):
        rate = spec.rate_at(spec.duration_s * k / ticks)
        over = max(rate - cap, 0.0) / cap
        stub.depth = min(stub.queue_bound, int(stub.queue_bound * over))
        ev = auto.evaluate_once()
        if ev is not None:
            events.append((k, ev, stub.n))
    assert auto.evaluations == ticks
    return events


def test_scale_events_deterministic_from_seeded_trace():
    """The controller is a pure function of its seeded scenario input: the
    diurnal crest buys exactly one scale-up, the falling edge drains it,
    and a second identical drive reproduces the event sequence tick-for-
    tick."""
    spec = ScenarioSpec(name="d", duration_s=8.0, base_rate=100.0,
                        rate_profile="diurnal", peak_mult=3.0, seed=11)
    events = _diurnal_events(spec)
    assert [e[1] for e in events] == ["up", "down"]
    up_tick, down_tick = events[0][0], events[1][0]
    assert up_tick < 32 <= down_tick  # up on the rise, down past the crest
    assert events[0][2] == 2 and events[1][2] == 1
    assert _diurnal_events(spec) == events  # bit-identical replay


def test_no_flap_on_oscillating_signal():
    """A signal bouncing between pressured and healthy every tick never
    accumulates either dwell: the fleet size parks."""
    stub, auto = _autoscaler(dwell_up=2, dwell_down=2)
    for k in range(40):
        stub.depth = 90 if k % 2 == 0 else 0
        assert auto.evaluate_once() is None
    assert stub.n == 1 and auto.scale_ups == 0 and auto.scale_downs == 0


def test_dead_band_holds_both_dwells():
    """Between the bands (healthy queue but not-yet-clean latency, or the
    mid-queue region) neither dwell advances — the ladder's dead-band
    semantics, reused."""
    stub, auto = _autoscaler(dwell_up=2, dwell_down=2)
    stub.depth = 10  # between queue_low (5) and queue_high (25)
    for _ in range(20):
        assert auto.evaluate_once() is None
    assert stub.n == 1


def test_scale_bounds_and_cooldown():
    """max_replicas caps growth, min_replicas floors the drain, and the
    post-event cooldown holds the next decision."""
    stub, auto = _autoscaler(max_replicas=2, cooldown_s=60.0)
    stub.depth = 90
    evs = [auto.evaluate_once() for _ in range(8)]
    # one scale-up, then the cooldown holds even under sustained pressure
    assert evs.count("up") == 1 and stub.n == 2
    stub2, auto2 = _autoscaler(min_replicas=1, dwell_down=2)
    stub2.depth = 0
    for _ in range(10):
        auto2.evaluate_once()
    assert stub2.n == 1 and auto2.scale_downs == 0  # floored at min


def test_drain_holds_until_a_replica_goes_idle():
    """drain_requires_idle (default): a healthy fleet whose replicas are
    all still talking parks at its current size — health signals
    describe the fleet at its CURRENT size, so a comfortable fleet must
    not drain into a crest. The drain fires only once some replica has
    demonstrably nothing to say."""
    class _BusyStub(_ElasticStub):
        def __init__(self):
            super().__init__(n=2)
            self.ages = [0.01, 0.01]

        def stats(self):
            st = super().stats()
            st["replica_last_request_age_s"] = list(self.ages)
            return st

    stub = _BusyStub()
    auto = Autoscaler(stub, AutoscaleConfig(
        min_replicas=1, max_replicas=2, dwell_down=2, cooldown_s=0.0,
        idle_age_s=1.0, min_samples=4,
    ))
    stub.depth = 0
    for _ in range(6):
        assert auto.evaluate_once() is None  # armed, holding
    assert stub.n == 2 and auto.drain_holds >= 4
    stub.ages[0] = 9.0  # replica 0 went quiet
    assert auto.evaluate_once() == "down" and stub.n == 1


def test_pressure_margin_buys_capacity_inside_the_slo():
    """The predictive trigger: p99 past margin*slo — but still INSIDE the
    SLO — is pressure, because a scale-up takes seconds to land and must
    be bought before misses start. At margin 1.0 the same latencies are
    healthy."""
    stub, auto = _autoscaler(pressure_margin=0.5, dwell_up=2)
    for _ in range(8):
        auto.window.observe(0.030)  # 30 ms: over 0.5*50, under the SLO
    evs = [auto.evaluate_once() for _ in range(3)]
    assert "up" in evs and stub.n == 2
    stub2, auto2 = _autoscaler(pressure_margin=1.0, dwell_up=2)
    for _ in range(8):
        auto2.window.observe(0.030)
    assert [auto2.evaluate_once() for _ in range(6)] == [None] * 6
    assert stub2.n == 1


def test_drain_picks_the_idle_replica():
    stub, auto = _autoscaler(dwell_down=2)
    stub.n = 2
    stub.depth = 0
    evs = [auto.evaluate_once() for _ in range(3)]
    assert ("down", 0) in stub.events  # replica 0 is the idle one
    assert "down" in evs


# ---------------------------------------------------------------- interlock


def test_interlock_gates_rung_up_until_scale_inflight():
    """The scale-vs-degrade interlock: under sustained pressure below
    max_replicas the ladder's rung-up is HELD (capacity answers, not
    quality) — and the held dwell fires the first tick the gate opens
    (here: the scale-up pins the fleet at max, so capacity can no longer
    answer; the cooldown itself does NOT hold the gate open — once a
    replica lands below max, the new capacity drains the backlog and the
    ladder stays parked)."""
    stub = _ElasticStub()
    stub.degrade = DegradeController(
        stub, DegradeConfig(dwell_up=2, dwell_down=3, min_samples=4,
                            eval_interval_s=0.01)
    )
    auto = Autoscaler(stub, AutoscaleConfig(
        min_replicas=1, max_replicas=2, dwell_up=3, cooldown_s=60.0,
        min_samples=4,
    ))
    assert auto.window is stub.degrade.window  # ONE shared window
    ctl = stub.degrade
    stub.depth = 90
    # ladder dwell (2) is satisfied first, but the gate is closed: held
    assert ctl.evaluate_once() is None
    assert ctl.evaluate_once() is None
    assert ctl.rung == 0 and ctl.gated_holds >= 1
    # autoscaler reaches ITS dwell (3) and scales up; gate now open
    for _ in range(3):
        auto.evaluate_once()
    assert stub.n == 2
    assert ctl.evaluate_once() == "admit"  # held dwell fires immediately
    # recovery is never gated
    stub.depth = 0
    for _ in range(3):
        ctl.window.observe(0.001)
        ctl.evaluate_once()
    assert ctl.rung == 0


def test_interlock_closes_once_the_replica_lands_below_max():
    """After a scale-up completes BELOW max_replicas the gate closes even
    inside the cooldown: the new capacity is draining the backlog, and an
    open gate there would let the ladder ratchet into the quality arms
    against a receding queue — a shed equilibrium."""
    stub = _ElasticStub()
    stub.degrade = DegradeController(
        stub, DegradeConfig(dwell_up=2, dwell_down=3, min_samples=4,
                            eval_interval_s=0.01)
    )
    auto = Autoscaler(stub, AutoscaleConfig(
        min_replicas=1, max_replicas=3, dwell_up=1, cooldown_s=60.0,
        min_samples=4,
    ))
    ctl = stub.degrade
    stub.depth = 90
    assert auto.evaluate_once() == "up" and stub.n == 2  # cooldown armed
    held = ctl.gated_holds
    # still pressured, still below max, deep inside the cooldown: the
    # ladder's dwell keeps being HELD, no rung fires
    assert [ctl.evaluate_once() for _ in range(4)] == [None] * 4
    assert ctl.rung == 0 and ctl.gated_holds > held


def test_interlock_opens_at_max_replicas():
    """A fleet pinned at max_replicas cannot answer with capacity: the
    ladder must be free to degrade exactly as before the autoscaler
    existed."""
    stub = _ElasticStub(n=2)
    stub.degrade = DegradeController(
        stub, DegradeConfig(dwell_up=2, dwell_down=3, min_samples=4,
                            eval_interval_s=0.01)
    )
    Autoscaler(stub, AutoscaleConfig(min_replicas=1, max_replicas=2,
                                     min_samples=4))
    ctl = stub.degrade
    stub.depth = 90
    steps = [ctl.evaluate_once() for _ in range(4)]
    assert steps == [None, "admit", None, "bf16"]
    assert ctl.gated_holds == 0


# ------------------------------------------------------- live fleet, bitwise


def test_fleet_scale_up_and_down_bit_exact():
    """The acceptance criterion: a live fleet grows by one replica and
    later drains one, mid-traffic — `sessions_lost == 0` through BOTH
    events and every session's response stream continues BITWISE, as if
    the fleet size never changed. The autoscaler thread is running (its
    dwells parked out of reach) so the supervised lifecycle is exercised;
    the events themselves fire through its verbs deterministically."""
    cfg = tiny_test().replace(
        serve_devices=1, serve_spill=64, serve_autoscale=True,
        autoscale_min_replicas=1, autoscale_max_replicas=2,
        autoscale_dwell_up=10**6, autoscale_dwell_down=10**6,
        # the mid-traffic drain is the point here (bitwise migration
        # under load); the idle-hold policy has its own unit test
        autoscale_drain_requires_idle=False,
    )
    srv = MultiDeviceServer(
        cfg, ServeConfig(buckets=(2, 4), max_wait_ms=1.0, cache_capacity=8)
    )
    assert srv.autoscale is not None
    srv.warmup()
    srv.start()
    client = LocalClient(srv)
    rng = np.random.default_rng(17)
    refs: dict = {}

    def step_all(sids, first: bool = False) -> None:
        for sid in sids:
            if first:
                refs[sid] = SessionReference(srv.net, cfg.hidden_dim)
            obs = rng.integers(0, 255, cfg.obs_shape, dtype=np.uint8)
            reward = float(rng.normal())
            res = client.act(sid, obs, reward=reward, reset=first)
            q_ref, a_ref = refs[sid].step(srv._params_host, obs, reward,
                                          first, bucket=res.bucket)
            np.testing.assert_array_equal(q_ref, np.asarray(res.q))
            assert a_ref == res.action

    gen_a = [f"ela-{s}" for s in range(8)]
    gen_b = [f"elb-{s}" for s in range(6)]
    try:
        step_all(gen_a, first=True)
        step_all(gen_a)
        # SCALE UP: spawn/warm/publish/activate — then keep serving. The
        # pre-scale sessions keep their replica-0 affinity and continue
        # bitwise across the fleet-size change.
        slot = srv.add_replica()
        assert slot == 1 and srv.active_replicas() == 2
        step_all(gen_a)
        # a second generation of sessions lands on the new (least-loaded)
        # replica, so the upcoming drain has real state to migrate
        step_all(gen_b, first=True)
        step_all(gen_a + gen_b)
        counts = srv.router.counts()
        assert counts[1] == len(gen_b)
        # SCALE DOWN through the autoscaler's own drain choice: the
        # less-loaded replica 1 is the victim, and every one of its
        # sessions migrates through the spill tier
        victim = srv.autoscale._pick_drain_victim()
        assert victim == 1
        outcome = srv.kill_replica(victim)
        assert outcome["lost"] == 0
        assert outcome["migrated"] == len(gen_b)
        assert srv.active_replicas() == 1
        # post-drain: the migrated carries promote from the survivor's
        # slab and BOTH generations continue their streams bit-for-bit
        step_all(gen_a + gen_b)
        step_all(gen_a + gen_b)
        srv.check()  # autoscaler supervisor folded into the fleet check
    finally:
        srv.stop()
    st = srv.stats()
    assert st["sessions_lost"] == 0
    assert st["sessions_migrated"] == len(gen_b)
    assert st["replicas_added"] == 1 and st["replicas_killed"] == 1
    assert st["autoscale_evaluations"] >= 0  # autoscale stats ride along
    assert len(st["replica_active"]) == 2
    assert st["replica_active"] == [True, False]
    assert len(st["replica_inflight"]) == 2
    assert len(st["replica_last_request_age_s"]) == 2


def test_added_replica_follows_fleet_publish():
    """A replica born after a reload serves the SAME params version as the
    fleet — and joins subsequent reloads (the adopt-under-one-version
    discipline in add_replica)."""
    cfg = tiny_test().replace(serve_devices=1, serve_spill=16)
    srv = MultiDeviceServer(
        cfg, ServeConfig(buckets=(2,), max_wait_ms=1.0, cache_capacity=8)
    )
    srv.warmup()
    srv.start()
    try:
        srv.add_replica()
        r0, r1 = srv.replicas
        assert r0._published[2] == r1._published[2]  # same version
        # a fleet-wide arm switch reaches the adopted replica too
        srv.set_arm("bf16")
        assert r0._published[3] == r1._published[3] == "bf16"
        assert r0._published[2] == r1._published[2]
    finally:
        srv.stop()


# ------------------------------------------------------- router elasticity


def test_router_bound_tracks_active_set():
    """The affinity-LRU bound is per-replica capacity x ACTIVE replicas:
    deactivation shrinks it (and trims), activation restores it."""
    from r2d2_tpu.serve.multi import SessionRouter

    r = SessionRouter(2, max_tracked=8)  # 4 per replica
    for i in range(8):
        r.route(f"s{i}")
    assert len(r._map) == 8
    r.deactivate(1)
    assert r.max_tracked == 4 and len(r._map) == 4
    assert r.dropped == 4
    r.activate(1)
    assert r.max_tracked == 8
    slot = r.add_slot()
    assert slot == 2 and r.active() == [True, True, False]
    r.activate(slot)
    assert r.max_tracked == 12
    assert r.active() == [True, True, True]


# --------------------------------------------------------- learner reshard


@pytest.mark.slow
def test_reshard_live_is_bit_exact(tmp_path):
    """The learner half of elasticity: snapshot -> reshard -> resume IN
    PROCESS, then keep training — bit-identical to a run that never
    resharded."""
    from r2d2_tpu.train import Trainer

    def build(sub):
        return tiny_test().replace(
            env_name="catch", checkpoint_dir=str(tmp_path / sub),
            snapshot_replay=True, training_steps=4, save_interval=2,
            learning_starts=48,
        )

    a = Trainer(build("a"))
    a.run_inline(env_steps_per_update=4)
    info = a.reshard_live(dp_size=1)
    assert info["replay_size"] == info["replay_size_before"]
    assert info["env_steps"] == info["env_steps_before"]
    a.cfg = a.cfg.replace(training_steps=6)
    a.run_inline(env_steps_per_update=4)

    b = Trainer(build("b").replace(training_steps=6))
    b.run_inline(env_steps_per_update=4)

    assert int(a.state.step) == int(b.state.step) == 6
    import jax

    for pa, pb in zip(jax.tree.leaves(a.state.params),
                      jax.tree.leaves(b.state.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    assert len(a.replay) == len(b.replay)
    assert a.replay.env_steps == b.replay.env_steps
    np.testing.assert_allclose(a.replay.tree.tree, b.replay.tree.tree,
                               rtol=1e-12)


def test_reshard_live_rejects_bad_inputs(tmp_path):
    from r2d2_tpu.train import Trainer

    cfg = tiny_test().replace(
        env_name="catch", checkpoint_dir=str(tmp_path / "c"),
        snapshot_replay=True, training_steps=1, learning_starts=48,
    )
    t = Trainer(cfg)
    with pytest.raises(ValueError, match="reshard_live accepts"):
        t.reshard_live(hidden_dim=128)
    with pytest.raises(NotImplementedError, match="single-process"):
        t.reshard_live(replay_plane="multihost")


# ----------------------------------------------------------- config gating


def test_autoscale_defaults_off_and_validates():
    cfg = tiny_test()
    assert cfg.serve_autoscale is False
    srv_cfg = cfg.replace(serve_devices=1)
    # default-off: no autoscaler object is even constructed
    srv = MultiDeviceServer(
        srv_cfg, ServeConfig(buckets=(2,), max_wait_ms=1.0,
                             cache_capacity=4)
    )
    assert srv.autoscale is None
    with pytest.raises(ValueError, match="autoscale"):
        cfg.replace(serve_autoscale=True, serve_devices=4,
                    autoscale_max_replicas=2).validate()
    with pytest.raises(ValueError, match="autoscale"):
        cfg.replace(autoscale_min_replicas=3,
                    autoscale_max_replicas=2).validate()


# ------------------------------------------------------- drain provenance


def test_drain_logs_victim_idle_age():
    """Every drain records WHICH replica went and how quiet it was —
    idle-age straight from the fleet's per-replica stats triplet — in
    stats()['autoscale_drain_log'] (the audit trail the pod-loop bench
    and ops dashboards read)."""
    stub, auto = _autoscaler(dwell_down=2)
    stub.n = 2
    stub.depth = 0
    evs = [auto.evaluate_once() for _ in range(3)]
    assert "down" in evs
    log = auto.stats()["autoscale_drain_log"]
    assert len(log) == 1
    entry = log[0]
    assert entry["replica"] == 0  # the idle one (age 9.0 in the stub)
    assert entry["idle_age_s"] == pytest.approx(9.0)
    assert entry["inflight"] == 0
    assert entry["affinities"] == 1
    # a held drain (nobody idle) logs nothing
    class _Busy(_ElasticStub):
        def stats(self):
            st = super().stats()
            st["replica_last_request_age_s"] = [0.01] * self.n
            return st

    stub2 = _Busy(n=2)
    auto2 = Autoscaler(stub2, AutoscaleConfig(
        min_replicas=1, max_replicas=2, dwell_down=2, cooldown_s=0.0,
        idle_age_s=1.0, min_samples=4,
    ))
    stub2.depth = 0
    for _ in range(4):
        auto2.evaluate_once()
    assert auto2.drain_holds >= 1
    assert auto2.stats()["autoscale_drain_log"] == []


def test_drain_during_active_tap_never_strands_accumulator():
    """A drain that LOSES sessions (no spill room on the survivor) must
    disconnect them from the fleet-shared liveloop hooks too: each lost
    session's partial block is cut into the ingest stream and its tap
    accumulator stream closes — nothing is stranded unflushed with no
    writer left."""
    from r2d2_tpu.liveloop import LiveLoopPlane

    class _Sink:
        def __init__(self):
            self.items = []

        def add_blocks_batch(self, items):
            self.items.extend(items)

    cfg = tiny_test().replace(serve_devices=1, serve_spill=8, liveloop=True)
    srv = MultiDeviceServer(
        cfg, ServeConfig(buckets=(2,), max_wait_ms=1.0, cache_capacity=8)
    )
    sink = _Sink()
    plane = LiveLoopPlane(cfg, srv, sink)  # hooks installed, driven inline
    srv.warmup()
    srv.start()
    client = LocalClient(srv)
    rng = np.random.default_rng(23)

    def step_all(sids, first=False):
        for sid in sids:
            obs = rng.integers(0, 255, cfg.obs_shape, dtype=np.uint8)
            client.act(sid, obs, reward=0.1, reset=first)

    gen_a = [f"keep-{i}" for i in range(3)]
    gen_b = [f"lose-{i}" for i in range(3)]
    try:
        step_all(gen_a, first=True)
        srv.add_replica()
        step_all(gen_b, first=True)  # land on the new least-loaded replica
        step_all(gen_a + gen_b)      # a couple of captured transitions each
        plane.tap.process_pending(timeout=0.0)
        assert plane.tap.stats()["tap_open_sessions"] == 6
        assert all(srv.router.peek(s) == 1 for s in gen_b)
        # survivor refuses every migrating row: all of replica 1's
        # sessions are genuinely lost mid-ingest
        srv.replicas[0].cache.import_spilled = lambda *a, **k: False
        outcome = srv.kill_replica(1)
        assert outcome["lost"] == len(gen_b)
        # the lost sessions' queued evictions cut their partials and close
        # their streams; the survivors' accumulators are untouched
        plane.tap.process_pending(timeout=0.0)
        st = plane.tap.stats()
        assert st["tap_open_sessions"] == len(gen_a)
        assert st["tap_emitted_blocks"] == len(gen_b)  # the cut partials
        plane.bridge.drain_once()
        assert len(sink.items) == len(gen_b)  # ...and they reached replay
    finally:
        plane.stop()
        srv.stop()
    assert srv.stats()["sessions_lost"] == len(gen_b)
