"""Pod-loop processes: many serve hosts feed one learner over the
block-stream transport, across REAL process boundaries.

This module is the single definition of both process bodies — `bench.py
--mode podloop` and the transport tests spawn the same code paths the
module's own CLI exposes:

    python -m r2d2_tpu.transport.podloop --role serve \
        --learner-port P --host-id h0 --spool-dir /tmp/spool --stats s.jsonl
    python -m r2d2_tpu.transport.podloop --role learner \
        --port P --stats s.jsonl

Serve host process: a one-replica `MultiDeviceServer` behind the stock
JSON-lines TCP frontend, with the full liveloop capture stack
(`LiveLoopPlane`) — except the plane's "replay" is a
`BlockStreamPublisher`, so finished Blocks stream to the learner instead
of landing in a local store. Checkpoints arrive back over the same
socket; the CKPT apply reconstructs the param tree against the host's
own template treedef and runs the fleet publish
(`MultiDeviceServer.publish_params`), so hot-reload needs no shared
filesystem.

Learner process: a `LiveLoopTrainer` whose replay store fills from an
`IngestService`; every `save_interval` crossing broadcasts the freshly
trained params to every connected host.

Both processes append one JSON line per second to `--stats` (counters
only, no analysis) and exit cleanly on SIGTERM after draining — the
bench driver owns traffic generation, the SIGKILL drill, and all
assertions.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

import numpy as np


def podloop_config(seed: int, checkpoint_dir: str, spool_dir: str = ""):
    """The ONE config both roles build: the serve hosts' network must be
    architecturally identical to the learner's (the CKPT broadcast ships
    leaves only; the treedef is reconstructed locally)."""
    from r2d2_tpu.config import tiny_test

    return tiny_test().replace(
        env_name="catch",
        action_dim=3,
        liveloop=True,
        checkpoint_dir=checkpoint_dir,
        save_interval=20,
        learning_starts=128,
        buffer_capacity=4096,
        training_steps=1_000_000,  # wall clock, not step count, ends the run
        serve_spill=64,
        transport_spool_dir=spool_dir,
        transport_heartbeat_s=0.5,
        transport_dead_peer_s=5.0,
    ).validate()


def _emit_stats(path: str, row: dict) -> None:
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps(row, default=str) + "\n")


def _install_sigterm(stop: threading.Event) -> None:
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())


def run_serve_host(
    host_id: str,
    learner_port: int,
    port: int = 0,
    spool_dir: str = "",
    stats_path: str = "",
    seed: int = 0,
    learner_host: str = "127.0.0.1",
    stats_interval_s: float = 1.0,
) -> None:
    import jax

    from r2d2_tpu.liveloop import LiveLoopPlane
    from r2d2_tpu.serve import MultiDeviceServer, ServeConfig
    from r2d2_tpu.serve.client import serve_tcp
    from r2d2_tpu.transport.publisher import BlockStreamPublisher

    cfg = podloop_config(seed, checkpoint_dir="", spool_dir=spool_dir)
    serve_cfg = ServeConfig(
        buckets=(2, 4, 8), max_wait_ms=2.0, cache_capacity=32,
        poll_interval_s=3600.0,  # no fs watcher: reloads arrive over CKPT
        seed=seed,
    )
    d0 = jax.local_devices()[0]
    server = MultiDeviceServer(cfg, serve_cfg, devices=[d0])
    treedef = jax.tree.structure(server._template.params)
    leaf_template = jax.tree.leaves(server._template.params)

    def apply_ckpt(leaves, step, version):
        if len(leaves) != len(leaf_template):
            raise ValueError(
                f"CKPT leaf count {len(leaves)} != template "
                f"{len(leaf_template)} — config drift between learner "
                "and serve host"
            )
        params = jax.tree.unflatten(treedef, leaves)
        server.publish_params(params, step, version=version)

    publisher = BlockStreamPublisher(
        cfg, (learner_host, learner_port), host_id,
        on_checkpoint=apply_ckpt, seed=seed,
    )
    plane = LiveLoopPlane(cfg, server, replay=publisher, seed=seed)
    # the tap appends each block's audit entry immediately before the
    # emit that reaches the publisher, on the same thread — so "freshest
    # audit-tail entry" is exactly the block being offered
    publisher.audit_source = (
        lambda: plane.tap.audit_tail[-1] if plane.tap.audit_tail else None
    )

    server.warmup()
    server.start(watch_checkpoints=False)
    plane.start()
    publisher.start()
    tcp, _ = serve_tcp(server, port=port)

    stop = threading.Event()
    _install_sigterm(stop)
    print(json.dumps({
        "podloop_ready": True, "role": "serve", "host": host_id,
        "serve_port": tcp.server_address[1],
    }), flush=True)

    t0 = time.time()
    while not stop.is_set():
        plane.check()
        publisher.check()
        server.check()
        _emit_stats(stats_path, {
            "t": round(time.time() - t0, 3), "role": "serve",
            "host": host_id,
            **{k: v for k, v in server.stats().items()
               if isinstance(v, (int, float, str, bool))},
            **plane.stats(),
            **publisher.stats(),
        })
        stop.wait(stats_interval_s)

    tcp.shutdown()
    tcp.server_close()
    plane.stop()        # final tap/bridge drains land in the publisher
    publisher.stop()    # flush: spool -> learner, best effort
    server.stop()
    _emit_stats(stats_path, {
        "t": round(time.time() - t0, 3), "role": "serve", "host": host_id,
        "final": True,
        **{k: v for k, v in server.stats().items()
           if isinstance(v, (int, float, str, bool))},
        **plane.stats(), **publisher.stats(),
    })


def run_learner(
    port: int,
    checkpoint_dir: str,
    stats_path: str = "",
    seed: int = 0,
    host: str = "127.0.0.1",
    stats_interval_s: float = 1.0,
) -> None:
    import jax

    from r2d2_tpu.liveloop import LiveLoopTrainer
    from r2d2_tpu.transport.ingest import IngestService

    cfg = podloop_config(seed, checkpoint_dir=checkpoint_dir)
    trainer = LiveLoopTrainer(cfg)
    version = {"n": 0}
    service = IngestService(
        cfg, trainer.replay, host=host, port=port,
        version_source=lambda: version["n"],
    )
    service.start()

    stop = threading.Event()
    _install_sigterm(stop)
    print(json.dumps({
        "podloop_ready": True, "role": "learner",
        "ingest_port": service.port,
    }), flush=True)

    t0 = time.time()
    last_stats = 0.0
    last_ckpt_bucket = 0
    while not stop.is_set():
        service.check()
        if trainer.can_train():
            trainer.train(8, deadline=time.monotonic() + 0.5)
        else:
            stop.wait(0.05)
        bucket = trainer.step // cfg.save_interval
        if bucket > last_ckpt_bucket:
            last_ckpt_bucket = bucket
            version["n"] += 1
            leaves = [
                np.asarray(x)
                for x in jax.tree.leaves(trainer.trainer.state.params)
            ]
            service.broadcast_checkpoint(leaves, trainer.step, version["n"])
        now = time.time()
        if now - last_stats >= stats_interval_s:
            last_stats = now
            _emit_stats(stats_path, {
                "t": round(now - t0, 3), "role": "learner",
                "params_version": version["n"],
                **trainer.stats(), **service.stats(),
            })

    trainer.finish()
    service.stop()
    _emit_stats(stats_path, {
        "t": round(time.time() - t0, 3), "role": "learner", "final": True,
        "params_version": version["n"],
        **trainer.stats(), **service.stats(),
    })


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="pod-loop process bodies")
    p.add_argument("--role", required=True, choices=["serve", "learner"])
    p.add_argument("--port", type=int, default=0,
                   help="serve: TCP frontend port; learner: ingest port")
    p.add_argument("--learner-port", type=int, default=0,
                   help="serve role: the learner's ingest port")
    p.add_argument("--learner-host", default="127.0.0.1")
    p.add_argument("--host-id", default="h0")
    p.add_argument("--spool-dir", default="")
    p.add_argument("--ckpt-dir", default="")
    p.add_argument("--stats", default="")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.role == "serve":
        if not args.learner_port:
            p.error("--role serve requires --learner-port")
        run_serve_host(
            host_id=args.host_id, learner_port=args.learner_port,
            port=args.port, spool_dir=args.spool_dir,
            stats_path=args.stats, seed=args.seed,
            learner_host=args.learner_host,
        )
    else:
        run_learner(
            port=args.port, checkpoint_dir=args.ckpt_dir,
            stats_path=args.stats, seed=args.seed,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
