#!/bin/bash
# SUPERSEDED after rung 1: the 12x12 rung below is geometrically invalid
# (obs 64 not divisible into a 12-cell grid) — rungs 2-3 are replaced by
# runs/run_r5h2_chain.sh (16x16 warm-started directly from the 8x8 seed,
# the corrected round-4 protocol). Kept as provenance for the rung-1
# (procmaze8_r5) invocation, which completed successfully.
# Round-5 chain H (queued behind chain G): make the 16x16 procmaze rung
# decisive on the POSITIVE side (VERDICT r4 item 5's first arm).
#
# Where the evidence stands after chain D: from-scratch 16x16 is
# decisively DEAD — 120k updates (4x the round-4 budget) with the
# flattened exploration ladder (eps_alpha=3) land 3.2-6.8 sigma BELOW
# the measured random-walk null at every one of 16 checkpoints
# (runs/procmaze16_flat/eval_stats.jsonl: means 0.05-0.09 vs null
# 0.1434 +/- 0.008 at n=2048) — the greedy policy learns a systematically
# WORSE-than-random behavior at this scale. Round 4's warm-started
# ladder (8x8 solved -> 12x12 +30k -> 16x16 +30k) was above its
# baseline at every final checkpoint but under-powered: +0.02..+0.038
# margins at n=256 are ~1-2 sigma each.
#
# This chain replicates the round-4 ladder EXACTLY (same recipe, same
# budgets, fresh dirs — the r4 checkpoint dirs were cleaned at the
# session boundary so no warm seed survives) and then measures the
# 16x16 series with the round-5 z-instrument (runs/eval_stats.py) at
# n=1024 episodes/checkpoint, which puts the per-checkpoint stderr at
# ~0.009 and makes a +0.03 margin a ~3-sigma read. Verdict criteria
# (pre-registered): final-three-checkpoint margins all positive with
# pooled z >= 3 on their mean => the rung is decisively above-null via
# transfer; positive but z < 3 => the honest "consistently above,
# modest magnitude" read stands with real error bars; at/below null =>
# the round-4 warm result does not replicate and the rung is recorded
# as open.
cd /root/repo
while ! grep -q R5G_CHAIN_ALL_DONE runs/r5g_chain.log 2>/dev/null; do sleep 60; done

. runs/lib.sh

# rung 1: 8x8 from scratch (the round-3 recipe verbatim).
# RELAUNCH NOTE: the first firing of this chain died at startup —
# MetricsLogger open()s cfg.metrics_path without creating the parent
# directory, and this script (unlike the r3/r4 chains) had no mkdir for
# the rung-1 dir; worse, the failure CASCADED silently (rung 2's cp had
# no source, rung 3's --resume on an empty ckpt dir started a useless
# fresh 16x16 run). Fixed: mkdir -p per rung + a hard gate on the
# previous rung's checkpoint existing before any warm rung may start.
mkdir -p runs/procmaze8_r5
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:8 \
  --mode fused --steps 30000 --updates-per-dispatch 16 \
  --set checkpoint_dir=runs/procmaze8_r5/ckpt \
  --set metrics_path=runs/procmaze8_r5/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE8_R5 TRAIN EXIT: $? ==="

# rung 2: 12x12 warm-started from the 8x8 policy (+30k)
if [ ! -d runs/procmaze8_r5/ckpt/step_30000 ]; then
  echo "=== ABORT: rung-1 checkpoint missing; warm rungs would silently run fresh ==="
  echo R5H_CHAIN_ALL_DONE
  exit 1
fi
mkdir -p runs/procmaze12_warm2/ckpt
if [ ! -d runs/procmaze12_warm2/ckpt/step_30000 ]; then
  cp -r runs/procmaze8_r5/ckpt/step_30000 runs/procmaze12_warm2/ckpt/step_30000
fi
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:12 \
  --mode fused --steps 60000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze12_warm2/ckpt \
  --set metrics_path=runs/procmaze12_warm2/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE12_WARM2 TRAIN EXIT: $? ==="

# rung 3: 16x16 warm-started from the 12x12 policy (+30k)
if [ ! -d runs/procmaze12_warm2/ckpt/step_60000 ]; then
  echo "=== ABORT: rung-2 checkpoint missing; warm rung would silently run fresh ==="
  echo R5H_CHAIN_ALL_DONE
  exit 1
fi
mkdir -p runs/procmaze16_warm2/ckpt
if [ ! -d runs/procmaze16_warm2/ckpt/step_60000 ]; then
  cp -r runs/procmaze12_warm2/ckpt/step_60000 runs/procmaze16_warm2/ckpt/step_60000
fi
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
  --mode fused --steps 90000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze16_warm2/ckpt \
  --set metrics_path=runs/procmaze16_warm2/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE16_WARM2 TRAIN EXIT: $? ==="

# the decisive measurement: n=1024/checkpoint, z vs the measured null
python runs/eval_stats.py --preset procgen_impala --env procmaze_shaped:16 \
  --ckpt runs/procmaze16_warm2/ckpt --episodes 1024 --null-episodes 2048 \
  --set forward_steps=20 --set num_actors=16 \
  --out runs/procmaze16_warm2/eval_stats.jsonl
echo "=== PROCMAZE16_WARM2 STATS EXIT: $? ==="

echo R5H_CHAIN_ALL_DONE
