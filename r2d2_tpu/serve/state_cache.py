"""Device-resident session-state cache for the serving plane.

R2D2's policy is stateful: every user session carries an LSTM carry plus
its last action and last reward across requests (models/r2d2.py `act`).
Shipping that state to the client and back would add two host<->device
round trips of 2*H floats per request; instead the state lives HERE, in
fixed-capacity device arrays, and requests carry only a session id. Batch
formation gathers the rows for the sessions in the batch, the jitted serve
step advances them, and the updated rows scatter back — recurrent state
never leaves the device between requests.

Host side this is an LRU map session_id -> slot index (an OrderedDict —
hits move to the back, evictions pop the front). A session that was
evicted and returns is re-admitted FRESH (zero carry, NOOP last action,
zero last reward — exactly the training episode-start state,
models/r2d2.py `initial_carry`), which is also what per-session reset
produces. The device arrays hold one extra scratch row at index
`capacity`: padding rows of a bucketed batch gather from and scatter into
it, so partially-full batches need no masking inside the jitted step.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class RecurrentStateCache:
    """Fixed-capacity device store: session_id -> (carry, last_action,
    last_reward) with LRU eviction.

    Array mutation (`arrays` / `commit`) is single-writer by contract —
    only the serve loop touches the device rows. The host-side map is
    lock-protected so `reset` / `evict` / `stats` may be called from any
    thread.
    """

    def __init__(self, capacity: int, hidden_dim: int, dtype=jnp.float32):
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        self.capacity = capacity
        self.hidden_dim = hidden_dim
        # carry storage dtype: float32, or bfloat16 under the bf16
        # precision policy (cfg.state_dtype) — halves per-session HBM
        self.dtype = jnp.dtype(dtype)
        # +1 scratch row for bucket padding (gathered/scattered harmlessly)
        self.h = jnp.zeros((capacity + 1, hidden_dim), self.dtype)
        self.c = jnp.zeros((capacity + 1, hidden_dim), self.dtype)
        self.last_action = jnp.zeros((capacity + 1,), jnp.int32)
        self.last_reward = jnp.zeros((capacity + 1,), jnp.float32)
        self._slots: "OrderedDict[str, int]" = OrderedDict()
        self._free: List[int] = list(range(capacity))
        self._lock = threading.Lock()
        self.evictions = 0
        self.admissions = 0

    @property
    def pad_slot(self) -> int:
        """The scratch row index padding gathers/scatters target."""
        return self.capacity

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def __contains__(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._slots

    # ------------------------------------------------------------ admission

    def assign(self, session_ids: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Map session ids to slot indices, admitting unknown sessions
        (evicting the LRU session when full). Returns (slots, fresh) where
        fresh[i] marks sessions that must start from zero state (new,
        or evicted-and-readmitted). Ids must be unique within one call —
        the batcher guarantees at most one request per session per batch.
        """
        if len(set(session_ids)) != len(session_ids):
            raise ValueError("duplicate session ids in one batch")
        slots = np.empty(len(session_ids), np.int32)
        fresh = np.zeros(len(session_ids), bool)
        with self._lock:
            for i, sid in enumerate(session_ids):
                slot = self._slots.get(sid)
                if slot is None:
                    fresh[i] = True
                    self.admissions += 1
                    if self._free:
                        slot = self._free.pop()
                    else:
                        # evict the least-recently-used session NOT part of
                        # this batch (batch members were just admitted to
                        # the back of the order, so the front is safe)
                        _, slot = self._slots.popitem(last=False)
                        self.evictions += 1
                self._slots[sid] = slot
                self._slots.move_to_end(sid)
                slots[i] = slot
        return slots, fresh

    def reset(self, session_id: str) -> None:
        """Forget a session's state without freeing its slot: the next
        request re-runs admission-fresh semantics via the reset flag, so
        dropping the mapping is enough (and cheaper than touching device
        rows from a foreign thread)."""
        self.evict(session_id)

    def evict(self, session_id: str) -> bool:
        """Explicitly free a session's slot (client disconnect)."""
        with self._lock:
            slot = self._slots.pop(session_id, None)
            if slot is None:
                return False
            self._free.append(slot)
            return True

    # ------------------------------------------------------------ device IO

    def arrays(self):
        """The device arrays the jitted serve step reads and rewrites."""
        return self.h, self.c, self.last_action, self.last_reward

    def commit(self, h, c, last_action, last_reward) -> None:
        """Install the serve step's updated arrays (serve-loop thread
        only). The old arrays may have been donated into the step."""
        self.h, self.c = h, c
        self.last_action, self.last_reward = last_action, last_reward

    @property
    def session_carry_bytes(self) -> int:
        """Device bytes of recurrent state per session: h + c rows."""
        return 2 * self.hidden_dim * self.dtype.itemsize

    def stats(self) -> dict:
        with self._lock:
            return {
                "cache_sessions": len(self._slots),
                "cache_capacity": self.capacity,
                "cache_evictions": self.evictions,
                "cache_admissions": self.admissions,
                "cache_dtype": self.dtype.name,
                "session_carry_bytes": self.session_carry_bytes,
            }
