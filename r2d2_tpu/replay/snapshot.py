"""Replay snapshots: persist the full replay state for true resume.

The reference has no resume path at all (SURVEY.md section 5.4); even this
framework's learner checkpoints (utils/checkpoint.py) restore optimization
exactly but refill replay from fresh experience. For workloads where replay
contents matter across restarts (long warmups, offline analysis, failure
recovery mid-curriculum), these helpers save and restore EVERYTHING the
replay subsystem holds:

- control plane: sum-tree leaf priorities, circular block pointer, size /
  env-step / episode accounting, per-slot sequence counts, staleness state;
- data plane: every store field — host numpy arrays (ReplayBuffer),
  single-chip HBM stores (DeviceReplayBuffer, downloaded/uploaded once),
  or dp-sharded HBM stores (ShardedDeviceReplay, restored with their
  NamedSharding intact).

A restored buffer is bit-identical to the saved one: sampling with the same
RNG stream yields the same batches (pinned by tests/test_snapshot.py).
Consistency: the whole payload is captured under the buffer lock(s), so a
snapshot taken while collection threads are writing is a clean point-in-time
cut; the file write itself happens outside the locks and lands atomically
(temp file + os.replace), so a crash mid-write can never leave a truncated
snapshot that poisons --resume.

Format: one .npz (uncompressed — obs dominate and are incompressible-ish
uint8; write speed matters more). Obs storage dominates the file size:
~7 KB/transition at 84x84, so snapshot cadence is the caller's cost knob —
the Trainer writes one at end-of-run when cfg.snapshot_replay is set and
restores it on --resume.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import ml_dtypes
import numpy as np

from r2d2_tpu.replay.control_plane import ReplayControlPlane
from r2d2_tpu.replay.device_store import DeviceReplayBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.utils.faults import fault_point

STORE_FIELDS = (
    "obs", "last_action", "last_reward", "action", "n_step_reward",
    "gamma", "hidden", "burn_in", "learning", "forward",
)

# ptr_advances is the lap-detection stamp deferred write-backs compare
# against; dropping it across a resume would let a stale write-back land
# after a full buffer lap. Old snapshots (pre ptr_advances) restore with 0.
_COUNTERS = (
    "block_ptr", "size", "env_steps", "num_episodes", "episode_reward_sum",
    "total_episodes", "total_reward_sum", "ptr_advances",
)

# extras ride in the same npz under this prefix (mid-run carry: trainer
# RNG / actor / env / pending write-back state), so snapshot + carry land
# or are lost atomically — one os.replace
_EXTRA_PREFIX = "x_"

# topology manifest keys ride under this prefix: every snapshot records
# the (dp, tp, process_count) layout it was written under, the global
# block ranges its slabs cover, and its RNG stream identity, so a resume
# on a DIFFERENT layout can regather the slabs (replay/reshard.py)
# instead of aborting
_TOPO_PREFIX = "topo_"


class TopologyMismatch(ValueError):
    """A snapshot's recorded topology differs from the replay restoring it.

    Carries structured `saved` and `current` dicts (plane, dp, tp,
    process_count, local_ids, ...) so callers — the Trainer's resume path,
    the reshard CLI — can decide programmatically; the message names the
    escape hatch. Subclasses ValueError so pre-elasticity callers that
    caught the bare layout error keep working."""

    def __init__(self, saved: Dict, current: Dict, detail: str = ""):
        self.saved = dict(saved)
        self.current = dict(current)

        def _fmt(t: Dict) -> str:
            return (
                f"plane={t.get('plane')} dp={t.get('dp')} tp={t.get('tp')} "
                f"process_count={t.get('process_count')} "
                f"local_ids={t.get('local_ids')}"
            )

        msg = f"snapshot topology [{_fmt(self.saved)}] != current [{_fmt(self.current)}]"
        if detail:
            msg += f" ({detail})"
        msg += (
            " — pass --reshard (cfg.reshard_on_resume) to regather the "
            "replay slabs and re-split them across the new layout"
        )
        super().__init__(msg)


def snapshot_topology(replay, tp: int = 1) -> Dict[str, np.ndarray]:
    """The topology manifest a snapshot embeds: which layout wrote it.

    Records the logical shard structure (dp, blocks per shard), the
    process layout (process_count/index, the global shard ids THIS file
    holds), the per-slab partition map rows this host owns (global block
    ranges, mirroring parallel/mesh.slab_partition_map), and the
    per-logical-shard RNG stream identity (the multihost draw stream is
    keyed (seed, GLOBAL shard id, epoch) — layout-independent by design,
    which is exactly what makes elastic resume deterministic per logical
    shard). `tp` is the mesh's tensor-parallel degree; the replay object
    alone cannot know it, so snapshot writers pass it explicitly (the
    snapshot-missing-topology lint keeps them honest)."""
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay
    from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay

    cfg = replay.cfg
    nb = cfg.num_blocks
    if isinstance(replay, MultiHostShardedReplay):
        plane, dp = "multihost", replay.dp
        local_ids = list(replay.local_ids)
        bps = replay.blocks_per_shard
        seed, epoch = replay._seed, replay._epoch
    elif isinstance(replay, ShardedDeviceReplay):
        plane, dp = "sharded", replay.dp
        local_ids = list(range(replay.dp))
        bps = replay.blocks_per_shard
        seed = epoch = 0
    elif isinstance(replay, DeviceReplayBuffer):
        plane, dp, local_ids, bps, seed, epoch = "device", 1, [0], nb, 0, 0
    elif isinstance(replay, ReplayBuffer):
        plane, dp, local_ids, bps, seed, epoch = "host", 1, [0], nb, 0, 0
    else:
        raise TypeError(f"unknown replay type {type(replay).__name__}")
    return {
        "plane": np.asarray(plane),
        "dp": np.asarray(dp, np.int64),
        "tp": np.asarray(tp, np.int64),
        "process_count": np.asarray(jax.process_count(), np.int64),
        "process_index": np.asarray(jax.process_index(), np.int64),
        "num_blocks": np.asarray(nb, np.int64),
        "blocks_per_shard": np.asarray(bps, np.int64),
        "seqs_per_block": np.asarray(cfg.seqs_per_block, np.int64),
        "local_ids": np.asarray(local_ids, np.int64),
        "slab_ranges": np.asarray(
            [[g * bps, (g + 1) * bps] for g in local_ids], np.int64
        ).reshape(len(local_ids), 2),
        "rng_streams": np.asarray(local_ids, np.int64),
        "rng_seed": np.asarray(seed, np.int64),
        "rng_epoch": np.asarray(epoch, np.int64),
        # disk tier below the host slab (0 = no tier): reshard's
        # gather_logical flattens these records into plain store rows
        "disk_blocks": np.asarray(
            getattr(getattr(replay, "disk", None), "disk_blocks", 0), np.int64
        ),
    }


def _plain(topo: Dict) -> Dict:
    """A manifest as plain python scalars/lists (json-able, error-printable)."""
    out = {}
    for k, v in topo.items():
        v = np.asarray(v)
        if v.dtype.kind in ("U", "S"):
            out[k] = str(v)
        elif v.ndim == 0:
            out[k] = int(v)
        else:
            out[k] = v.tolist()
    return out


def _topology_from(d) -> Optional[Dict]:
    """Extract the plain-form manifest from an open npz (view); None for
    pre-manifest snapshots."""
    names = getattr(d, "files", None) or list(d)
    if _TOPO_PREFIX + "plane" not in names:
        return None
    return _plain({
        k[len(_TOPO_PREFIX):]: d[k]
        for k in names
        if k.startswith(_TOPO_PREFIX)
    })


def read_manifest(path: str) -> Optional[Dict]:
    """The topology manifest embedded in a snapshot file, as plain python
    values; None for pre-manifest snapshots."""
    with np.load(path, allow_pickle=False) as npz:
        return _topology_from(npz)


def _plane_state(plane: ReplayControlPlane, prefix: str = "") -> Dict[str, np.ndarray]:
    d = {prefix + "tree_leaves": plane.tree.leaves()}
    if plane.dtree is not None:
        # priority_plane="device": the float32 HBM tree is AUTHORITATIVE
        # for sampling and carries the learner's write-backs (the host
        # tree only sees ingestion there) — snapshot its leaves so
        # --resume continues from the same priority distribution
        d[prefix + "dtree_leaves"] = np.asarray(plane.dtree.leaves(), np.float32)
    for k in _COUNTERS:
        d[prefix + k] = np.asarray(getattr(plane, k))
    d[prefix + "learning_sum"] = plane.learning_sum.copy()
    d[prefix + "occupied"] = plane.occupied.copy()
    d[prefix + "num_seq_store"] = plane.num_seq_store.copy()
    return d


def _restore_plane(plane: ReplayControlPlane, d, prefix: str = "") -> None:
    plane.tree.load_leaves(d[prefix + "tree_leaves"])
    names = getattr(d, "files", None) or list(d)
    if plane.dtree is not None:
        if prefix + "dtree_leaves" in names:
            plane.dtree.load_leaves(d[prefix + "dtree_leaves"])
        else:
            # host-plane snapshot restored under priority_plane="device":
            # seed the device tree from the host leaves (f64 -> f32, the
            # parity-bounded drift class, ARCHITECTURE.md)
            plane.dtree.load_leaves(
                np.asarray(d[prefix + "tree_leaves"], np.float32)
            )
    for k in _COUNTERS:
        if prefix + k not in names:  # pre-ptr_advances snapshot
            setattr(plane, k, 0)
            continue
        v = d[prefix + k][()]
        setattr(plane, k, float(v) if "reward" in k else int(v))
    plane.learning_sum[:] = d[prefix + "learning_sum"]
    plane.occupied[:] = d[prefix + "occupied"]
    plane.num_seq_store[:] = d[prefix + "num_seq_store"]


def _check_kind(kind: str, want: str, replay, saved_topo: Optional[Dict]) -> None:
    if kind != want:
        raise TopologyMismatch(
            saved_topo or {"plane": kind},
            _plain(snapshot_topology(replay)),
            f"snapshot kind {kind!r} != replay plane {want!r}",
        )


def _validated_stores(
    d, current: Dict[str, np.ndarray], prefix: str = "store_"
) -> Dict[str, np.ndarray]:
    """Load every store field from the npz ONCE (NpzFile re-parses per
    access, and obs dominate the file), checking shape/dtype against the
    live buffer BEFORE the caller mutates anything — a mismatched snapshot
    must leave the buffer untouched."""
    out = {}
    for k in STORE_FIELDS:
        cur = current[k]
        val = d[prefix + k]
        if val.shape != cur.shape or val.dtype != cur.dtype:
            raise ValueError(
                f"store {prefix}{k}: snapshot {val.shape}/{val.dtype} != "
                f"buffer {cur.shape}/{cur.dtype}"
            )
        out[k] = val
    return out


# bfloat16 stores (precision="bf16" carry slabs, and actor carries in the
# extras payload under bf16 compute) cannot ride npz directly: np.savez
# writes the ml_dtypes extension dtype but np.load hands it back as raw
# void bytes. Round-trip them as uint16 bit-views plus a key manifest —
# the restore side views them back, so _validated_stores still sees the
# exact storage dtype and `--resume` stays bit-exact per plane.
_BF16 = np.dtype(ml_dtypes.bfloat16)
_BF16_KEYS = "bf16_keys"


def _encode_bf16(payload: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    keys = sorted(k for k, v in payload.items() if v.dtype == _BF16)
    if not keys:
        return payload
    out = dict(payload)
    for k in keys:
        out[k] = payload[k].view(np.uint16)
    out[_BF16_KEYS] = np.asarray(keys)
    return out


class _Bf16NpzView:
    """Read-side counterpart of _encode_bf16: an NpzFile facade that hands
    back bfloat16 arrays with their dtype restored."""

    def __init__(self, npz):
        self._npz = npz
        self._bf16 = (
            {str(k) for k in npz[_BF16_KEYS]} if _BF16_KEYS in npz.files else set()
        )
        self.files = [k for k in npz.files if k != _BF16_KEYS]

    def __getitem__(self, k):
        v = self._npz[k]
        return v.view(_BF16) if k in self._bf16 else v


def _atomic_savez(path: str, payload: Dict[str, np.ndarray]) -> None:
    # keep the .npz suffix on the temp name: np.savez APPENDS .npz to
    # filenames without it, which would break the rename
    fault_point("snapshot.write")
    tmp = path + ".tmp.npz"
    np.savez(tmp, **_encode_bf16(payload))
    os.replace(tmp, path)


def save_replay(
    replay,
    path: str,
    extra: Optional[Dict[str, np.ndarray]] = None,
    topology: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Snapshot any replay plane (host / device / sharded) to `path`.

    The payload (control state + a copy of every store) is captured under
    the buffer lock; the npz write happens after release. `extra` carries
    caller state (trainer RNG / actor / env / pending write-backs) in the
    same file under a reserved prefix — restore_replay hands it back.
    `topology` is the snapshot_topology manifest; callers that know the
    mesh pass snapshot_topology(replay, tp=...) explicitly (enforced by
    the snapshot-missing-topology lint), None derives a tp=1 manifest —
    either way EVERY snapshot embeds one."""
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay
    from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay

    if isinstance(replay, MultiHostShardedReplay):
        # PER-HOST snapshot: each process saves only the shards it owns
        # (keyed by GLOBAL shard id), to its own path — restore requires
        # the same process layout, which is validated, not assumed
        with replay.lock:
            payload = {"kind": np.asarray("multihost")}
            payload["local_ids"] = np.asarray(replay.local_ids, np.int64)
            payload["rr"] = np.asarray(replay._rr)
            for g in replay.local_ids:
                shard = replay.shards[g]
                with shard.lock:
                    payload.update(_plane_state(shard, prefix=f"g{g}_"))
                    for k in STORE_FIELDS:
                        payload[f"g{g}_store_{k}"] = np.asarray(replay.stores[g][k])
    elif isinstance(replay, ShardedDeviceReplay):
        with replay.lock:
            payload: Dict[str, np.ndarray] = {"kind": np.asarray("sharded")}
            payload["rr"] = np.asarray(replay._rr)
            for i, shard in enumerate(replay.shards):
                with shard.lock:
                    payload.update(_plane_state(shard, prefix=f"shard{i}_"))
            for k in STORE_FIELDS:
                payload["store_" + k] = np.asarray(replay.stores[k])
    elif isinstance(replay, DeviceReplayBuffer):
        with replay.lock:
            payload = {"kind": np.asarray("device")}
            payload.update(_plane_state(replay))
            for k in STORE_FIELDS:
                payload["store_" + k] = np.asarray(replay.stores[k])
    elif isinstance(replay, ReplayBuffer):
        with replay.lock:
            payload = {"kind": np.asarray("host")}
            payload.update(_plane_state(replay))
            for k in STORE_FIELDS:
                # copy under the lock: np.savez runs after release, and the
                # live stores keep mutating under collection threads
                payload["store_" + k] = getattr(replay, k + "_store").copy()
            disk = getattr(replay, "disk", None)
            if disk is not None:
                # disk tier manifest: occupied records ride VERBATIM as
                # their encoded segment bytes (no decode/re-encode round
                # trip), so --resume rewrites segments bit-exactly — and a
                # torn segment left by a kill mid-demotion is healed by the
                # rewrite rather than trusted
                payload["disk_blocks"] = np.asarray(disk.disk_blocks, np.int64)
                payload["disk_ptr"] = np.asarray(replay._disk_ptr, np.int64)
                payload["slot_stamp"] = replay.slot_stamp.copy()
                occ = np.nonzero(replay.occupied[replay.cfg.num_blocks:])[0]
                payload["disk_occupied_slots"] = occ.astype(np.int64)
                for i in occ:
                    payload[f"disk_rec_{int(i)}"] = disk.record_bytes(int(i))
    else:
        raise TypeError(f"unknown replay type {type(replay).__name__}")
    for k, v in (extra or {}).items():
        payload[_EXTRA_PREFIX + k] = np.asarray(v)
    topo = topology if topology is not None else snapshot_topology(replay)
    for k, v in topo.items():
        payload[_TOPO_PREFIX + k] = np.asarray(v)
    _atomic_savez(path, payload)


def restore_replay(replay, path: str) -> Dict[str, np.ndarray]:
    """Restore a snapshot into a freshly built replay of the SAME config.

    Mismatches raise BEFORE any state is touched — a failed restore leaves
    the buffer exactly as constructed. Layout mismatches (plane kind, dp,
    process/shard ownership) raise TopologyMismatch, which the Trainer's
    --reshard path catches to regather the slabs (replay/reshard.py);
    content mismatches (capacity, obs shape, hidden dim) stay plain
    ValueErrors. Returns the `extra` dict the snapshot was saved with
    (empty for plain snapshots), fully materialized."""
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay
    from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay

    with np.load(path, allow_pickle=False) as npz:
        d = _Bf16NpzView(npz)
        kind = str(d["kind"])
        saved_topo = _topology_from(d)
        # materialize extras before the NpzFile closes
        extras = {
            k[len(_EXTRA_PREFIX):]: np.asarray(d[k])
            for k in d.files
            if k.startswith(_EXTRA_PREFIX)
        }
        if isinstance(replay, MultiHostShardedReplay):
            _check_kind(kind, "multihost", replay, saved_topo)
            with replay.lock:
                saved_ids = [int(x) for x in d["local_ids"]]
                if saved_ids != list(replay.local_ids):
                    raise TopologyMismatch(
                        saved_topo or {"plane": kind, "local_ids": saved_ids},
                        _plain(snapshot_topology(replay)),
                        f"snapshot owns global shards {saved_ids}, this "
                        f"process owns {list(replay.local_ids)}",
                    )
                # validate EVERY shard before mutating anything (the
                # validated arrays are reused below — one npz read each)
                vals_by_shard = {}
                for g in replay.local_ids:
                    if len(d[f"g{g}_tree_leaves"]) != replay.shards[g].tree.capacity:
                        raise ValueError(f"shard {g}: tree size mismatch")
                    vals_by_shard[g] = _validated_stores(
                        d, replay.stores[g], prefix=f"g{g}_store_"
                    )
                replay._rr = int(d["rr"][()])
                for g in replay.local_ids:
                    shard = replay.shards[g]
                    with shard.lock:
                        _restore_plane(shard, d, prefix=f"g{g}_")
                        replay.stores[g] = {
                            k: jax.device_put(v, replay._shard_device[g])
                            for k, v in vals_by_shard[g].items()
                        }
        elif isinstance(replay, ShardedDeviceReplay):
            _check_kind(kind, "sharded", replay, saved_topo)
            saved_dp = (
                saved_topo["dp"] if saved_topo
                else sum(
                    1 for k in d.files
                    if k.startswith("shard") and k.endswith("_block_ptr")
                )
            )
            if saved_dp != replay.dp:
                raise TopologyMismatch(
                    saved_topo or {"plane": kind, "dp": saved_dp},
                    _plain(snapshot_topology(replay)),
                    f"snapshot holds {saved_dp} dp shards, replay has {replay.dp}",
                )
            with replay.lock:
                vals = _validated_stores(d, replay.stores)
                for i in range(len(replay.shards)):  # leaf-count pre-check
                    if len(d[f"shard{i}_tree_leaves"]) != replay.shards[i].tree.capacity:
                        raise ValueError(f"shard {i}: tree size mismatch")
                replay._rr = int(d["rr"][()])
                for i, shard in enumerate(replay.shards):
                    with shard.lock:
                        _restore_plane(shard, d, prefix=f"shard{i}_")
                replay.stores = {
                    k: jax.device_put(v, replay.stores[k].sharding)
                    for k, v in vals.items()
                }
        elif isinstance(replay, DeviceReplayBuffer):
            _check_kind(kind, "device", replay, saved_topo)
            with replay.lock:
                vals = _validated_stores(d, replay.stores)
                if len(d["tree_leaves"]) != replay.tree.capacity:
                    raise ValueError("tree size mismatch")
                _restore_plane(replay, d)
                replay.stores = {k: jax.device_put(v) for k, v in vals.items()}
        elif isinstance(replay, ReplayBuffer):
            _check_kind(kind, "host", replay, saved_topo)
            with replay.lock:
                current = {k: getattr(replay, k + "_store") for k in STORE_FIELDS}
                vals = _validated_stores(d, current)
                if len(d["tree_leaves"]) != replay.tree.capacity:
                    raise ValueError("tree size mismatch")
                disk = getattr(replay, "disk", None)
                saved_db = (
                    int(d["disk_blocks"][()]) if "disk_blocks" in d.files else 0
                )
                live_db = disk.disk_blocks if disk is not None else 0
                if saved_db != live_db:
                    raise ValueError(
                        f"disk tier mismatch: snapshot holds {saved_db} disk "
                        f"blocks, replay configured for {live_db}"
                    )
                _restore_plane(replay, d)
                for k in STORE_FIELDS:
                    current[k][:] = vals[k]
                if disk is not None:
                    replay._disk_ptr = int(d["disk_ptr"][()])
                    replay.slot_stamp[:] = d["slot_stamp"]
                    replay._disk_cache.clear()
                    for i in d["disk_occupied_slots"]:
                        disk.write_record_bytes(int(i), d[f"disk_rec_{int(i)}"])
                    disk.flush()
        else:
            raise TypeError(f"unknown replay type {type(replay).__name__}")
    return extras
