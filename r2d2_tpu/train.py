"""Training orchestrator (L5) and CLI (L6).

Reference topology (reference train.py:29-62): 8 actor processes + a replay
process (3 service threads) + the learner in the main process, wired by
pickling mp.Queues. On TPU the device does the heavy lifting in two jitted
functions (act, train_step), so the host side collapses to threads sharing
the replay object directly — no pickling, no process forks (and it must:
this class of host has few cores; SURVEY.md section 5.8 maps the reference's
3 queues onto (a) direct add_block calls, (b) an in-memory prefetch queue of
device-resident batches, (c) a direct update_priorities call).

Two modes:
- inline: strict actor/learner alternation in one thread — the minimum
  end-to-end slice of SURVEY.md section 7.2, used by integration tests.
- threaded: actor thread + sampler/prefetch thread + learner loop, with the
  reference's backpressure depth (batch queue 8: train.py:35).

Cadences preserved (SURVEY.md section 2.6): publish weights every 4
updates, actor pull every 400 env steps, target sync every 2000 (inside the
jitted step), checkpoint every 500, stop at training_steps, sampling gated
on learning_starts.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import queue
import signal
import sys
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

import jax.numpy as jnp

from r2d2_tpu.actor import HostEnvPool, ParamStore, VectorizedActor
from r2d2_tpu.config import PRESETS, R2D2Config, parse_overrides, tiny_test
from r2d2_tpu.envs import make_env
from r2d2_tpu.envs.catch import CatchVecEnv
from r2d2_tpu.learner import (
    DeviceBatch,
    init_train_state,
    make_batch_train_step,
    make_fused_train_step,
    make_gather_step,
    make_manual_train_step,
    make_sharded_fused_train_step,
    make_sharded_gather_step,
    make_stacked_batch_train_step,
    make_train_step,
)
from r2d2_tpu.ops.epsilon import epsilon_ladder
from r2d2_tpu.parallel.mesh import (
    make_mesh,
    manual_batch_sharding,
    replicated_sharding,
    shard_batch,
)
from r2d2_tpu.replay.device_store import DeviceReplayBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay
from r2d2_tpu.replay.tiered_store import (
    StagedChunk,
    TieredPrefetchPipeline,
    TieredReplayBuffer,
    stage_chunk,
)
from r2d2_tpu.utils.checkpoint import latest_checkpoint_step, restore_checkpoint, save_checkpoint
from r2d2_tpu.utils.faults import fault_point, install_from_env, total_retries, with_retries
from r2d2_tpu.utils.metrics import MetricsLogger
from r2d2_tpu.utils.profiling import TransferTimer, span, start_profiler_server, step_span
from r2d2_tpu.utils.supervision import PREEMPT_EXIT_CODE, Supervisor, WorkerStalledError


def _is_procmaze(name: str) -> bool:
    from r2d2_tpu.envs.procmaze import is_procmaze_name

    return is_procmaze_name(name)


def _build_procmaze(cfg: R2D2Config, name: str):
    from r2d2_tpu.envs.procmaze import build_procmaze_env

    return build_procmaze_env(cfg.obs_shape, cfg.max_episode_steps, name)


def _build_multitask_family(cfg: R2D2Config, name: str):
    """Functional core for the keydoor/drift/banditgrid families (None if
    the name is not one of them) — each family's single build_*_env
    factory, driven by cfg geometry like procmaze above."""
    from r2d2_tpu.envs.banditgrid import build_banditgrid_env, is_banditgrid_name
    from r2d2_tpu.envs.drift import build_drift_env, is_drift_name
    from r2d2_tpu.envs.keydoor import build_keydoor_env, is_keydoor_name

    if is_keydoor_name(name):
        return build_keydoor_env(cfg.obs_shape, cfg.max_episode_steps, name)
    if is_drift_name(name):
        return build_drift_env(cfg.obs_shape, cfg.max_episode_steps, name)
    if is_banditgrid_name(name):
        return build_banditgrid_env(cfg.obs_shape, cfg.max_episode_steps, name)
    return None


def build_vec_env(cfg: R2D2Config, seed: int = 0):
    """One vectorized env spanning cfg.num_actors slots."""
    from r2d2_tpu.envs.catch import catch_params, is_catch_name

    name = cfg.env_name.lower()
    if is_catch_name(name):
        return CatchVecEnv(
            num_envs=cfg.num_actors, height=cfg.obs_shape[0], width=cfg.obs_shape[1],
            seed=seed, **catch_params(name),
        )
    if _is_procmaze(name):
        from r2d2_tpu.envs.functional import FnVecEnv

        return FnVecEnv(
            _build_procmaze(cfg, name), num_envs=cfg.num_actors, seed=seed
        )
    family_env = _build_multitask_family(cfg, name)
    if family_env is not None:
        from r2d2_tpu.envs.functional import FnVecEnv

        return FnVecEnv(family_env, num_envs=cfg.num_actors, seed=seed)
    envs = [make_env(cfg, seed=seed + i) for i in range(cfg.num_actors)]
    if cfg.env_pool_workers > 0:
        from r2d2_tpu.actor import ThreadedHostEnvPool

        return ThreadedHostEnvPool(envs, workers=cfg.env_pool_workers)
    return HostEnvPool(envs)


def build_fn_env(cfg: R2D2Config):
    """Functional (jit/vmap-safe) env core for the on-device collector."""
    from r2d2_tpu.envs.catch import CatchEnv, catch_params, is_catch_name

    name = cfg.env_name.lower()
    if is_catch_name(name):
        return CatchEnv(
            height=cfg.obs_shape[0], width=cfg.obs_shape[1], **catch_params(name)
        )
    if _is_procmaze(name):
        return _build_procmaze(cfg, name)
    family_env = _build_multitask_family(cfg, name)
    if family_env is not None:
        return family_env
    if name == "scripted" or name.startswith("scripted:"):
        from r2d2_tpu.envs.fake import ScriptedFnEnv

        # "scripted:A" pins the action space (same rule as make_env)
        adim = int(name.split(":", 1)[1]) if ":" in name else cfg.action_dim
        return ScriptedFnEnv(obs_shape=cfg.obs_shape, action_dim=adim)
    raise ValueError(
        f"env {cfg.env_name!r} has no pure-JAX functional core; "
        "use collector='host' for emulator/host-protocol envs"
    )


class _HostPlane:
    """Host numpy replay; batches ship host->device each update. With a
    mesh, batches shard over dp and XLA inserts the gradient psum. Batches
    are copied out of the store at sample time, so queued items can never
    go stale (pipelined == inline here).

    partitioning="manual" (the tp×fsdp path GSPMD can't compile — see
    learner.make_manual_train_step): the step is an explicit shard_map over
    every mesh axis and the batch additionally splits over fsdp (ZeRO-2),
    so this plane lifts batches with manual_batch_sharding instead of the
    dp-only shard_batch."""

    steps_per_update = 1

    def __init__(self, tr: "Trainer"):
        self.tr = tr
        self.replay = ReplayBuffer(tr.cfg)
        self.manual = (
            tr.mesh is not None and tr.cfg.resolved_partitioning == "manual"
        )
        if self.manual:
            self.step_fn = make_manual_train_step(tr.cfg, tr.mesh)
        else:
            self.step_fn = make_train_step(tr.cfg, tr.net)

    def sample(self, pipelined: bool = False):
        with span("replay/sample"):
            b = self.replay.sample_batch(self.tr.sample_rng)

            def lift():
                fault_point("host_plane.h2d")
                dev = DeviceBatch.from_sampled(b)
                if self.manual:
                    sh = manual_batch_sharding(self.tr.mesh)
                    dev = jax.tree.map(lambda x: jax.device_put(x, sh), dev)
                elif self.tr.mesh is not None:
                    dev = DeviceBatch(*shard_batch(self.tr.mesh, tuple(dev)))
                return dev

            # a flaky h2d re-lifts the already-drawn host batch: retries
            # never touch the sampling RNG, so the draw stream is stable
            dev = with_retries(lift, "host_plane.h2d")
            return "batch", dev, b.idxes, (b.old_ptr, b.old_advances)

    def update(self, state, item):
        _, dev, idxes, (old_ptr, old_adv) = item
        state, m, priorities = self.step_fn(state, dev)
        self.replay.update_priorities(idxes, np.asarray(priorities), old_ptr, old_adv)
        return state, m


class _TieredPlane:
    """Full-capacity host store + double-buffered HBM staging
    (replay/tiered_store.py): the plane that serves the paper's 2M-
    transition capacity at device-plane update throughput.

    A staging thread draws K batches under one lock hold, host-gathers
    their windows through the vectorized native multi-gather, and lifts
    the stacked chunk into HBM while the learner's K-update scan
    (make_stacked_batch_train_step) consumes the previous chunk — the
    host->device tunnel runs behind compute instead of ahead of it. The
    priority readback is deferred one dispatch exactly like _DevicePlane's;
    staleness needs no extra machinery because chunks are BY-VALUE (bytes
    copied out at stage time) and carry their stage-time window stamps.
    The TransferTimer's overlap fraction lands in the metrics stream via
    log_extras."""

    def __init__(self, tr: "Trainer"):
        self.tr = tr
        self.replay = TieredReplayBuffer(tr.cfg)
        self.K = self.steps_per_update = tr.cfg.updates_per_dispatch
        self._pending = None  # deferred (priorities, chunk) readback
        self.xfer = TransferTimer()
        self.multi_fn = make_stacked_batch_train_step(tr.cfg, tr.net, self.K)
        # r2d2: ephemeral(lazily rebuilt by _ensure_pipeline on the next sample; capture_pending stops it with an RNG rewind so the resumed pipeline re-draws identically)
        self._pipe: Optional[TieredPrefetchPipeline] = None

    def _ensure_pipeline(self) -> TieredPrefetchPipeline:
        # lazy: started on first sample, i.e. after warmup opened the
        # sampling gate (and restartable after a finish_updates drain)
        if self._pipe is None:
            self._pipe = TieredPrefetchPipeline(
                self.replay, self.tr.sample_rng, self.K, timer=self.xfer
            )
        return self._pipe

    def sample(self, pipelined: bool = False):
        if self.tr.cfg.deterministic_staging:
            # synchronous stage on the consumer thread: no staging-thread
            # RNG race with write-backs, so the sampling stream is
            # bit-reproducible (the chaos suite's resume contract); trades
            # away the pipeline's transfer/compute overlap
            with span("replay/staged_chunk"):
                chunk = stage_chunk(
                    self.replay, self.tr.sample_rng, self.K, self.xfer
                )
                return "staged", chunk, None, None
        # both modes consume the staging pipeline: it IS the prefetcher
        # (threaded mode's sampler thread just forwards chunks into its
        # queue, adding one more buffered chunk of depth)
        with span("replay/staged_chunk"):
            return "staged", self._ensure_pipeline().get(), None, None

    def update(self, state, item):
        _, chunk, _, _ = item
        state, m, priorities = self.multi_fn(state, chunk.batch)
        try:
            priorities.copy_to_host_async()
        except AttributeError:
            pass
        # deferred one dispatch (_DevicePlane._multi_update rationale): the
        # readback lands while the NEXT chunk executes
        prev, self._pending = self._pending, (priorities, chunk)
        if prev is not None:
            self.drain_pending(prev)
        return state, m

    def drain_pending(self, pending=None) -> None:
        """Apply a deferred (priorities, chunk) pair. Called with the
        previous pair each update; called with no argument on run-mode
        exit, where it ALSO stops the staging thread — an undrained staged
        chunk is simply dropped (by-value bytes, no tree writes pending),
        leaving the sum tree consistent."""
        if pending is None:
            if self._pipe is not None:
                self._pipe.stop()
                self._pipe = None
            pending, self._pending = self._pending, None
        if pending is None:
            return
        prios, chunk = pending
        for row, idx in zip(np.asarray(prios), chunk.idxes):
            self.replay.update_priorities(idx, row, chunk.old_ptr, chunk.old_advances)

    def capture_pending(self) -> Optional[dict]:
        """Preemption capture: serialize the deferred write-back INSTEAD of
        applying it. In an uninterrupted run the next draw happens before
        this write-back lands (update() applies it one dispatch later), so
        draining it at preemption would make the resumed draw see a tree
        the uninterrupted run never had — restore_pending re-queues it so
        the resumed iteration replays the exact apply order. Also stops the
        staging pipeline with an RNG rewind: queued/in-flight chunks are
        discarded and their draws re-happen identically after resume."""
        if self._pipe is not None:
            self._pipe.stop(rewind=True)
            self._pipe = None
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        prios, chunk = pending
        return {
            "prios": np.asarray(prios),
            "idxes": np.asarray(chunk.idxes),
            "old_ptr": np.asarray(chunk.old_ptr, np.int64),
            "old_advances": np.asarray(chunk.old_advances, np.int64),
        }

    def restore_pending(self, d: dict) -> None:
        chunk = StagedChunk(
            batch=None,  # already consumed pre-preempt; only stamps remain
            idxes=np.asarray(d["idxes"]),
            old_ptr=int(np.asarray(d["old_ptr"])[()]),
            old_advances=int(np.asarray(d["old_advances"])[()]),
            env_steps=0,
        )
        self._pending = (np.asarray(d["prios"]), chunk)

    def log_extras(self) -> dict:
        # disk_stats() is {} when the disk tier is off, so the default
        # metrics stream is unchanged
        return {**self.xfer.stats(), **self.replay.disk_stats()}


class _DevicePlane:
    """Single-chip HBM replay (replay/device_store.py).

    Inline mode queues only sample COORDINATES and the fused step gathers
    in-jit at update time (fastest: nothing but a kilobyte crosses the
    wire, no intermediate batch). Pipelined mode materializes the batch in
    HBM at sample time (make_gather_step) so an item sitting in the
    prefetch queue cannot be invalidated by a concurrent block write."""

    def __init__(self, tr: "Trainer"):
        self.tr = tr
        self.replay = DeviceReplayBuffer(tr.cfg)
        self.K = self.steps_per_update = tr.cfg.updates_per_dispatch
        self._pending = None  # deferred (priorities, draws) readback
        self.device_priority = tr.cfg.priority_plane == "device"
        if self.device_priority:
            from r2d2_tpu.megastep import make_priority_superstep

            self.N = tr.cfg.superstep_dispatches
            self.steps_per_update = self.N * self.K
            self.superstep_fn = make_priority_superstep(
                tr.cfg, tr.net, self.N, self.K
            )
            # key stream derived from the STEP COUNTER, not carried state:
            # a --resume at step s re-derives superstep s/(N*K)'s key
            # exactly, with nothing extra to snapshot
            self._superstep_base_key = jax.random.PRNGKey(tr.cfg.seed + 4)
        elif self.K > 1:
            from r2d2_tpu.learner import make_fused_multi_train_step

            self.multi_fn = make_fused_multi_train_step(tr.cfg, tr.net, self.K)
        self.step_fn = make_fused_train_step(tr.cfg, tr.net)
        self.gather_fn = make_gather_step(tr.cfg)
        self.batch_step_fn = make_batch_train_step(tr.cfg, tr.net)

    def _superstep_update(self, state):
        """priority_plane="device": ONE dispatch runs N x K updates with
        sampling, IS weights, gather, train, and priority write-back all
        in-jit against the HBM tree (megastep.make_priority_superstep).
        Nothing is drawn on host, nothing drains afterwards — the host's
        only work here is deriving the dispatch key and swapping the tree
        handle under the buffer lock."""
        key = jax.random.fold_in(
            self._superstep_base_key, self.tr._step // self.steps_per_update
        )

        def dispatch(stores, tree, nss):
            new_state, tree_out, m = self.superstep_fn(state, stores, tree, nss, key)
            return tree_out, (new_state, m)

        return self.replay.superstep_run(dispatch)

    def sample(self, pipelined: bool = False):
        if self.device_priority:
            # sampling happens in-jit at update time, against the live tree
            return ("superstep", None, None, None)
        if self.K > 1:
            # multi-update dispatch draws its own coordinates at update
            # time (atomically with the dispatch) — queued coordinates
            # could be retargeted by adds landing while the item waits
            return ("multi", None, None, None)
        with span("replay/sample"):
            si = self.replay.sample_indices(self.tr.sample_rng)
            coords = (jax.device_put(si.b), jax.device_put(si.s), jax.device_put(si.is_weights))
            stamp = (si.old_ptr, si.old_advances)
            if pipelined:
                batch = self.replay.run_with_stores(lambda stores: self.gather_fn(stores, *coords))
                return "batch", batch, si.idxes, stamp
            return "coords", coords, si.idxes, stamp

    def _multi_update(self, state):
        """K updates in one dispatch: draw + dispatch under one lock hold
        (DeviceReplayBuffer.sample_and_run), then apply the (K, B)
        priorities row-by-row under each draw's own staleness window.

        The priority readback is DEFERRED one dispatch: reading this
        chunk's priorities immediately would stall the host for the chunk's
        execution plus a full device->host round trip; instead the transfer
        is started async and collected while the NEXT chunk executes. Tree
        priorities lag one extra chunk (bounded, same class as the
        reference's ~12-batch pipeline lag); the pointer-window mask still
        rejects rows whose slots were overwritten meanwhile."""

        def dispatch(stores, draws):
            b = jnp.asarray(np.stack([d.b for d in draws]))
            s = jnp.asarray(np.stack([d.s for d in draws]))
            w = jnp.asarray(np.stack([d.is_weights for d in draws]))
            return self.multi_fn(state, stores, b, s, w)

        draws, (new_state, m, priorities) = self.replay.sample_and_run(
            self.tr.sample_rng, self.K, dispatch
        )
        try:
            priorities.copy_to_host_async()
        except AttributeError:
            pass
        prev, self._pending = self._pending, (priorities, draws)
        if prev is not None:
            self.drain_pending(prev)
        return new_state, m

    def drain_pending(self, pending=None) -> None:
        """Apply a deferred (priorities, draws) pair to the tree. Called
        with the previous chunk's pair each update, and once with the final
        in-flight pair when a run mode exits."""
        if pending is None:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        prios, draws = pending
        for row, d in zip(np.asarray(prios), draws):
            # old_advances: a free-running collector could lap the whole
            # ring while this chunk's readback was deferred — the stamp
            # drops the batch instead of mis-applying it (control_plane)
            self.replay.update_priorities(d.idxes, row, d.old_ptr, d.old_advances)

    def capture_pending(self) -> Optional[dict]:
        """Preemption capture of the K>1 deferred readback — same apply-
        order-preservation rationale as _TieredPlane.capture_pending."""
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        prios, draws = pending
        return {
            "prios": np.asarray(prios),
            "idxes": np.stack([np.asarray(d.idxes) for d in draws]),
            "old_ptr": np.asarray([d.old_ptr for d in draws], np.int64),
            "old_advances": np.asarray([d.old_advances for d in draws], np.int64),
        }

    def restore_pending(self, d: dict) -> None:
        import types

        draws = [
            types.SimpleNamespace(
                idxes=np.asarray(idx), old_ptr=int(p), old_advances=int(a)
            )
            for idx, p, a in zip(d["idxes"], d["old_ptr"], d["old_advances"])
        ]
        self._pending = (np.asarray(d["prios"]), draws)

    def update(self, state, item):
        kind, payload, idxes, stamp = item
        if kind == "superstep":
            return self._superstep_update(state)
        if kind == "multi":
            return self._multi_update(state)
        if kind == "batch":
            state, m, priorities = self.batch_step_fn(state, payload)
        else:
            state, m, priorities = self.replay.run_with_stores(
                lambda stores: self.step_fn(state, stores, *payload)
            )
        old_ptr, old_adv = stamp
        self.replay.update_priorities(idxes, np.asarray(priorities), old_ptr, old_adv)
        return state, m


class _ShardedPlane:
    """dp-sharded HBM replay + shard_map train step: local gathers per
    shard, gradient psum over dp (replay/sharded_store.py). Same
    inline/pipelined split as _DevicePlane; the pipelined gather runs under
    shard_map so each device materializes its local sub-batch. K > 1 folds
    K updates into one shard_map dispatch with the same deferred priority
    readback as the device plane."""

    def __init__(self, tr: "Trainer"):
        if tr.mesh is None:
            raise ValueError("replay_plane='sharded' needs dp_size*tp_size > 1")
        self.tr = tr
        self.replay = ShardedDeviceReplay(tr.cfg, tr.mesh)
        self.K = self.steps_per_update = tr.cfg.updates_per_dispatch
        self._pending = None  # deferred (priorities, draws) readback
        self.device_priority = tr.cfg.priority_plane == "device"
        if self.device_priority:
            from r2d2_tpu.megastep import make_sharded_priority_superstep

            self.N = tr.cfg.superstep_dispatches
            self.steps_per_update = self.N * self.K
            self.superstep_fn = make_sharded_priority_superstep(
                tr.cfg, tr.net, tr.mesh, self.N, self.K
            )
            self._superstep_base_key = jax.random.PRNGKey(tr.cfg.seed + 4)
        elif self.K > 1:
            from r2d2_tpu.learner import make_sharded_fused_multi_train_step

            self.multi_fn = make_sharded_fused_multi_train_step(
                tr.cfg, tr.net, tr.mesh, self.K
            )
        self.step_fn = make_sharded_fused_train_step(tr.cfg, tr.net, tr.mesh)
        self.gather_fn = make_sharded_gather_step(tr.cfg, tr.mesh)
        self.batch_step_fn = make_batch_train_step(tr.cfg, tr.net)

    def _superstep_update(self, state):
        """Sharded in-jit superstep: one independent key stream per dp
        shard (fold_in by shard id, then by superstep counter — counter-
        derived like _DevicePlane's, so --resume re-derives the streams)."""
        ctr = self.tr._step // self.steps_per_update
        base = jax.random.fold_in(self._superstep_base_key, ctr)
        keys = jnp.stack(
            [jax.random.fold_in(base, sid) for sid in range(self.replay.dp)]
        )

        def dispatch(stores, trees, nss):
            new_state, trees_out, m = self.superstep_fn(
                state, stores, trees, jnp.asarray(nss), keys
            )
            return trees_out, (new_state, m)

        return self.replay.superstep_run(dispatch)

    def sample(self, pipelined: bool = False):
        if self.device_priority:
            return ("superstep", None, None, None)
        if self.K > 1:
            # multi-update dispatch draws its own coordinates at update
            # time, atomically with the dispatch (_DevicePlane rationale)
            return ("multi", None, None, None)
        with span("replay/sample"):
            si = self.replay.sample_indices(self.tr.sample_rng)
            coords = (jnp.asarray(si.b), jnp.asarray(si.s), jnp.asarray(si.is_weights))
            stamp = (si.old_ptrs, si.old_advances)
            if pipelined:
                batch = self.replay.run_with_stores(lambda stores: self.gather_fn(stores, *coords))
                return "batch", batch, si.idxes, stamp
            return "coords", coords, si.idxes, stamp

    def _multi_update(self, state):
        """K sharded updates in one dispatch; priorities (K, dp, B/dp)
        drain one dispatch late under each draw's per-shard windows."""

        def dispatch(stores, draws):
            b = jnp.asarray(np.stack([d.b for d in draws]))
            s = jnp.asarray(np.stack([d.s for d in draws]))
            w = jnp.asarray(np.stack([d.is_weights for d in draws]))
            return self.multi_fn(state, stores, b, s, w)

        draws, (new_state, m, priorities) = self.replay.sample_and_run(
            self.tr.sample_rng, self.K, dispatch
        )
        try:
            priorities.copy_to_host_async()
        except AttributeError:
            pass
        prev, self._pending = self._pending, (priorities, draws)
        if prev is not None:
            self.drain_pending(prev)
        return new_state, m

    def drain_pending(self, pending=None) -> None:
        if pending is None:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        prios, draws = pending
        for row, d in zip(np.asarray(prios), draws):
            self.replay.update_priorities(d.idxes, row, d.old_ptrs, d.old_advances)

    def update(self, state, item):
        kind, payload, idxes, stamp = item
        if kind == "superstep":
            return self._superstep_update(state)
        if kind == "multi":
            return self._multi_update(state)
        old_ptrs, old_adv = stamp
        if kind == "batch":
            # gathered batch is dp-sharded; plain jit inserts the grad psum
            state, m, priorities = self.batch_step_fn(state, payload)
            priorities = np.asarray(priorities).reshape(self.replay.dp, -1)
        else:
            state, m, priorities = self.replay.run_with_stores(
                lambda stores: self.step_fn(state, stores, *payload)
            )
            priorities = np.asarray(priorities)
        self.replay.update_priorities(idxes, priorities, old_ptrs, old_adv)
        return state, m


class _MultiHostPlane:
    """Per-process local replay shards over a GLOBAL (possibly multi-
    process) mesh; collective shard_map updates with in-step IS
    normalization (replay/multihost_store.py). Every process runs the
    same Trainer loop — updates are SPMD-collective, so processes stay in
    lockstep through the step dispatches themselves; collection, logging,
    and the priority drain are host-local.

    K = updates_per_dispatch > 1 folds K collective updates into ONE
    shard_map K-scan dispatch with the priority readback deferred one
    dispatch (replay.run_step_k) — the same dispatch-latency amortization
    the repo measured as mandatory on single-chip (ARCHITECTURE.md
    "dispatch granularity"), now on the scale-out plane."""

    def __init__(self, tr: "Trainer"):
        from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay

        if tr.mesh is None:
            raise ValueError("multihost plane needs a mesh")
        self.tr = tr
        self.replay = MultiHostShardedReplay(tr.cfg, tr.mesh, seed=tr.cfg.seed + 3)
        self.K = self.steps_per_update = tr.cfg.updates_per_dispatch
        if self.K > 1:
            from r2d2_tpu.learner import make_sharded_fused_multi_train_step

            self.multi_fn = make_sharded_fused_multi_train_step(
                tr.cfg, tr.net, tr.mesh, self.K, is_from_priorities=True
            )
        self.step_fn = make_sharded_fused_train_step(
            tr.cfg, tr.net, tr.mesh, is_from_priorities=True
        )

    def sample(self, pipelined: bool = False):
        # draws happen inside run_step(_k), atomically with the dispatch
        return ("multihost", None, None, None)

    def update(self, state, item):
        if self.K > 1:
            return self.replay.run_step_k(self.multi_fn, state, self.K)
        return self.replay.run_step(self.step_fn, state)

    def drain_pending(self, pending=None) -> None:
        self.replay.drain_pending(pending)


_PLANES = {
    "host": _HostPlane,
    "tiered": _TieredPlane,
    "device": _DevicePlane,
    "sharded": _ShardedPlane,
    "multihost": _MultiHostPlane,
}


class Trainer:
    def __init__(
        self,
        cfg: R2D2Config,
        vec_env=None,
        fn_env=None,
        resume: bool = False,
        metrics: Optional[MetricsLogger] = None,
        profile_dir: Optional[str] = None,
        profile_steps: int = 20,
    ):
        from r2d2_tpu.utils.compilation_cache import enable_compilation_cache

        enable_compilation_cache()
        # profiling hooks (SURVEY.md 5.1): trace the first `profile_steps`
        # post-warmup updates — the steady-state pipeline shape
        self.profile_dir = profile_dir
        self._profile_remaining = profile_steps if profile_dir else 0
        self._profile_active = False
        self.cfg = cfg
        self.fn_env = None
        if cfg.collector == "device":
            self.vec_env = None
            self.fn_env = fn_env if fn_env is not None else build_fn_env(cfg)
            env_action_dim = self.fn_env.NUM_ACTIONS
        else:
            self.vec_env = vec_env if vec_env is not None else build_vec_env(cfg, seed=cfg.seed)
            env_action_dim = self.vec_env.action_dim
        if env_action_dim != cfg.action_dim:
            cfg = cfg.replace(action_dim=env_action_dim)
            self.cfg = cfg

        # mesh: dp x tp when the config asks for parallelism (collectives
        # ride ICI on a real slice; tests run on the 8-fake-device CPU mesh)
        self.mesh = None
        if cfg.replay_plane == "multihost":
            # GLOBAL mesh over every process's devices (parallel/multihost);
            # dp_size<=1 means "all global devices". A partial dp_size is
            # rejected here: slicing the global device list could leave a
            # process with zero local shards.
            from r2d2_tpu.parallel.multihost import make_global_mesh

            n_global = len(jax.devices())
            if cfg.dp_size > 1 and cfg.dp_size != n_global:
                raise ValueError(
                    f"multihost plane spans ALL global devices: dp_size="
                    f"{cfg.dp_size} != {n_global} devices (set dp_size<=1 "
                    "to mean 'all', or use replay_plane='sharded' for a "
                    "single-host subset)"
                )
            self.mesh = make_global_mesh(
                dp=cfg.dp_size if cfg.dp_size > 1 else None, tp=1
            )
        elif cfg.dp_size * cfg.tp_size * cfg.fsdp_size > 1:
            # fsdp > 1 grows the third mesh axis that shards the Adam
            # mu/nu trees (parallel/sharding_map.py); the replay layout
            # stays dp-determined, so --resume/--reshard snapshots are
            # fsdp-agnostic (their topology manifests record dp/tp only).
            n_mesh = cfg.dp_size * cfg.tp_size * cfg.fsdp_size
            self.mesh = make_mesh(dp=cfg.dp_size, tp=cfg.tp_size,
                                  devices=jax.devices()[:n_mesh],
                                  fsdp=cfg.fsdp_size)

        # resolved sequence-backward arm (config-static): stamped into
        # every metrics record so runs are attributable to the arm the
        # auto-selector actually picked (bench.py stamps BENCH rows the
        # same way)
        self._backward_arm, self._backward_arm_stride = cfg.resolve_backward_arm()

        self.net, self.state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
        if self.mesh is not None:
            if cfg.replay_plane != "multihost":
                # LSTM/encoder kernels shard over tp; tp=1 degenerates to
                # replicated. Plain-jit planes: GSPMD partitions from
                # these shardings alone. The "sharded" shard_map plane is
                # manual over dp only (axis_names={"dp"}), so the same tp
                # shardings partition the per-dp-shard body.
                from r2d2_tpu.parallel.mesh import train_state_shardings

                self.state = jax.device_put(
                    self.state, train_state_shardings(self.state, self.mesh)
                )
            else:
                # multihost declares P() (dp-replicated) params and tp=1
                self.state = jax.device_put(self.state, replicated_sharding(self.mesh))
        self.env_steps_offset = 0
        self.wall_minutes_offset = 0.0
        self._resumed = False
        if resume and latest_checkpoint_step(cfg.checkpoint_dir) is not None:
            self.state, self.env_steps_offset, self.wall_minutes_offset = restore_checkpoint(
                cfg.checkpoint_dir, self.state
            )
            self._resumed = True

        # first update after THIS construction compiles the jitted step;
        # the profiler gate skips it even when resuming from step > 0
        self._initial_step = int(self.state.step)
        # host-side mirror of state.step: reading the device scalar every
        # update would force a full stream sync per update (the tunneled
        # backend only syncs on host readback); increments are known
        # exactly (updates_per_dispatch per plane.update)
        self._step = self._initial_step
        _quantum = cfg.updates_per_dispatch * cfg.superstep_dispatches
        if self._initial_step % _quantum != 0:
            raise ValueError(
                f"resumed step {self._initial_step} is not a multiple of "
                f"updates_per_dispatch*superstep_dispatches={_quantum}; "
                "training would overshoot training_steps — resume with the "
                "N and K the checkpoint was trained with (or N=K=1)"
            )
        self.sample_rng = np.random.default_rng(cfg.seed + 2)
        # deferred metrics queue (_log / _flush_log): latest un-emitted
        # (m, step, extra); epoch-zero stamp emits the FIRST record eagerly
        self._pending_metrics = None
        self._last_log_emit = 0.0
        # preemption protocol: request_preempt (usually via SIGTERM inside
        # a run mode's _sigterm_to_preempt window) sets the event; the run
        # loop honors it at the next iteration boundary, snapshots replay +
        # mid-run carry, writes a finalized checkpoint, and the CLI exits
        # with PREEMPT_EXIT_CODE
        self.preempted = False
        self._preempt = threading.Event()
        self._snap_thread: Optional[threading.Thread] = None
        self._resume_carry: dict = {}
        self.plane = _PLANES[cfg.replay_plane](self)
        self.replay = self.plane.replay
        if self._resumed and cfg.snapshot_replay:
            # restored env steps are part of the run total already counted
            # by env_steps_offset from the learner checkpoint; rebase so
            # the sum isn't double-counted. The offset is a GLOBAL total,
            # so a multi-process run subtracts the GLOBAL restored count
            # (each host's snapshot holds only its local shards' steps).
            # EVERY process participates in the collective unconditionally
            # — a host whose snapshot is missing contributes 0, and a
            # failed restore is agreed across hosts — because a collective
            # guarded by per-host file checks deadlocks the others.
            restored, failed = 0, 0
            try:
                if self._restore_replay_snapshot():
                    restored = self.replay.env_steps
            except Exception as e:  # noqa: BLE001 — agreed below
                failed = 1
                restore_err = e
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                gathered = multihost_utils.process_allgather(
                    np.asarray([restored, failed], np.int64)
                )
                restored = int(gathered[:, 0].sum())
                if int(gathered[:, 1].sum()):
                    bad = [int(p) for p in np.nonzero(gathered[:, 1])[0]]
                    raise RuntimeError(
                        f"replay snapshot restore failed on process(es) "
                        f"{bad}"
                    ) from (restore_err if failed else None)
            elif failed:
                raise restore_err
            self.env_steps_offset -= restored
        self.param_store = ParamStore(self.state.params)
        if cfg.collector == "device":
            from r2d2_tpu.collect import DeviceCollector

            self.actor = DeviceCollector(
                cfg, self.net, self.param_store, self.fn_env, self.replay,
                seed=cfg.seed + 1,
            )
        else:
            self.actor = VectorizedActor(
                cfg,
                self.net,
                self.param_store,
                self.vec_env,
                epsilon_ladder(cfg.num_actors, cfg.base_eps, cfg.eps_alpha),
                self.replay.add_block,
                seed=cfg.seed + 1,
            )
        self.metrics = metrics or MetricsLogger(cfg.metrics_path, cfg.log_interval)
        if self._resumed:
            self._maybe_restore_carry()

    # ---------------------------------------------------- preemption / carry

    def request_preempt(self, signum=None, frame=None) -> None:
        """Ask the run loop to cut at its next iteration boundary.
        Signal-handler-safe: sets a flag and returns — a SIGTERM landing
        mid-update lets the update finish, so the cut is always at a clean
        step boundary."""
        self._preempt.set()

    def _preempt_now(self) -> bool:
        """Checked once per run-loop iteration. Multi-process runs agree
        via an UNCONDITIONAL allgather — the loop is in lockstep through
        the collective update dispatches, so every process reaches this
        the same number of times, and any host's SIGTERM cuts ALL hosts at
        the same step (a guarded collective would deadlock the others)."""
        local = 1 if self._preempt.is_set() else 0
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            local = int(multihost_utils.process_allgather(np.int32(local)).sum())
        if local:
            self.preempted = True
        return bool(local)

    @contextlib.contextmanager
    def _sigterm_to_preempt(self):
        """Route SIGTERM into the preemption protocol for the enclosed run.
        Installed only on the main thread (signal.signal raises ValueError
        elsewhere — library callers driving a Trainer from a worker thread
        keep their process-level handler and can call request_preempt
        themselves); the previous handler is restored on exit."""
        try:
            prev = signal.signal(
                signal.SIGTERM, lambda s, f: self.request_preempt(s, f)
            )
        except ValueError:
            prev = None
        try:
            yield
        finally:
            if prev is not None:
                signal.signal(signal.SIGTERM, prev)

    def _carry_payload(self) -> dict:
        """Everything OUTSIDE the replay tree and learner state that the
        next iteration reads: the cut step, the sampling RNG, the published
        params, any captured deferred priority write-back, and the actor /
        env episode streams. Together with the replay snapshot it rides in
        and the finalized checkpoint, a --resume restores the exact
        mid-run program point (bit-identical next update AND next draw,
        pinned by tests/test_chaos.py)."""
        carry = {
            "carry_step": np.asarray(self._step, np.int64),
            "sample_rng": np.asarray(
                json.dumps(self.sample_rng.bit_generator.state)
            ),
        }
        params, version = self.param_store.latest()
        carry["pub_version"] = np.asarray(version, np.int64)
        for j, leaf in enumerate(jax.tree.leaves(params)):
            carry[f"pub_{j}"] = np.asarray(leaf)
        capture = getattr(self.plane, "capture_pending", None)
        if capture is not None:
            pend = capture()
            if pend:
                for k, v in pend.items():
                    carry[f"pend_{k}"] = v
        env_state = None
        if self.vec_env is not None and hasattr(self.vec_env, "get_state"):
            env_state = self.vec_env.get_state()
        if self.cfg.collector == "device":
            for k, v in self.actor.carry_state().items():
                carry[f"actor_{k}"] = v
        elif env_state is not None:
            # host actor carry is only useful if the ENV also resumes
            # exactly; emulator pools without get_state fall back to fresh
            # episodes on resume (the actor's resync-style cold start)
            for k, v in self.actor.carry_state().items():
                carry[f"actor_{k}"] = v
            for k, v in env_state.items():
                carry[f"env_{k}"] = v
        return carry

    def _capture_carry_safe(self) -> Optional[dict]:
        """Preempt-path carry capture for the run modes' finally blocks: a
        capture failure must degrade to a carry-less snapshot (still a
        valid end-of-run-style resume), never mask the original unwind.
        Must run BEFORE finish_updates — capture_pending serializes the
        deferred write-back that finish_updates would otherwise apply."""
        if not (self.preempted and self.cfg.snapshot_replay):
            return None
        try:
            return self._carry_payload()
        except Exception:  # noqa: BLE001 — degrade, don't mask
            import traceback

            traceback.print_exc()
            return None

    def _maybe_restore_carry(self) -> None:
        """Rehydrate the mid-run carry a preemption snapshot stored. The
        carry is only valid at the exact step it was cut at: a snapshot
        lagging the checkpoint (e.g. a periodic snapshot plus a later
        crash) is still restored as DATA by the replay restore above, but
        its carry is discarded and the run falls back to fresh episode
        streams — data-safe either way."""
        carry = self._resume_carry
        if "carry_step" not in carry:
            return
        carry_step = int(np.asarray(carry["carry_step"])[()])
        if carry_step != self._initial_step:
            print(
                f"[resume] discarding mid-run carry cut at step {carry_step} "
                f"(checkpoint is at step {self._initial_step}); resuming "
                "with fresh episode streams",
                file=sys.stderr,
            )
            return
        self.sample_rng.bit_generator.state = json.loads(
            str(np.asarray(carry["sample_rng"])[()])
        )
        if "pub_version" in carry:
            treedef = jax.tree.structure(self.param_store._params)
            leaves = [
                jnp.asarray(carry[f"pub_{j}"])
                for j in range(treedef.num_leaves)
            ]
            with self.param_store._lock:
                self.param_store._params = jax.tree.unflatten(treedef, leaves)
                self.param_store.version = int(
                    np.asarray(carry["pub_version"])[()]
                )
        pend = {
            k[len("pend_"):]: v for k, v in carry.items()
            if k.startswith("pend_")
        }
        restore_pending = getattr(self.plane, "restore_pending", None)
        if pend and restore_pending is not None:
            restore_pending(pend)
        act = {
            k[len("actor_"):]: v for k, v in carry.items()
            if k.startswith("actor_")
        }
        if act and hasattr(self.actor, "restore_carry"):
            self.actor.restore_carry(act)
        envd = {
            k[len("env_"):]: v for k, v in carry.items()
            if k.startswith("env_")
        }
        if envd and self.vec_env is not None and hasattr(self.vec_env, "set_state"):
            self.vec_env.set_state(envd)

    def _finalize_preempt(self) -> None:
        """The preemption COMMIT: a finalized checkpoint at the cut step,
        written strictly AFTER the replay snapshot + carry landed. Resume
        keys off the latest finalized checkpoint, so a crash between the
        two leaves the previous checkpoint/snapshot pair in force — at no
        point does a checkpoint reference a snapshot that isn't on disk."""
        if latest_checkpoint_step(self.cfg.checkpoint_dir) == self._step:
            return  # the cadence crossing already checkpointed this step
        save_checkpoint(
            self.cfg.checkpoint_dir,
            self.state,
            self._global_env_steps(),
            self.wall_minutes_offset + (time.time() - self._start_time) / 60.0,
        )

    # ------------------------------------------------------------- plumbing

    def _profile_gate(self) -> None:
        """Start the trace AFTER the first update: update 1 compiles the
        jitted step, and a trace dominated by XLA compile time defeats the
        point (steady-state pipeline shape)."""
        if (
            self._profile_remaining > 0
            and not self._profile_active
            and self._step >= self._initial_step + 1
        ):
            jax.profiler.start_trace(self.profile_dir)
            self._profile_active = True

    def _profile_tick(self, n: int) -> None:
        if self._profile_active:
            self._profile_remaining -= n
            if self._profile_remaining <= 0:
                self._stop_profile()

    def _one_update(self, item):
        fault_point("trainer.update")
        self._profile_gate()
        prev = self._step
        with step_span("learner_update", prev):
            self.state, m = self.plane.update(self.state, item)
        self._step += self.plane.steps_per_update
        step = self._step
        self._profile_tick(self.plane.steps_per_update)
        self._cadences(prev, step)
        return m, step

    def _cadences(self, prev: int, step: int) -> None:
        """Publish/checkpoint interval CROSSINGS, not equality: a K-update
        dispatch may jump past the exact multiple."""
        if step // self.cfg.publish_interval > prev // self.cfg.publish_interval:
            self.param_store.publish(self.state.params)
        if step // self.cfg.save_interval > prev // self.cfg.save_interval:
            # in a multi-process run every process calls this: orbax saves
            # distributed arrays collectively (needs a shared checkpoint
            # path across hosts, the standard orbax contract)
            save_checkpoint(
                self.cfg.checkpoint_dir,
                self.state,
                self._global_env_steps(),
                self.wall_minutes_offset + (time.time() - self._start_time) / 60.0,
            )
        if (
            self.cfg.snapshot_every > 0
            and step // self.cfg.snapshot_every > prev // self.cfg.snapshot_every
        ):
            # cut point: the metrics record preceding a snapshot must land
            # in the jsonl before the snapshot it describes
            self._flush_log()
            self._snapshot_async()

    def _global_env_steps(self) -> int:
        """Run-total env steps. replay.env_steps is host-local on the
        multihost plane, so a multi-process run sums it across processes
        (an allgather collective — safe here because every process reaches
        the checkpoint crossing in lockstep). env_steps_offset is ALREADY a
        global total restored from the checkpoint, so it is added exactly
        once, outside the sum."""
        local = self.replay.env_steps
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            local = int(multihost_utils.process_allgather(np.int64(local)).sum())
        return local + self.env_steps_offset

    def finish_updates(self) -> None:
        """Flush any deferred per-plane work (e.g. the K>1 device plane's
        in-flight priority readback). Every update-driving loop — the run
        modes here and external drivers like bench.py — calls this once
        when it stops updating."""
        drain = getattr(self.plane, "drain_pending", None)
        if drain is not None:
            drain()
        self._flush_log()

    def _replay_snapshot_path(self) -> str:
        # the multihost plane snapshots PER PROCESS (each host owns its
        # shards); a shared checkpoint dir must not collide across hosts
        if self.cfg.replay_plane == "multihost":
            return os.path.join(
                self.cfg.checkpoint_dir,
                f"replay_snapshot_p{jax.process_index()}.npz",
            )
        return os.path.join(self.cfg.checkpoint_dir, "replay_snapshot.npz")

    def _restore_replay_snapshot(self) -> bool:
        """Resume-time replay restore, topology-aware. Tries the exact
        same-layout restore of this process's own snapshot first; a
        TopologyMismatch (or a missing per-process file while OTHER
        snapshot files exist — a changed process layout renames them)
        falls through to the reshard path when cfg.reshard_on_resume is
        set, which regathers EVERY snapshot file the old run left and
        re-splits the slabs across the current layout
        (replay/reshard.py). Returns True if replay state was restored."""
        from r2d2_tpu.replay.reshard import reshard_replay, snapshot_paths
        from r2d2_tpu.replay.snapshot import TopologyMismatch, restore_replay

        snap = self._replay_snapshot_path()
        if os.path.exists(snap):
            try:
                self._resume_carry = restore_replay(self.replay, snap)
                return True
            except TopologyMismatch:
                if not self.cfg.reshard_on_resume:
                    raise
        else:
            others = snapshot_paths(self.cfg.checkpoint_dir)
            if not others:
                return False  # no snapshot at all: refill from scratch
            if not self.cfg.reshard_on_resume:
                from r2d2_tpu.replay.snapshot import (
                    _plain, read_manifest, snapshot_topology,
                )

                raise TopologyMismatch(
                    read_manifest(others[0]) or {},
                    _plain(snapshot_topology(self.replay, tp=self.cfg.tp_size)),
                    f"no snapshot named {os.path.basename(snap)} for this "
                    f"process, but {len(others)} snapshot file(s) exist — "
                    "a changed process layout",
                )
        self._resume_carry = reshard_replay(
            self.replay, snapshot_paths(self.cfg.checkpoint_dir)
        )
        return True

    def save_replay_snapshot(self, extra: Optional[dict] = None) -> str:
        """Persist full replay contents (replay/snapshot.py); returns the
        path. Run modes call this on exit when cfg.snapshot_replay is set.
        `extra` rides in the same atomic write (preemption carry: RNG,
        published params, deferred write-backs, actor/env streams). The
        embedded topology manifest carries the mesh's tp (the replay
        object alone cannot know it), keeping the snapshot portable
        across layouts (replay/reshard.py)."""
        from r2d2_tpu.replay.snapshot import save_replay, snapshot_topology

        os.makedirs(self.cfg.checkpoint_dir, exist_ok=True)
        path = self._replay_snapshot_path()
        save_replay(
            self.replay, path, extra=extra,
            topology=snapshot_topology(self.replay, tp=self.cfg.tp_size),
        )
        return path

    _RESHARD_LIVE_KEYS = ("dp_size", "tp_size", "fsdp_size", "replay_plane")

    def reshard_live(self, **topology) -> dict:
        """Elastic live reshard: re-split the replay plane across a new
        dp/tp/fsdp topology IN THIS PROCESS — the learner-side half of the
        serve plane's elastic story (serve/autoscale.py): when the fleet
        grows or drains, the learner follows the topology change without a
        process exit and restart.

        Sequence: quiesce (drain every deferred plane write-back) ->
        snapshot the replay through the same atomic writer the preemption
        path uses -> swap the config/mesh/state placement to the new
        topology -> rebuild the replay plane -> regather + re-deal the
        snapshot slabs across the new layout (replay/reshard.py) ->
        rebind the live actor's replay hooks. The actor object itself is
        untouched — its RNG streams, env state, and param store carry
        straight through — and the replay contents round-trip through the
        lossless snapshot/reshard path, so the resumed run is bit-exact
        with one that never resharded (tests/test_autoscale.py proves it).

        Accepts only the topology knobs (`dp_size`, `tp_size`,
        `fsdp_size`, `replay_plane`). Single-process only: the multihost
        plane reshards through the exit/resume path (reshard_on_resume),
        where every process re-reads the shared snapshot set. The
        snapshot file is left in place — it is the crash-safety artifact
        until the next one overwrites it. Returns a summary dict."""
        unknown = set(topology) - set(self._RESHARD_LIVE_KEYS)
        if unknown:
            raise ValueError(
                f"reshard_live accepts {self._RESHARD_LIVE_KEYS}, "
                f"got {sorted(unknown)}"
            )
        if (
            jax.process_count() > 1
            or self.cfg.replay_plane == "multihost"
            or topology.get("replay_plane") == "multihost"
        ):
            raise NotImplementedError(
                "live reshard is single-process; multihost topologies "
                "reshard through exit + resume (cfg.reshard_on_resume)"
            )
        from r2d2_tpu.replay.reshard import reshard_replay, snapshot_paths

        # 1. quiesce: every in-flight priority write-back must land in the
        #    slabs before they are snapshotted
        self.finish_updates()
        snap = self.save_replay_snapshot()
        before_env_steps = self.replay.env_steps
        before_size = len(self.replay)
        # 2. swap the topology: new config, new mesh, state re-placed the
        #    same way __init__ places it (values untouched -> bit-exact)
        cfg = self.cfg.replace(**topology).validate()
        self.cfg = cfg
        self._backward_arm, self._backward_arm_stride = (
            cfg.resolve_backward_arm()
        )
        self.mesh = None
        if cfg.dp_size * cfg.tp_size * cfg.fsdp_size > 1:
            n_mesh = cfg.dp_size * cfg.tp_size * cfg.fsdp_size
            self.mesh = make_mesh(dp=cfg.dp_size, tp=cfg.tp_size,
                                  devices=jax.devices()[:n_mesh],
                                  fsdp=cfg.fsdp_size)
        state_host = jax.device_get(self.state)
        if self.mesh is not None:
            from r2d2_tpu.parallel.mesh import train_state_shardings

            self.state = jax.device_put(
                state_host, train_state_shardings(state_host, self.mesh)
            )
        else:
            self.state = jax.device_put(state_host)
        # 3. rebuild the plane (its jitted steps re-trace against the new
        #    mesh) and re-deal the snapshot across the new layout
        self.plane = _PLANES[cfg.replay_plane](self)
        self.replay = self.plane.replay
        self._resume_carry = reshard_replay(
            self.replay, snapshot_paths(cfg.checkpoint_dir)
        )
        # env_steps_offset is unchanged: the restored counter equals the
        # pre-reshard one, so the global total carries straight through
        # 4. rebind the actor's replay hooks — the ONLY replay references
        #    living outside the plane
        if hasattr(self.actor, "push_block"):
            self.actor.push_block = self.replay.add_block
        if hasattr(self.actor, "replay"):
            self.actor.replay = self.replay
        return {
            "snapshot": snap,
            "replay_plane": cfg.replay_plane,
            "dp_size": cfg.dp_size,
            "tp_size": cfg.tp_size,
            "fsdp_size": cfg.fsdp_size,
            "env_steps": self.replay.env_steps,
            "env_steps_before": before_env_steps,
            "replay_size": len(self.replay),
            "replay_size_before": before_size,
        }

    def _snapshot_async(self) -> None:
        """Periodic (snapshot_every) snapshot off the hot path: the write
        runs on a background thread; if the previous one is still going it
        is simply skipped (next crossing tries again). The write itself is
        atomic (tmp+rename), so the previous snapshot stays valid until
        the new one fully lands."""
        if self._snap_thread is not None and self._snap_thread.is_alive():
            return
        t = threading.Thread(
            target=self._snapshot_on_exit, name="replay-snapshot", daemon=True
        )
        self._snap_thread = t
        t.start()

    def _snapshot_on_exit(self, extra: Optional[dict] = None) -> None:
        """finally-block wrapper: the snapshot is the largest write of the
        run (obs-store-sized), so a failure here (ENOSPC) must not replace
        the in-flight training exception with its own."""
        t = self._snap_thread
        if t is not None and t is not threading.current_thread() and t.is_alive():
            # a periodic snapshot is mid-write: let it land (its rename and
            # ours would race on the same final path otherwise)
            t.join(timeout=60.0)
        try:
            self.save_replay_snapshot(extra=extra)
        except Exception as e:  # noqa: BLE001 — log-and-continue on exit
            import traceback

            print(f"replay snapshot failed on exit: {e!r}")
            traceback.print_exc()

    def _stop_profile(self) -> None:
        """Finalize an in-flight trace; safe to call repeatedly. Run modes
        call this on every exit path so a crash or an early end of training
        cannot lose the requested trace."""
        if self._profile_active:
            jax.block_until_ready(self.state.params)
            jax.profiler.stop_trace()
            self._profile_active = False
            self._profile_remaining = 0

    def _log(self, m, step, extra: Optional[dict] = None):
        """Queue this update's metrics WITHOUT materializing them.

        float(m["loss"]) on a live device handle is a full stream sync —
        paid once per update, it re-serializes the pipeline every dispatch
        ("async dispatch tax"). Instead: start the device->host copies
        async, remember the LATEST (m, step, extra), and materialize only
        when the log cadence fires (cfg.log_interval seconds) or at a cut
        point (finish_updates, snapshot crossings, run-mode exit — via
        _flush_log). Updates between cadence firings are never fetched:
        the metrics jsonl samples the update stream at the log cadence
        rather than recording every update (episode stats still aggregate
        exactly — pop_episode_stats moves to emit time)."""
        for v in (m or {}).values():
            copy = getattr(v, "copy_to_host_async", None)
            if copy is not None:
                copy()
        self._pending_metrics = (m, step, extra)
        if time.time() - self._last_log_emit >= self.cfg.log_interval:
            self._flush_log()

    def _flush_log(self) -> None:
        """Materialize and emit the queued metrics record, if any."""
        pend, self._pending_metrics = self._pending_metrics, None
        if pend is None:
            return
        m, step, extra = pend
        self._last_log_emit = time.time()
        log_extras = getattr(self.plane, "log_extras", None)
        if log_extras is not None:
            extra = {**(extra or {}), **log_extras()}
        retries = total_retries()
        if retries:
            extra = {**(extra or {}), "io_retries": retries}
        n_ep, r_sum = self.replay.pop_episode_stats()
        if self.cfg.replay_plane == "multihost" and jax.process_count() > 1:
            # env_steps_offset is a GLOBAL restored total (the snapshot
            # restore rebases it against the globally-summed restored
            # count), so local + offset would understate — possibly go
            # negative — on a resumed multi-process run. Log the two
            # unambiguous pieces instead; checkpoints carry the true
            # global total via _global_env_steps() (no collective here:
            # logging is per-host and must not require lockstep).
            env_steps = {
                "env_steps_local": self.replay.env_steps,
                "env_steps_offset_global": self.env_steps_offset,
            }
        else:
            env_steps = {"env_steps": self.replay.env_steps + self.env_steps_offset}
        self.metrics.log(
            {
                "step": step,
                **env_steps,
                "replay_size": len(self.replay),
                "loss": float(m["loss"]),
                "q_mean": float(m["q_mean"]),
                "episodes": n_ep,
                "mean_return": (r_sum / n_ep) if n_ep else None,
                "backward_arm": self._backward_arm,
                **(
                    {"backward_arm_stride": self._backward_arm_stride}
                    if self._backward_arm == "ckpt"
                    else {}
                ),
                **(extra or {}),
            }
        )

    # ---------------------------------------------------------------- modes

    def warmup(
        self, max_steps: Optional[int] = None, beat: Optional[Callable[[], None]] = None
    ) -> None:
        """Collect until sampling opens (reference worker.py:150).
        `beat` (e.g. Supervisor.main_beat) is stamped between collection
        steps so an armed watchdog covers the warmup phase too.

        Stall guard: batched ring writes shrink effective capacity to
        floor(num_blocks/E)*E slots (ReplayControlPlane._reserve_contiguous
        retires the tail), and episode-aligned chunks store fewer than
        block_length transitions per slot — so a learning_starts that
        exceeds what the ring can actually hold would loop here forever.
        The guard counts RECORDED insertions (replay.env_steps delta, not
        attempted env steps — episode-aligned chunks record only a
        fraction of attempts): once enough transitions to fill the ring
        twice over have been inserted without sampling opening, the replay
        has provably saturated below learning_starts — raise instead of
        spinning."""
        steps = 0
        inserted0 = last_inserted = self.replay.env_steps
        progress_mark = 0  # attempted steps at the last recorded insertion
        saturation = 2 * self.cfg.buffer_capacity + self.cfg.learning_starts
        while not self.replay.can_sample():
            # single-process only: warmup iterations are NOT in lockstep
            # across hosts (each fills at its own rate), so the allgather
            # handshake _preempt_now uses would deadlock here. Multi-host
            # preemption during warmup falls through to the run loop's
            # first iteration check instead.
            if jax.process_count() == 1 and self._preempt.is_set():
                self.preempted = True
                return
            self.actor.step()
            if beat is not None:
                beat()
            steps += self.actor.steps_per_call
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError("warmup exceeded max_steps without filling replay")
            if self.replay.env_steps != last_inserted:
                last_inserted = self.replay.env_steps
                progress_mark = steps
            if self.replay.env_steps - inserted0 >= saturation:
                raise RuntimeError(
                    f"replay saturated at {len(self.replay)} transitions, below "
                    f"learning_starts={self.cfg.learning_starts}: the ring's "
                    "effective capacity (tail retirement for batched writes, "
                    "short-episode blocks) cannot reach the sampling gate — "
                    "lower learning_starts or grow buffer_capacity"
                )
            if steps - progress_mark >= saturation:
                # termination backstop: recording has STALLED (a whole
                # saturation-window of attempted env steps with zero
                # insertions, e.g. an env whose episodes never complete a
                # chunk) — the recorded-insertion guard above would never
                # fire, so raise here instead of spinning forever
                raise RuntimeError(
                    f"warmup recorded no insertions over {saturation} attempted "
                    f"env steps (replay stuck at {len(self.replay)} transitions): "
                    "episodes may never complete within the collector's chunks — "
                    "check max_episode_steps vs chunk/block length"
                )

    def reset_clock(self) -> None:
        """(Re)start the wall-minutes clock that the checkpoint cadence
        stamps (_cadences / _finalize_preempt). Run modes call this on
        entry; external drivers that act as their own run mode (the live
        loop) call it too instead of poking _start_time directly."""
        self._start_time = time.time()

    def run_inline(self, env_steps_per_update: Optional[int] = None) -> None:
        """Strict alternation: k env steps, one update (SURVEY.md 7.2)."""
        cfg = self.cfg
        self.reset_clock()
        k = env_steps_per_update or max(cfg.num_actors, 1)
        # one dispatch is steps_per_update learner updates: scale collection
        # so the env-step : update ratio the caller asked for is preserved
        k *= self.plane.steps_per_update
        # single-threaded loop: the main-thread watchdog is the only stall
        # protection (utils/supervision.py — hard-exits a wedged process)
        sup = self._sup = self._make_supervisor()
        with self._sigterm_to_preempt(), sup.armed_watchdog():
            self.warmup(beat=sup.main_beat)
            try:
                while self._step < cfg.training_steps:
                    sup.main_beat()
                    if self._preempt_now():
                        break
                    for _ in range(max(k // self.actor.steps_per_call, 1)):
                        self.actor.step()
                    m, step = self._one_update(self.plane.sample())
                    self._log(m, step)
            finally:
                # watchdog off before the drain: cleanup must not count as
                # a stall
                sup.stop.set()
                self._stop_profile()
                # carry BEFORE finish_updates: capture_pending serializes
                # the deferred write-back that the drain would apply
                carry = self._capture_carry_safe()
                self.finish_updates()
                if cfg.snapshot_replay:
                    self._snapshot_on_exit(extra=carry)
        if self.preempted:
            self._finalize_preempt()

    def run_threaded(self) -> None:
        """Actor thread + prefetch thread + learner loop (reference
        worker.py:110-175,364-371 collapsed into shared memory). Worker
        threads run under a Supervisor (utils/supervision.py): a crashed
        actor/sampler iteration is restarted with the traceback recorded
        instead of silently starving the learner (SURVEY.md section 5.3)."""
        cfg = self.cfg
        self.reset_clock()
        batch_q: "queue.Queue" = queue.Queue(maxsize=8)
        sup = self._sup = self._make_supervisor()
        with self._sigterm_to_preempt(), sup.armed_watchdog():
            self._run_threaded_body(sup, batch_q)
        if self.preempted:
            self._finalize_preempt()

    def _make_supervisor(self) -> Supervisor:
        return Supervisor(
            heartbeat_timeout=self.cfg.heartbeat_timeout,
            stall_fatal_timeout=self.cfg.stall_fatal_timeout,
        )

    def disarm_watchdog(self) -> None:
        """For library callers that catch WorkerStalledError and keep the
        process alive: the watchdog deliberately survives that unwind (it
        guards against atexit hangs on the wedged backend), so it must be
        disarmed explicitly before doing anything long-running."""
        if getattr(self, "_sup", None) is not None:
            self._sup.disarm()

    def _run_threaded_body(self, sup: Supervisor, batch_q: "queue.Queue") -> None:
        cfg = self.cfg
        # armed BEFORE warmup (caller holds armed_watchdog): the warmup
        # collection loop runs on the main thread against the same backend
        # the watchdog guards
        self.warmup(beat=sup.main_beat)

        spi = cfg.samples_per_insert
        # THIS-RUN, THIS-HOST accounting: inserts baseline at the current
        # counter (a restored replay snapshot's lifetime total must not
        # starve collection), and a multi-process run divides the global
        # batch by process count so the ratio compares host-local apples
        consumed_per_update = cfg.batch_size * cfg.learning_steps / max(jax.process_count(), 1)
        inserted0 = self.replay.env_steps

        def actor_body():
            if spi > 0 and self.replay.can_sample():
                consumed = (self._step - self._initial_step) * consumed_per_update
                inserted = max(self.replay.env_steps - inserted0, 1)
                if consumed / inserted < spi:
                    # data is plentiful relative to optimization: yield the
                    # device to the learner (bounded sleep keeps the
                    # supervisor heartbeat fresh)
                    time.sleep(0.05)
                    return
            self.actor.step()

        # one sample + one bounded put attempt per call: a full queue (the
        # learner compiling or checkpointing) retries across calls, keeping
        # the heartbeat fresh instead of looking like a stall
        pending = [None]

        def sampler_body():
            if pending[0] is None:
                # pipelined: gather/copy at sample time so queued items
                # cannot be invalidated by concurrent block writes
                pending[0] = self.plane.sample(pipelined=True)
            try:
                batch_q.put(pending[0], timeout=0.5)
                pending[0] = None
            except queue.Full:
                pass

        def sampler_recover():
            pending[0] = None  # a half-built item may be inconsistent

        sup.spawn("actor", actor_body, max_restarts=cfg.worker_max_restarts,
                  on_restart=self.actor.resync)
        sup.spawn("sampler", sampler_body, max_restarts=cfg.worker_max_restarts,
                  on_restart=sampler_recover)
        last_health: Optional[dict] = None

        def cleanup():
            # shutdown FIRST: it stops the main-thread watchdog, whose
            # timeout must not count the (possibly minutes-long) priority
            # drain and replay snapshot below as a "stall"; it also joins
            # the actor/sampler threads, so the carry below sees quiescent
            # accumulators and a frozen replay
            sup.shutdown()
            self._stop_profile()
            carry = self._capture_carry_safe()
            self.finish_updates()
            if cfg.snapshot_replay:
                self._snapshot_on_exit(extra=carry)

        try:
            while self._step < cfg.training_steps:
                sup.main_beat()
                if self._preempt_now():
                    break
                try:
                    item = batch_q.get(timeout=2.0)
                except queue.Empty:
                    # raises WorkerFatalError on a dead worker; stall/restart
                    # transitions still reach the metrics stream even though
                    # no update is flowing (that is exactly when they matter)
                    stats = sup.check()
                    if stats != last_health:
                        last_health = stats
                        self.metrics.log({"step": self._step, **stats})
                    continue
                m, step = self._one_update(item)
                health = sup.check()
                last_health = health
                self._log(m, step, extra=health)
        except WorkerStalledError:
            # a wedged worker means the backend itself is suspect: any
            # cleanup that blocks on device work (priority drain, profile
            # sync, replay snapshot) would hang the very exit this error
            # exists to force — skip it ALL, including Supervisor.shutdown
            # (which would stop the main-thread watchdog: it must stay
            # armed so a hang in interpreter-shutdown atexit hooks still
            # gets hard-exited). Worker threads are daemons; the process
            # is going down either way.
            raise
        except BaseException:
            cleanup()
            raise
        else:
            cleanup()

    def run_fused(self, collect_every: Optional[int] = None) -> None:
        """Fused actor-learner loop: ONE dispatch per iteration runs K
        updates plus (every collect_every'th dispatch) a full collection
        chunk and its store scatter (megastep.py). No worker threads: the
        host only does sum-tree bookkeeping between dispatches.

        collect_every=None paces collection from cfg.samples_per_insert on
        ACTUAL consumed/inserted counters (the threaded pacer's rule);
        samples_per_insert == 0 collects every dispatch. An explicit
        collect_every overrides both."""
        cfg = self.cfg
        if cfg.collector != "device" or cfg.replay_plane not in (
            "device", "sharded", "multihost"
        ):
            raise ValueError(
                "run_fused needs collector='device' and replay_plane="
                f"'device'/'sharded'/'multihost' (got {cfg.collector!r}, "
                f"{cfg.replay_plane!r})"
            )
        self.reset_clock()
        # main-thread watchdog: this loop has no worker threads, so a
        # wedged device readback would hang it silently forever — the
        # watchdog hard-exits (utils/supervision.STALL_EXIT_CODE) instead.
        # Armed before warmup so the warmup collection is covered too.
        sup = self._sup = self._make_supervisor()
        with self._sigterm_to_preempt(), sup.armed_watchdog():
            self._run_fused_body(sup, collect_every)
        if self.preempted:
            self._finalize_preempt()

    def _run_fused_body(self, sup: Supervisor, collect_every: Optional[int]) -> None:
        cfg = self.cfg
        from r2d2_tpu.megastep import (
            FusedSystemRunner,
            MultiHostFusedRunner,
            ShardedFusedRunner,
        )

        self.warmup(beat=sup.main_beat)
        common = dict(
            collect_every=1 if collect_every is None else collect_every,
            chunk_len=self.actor.chunk,
            sample_rng=self.sample_rng,
            samples_per_insert=cfg.samples_per_insert if collect_every is None else 0.0,
        )
        if cfg.replay_plane == "multihost":
            # collective megastep over the GLOBAL mesh: the runner builds
            # its own per-local-shard env slots (pinned-slot rule); the
            # warmup collector's episodes end here
            runner = MultiHostFusedRunner(
                cfg, self.net, self.fn_env, self.replay,
                self.actor.epsilons, self.actor.key, self.mesh, **common,
            )
        elif cfg.replay_plane == "sharded":
            runner = ShardedFusedRunner(
                cfg, self.net, self.fn_env, self.replay,
                self.actor.epsilons, self.actor.env_state, self.actor.key,
                self.mesh, **common,
            )
        else:
            runner = FusedSystemRunner(
                cfg, self.net, self.fn_env, self.replay,
                self.actor.epsilons, self.actor.env_state, self.actor.key,
                **common,
            )
        try:
            # metrics log lags ONE dispatch: reading a dispatch's loss
            # floats immediately would sync on it, re-serializing the very
            # readback the runner's deferred-drain protocol pipelines away
            # — a previous dispatch's floats have already landed
            pending_log = None
            while self._step < cfg.training_steps:
                sup.main_beat()
                if self._preempt_now():
                    break
                self._profile_gate()
                prev = self._step
                with step_span("fused_megastep", prev):
                    self.state, m, recorded = runner.step(self.state)
                self._step += cfg.updates_per_dispatch
                self._profile_tick(cfg.updates_per_dispatch)
                self._cadences(prev, self._step)
                # log on drain dispatches (a chunk's accounting landed):
                # same cadence class as the old collect-dispatch logging
                if recorded and pending_log is not None:
                    self._log(*pending_log)
                pending_log = (m, self._step)
        finally:
            # watchdog off before the drain: cleanup must not count as a stall
            sup.stop.set()
            self._stop_profile()
            runner.finish()
            # the deferred metrics of the final dispatch have landed by now
            if pending_log is not None:
                self._log(*pending_log)
            self._flush_log()
            # hand the collector loop state back so a later warmup/eval on
            # this Trainer continues from consistent episodes (the sharded
            # runner keeps one PRNG stream per shard; shard 0's continues
            # the actor's single stream)
            self.actor.env_state = runner.env_state
            self.actor.key = runner.key if hasattr(runner, "key") else runner.keys[0]
            self.actor.total_steps += runner.total_env_steps
            if cfg.snapshot_replay:
                # carry AFTER the actor handback so the DeviceCollector
                # carry captures the runner's final env/PRNG state
                self._snapshot_on_exit(extra=self._capture_carry_safe())


def main(argv=None):
    p = argparse.ArgumentParser(description="r2d2_tpu trainer")
    p.add_argument("--preset", default="atari", choices=sorted(PRESETS))
    p.add_argument("--env", default=None, help="override env name (e.g. catch)")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--mode", default="threaded", choices=["threaded", "inline", "fused"],
                   help="fused: one dispatch = K updates + collection chunk "
                        "(collector='device' + replay 'device' only)")
    p.add_argument("--replay", default=None,
                   choices=["host", "tiered", "device", "sharded", "multihost"],
                   help="replay data plane (default: preset's replay_plane)")
    p.add_argument("--distributed", action="store_true",
                   help="initialize jax.distributed from the standard env "
                        "vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES "
                        "/ JAX_PROCESS_ID) before building the trainer; "
                        "pair with --replay multihost")
    p.add_argument("--collector", default=None, choices=["host", "device"],
                   help="experience collection: host actor loop or fully "
                        "on-device jitted chunks (pure-JAX envs only)")
    p.add_argument("--updates-per-dispatch", type=int, default=None,
                   help="fold K learner updates into one jitted dispatch "
                        "(device replay plane; amortizes launch latency)")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel mesh size (overrides preset dp_size)")
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel mesh size (overrides preset tp_size)")
    p.add_argument("--fsdp", type=int, default=None,
                   help="fsdp mesh-axis size (overrides preset fsdp_size): "
                        "shards the Adam mu/nu trees over a third mesh axis "
                        "(parallel/sharding_map.py); replay snapshots are "
                        "fsdp-agnostic, so --resume/--reshard compose freely")
    p.add_argument("--model-preset", default=None,
                   help="named model-size preset (config.MODEL_PRESETS: "
                        "wide/xl widen the LSTM, deep/deep_wide add encoder "
                        "Dense layers) applied over the run preset; "
                        "--set still wins on individual fields")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--reshard", action="store_true",
                   help="on --resume, a replay snapshot saved under a "
                        "different (dp, tp, process_count) topology is "
                        "regathered and re-split across the current layout "
                        "(replay/reshard.py) instead of aborting with "
                        "TopologyMismatch")
    p.add_argument("--snapshot-replay", action="store_true",
                   help="save full replay contents at end of run and restore "
                        "them on --resume (replay/snapshot.py)")
    p.add_argument("--metrics", default=None)
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="override any R2D2Config field, typed by the field "
                        "(repeatable; e.g. --set gamma=0.99 --set "
                        "batch_size=32 --set obs_shape=64,64,3)")
    p.add_argument("--profile-dir", default=None,
                   help="record a jax.profiler trace of the first post-warmup updates")
    p.add_argument("--profile-steps", type=int, default=20)
    p.add_argument("--profile-port", type=int, default=0,
                   help="if set, start a live profiler server on this port")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(R2D2_COMPILE_CACHE env var is the same knob; "
                        "default: repo-local .jax_cache on accelerator "
                        "backends)")
    args = p.parse_args(argv)

    if args.compile_cache:
        from r2d2_tpu.utils.compilation_cache import enable_compilation_cache

        enable_compilation_cache(args.compile_cache)

    if args.distributed:
        from r2d2_tpu.parallel.multihost import initialize_distributed

        initialize_distributed()

    cfg = PRESETS[args.preset]()
    if args.model_preset:
        from r2d2_tpu.config import apply_model_preset

        cfg = apply_model_preset(cfg, args.model_preset)
    overrides = {}
    if args.env:
        overrides["env_name"] = args.env
    if args.steps:
        overrides["training_steps"] = args.steps
    if args.metrics:
        overrides["metrics_path"] = args.metrics
    if args.replay:
        overrides["replay_plane"] = args.replay
    if args.mode == "fused" and args.collector is None:
        args.collector = "device"  # the only collector run_fused supports
    if args.collector:
        overrides["collector"] = args.collector
        if args.collector == "device" and args.replay is None:
            overrides["replay_plane"] = "device"
    if args.snapshot_replay:
        overrides["snapshot_replay"] = True
    if args.reshard:
        overrides["reshard_on_resume"] = True
    if args.dp is not None:
        overrides["dp_size"] = args.dp
    if args.tp is not None:
        overrides["tp_size"] = args.tp
    if args.fsdp is not None:
        overrides["fsdp_size"] = args.fsdp
    if args.updates_per_dispatch is not None:
        overrides["updates_per_dispatch"] = args.updates_per_dispatch
        # convenience only for the single-chip default: never silently
        # replace an explicitly-chosen or preset sharded/device plane —
        # config.validate() surfaces incompatible combinations instead
        if (
            args.updates_per_dispatch > 1
            and args.replay is None
            and args.collector != "device"
            and cfg.replay_plane == "host"
        ):
            overrides["replay_plane"] = "device"
    if args.set:
        # applied LAST: --set is the explicit word on any field
        overrides.update(parse_overrides(args.set))
    if overrides:
        cfg = cfg.replace(**overrides)

    if args.profile_port:
        start_profiler_server(args.profile_port)
    # deterministic fault injection for chaos drills (R2D2_FAULTS env var;
    # utils/faults.py) — a no-op when unset
    install_from_env()
    trainer = Trainer(
        cfg,
        resume=args.resume,
        profile_dir=args.profile_dir,
        profile_steps=args.profile_steps,
    )
    try:
        if args.mode == "inline":
            trainer.run_inline()
        elif args.mode == "fused":
            trainer.run_fused()
        else:
            trainer.run_threaded()
    except WorkerStalledError as e:
        # CLI contract: a wedged runtime exits with STALL_EXIT_CODE so an
        # external supervisor can distinguish "restart with --resume" from
        # an ordinary crash. (Library callers instead receive the
        # exception; if they keep the process alive they must disarm via
        # Trainer.disarm_watchdog or e.supervisor.disarm().)
        from r2d2_tpu.utils.supervision import exit_for_stall

        exit_for_stall(e)
    from r2d2_tpu.utils.compilation_cache import log_compile_cache_stats

    log_compile_cache_stats()
    if trainer.preempted:
        # CLI contract: SIGTERM was absorbed into a clean cut — replay
        # snapshot + mid-run carry + finalized checkpoint are on disk.
        # PREEMPT_EXIT_CODE tells the external supervisor "restart with --resume
        # and training continues bit-exactly", vs STALL_EXIT_CODE's
        # "state may be stale".
        sys.exit(PREEMPT_EXIT_CODE)


if __name__ == "__main__":
    main()
