"""TransitionTap — capture served traffic as burn-in-correct replay Blocks.

The serve plane already sees everything R2D2 replay needs: each request
carries (obs_t, reward_{t-1}, reset_t), the jitted step produces
(q_t, action_t) and commits the post-step carry, and the publish cell
stamps (ckpt_step, params_version) on every answer. The tap records those
per-batch facts off the hot path and replays them, per session, through
the SAME `SequenceAccumulator` the actor uses (replay/accumulator.py), so
live-traffic Blocks carry identical stored-state / burn-in / n-step
semantics to actor-collected ones.

Serving shifts the actor's event ordering by one request: the reward and
next_obs for the action chosen at request t only arrive WITH request t+1.
The tap therefore holds one `pending` tuple (action_t, q_t, hidden_t,
eps_t, version_t) per session and completes the transition when the next
request lands:

    continuing row t+1:  acc.add(a_t, reward_row, obs_row, q_t, hidden_t)
                         block full -> finish(last_qval=q_{t+1}) (the cut
                         bootstrap the actor defers one step for is already
                         in hand here)
    reset row:           complete the pending transition with the row's
                         reward (the liveloop client protocol sends the
                         previous episode's terminal reward on the
                         reset=True request; the policy ignores it — the
                         serve step zeroes last_reward on reset — so only
                         the tap consumes it), finish(None), reseed.

Two approximations, both documented in ARCHITECTURE.md: the true terminal
frame never reaches the server, so the reset row's fresh obs stands in for
it (harmless — gamma_n = 0 zeroes the terminal bootstrap); and a cache
eviction seam (fresh admission without client reset) is encoded as a
terminal rather than a bootstrap cut, since the recurrent carry is
genuinely lost there.

Capture cost on the serve side is one fused device gather of the batch
rows' post-step carries (`gather_carry_rows`, jitted and covered by the
jaxpr entry-point gate) plus a bounded deque append; accumulation itself
runs on the supervised "liveloop-tap" thread. Under the depth-2 serve
pipeline the two halves split across its stages: the serve thread calls
`gather_rows` at DISPATCH time — the gather must be stream-ordered right
after the carry commit, before a later donated step can consume the
stores — and the serve-complete worker passes the pre-gathered rows to
`observe_batch(rows=...)` when it materializes the batch. The serial
path keeps the legacy shape (observe_batch gathers internally when
`rows` is None). The deque sheds drop-oldest (counted) under pressure,
and sessions seen in a dropped record are re-seeded at next sight with
their partial block cut cleanly (bootstrapped from the pending Q) — a
drop costs data, never correctness of what is emitted.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.accumulator import SequenceAccumulator


def gather_carry_rows(h_store, c_store, slots):
    """Pure gather of the batch rows' post-step carries out of the session
    stores, cast to float32 (the cache may hold bf16 — the accumulator
    contract is f32 (2, H) stored state)."""
    return (
        jnp.take(h_store, slots, axis=0).astype(jnp.float32),
        jnp.take(c_store, slots, axis=0).astype(jnp.float32),
    )


_gather_jit = None


def _gather(h_store, c_store, slots):
    global _gather_jit
    if _gather_jit is None:
        _gather_jit = jax.jit(gather_carry_rows)
    return _gather_jit(h_store, c_store, slots)


@dataclasses.dataclass
class BatchRecord:
    """One served batch's tap-relevant facts, already on host."""

    sids: List[str]
    obs: np.ndarray        # (n, *obs_shape)
    actions: np.ndarray    # (n,) int
    qvals: np.ndarray      # (n, A) f32
    rewards: np.ndarray    # (n,) f32 — reward_{t-1}, rides request t
    resets: np.ndarray     # (n,) bool — effective (client reset | fresh)
    eps: np.ndarray        # (n,) f32 — per-row exploration epsilon
    h_rows: np.ndarray     # (n, H) f32 post-step carry
    c_rows: np.ndarray     # (n, H) f32
    ckpt_step: int
    version: int


class _SessionStream:
    """Per-session accumulator + the one-step pending tuple + audit stamps
    (one (epsilon, params_version) per added transition)."""

    __slots__ = ("acc", "pending", "eps_stamps", "ver_stamps")

    def __init__(self, cfg: R2D2Config):
        self.acc = SequenceAccumulator(cfg)
        self.pending = None  # (action, q, hidden(2,H), eps, version)
        self.eps_stamps: List[float] = []
        self.ver_stamps: List[int] = []


class TransitionTap:
    """Bounded batch-record queue + per-session stream state.

    `observe_batch` is the only method the serve loop calls; everything
    else runs on the liveloop-tap thread (or synchronously in tests via
    `process_pending`). Counters and the record queue share one lock;
    per-session streams are touched only by the processing side, so the
    serve loop is never blocked on accumulation.
    """

    def __init__(self, cfg: R2D2Config, depth: Optional[int] = None,
                 emit: Optional[Callable] = None):
        self.cfg = cfg
        self.depth = int(depth if depth is not None else cfg.liveloop_tap_depth)
        # r2d2: ephemeral(process-local plumbing: the owner rewires the callback via set_emit on every (re)construction, it is never part of replayed state)
        self._emit = emit  # (block, priorities, episode_reward) -> None
        self._lock = threading.Lock()
        self._q: deque = deque()
        self._wake = threading.Event()
        self._sessions: Dict[str, _SessionStream] = {}
        # r2d2: ephemeral(only guards seam accounting for batches still queued in _q; the tap thread drains _q before any snapshot cut, so it is empty whenever carry_state runs)
        self._broken: set = set()  # sids whose continuity a drop severed
        # r2d2: ephemeral(pending disconnects are applied by the same process_pending cycle that would precede a snapshot cut; a resumed run re-evicts via live disconnects)
        self._evictions: List[str] = []  # disconnects queued for the tap thread
        # counters (all guarded by _lock) — monitoring only: stats() feeds
        # the metrics stream, never replay or the resume fingerprint, so a
        # resumed process restarts them from zero by design
        # r2d2: ephemeral(monitoring counter; stats-only, restarts at 0 on resume)
        self.captured_steps = 0
        # r2d2: ephemeral(monitoring counter; stats-only, restarts at 0 on resume)
        self.emitted_blocks = 0
        # r2d2: ephemeral(monitoring counter; stats-only, restarts at 0 on resume)
        self.dropped_batches = 0
        # r2d2: ephemeral(monitoring counter; stats-only, restarts at 0 on resume)
        self.seam_breaks = 0
        # bounded off-policy audit trail: per emitted block, the aligned
        # (epsilon, params_version) stamps of its transitions
        self.audit_tail: deque = deque(maxlen=64)

    def set_emit(self, emit: Callable) -> None:
        self._emit = emit

    # ------------------------------------------------------------ serve side

    def gather_rows(self, h_store, c_store, slots):
        """Dispatch the fused carry gather on the CALLER's thread (the
        serve thread, at dispatch time) and return the still-async device
        pair for a later `observe_batch(rows=...)`. The pipelined server
        needs the gather ordered on the device stream before the next
        donated step consumes the stores; materialization happens on the
        completion side, off the serve thread."""
        return _gather(h_store, c_store, jnp.asarray(slots))

    def observe_batch(
        self,
        sids: Sequence[str],
        obs: np.ndarray,
        actions: np.ndarray,
        qvals: np.ndarray,
        rewards: np.ndarray,
        resets: np.ndarray,
        eps: np.ndarray,
        ckpt_step: int,
        version: int,
        h_store,
        c_store,
        slots: np.ndarray,
        rows=None,
    ) -> None:
        """Record one served batch (first n = len(sids) rows of each array
        are real; pads were already sliced off by the caller or are sliced
        here). `rows` (an (h_rows, c_rows) pair from `gather_rows`) skips
        the internal carry gather — the pipelined server pre-gathers at
        dispatch time and h_store/c_store may then be None. One D2H wait +
        bounded append either way."""
        n = len(sids)
        if rows is not None:
            h_rows, c_rows = rows
        else:
            h_rows, c_rows = _gather(h_store, c_store, jnp.asarray(slots[:n]))
        rec = BatchRecord(
            sids=list(sids),
            obs=np.asarray(obs[:n]),
            actions=np.asarray(actions[:n]),
            qvals=np.asarray(qvals[:n], np.float32),
            rewards=np.asarray(rewards[:n], np.float32),
            resets=np.asarray(resets[:n], bool),
            eps=np.asarray(eps[:n], np.float32),
            h_rows=np.asarray(h_rows),
            c_rows=np.asarray(c_rows),
            ckpt_step=int(ckpt_step),
            version=int(version),
        )
        with self._lock:
            if len(self._q) >= self.depth:
                dropped = self._q.popleft()
                self.dropped_batches += 1
                self._broken.update(dropped.sids)
            self._q.append(rec)
        self._wake.set()

    def observe_evict(self, sid: str) -> None:
        """Session disconnected (client thread): queue the eviction so the
        tap thread — the only writer of per-session streams — applies it.
        The session's partial block is cut (pending-Q bootstrap) and its
        stream dropped at the next drain."""
        with self._lock:
            self._evictions.append(sid)
        self._wake.set()

    # -------------------------------------------------------- processing side

    def process_pending(self, timeout: float = 0.0) -> int:
        """Drain and accumulate every queued record; returns records
        processed. The liveloop-tap thread body calls this with a small
        timeout; tests call it with timeout=0 for synchronous drains."""
        if timeout > 0.0 and not self._wake.wait(timeout):
            return 0
        with self._lock:
            records = list(self._q)
            self._q.clear()
            self._wake.clear()
            broken, self._broken = self._broken, set()
            evictions, self._evictions = self._evictions, []
        for rec in records:
            self._apply(rec, broken)
        for sid in evictions:
            # single-writer contract: _sessions is only ever mutated by
            # the processing side — the liveloop-tap worker while it runs,
            # or the owning thread (tests, stop(), snapshot) strictly
            # before/after the worker's lifetime. Cross-thread inputs all
            # arrive through the lock-guarded record/eviction queues.
            # r2d2: disable=cross-thread-unguarded-write
            st = self._sessions.pop(sid, None)
            if st is not None and st.acc.size > 0:
                last_q = st.pending[1] if st.pending is not None else None
                self._finish(sid, st, last_qval=last_q)
        return len(records)

    def _apply(self, rec: BatchRecord, broken=None) -> None:
        broken = set() if broken is None else broken
        for i, sid in enumerate(rec.sids):
            st = self._sessions.get(sid)
            severed = sid in broken
            if severed:
                broken.discard(sid)
            if st is not None and severed:
                # continuity severed by a dropped record: cut the partial
                # block cleanly (pending.q is Q of the obs after the last
                # added transition — the correct cut bootstrap), reseed
                if st.acc.size > 0:
                    last_q = st.pending[1] if st.pending is not None else None
                    self._finish(sid, st, last_qval=last_q)
                with self._lock:
                    self.seam_breaks += 1
                st = None
            row_obs = rec.obs[i]
            hidden = np.stack([rec.h_rows[i], rec.c_rows[i]])
            if st is None:
                st = _SessionStream(self.cfg)
                st.acc.reset(row_obs)
                # r2d2: disable=cross-thread-unguarded-write  (single-writer contract in process_pending)
                self._sessions[sid] = st
            elif rec.resets[i]:
                if st.pending is not None:
                    # reset-row reward = previous episode's terminal reward;
                    # row_obs stands in for the unseen terminal frame
                    self._add(st, float(rec.rewards[i]), row_obs)
                    self._finish(sid, st, last_qval=None)
                st.acc.reset(row_obs)
            else:
                if st.pending is None:
                    # tap attached mid-session (or state lost): reseed
                    with self._lock:
                        self.seam_breaks += 1
                    st.acc.reset(row_obs)
                else:
                    self._add(st, float(rec.rewards[i]), row_obs)
                    if st.acc.size == self.cfg.block_length:
                        self._finish(sid, st, last_qval=rec.qvals[i])
            st.pending = (
                int(rec.actions[i]), rec.qvals[i], hidden,
                float(rec.eps[i]), rec.version,
            )

    def _add(self, st: _SessionStream, reward: float, next_obs: np.ndarray) -> None:
        action, q, hidden, eps, version = st.pending
        st.acc.add(action, reward, next_obs, q, hidden)
        st.eps_stamps.append(eps)
        st.ver_stamps.append(version)
        with self._lock:
            self.captured_steps += 1

    def _finish(self, sid: str, st: _SessionStream, last_qval) -> None:
        block, priorities, episode_reward = st.acc.finish(last_qval=last_qval)
        audit = {
            "session": sid,
            "epsilon": np.asarray(st.eps_stamps, np.float32),
            "params_version": np.asarray(st.ver_stamps, np.int64),
        }
        st.eps_stamps = []
        st.ver_stamps = []
        with self._lock:
            self.emitted_blocks += 1
            self.audit_tail.append(audit)
        st.pending = None
        if self._emit is not None:
            self._emit(block, priorities, episode_reward)

    def flush(self) -> int:
        """Cut every in-flight partial block (stop/drain time). Pending
        transitions cannot complete (their reward never arrived) so each
        partial is bootstrapped from its pending Q like a block cut."""
        cut = 0
        for sid, st in list(self._sessions.items()):
            if st.acc.size > 0:
                last_q = st.pending[1] if st.pending is not None else None
                self._finish(sid, st, last_qval=last_q)
                cut += 1
            # r2d2: disable=cross-thread-unguarded-write  (single-writer contract in process_pending)
            del self._sessions[sid]
        return cut

    # --------------------------------------------------------- snapshot/stats

    def carry_state(self) -> dict:
        """Per-session mutable state as npz-safe arrays (mirrors
        SequenceAccumulator.carry_state) for mid-loop snapshot/resume."""
        out = {}
        for sid, st in self._sessions.items():
            d = st.acc.carry_state()
            d["eps_stamps"] = np.asarray(st.eps_stamps, np.float64)
            d["ver_stamps"] = np.asarray(st.ver_stamps, np.int64)
            d["has_pending"] = np.asarray(int(st.pending is not None), np.int64)
            if st.pending is not None:
                action, q, hidden, eps, version = st.pending
                d["pending_action"] = np.asarray(action, np.int64)
                d["pending_q"] = np.asarray(q, np.float32)
                d["pending_hidden"] = np.asarray(hidden, np.float32)
                d["pending_eps"] = np.asarray(eps, np.float64)
                d["pending_version"] = np.asarray(version, np.int64)
            out[sid] = d
        return out

    def restore_carry(self, state: dict) -> None:
        # r2d2: disable=cross-thread-unguarded-write  (single-writer contract in process_pending)
        self._sessions.clear()
        for sid, d in state.items():
            st = _SessionStream(self.cfg)
            st.acc.restore_carry(d)
            st.eps_stamps = [float(e) for e in d["eps_stamps"]]
            st.ver_stamps = [int(v) for v in d["ver_stamps"]]
            if int(np.asarray(d["has_pending"])[()]):
                st.pending = (
                    int(np.asarray(d["pending_action"])[()]),
                    np.asarray(d["pending_q"], np.float32),
                    np.asarray(d["pending_hidden"], np.float32),
                    float(np.asarray(d["pending_eps"])[()]),
                    int(np.asarray(d["pending_version"])[()]),
                )
            # r2d2: disable=cross-thread-unguarded-write  (single-writer contract in process_pending)
            self._sessions[sid] = st

    def stats(self) -> dict:
        with self._lock:
            return {
                "tap_captured_steps": self.captured_steps,
                "tap_emitted_blocks": self.emitted_blocks,
                "tap_dropped_batches": self.dropped_batches,
                "tap_seam_breaks": self.seam_breaks,
                "tap_queue_depth": len(self._q),
                "tap_open_sessions": len(self._sessions),
            }
