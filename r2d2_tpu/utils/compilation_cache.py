"""Persistent XLA compilation cache (SURVEY.md 5.1 adjacent; VERDICT r2
item 8).

The flagship program set (fused megastep + eval collector + acting
forward) costs ~27-110 s to compile cold on the tunneled TPU backend —
BENCH_r01 measured 26.7 s, BENCH_r02 109.7 s for the same programs, the
spread being backend/tunnel noise, not repo changes. Every fresh process
(each curriculum stage, each bench run, each eval pass) repaid it.

jax's persistent compilation cache works on this backend (verified:
2.26 s cold -> 0.13 s warm across processes for a 2048^2 bf16 matmul
program). Enabling it makes multi-process drivers (runs/
run_mc_curriculum.py replays 7+ stages) pay compilation once per
distinct program, not once per process.

Opt-out: set R2D2_TPU_NO_COMPILE_CACHE=1 (e.g. when measuring true cold
compile times — bench.py does this for its compile-time metric).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_compilation_cache(cache_dir: str | None = None) -> bool:
    """Idempotently point jax at a persistent compilation cache directory.

    Returns True when the cache is (already) enabled, False when opted
    out. Safe to call before or after backend init; an explicit
    JAX_COMPILATION_CACHE_DIR env var or earlier jax.config setting
    wins."""
    if os.environ.get("R2D2_TPU_NO_COMPILE_CACHE"):
        return False
    import jax

    if jax.config.jax_compilation_cache_dir:  # env var or earlier caller
        return True
    if jax.default_backend() == "cpu":
        # XLA:CPU AOT cache loads warn about machine-feature mismatches
        # ("could lead to SIGILL") and CPU compiles are cheap — the cache
        # earns its keep only on the accelerator backend
        return False
    jax.config.update("jax_compilation_cache_dir", cache_dir or _DEFAULT_DIR)
    # the default 1 s floor would skip many of the small eval/acting
    # programs whose compiles still dominate short runs in aggregate
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return True
