"""Findings model for the static-analysis plane.

One shape for every checker — AST lints and jaxpr scanners alike — so the
CLI, the tier-1 gate (tests/test_analysis.py), and ad-hoc callers all
consume the same records: rule id, severity, file:line, message, and a fix
hint. JSON output is stable-sorted (path, line, col, rule, message) so two
runs over the same tree diff clean.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str  # one of SEVERITIES
    path: str  # source file, or "<jaxpr:label>" for traced-program findings
    line: int  # 1-based; 0 for whole-program (jaxpr) findings
    col: int  # 0-based column; 0 for jaxpr findings
    message: str
    hint: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc} [{self.severity}] {self.rule}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


def stable_sort(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings, key=Finding.sort_key)


def render_text(findings: Iterable[Finding]) -> str:
    fs = stable_sort(findings)
    if not fs:
        return "no findings"
    lines = [f.render() for f in fs]
    lines.append(f"{len(fs)} finding{'s' if len(fs) != 1 else ''}")
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    fs = stable_sort(findings)
    return json.dumps(
        {"count": len(fs), "findings": [f.to_dict() for f in fs]},
        indent=2,
        sort_keys=True,
    )
