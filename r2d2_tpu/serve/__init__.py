"""Policy serving plane (L4/L6): session-stateful batched online inference.

The training stack's inference-side counterpart (SEED RL-style centralized
batched acting over many user sessions): a device-resident recurrent-state
cache keyed by session id, a deadline micro-batcher with bucketed batch
shapes, and a threaded serve loop with atomic checkpoint hot-reload —
turning a trained R2D2 checkpoint into a low-latency policy service.
"""

from r2d2_tpu.serve.autoscale import Autoscaler, AutoscaleConfig
from r2d2_tpu.serve.batcher import MicroBatcher, QueueFullError, ServeRequest
from r2d2_tpu.serve.client import LocalClient, PolicyClient
from r2d2_tpu.serve.degrade import (
    RUNGS,
    DegradeConfig,
    DegradeController,
    SignalWindow,
)
from r2d2_tpu.serve.multi import MultiDeviceServer, SessionRouter
from r2d2_tpu.serve.scenarios import (
    Arrival,
    ScenarioRunner,
    ScenarioSpec,
    arrival_trace,
    builtin_scenarios,
)
from r2d2_tpu.serve.server import (
    PolicyServer,
    ServeConfig,
    ServeResult,
    reference_act,
)
from r2d2_tpu.serve.state_cache import RecurrentStateCache

__all__ = [
    "Arrival",
    "AutoscaleConfig",
    "Autoscaler",
    "DegradeConfig",
    "DegradeController",
    "LocalClient",
    "MicroBatcher",
    "MultiDeviceServer",
    "PolicyClient",
    "PolicyServer",
    "QueueFullError",
    "RUNGS",
    "RecurrentStateCache",
    "ScenarioRunner",
    "ScenarioSpec",
    "ServeConfig",
    "ServeRequest",
    "ServeResult",
    "SessionRouter",
    "SignalWindow",
    "arrival_trace",
    "builtin_scenarios",
    "reference_act",
]
