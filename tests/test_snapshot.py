"""Replay snapshots (replay/snapshot.py): a restored buffer is
bit-identical to the saved one across all three data planes — same
counters, same tree, and the same RNG stream draws the same batches."""

import jax
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.replay.device_store import DeviceReplayBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.replay.snapshot import restore_replay, save_replay
from r2d2_tpu.replay.sum_tree import SumTree


def _fill(replay, cfg, n_blocks=8, seed=0):
    from bench import synth_block

    rng = np.random.default_rng(seed)
    for _ in range(n_blocks):
        replay.add_block(
            synth_block(cfg, rng),
            rng.uniform(0.5, 2.0, cfg.seqs_per_block).astype(np.float32),
            float(rng.normal()),
        )


def test_sum_tree_leaves_round_trip():
    t = SumTree(37)
    rng = np.random.default_rng(0)
    t.update(rng.integers(0, 37, 60), rng.uniform(0.1, 3.0, 60))
    t2 = SumTree(37)
    t2.load_leaves(t.leaves())
    np.testing.assert_allclose(t2.tree, t.tree, rtol=1e-12)


@pytest.mark.parametrize("plane", ["host", "device"])
def test_snapshot_round_trip(tmp_path, plane):
    cfg = tiny_test()
    cls = ReplayBuffer if plane == "host" else DeviceReplayBuffer
    replay = cls(cfg)
    _fill(replay, cfg)
    path = str(tmp_path / "snap.npz")
    save_replay(replay, path)

    fresh = cls(cfg)
    restore_replay(fresh, path)
    assert len(fresh) == len(replay)
    assert fresh.env_steps == replay.env_steps
    assert fresh.block_ptr == replay.block_ptr
    assert fresh.episode_totals() == replay.episode_totals()
    np.testing.assert_allclose(fresh.tree.tree, replay.tree.tree, rtol=1e-12)

    if plane == "host":
        a = replay.sample_batch(np.random.default_rng(42))
        b = fresh.sample_batch(np.random.default_rng(42))
        np.testing.assert_array_equal(a.obs, b.obs)
        np.testing.assert_array_equal(a.idxes, b.idxes)
        np.testing.assert_allclose(a.is_weights, b.is_weights)
    else:
        a = replay.sample_indices(np.random.default_rng(42))
        b = fresh.sample_indices(np.random.default_rng(42))
        np.testing.assert_array_equal(a.idxes, b.idxes)
        np.testing.assert_allclose(a.is_weights, b.is_weights)
        for k, arr in replay.stores.items():
            np.testing.assert_array_equal(np.asarray(arr), np.asarray(fresh.stores[k]))


def test_snapshot_rejects_shape_mismatch(tmp_path):
    cfg = tiny_test()
    replay = ReplayBuffer(cfg)
    _fill(replay, cfg)
    path = str(tmp_path / "snap.npz")
    save_replay(replay, path)
    other = ReplayBuffer(cfg.replace(buffer_capacity=320))
    with pytest.raises(ValueError):
        restore_replay(other, path)
    wrong_plane = DeviceReplayBuffer(cfg)
    with pytest.raises(ValueError):
        restore_replay(wrong_plane, path)


def test_sharded_snapshot_round_trip(tmp_path):
    from r2d2_tpu.parallel.mesh import make_mesh
    from r2d2_tpu.replay.sharded_store import ShardedDeviceReplay

    dp = 4
    mesh = make_mesh(dp=dp, tp=1, devices=jax.devices()[:dp])
    cfg = tiny_test().replace(dp_size=dp, replay_plane="sharded", batch_size=8)
    replay = ShardedDeviceReplay(cfg, mesh)
    _fill(replay, cfg, n_blocks=2 * dp)
    path = str(tmp_path / "snap.npz")
    save_replay(replay, path)

    fresh = ShardedDeviceReplay(cfg, mesh)
    restore_replay(fresh, path)
    assert len(fresh) == len(replay)
    assert fresh._rr == replay._rr
    a = replay.sample_indices(np.random.default_rng(7))
    b = fresh.sample_indices(np.random.default_rng(7))
    np.testing.assert_array_equal(a.idxes, b.idxes)
    np.testing.assert_allclose(a.is_weights, b.is_weights)
    for k, arr in replay.stores.items():
        np.testing.assert_array_equal(np.asarray(arr), np.asarray(fresh.stores[k]))


def test_trainer_snapshot_resume(tmp_path):
    from r2d2_tpu.train import Trainer

    cfg = tiny_test().replace(
        env_name="catch",
        checkpoint_dir=str(tmp_path / "ckpt"),
        snapshot_replay=True,
        training_steps=6,
        save_interval=3,
        learning_starts=48,
    )
    t1 = Trainer(cfg)
    t1.run_inline(env_steps_per_update=4)
    saved_size = len(t1.replay)
    saved_env_steps = t1.replay.env_steps

    t2 = Trainer(cfg.replace(training_steps=8), resume=True)
    assert int(t2.state.step) == 6
    assert len(t2.replay) == saved_size
    # total env-step accounting doesn't double-count restored steps
    assert t2.replay.env_steps + t2.env_steps_offset == saved_env_steps
    # training continues with no warmup needed
    t2.run_inline(env_steps_per_update=4)
    assert int(t2.state.step) == 8


def test_restore_failure_leaves_buffer_untouched(tmp_path):
    """A mismatched snapshot must raise BEFORE mutating anything: the
    fresh buffer stays usable (empty) instead of half-restored."""
    cfg = tiny_test()
    replay = ReplayBuffer(cfg)
    _fill(replay, cfg)
    path = str(tmp_path / "snap.npz")
    save_replay(replay, path)
    other = ReplayBuffer(cfg.replace(obs_shape=(8, 8, 1)))
    with pytest.raises(ValueError):
        restore_replay(other, path)
    assert len(other) == 0
    assert other.tree.total == 0.0
    assert not other.occupied.any()
