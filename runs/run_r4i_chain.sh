#!/bin/bash
# Round-4 chain I: tighten the temporal break point. Blind 126
# (fall_every=6) solves with the stored-state machinery; blind ~270
# (fall_every=12) does not separate from its null. This rung sits
# between: memory_catch:10:9 — 216-step episodes, blind ~194, measured
# random -0.479 (runs/long_context_mid9/baseline.json). Same recipe as
# the solved rung (lru + cosine, two 128-step windows/block, window 1
# from stored state; seq 212).
cd /root/repo
run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}
run_with_retry python examples/long_context_demo.py --out runs/long_context_mid9 \
  --env memory_catch:10:9 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=216 \
  --set learning_steps=128 --set block_length=256 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID9 EXIT: $? ==="
echo R4I_CHAIN_ALL_DONE
