#!/bin/bash
# Round-5 chain A: deconfound the flagship-net ablation (VERDICT r4 item 2).
#
# The round-4 pair (mc84_full_lru vs _zerostate, cue 60 at 84x84) has a
# geometry confound the runs/README admits: blind span 22 vs L=20 learning
# windows, so a window starting late in the cue phase carries the cue
# WITHIN-window and zero-state replay is not information-starved — the
# pair demonstrates a speed gap, not the feasibility claim.
#
# Fix by construction: cue 40 => blind span 42 >> L=20. Now every window
# that contains cue frames ends >= 22 steps before the ball lands, and the
# whole final positioning phase lies in windows with NO cue access — a
# zero-state policy has nothing to position from, so only carried
# recurrent state can close the loop. Same net (full Nature/512), same
# proven recipe as mc84_full_lru otherwise (lru core, gamma .99, sync 250,
# L=B=20, 100k updates, n=64 eval).
#
# Stored-state solves (>= 0.5) => run the zero-state arm at the same
# geometry/budget to complete the controlled pair. If stored-state does
# NOT solve, the fallback geometry (cue 60 with L=10: blind 22 >> L=10,
# attacks the confound from the window side on the KNOWN-solvable task)
# runs instead — both arms.
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_cue40 \
  --env memory_catch:40 --full --mode fused --steps 100000 \
  --set recurrent_core=lru --set gamma=0.99 \
  --set target_net_update_interval=250 \
  --set learning_steps=20 --set burn_in_steps=20 --set save_interval=12500
echo "=== MC84_FULL_LRU_CUE40 EXIT: $? ==="
EV=$(last_eval runs/mc84_full_lru_cue40/eval.jsonl)
echo "=== MC84_FULL_LRU_CUE40 EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_cue40_zs \
    --env memory_catch:40 --full --mode fused --steps 100000 \
    --set recurrent_core=lru --set gamma=0.99 \
    --set target_net_update_interval=250 \
    --set learning_steps=20 --set burn_in_steps=20 --set save_interval=12500 \
    --ablate-zero-state
  echo "=== MC84_FULL_LRU_CUE40_ZS EXIT: $? ==="
else
  # fallback: attack the confound from the window side at the geometry
  # the net is KNOWN to solve (cue 60, blind 22) with L=B=10 windows
  run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_L10 \
    --env memory_catch:60 --full --mode fused --steps 100000 \
    --set recurrent_core=lru --set gamma=0.99 \
    --set target_net_update_interval=250 \
    --set learning_steps=10 --set burn_in_steps=10 --set save_interval=12500
  echo "=== MC84_FULL_LRU_L10 EXIT: $? ==="
  EV=$(last_eval runs/mc84_full_lru_L10/eval.jsonl)
  echo "=== MC84_FULL_LRU_L10 EVAL: $EV ==="
  if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
    run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_L10_zs \
      --env memory_catch:60 --full --mode fused --steps 100000 \
      --set recurrent_core=lru --set gamma=0.99 \
      --set target_net_update_interval=250 \
      --set learning_steps=10 --set burn_in_steps=10 --set save_interval=12500 \
      --ablate-zero-state
    echo "=== MC84_FULL_LRU_L10_ZS EXIT: $? ==="
  fi
fi

echo R5A_CHAIN_ALL_DONE
