"""Shims over jax API drift so the parallel planes run on both the
current jax (`jax.shard_map`, `check_vma=`/`axis_names=`) and the older
releases that only ship `jax.experimental.shard_map.shard_map`
(`check_rep=`/`auto=`). Every shard_map call in the codebase routes
through here instead of importing from jax directly."""

from __future__ import annotations

try:  # jax >= 0.6: top-level export with the new kwarg names
    from jax import shard_map as _new_shard_map
except ImportError:  # older jax: experimental module, old kwarg names
    _new_shard_map = None
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    """New-style shard_map signature, translated for old jax.

    `axis_names` is the set of mesh axes the body is manual over; any
    other mesh axis stays GSPMD-auto (old API: the `auto` frozenset is
    the complement). `check_vma` maps to the old `check_rep`."""
    if _new_shard_map is not None:
        kwargs = dict(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return _new_shard_map(f, **kwargs)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _old_shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        auto=auto,
    )
