"""Fused actor-learner megastep: collection + K updates in ONE dispatch.

The threaded full-system mode time-shares the chip between two dispatch
streams (collector chunks and K-update learner chunks) driven by two host
threads. On a single chip those dispatches serialize on the device anyway,
so the threads buy no overlap — they only add dispatch gaps, lock handoffs,
and GIL contention between the streams (measured: the concurrent system
sustained ~29% of the isolated learner rate while collection used ~12% of
the device).

The TPU-native fix is to stop round-tripping the host between the two
phases: ONE jitted dispatch runs

    K prioritized double-Q updates   (gathered in-jit from the HBM replay)
  + one full collection chunk        (policy + env dynamics + block packing,
                                      collect.make_collect_core)
  + the scatter of the E new blocks into the replay store

and the host's only per-dispatch work is sum-tree bookkeeping over a few
kilobytes of coordinates and priorities. XLA's SSA semantics give the
ordering for free: the update gathers read the store argument's PRE-scatter
contents (they were drawn against the host tree's current state), and the
donated scatter reuses the same HBM afterwards.

Semantics vs the threaded system mode (both reference-faithful):
- The chunk is collected with the params at dispatch entry (pre-update).
  The reference's actors run on weights up to publish_interval x
  actor_update_interval steps stale (reference worker.py:744-751); here the
  collection policy is at most K updates stale — strictly fresher — and no
  param publish transfer is needed at all for collection.
- New blocks enter the tree only after the dispatch returns, so updates
  within a dispatch never sample the chunk being collected alongside them —
  same one-chunk lag class as the threaded mode's queue depths (reference
  worker.py:364-371 tolerates ~12 batches).
- Priorities computed by the K updates land on the tree AFTER the chunk's
  blocks are accounted, so the pointer-window staleness mask (reference
  worker.py:290-307 invariant) rejects exactly the rows the scatter
  overwrote.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.collect import default_chunk_len, make_collect_core
from r2d2_tpu.learner import TrainState, make_multi_update_core
from r2d2_tpu.models.r2d2 import R2D2Network


def make_megastep(
    cfg: R2D2Config,
    net: R2D2Network,
    fn_env,
    num_envs: int,
    chunk_len: int,
    num_updates: int,
    donate: bool = True,
):
    """Build the fused dispatch.

    Signature:
      mega(state, stores, env_state, epsilons, key, b, s, w, ptr0) ->
        (state', stores', metrics, priorities (K, B),
         (chunk_prios, num_seq, sizes, dones, ep_rewards), env_state', key')

    b/s/w are (K, B) stacked sample coordinates drawn by the host against
    the current tree; ptr0 is the first of the E CONTIGUOUS store slots the
    host reserved for the chunk's blocks (ReplayControlPlane.
    _reserve_contiguous — a contiguous slab write runs at memcpy speed
    where a ring-crossing scatter costs seconds on TPU). Exactly
    equivalent to running learner.make_fused_multi_train_step on the same
    coordinates followed by collect + DeviceReplayBuffer.add_blocks_batch
    with the same key (pinned by tests/test_megastep.py)."""
    collect_core = make_collect_core(cfg, net, fn_env, num_envs, chunk_len)
    multi_core = make_multi_update_core(cfg, net, num_updates)

    def mega(state: TrainState, stores, env_state, epsilons, key, b, s, w, ptr0):
        # collection uses the dispatch-entry params: the freshest policy any
        # actor design could see without re-publishing mid-dispatch
        act_params = state.params
        state, metrics, priorities = multi_core(state, stores, b, s, w)

        (fields, chunk_prios, num_seq, sizes, dones, ep_rewards, fresh_env, key2) = (
            collect_core(act_params, env_state, epsilons, key)
        )
        new_stores = {
            k: jax.lax.dynamic_update_slice_in_dim(arr, fields[k], ptr0, axis=0)
            for k, arr in stores.items()
        }
        return (
            state,
            new_stores,
            metrics,
            priorities,
            (chunk_prios, num_seq, sizes, dones, ep_rewards),
            fresh_env,
            key2,
        )

    return jax.jit(mega, donate_argnums=(0, 1) if donate else ())


class FusedSystemRunner:
    """Drives the megastep against a DeviceReplayBuffer + DeviceCollector.

    Owns the per-dispatch protocol (the Trainer's fused mode and bench.py
    both go through here):

      1. under the replay lock: draw K x B coordinates, reserve the next E
         ring slots, dispatch (donating the stores), install the returned
         stores.
      2. read back the chunk's host-side bookkeeping (a few kB) and account
         the E new blocks — this advances the ring pointer past the
         reserved slots.
      3. apply the K update-priority rows under each draw's own staleness
         window: rows targeting slots the chunk overwrote are rejected by
         the pointer-window mask because accounting ran first.

    BOTH readbacks are DEFERRED one dispatch: reading this dispatch's
    priorities or chunk bookkeeping immediately would stall the host for
    the dispatch's execution plus a device->host round trip — on a
    tunneled backend the round trip alone rivals the compute. Instead both
    transfers start async and are collected while the NEXT dispatch
    executes, so the host never blocks on the dispatch it just issued.

    What makes chunk deferral safe is reserve-time pointer advancement
    (ReplayControlPlane._reserve_advance): the reserved slots' old blocks
    are retired (leaves zeroed, size deducted) and the ring pointer moves
    past them BEFORE the dispatch and BEFORE any draw — so (a) no draw can
    target a slot whose contents are in flight, and (b) the pointer-window
    staleness mask already rejects any stale priority row aimed at those
    slots. The deferred accounting (_account_blocks_at) then only has to
    install the new blocks' tree priorities and counters; ordering against
    the priority drain no longer matters. Replay availability of a chunk
    lags one extra dispatch — the same lag class as the threaded mode's
    queue depths (reference worker.py:364-371 tolerates ~12 batches).

    `collect_every` dispatches include the collection chunk; the others run
    the plain K-update dispatch (learner.make_fused_multi_train_step) so
    the insert:consume ratio is tunable without recompilation (two compiled
    programs, selected per dispatch)."""

    def __init__(
        self,
        cfg: R2D2Config,
        net: R2D2Network,
        fn_env,
        replay,
        epsilons: jnp.ndarray,
        env_state,
        key: jax.Array,
        collect_every: int = 1,
        chunk_len: Optional[int] = None,
        sample_rng: Optional[np.random.Generator] = None,
        samples_per_insert: float = 0.0,
    ):
        from r2d2_tpu.learner import make_fused_multi_train_step

        self.cfg = cfg
        self.replay = replay
        self.E = cfg.num_actors
        self.K = cfg.updates_per_dispatch
        self.chunk = int(chunk_len or default_chunk_len(cfg))
        # deferred-drain aliasing bound: between a draw and its priority
        # application (one dispatch later) at most two chunks can land,
        # each advancing the ring by E plus a wrap skip of < E. The
        # pointer-window mask is correct for any advancement < num_blocks;
        # a FULL lap would alias ptr == old_ptr and apply stale priorities
        # to fresh blocks, so reject configs where the bound can reach it.
        # The same guard covers the chunk-accounting deferral: a pending
        # chunk's slots could only be re-reserved by the next chunk when
        # num_blocks < 3E (reserve advances at most 2E-1 past the pending
        # slab), and consecutive collects require chunks_between=2 below,
        # i.e. num_blocks >= 4E-1 — strictly stronger.
        chunks_between = 2 if collect_every == 1 or samples_per_insert > 0 else 1
        max_advance = chunks_between * (2 * self.E - 1)
        if max_advance >= cfg.num_blocks:
            raise ValueError(
                f"store too small for deferred priorities: {cfg.num_blocks} "
                f"block slots but up to {max_advance} can be overwritten "
                f"between a draw and its application (E={self.E}); grow "
                "buffer_capacity or reduce num_actors"
            )
        if collect_every < 1:
            raise ValueError("collect_every must be >= 1")
        self.collect_every = collect_every
        # samples_per_insert > 0: ignore the fixed modulo and decide per
        # dispatch from ACTUAL counters (the threaded pacer's rule,
        # train.py actor_body) — chunks are episode-aligned and record
        # fewer than E*chunk_len transitions, so a ratio derived from the
        # theoretical max insert rate would silently overshoot the target
        self.samples_per_insert = samples_per_insert
        self._consumed = 0
        # pacing baseline: THIS-RUN insertions only, measured off the
        # replay's own recorded counter (the threaded pacer's rule,
        # train.py actor_body) — warmup/snapshot totals must not skew the
        # consumed:inserted ratio, and attempted-step proxies undercount
        # episode-aligned chunks
        self._inserted0 = replay.env_steps
        self.epsilons = epsilons
        self.env_state = env_state
        self.key = key
        self._mega = make_megastep(cfg, net, fn_env, self.E, self.chunk, self.K)
        self._multi = make_fused_multi_train_step(cfg, net, self.K)
        self._dispatch_count = 0
        self.total_env_steps = 0
        self._pending = None  # deferred (priorities, draws) readback
        self._pending_chunk = None  # deferred (ptr0, chunk bookkeeping) readback
        self.replay_rng = sample_rng if sample_rng is not None else np.random.default_rng(0)

    def step(self, state: TrainState):
        """One dispatch (K updates, plus the chunk on collect_every'th
        calls); returns (state', metrics, env_steps_recorded). With both
        readbacks deferred, `recorded` reports the PREVIOUS dispatch's
        chunk as its accounting lands (zero on the first collect)."""
        # consumption counted BEFORE the decision: this dispatch's K
        # updates are committed either way, and an understated consumed
        # would skip the first collect for no reason
        self._consumed += self.K * self.cfg.batch_size * self.cfg.learning_steps
        if self.samples_per_insert > 0:
            inserted = max(self.replay.env_steps - self._inserted0, 1)
            collect = self._consumed / inserted >= self.samples_per_insert
        else:
            collect = self._dispatch_count % self.collect_every == 0
        self._dispatch_count += 1
        replay = self.replay
        with replay.lock:
            if collect:
                # reserve BEFORE drawing: retires the slots' old blocks and
                # advances the ring pointer, so the draws below can neither
                # target the in-flight chunk's slots nor produce priority
                # rows the staleness mask would miss
                ptr0 = replay._reserve_advance(self.E)
            draws = [replay._draw_sample_idx(self.replay_rng) for _ in range(self.K)]
            b = jnp.asarray(np.stack([d.b for d in draws]))
            s = jnp.asarray(np.stack([d.s for d in draws]))
            w = jnp.asarray(np.stack([d.is_weights for d in draws]))
            if collect:
                (state, new_stores, m, prios, chunk_host, self.env_state, self.key) = (
                    self._mega(
                        state, replay.stores, self.env_state, self.epsilons,
                        self.key, b, s, w, jnp.int32(ptr0),
                    )
                )
                replay.stores = new_stores
            else:
                state, m, prios = self._multi(state, replay.stores, b, s, w)

        # start this dispatch's readbacks async; collect them next call
        for arr in (prios, *(chunk_host if collect else ())):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        recorded = 0
        prev_chunk = self._pending_chunk
        self._pending_chunk = (ptr0, chunk_host) if collect else None
        if prev_chunk is not None:
            recorded = self._drain_chunk(prev_chunk)
        prev, self._pending = self._pending, (prios, draws)
        if prev is not None:
            self._drain(prev)
        return state, m, recorded

    def _drain_chunk(self, pending) -> int:
        """Install a deferred chunk's accounting (tree priorities, sizes,
        episode stats) at its reserved slots; returns recorded steps."""
        ptr0, chunk_host = pending
        chunk_prios, num_seq, sizes, dones, ep_rewards = map(np.asarray, chunk_host)
        # chunks are episode-aligned: every recorded transition is a
        # learning step (collect.py _pack), so learning totals == sizes
        with self.replay.lock:
            self.replay._account_blocks_at(
                ptr0, num_seq, sizes, chunk_prios, ep_rewards, dones
            )
        recorded = int(sizes.sum())
        self.total_env_steps += recorded
        return recorded

    def _drain(self, pending) -> None:
        prios, draws = pending
        for row, d in zip(np.asarray(prios), draws):
            self.replay.update_priorities(d.idxes, row, d.old_ptr, d.old_advances)

    def finish(self) -> int:
        """Apply the final in-flight readbacks (chunk accounting first,
        then priorities); call once when the driving loop stops updating.
        Returns the env steps recorded by the final chunk drain."""
        recorded = 0
        pending_chunk, self._pending_chunk = self._pending_chunk, None
        if pending_chunk is not None:
            recorded = self._drain_chunk(pending_chunk)
        pending, self._pending = self._pending, None
        if pending is not None:
            self._drain(pending)
        return recorded
