"""Worker supervision: heartbeats, crash restart, stall detection.

The reference has no failure handling at all (SURVEY.md section 5.3): actors
are `while True` loops killed by terminate (reference train.py:61-62); a
crashed actor silently reduces throughput and a crashed learner hangs the
buffer process. Here every host-side worker loop runs under a Supervisor:

- each loop iteration stamps a heartbeat; a worker whose heartbeat goes
  stale past `heartbeat_timeout` is reported as stalled (Python threads
  cannot be preempted, so stalls are surfaced, not killed);
- a worker that raises has its traceback printed and recorded, its
  `on_restart` recovery hook run (e.g. VectorizedActor.resync, which
  discards in-flight state that a mid-iteration fault may have left
  inconsistent), and its loop re-entered — up to `max_restarts` times.
  Past the limit, or if the recovery hook itself fails, the worker is
  fatal and `check()` raises in the learner loop, failing the run loudly
  instead of silently starving it;
- restart/stall counts flow into the metrics stream.

Bodies should do a bounded amount of work per call (one actor step, one
queue-put attempt) so heartbeats stay fresh while blocked resources — a
full queue, a compiling learner — are retried across calls, not inside one.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional


class SupervisedWorker:
    """One host worker loop: `body()` is called repeatedly until stop."""

    def __init__(
        self,
        name: str,
        body: Callable[[], None],
        stop: threading.Event,
        max_restarts: int = 3,
        on_restart: Optional[Callable[[], None]] = None,
        error_history: int = 5,
    ):
        self.name = name
        self.body = body
        self.stop = stop
        self.max_restarts = max_restarts
        self.on_restart = on_restart
        self.restarts = 0
        self.last_beat = time.monotonic()
        self.errors: List[str] = []  # most recent `error_history` tracebacks
        self._error_history = error_history
        self.fatal = False
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def last_error(self) -> Optional[str]:
        return self.errors[-1] if self.errors else None

    def _record_error(self, context: str) -> None:
        tb = traceback.format_exc()
        with self._lock:
            self.errors.append(tb)
            del self.errors[: -self._error_history]
        print(f"[supervisor] worker {self.name!r} {context}:\n{tb}", file=sys.stderr)

    def _loop(self) -> None:
        while not self.stop.is_set():
            self.last_beat = time.monotonic()
            try:
                self.body()
            except BaseException:
                exhausted = self.restarts >= self.max_restarts
                self._record_error(
                    f"crashed (restart budget exhausted, {self.restarts}/{self.max_restarts})"
                    if exhausted
                    else f"crashed (restart {self.restarts + 1}/{self.max_restarts})"
                )
                if exhausted:
                    self.fatal = True
                    return
                self.restarts += 1
                if self.on_restart is not None:
                    try:
                        self.on_restart()
                    except BaseException:
                        self._record_error("recovery hook failed; going fatal")
                        self.fatal = True
                        return

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"supervised-{self.name}", daemon=True
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stalled_for(self) -> float:
        return time.monotonic() - self.last_beat


class WorkerFatalError(RuntimeError):
    pass


class Supervisor:
    def __init__(self, heartbeat_timeout: float = 120.0):
        self.heartbeat_timeout = heartbeat_timeout
        self.workers: List[SupervisedWorker] = []
        self.stop = threading.Event()
        self._stall_reported: Dict[str, bool] = {}

    def spawn(
        self,
        name: str,
        body: Callable[[], None],
        max_restarts: int = 3,
        on_restart: Optional[Callable[[], None]] = None,
    ) -> SupervisedWorker:
        w = SupervisedWorker(
            name, body, self.stop, max_restarts=max_restarts, on_restart=on_restart
        )
        self.workers.append(w)
        w.start()
        return w

    def check(self) -> Dict[str, int]:
        """Raise WorkerFatalError if any worker died for good; return
        restart/stall counters for the metrics stream."""
        restarts = 0
        stalls = 0
        for w in self.workers:
            if w.fatal:
                self.stop.set()
                raise WorkerFatalError(
                    f"worker {w.name!r} died ({w.restarts} restarts used); "
                    f"last error:\n{w.last_error}"
                )
            restarts += w.restarts
            if not self.stop.is_set() and w.stalled_for() > self.heartbeat_timeout:
                stalls += 1
                if not self._stall_reported.get(w.name):
                    self._stall_reported[w.name] = True
                    print(
                        f"[supervisor] worker {w.name!r} heartbeat stale for "
                        f"{w.stalled_for():.0f}s",
                        file=sys.stderr,
                    )
            else:
                self._stall_reported[w.name] = False
        return {"worker_restarts": restarts, "worker_stalls": stalls}

    def shutdown(self, timeout: float = 5.0) -> None:
        self.stop.set()
        for w in self.workers:
            w.join(timeout)
