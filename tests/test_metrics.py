"""MetricsLogger serialization: numpy/jax values must land as valid jsonl
(the old `default=float` raised TypeError on arrays), and close() must be
idempotent (train and serve teardown paths can both reach it)."""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from r2d2_tpu.utils.metrics import MetricsLogger, _json_default


def test_log_numpy_and_jax_values(tmp_path):
    path = tmp_path / "m.jsonl"
    m = MetricsLogger(str(path), stdout_interval=1e9)
    m.log(
        {
            "np_scalar": np.float32(1.5),
            "np_int": np.int64(7),
            "np_arr": np.arange(3),
            "np_big": np.zeros((64, 64)),
            "jax_scalar": jnp.asarray(2.5),
            "jax_arr": jnp.arange(4),
            "weird": object(),
            "plain": 3,
        }
    )
    m.close()
    rec = json.loads(path.read_text().strip())
    assert rec["np_scalar"] == 1.5
    assert rec["np_int"] == 7
    assert rec["np_arr"] == [0, 1, 2]
    # big arrays are summarized, never serialized element-wise
    assert "shape=(64, 64)" in rec["np_big"]
    assert rec["jax_scalar"] == 2.5
    assert rec["jax_arr"] == [0, 1, 2, 3]
    assert isinstance(rec["weird"], str)
    assert rec["plain"] == 3


def test_close_idempotent(tmp_path):
    m = MetricsLogger(str(tmp_path / "m.jsonl"))
    m.log({"a": 1})
    m.close()
    m.close()  # second close must be a no-op, not ValueError
    m2 = MetricsLogger(None)
    m2.log({"a": 1})  # no file -> stdout only, still fine
    m2.close()
    m2.close()


def test_json_default_zero_dim_array():
    assert _json_default(np.asarray(3.0)) == 3.0
    assert _json_default(jnp.asarray(3)) == 3
