"""Mesh construction and sharding rules.

Axes:
  dp — data parallel: the learner batch splits across this axis; gradient
       all-reduce (psum) is inserted by XLA because params are replicated.
  tp — tensor parallel: the LSTM's wide kernels shard their 4H axis over
       tp via the GSPMD annotations from `train_state_shardings` below.
       Plain-jit planes (host/device replay) partition directly from the
       shardings; the "sharded" shard_map plane composes dp×tp because
       its maps are manual over dp ONLY (axis_names={"dp"}) with tp left
       GSPMD-auto. The multihost plane pins tp=1 (config.validate).

Batches shard their leading (batch) dimension over dp; everything else is
replicated. With params replicated and batch sharded, jit emits a psum over
dp for the gradients — data parallelism without hand-written collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    dp: Optional[int] = None, tp: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if dp is None:
        dp = len(devices) // tp
    if dp * tp != len(devices):
        raise ValueError(f"dp*tp = {dp * tp} != {len(devices)} devices")
    dev_array = np.asarray(devices).reshape(dp, tp)
    return Mesh(dev_array, axis_names=("dp", "tp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis over dp, rest replicated."""
    return NamedSharding(mesh, P("dp"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def slab_sharding(mesh: Mesh) -> NamedSharding:
    """Replay-slab sharding: the block axis splits over dp, everything
    else replicated — the spec every dp-sharded replay store uses
    (sharded_store's flat stores, the reshard scatter's device_put)."""
    return NamedSharding(mesh, P("dp"))


def slab_partition_map(mesh: Mesh, num_blocks: int, axis: str = "dp"):
    """The per-slab partition map that extends slab_sharding with explicit
    block ownership: shard i on `axis` owns global block rows
    [start, end). This is what snapshot topology manifests record and the
    reshard-on-resume path (replay/reshard.py) re-splits against — the
    NamedSharding alone says "split over dp", the map says exactly which
    logical blocks each shard holds."""
    n = int(mesh.shape[axis])
    if num_blocks % n != 0:
        raise ValueError(f"num_blocks {num_blocks} not divisible by {axis}={n}")
    bps = num_blocks // n
    return {i: (i * bps, (i + 1) * bps) for i in range(n)}


def shard_batch(mesh: Mesh, batch_pytree):
    """device_put every leaf with its batch dim sharded over dp."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch_pytree)


def train_state_shardings(state, mesh: Mesh):
    """Per-leaf NamedShardings for a TrainState: every dense matmul in the
    model shards over tp in Megatron column/row pairs; with tp=1 this
    degenerates to fully-replicated, so it is safe to apply
    unconditionally on any mesh.

    The pairing (one collective per pair, inserted by GSPMD from the
    annotations alone):
    - LSTM `wi`/`wh` (in, 4H) + bias `b`: COLUMN-parallel — each tp shard
      owns a 4H/tp slice of every gate; the recurrence's h feeding back
      into wh re-gathers once per step (the scan's unavoidable tp
      collective).
    - encoder `Dense_0` (3136, 512) + bias: COLUMN-parallel (the largest
      single matmul in the model).
    - dueling `adv_hidden`/`val_hidden` (H, H) + biases: COLUMN-parallel,
      paired with `adv_out`/`val_out` (H, A)/(H, 1): ROW-parallel — the
      contraction over the sharded H axis psums, so each head pair costs
      one all-reduce and no intermediate gather.
    - conv kernels stay REPLICATED deliberately: the Nature/IMPALA stacks
      top out at 64/32 output channels — a tp=2 split leaves 16-32
      channel shards whose collective cost exceeds the FLOPs they save on
      the MXU. The convs' FLOPs share is also dominated by the batched
      seq dimension, which dp already covers.

    Scope: everywhere except multihost. On the plain-jit learner paths
    (host/device planes) XLA/GSPMD partitions the matmuls and inserts the
    tp collectives from these annotations alone (compile-level
    partitioning is pinned by tests/test_learner.py). The "sharded"
    shard_map paths are manual over dp only (axis_names={"dp"}), so
    inside each dp shard the SAME annotations partition the update body
    over the GSPMD-auto tp axis (dp×tp parity pinned by
    tests/test_sharded_replay.py / test_sharded_megastep.py). The
    multihost plane keeps params replicated per its P() in_specs.

    Adam's mu/nu mirror the param tree structure, so the same path rule
    shards them consistently (optimizer math is elementwise)."""

    COLUMN = {"wi", "wh", "adv_hidden", "val_hidden", "Dense_0"}
    ROW = {"adv_out", "val_out"}
    # bias of a column-parallel layer lives on the sharded output axis
    COLUMN_BIAS_OWNERS = {"core", "adv_hidden", "val_hidden", "Dense_0"}

    def spec_for(path, leaf):
        keys = {getattr(p, "key", getattr(p, "name", "")) for p in path}
        if leaf.ndim == 2:
            if keys & COLUMN:
                return P(None, "tp")
            if keys & ROW:
                return P("tp", None)
        if leaf.ndim == 1 and keys & {"b", "bias"} and keys & COLUMN_BIAS_OWNERS:
            return P("tp")
        return P()

    import jax.tree_util as jtu

    return jtu.tree_map_with_path(
        lambda p, l: NamedSharding(mesh, spec_for(p, l)), state
    )


def tp_probe_kernel(params):
    """The leaf to assert tp-sharding on, independent of recurrent core.

    With an LSTM core this is the gate kernel `core/wi` — the docstring
    above calls it the hard case (the scan's per-step h re-gather), so
    when it exists the checks keep probing it. The LRU core deliberately
    carries none of the Megatron-annotated names (models/lru.py), so
    there the probe falls back to the encoder's `Dense_0` kernel, which
    is COLUMN-parallel under every encoder and every core."""
    p = params["params"]
    core = p.get("core", {})
    if "wi" in core:
        return core["wi"]
    return p["enc"]["Dense_0"]["kernel"]
