#!/usr/bin/env bash
# CI entry for the static-analysis gate: run every rule family (AST lints,
# the interprocedural concurrency and determinism passes, and — unless
# SKIP_JAXPR=1 — the jaxpr entry-point gate) repo-wide and emit SARIF so
# the CI system can annotate findings inline on the diff. Exit status is
# the analyzer's: nonzero iff any unsuppressed finding remains, so this
# doubles as the blocking check. Usage:
#   runs/run_analyze_ci.sh [OUT.sarif]        # default: analysis.sarif
#   SKIP_JAXPR=1 runs/run_analyze_ci.sh ...   # AST-pass families only (fast)
set -u
cd "$(dirname "$0")/.."

out=${1:-analysis.sarif}
args=(--concurrency --determinism --format sarif)
if [ "${SKIP_JAXPR:-0}" != "1" ]; then
  args+=(--jaxpr)
fi

# keep tracing off any accelerator the CI runner may expose: the jaxpr
# gate only inspects program text, CPU avals are identical
JAX_PLATFORMS=cpu python -m r2d2_tpu.analysis "${args[@]}" > "$out"
rc=$?

# human-readable tail for the CI log (the SARIF is for the annotator)
python - "$out" <<'EOF'
import json, sys
with open(sys.argv[1], encoding="utf-8") as fh:
    doc = json.load(fh)
results = doc["runs"][0]["results"]
for r in results:
    loc = r["locations"][0]["physicalLocation"]
    print(f'{loc["artifactLocation"]["uri"]}:{loc["region"]["startLine"]} '
          f'[{r["level"]}] {r["ruleId"]}: {r["message"]["text"]}')
print(f'{len(results)} finding(s) -> {sys.argv[1]}')
EOF
exit $rc
