#!/bin/bash
# Round-5 chain D (rewritten before firing): make the 16x16 procmaze rung
# decisive (VERDICT r4 item 5) and land the multi-env sweep artifact
# (item 6).
#
# REWRITE NOTE: the original chain D warm-resumed from
# runs/procmaze16_warm/ckpt/step_60000, but the round-4 checkpoint dirs
# were cleaned between rounds and that checkpoint no longer exists —
# and `--resume` on an empty dir silently starts FRESH, which would have
# mislabeled a fresh run as warm-started. This version runs an honestly
# fresh 16x16 arm at 120k updates (2x the round-4 16x16 budget) with the
# exploration lever pulled: eps_alpha 7 -> 3 flattens the Ape-X ladder so
# the actor fleet spends most of its time at epsilon 0.05..0.4 instead of
# concentrating near the greedy floor. Verdict via runs/eval_stats.py:
# per-episode returns, stderr, z-score against an epsilon=1 null measured
# through the SAME device collector — "baseline + 3 sigma" becomes a
# number.
#
# The sweeps run FIRST (minutes, and the artifact is judged): one
# invocation per env family (obs geometries differ) under runs/sweep_r5/,
# converting sweep.py (BASELINE config 3's driver, unit-tested but never
# driven) into a driven tool.
cd /root/repo
while ! grep -q R5E_CHAIN_ALL_DONE runs/r5e_chain.log 2>/dev/null; do sleep 60; done

. runs/lib.sh

# Sweep sizing note (THIRD launch): the first attempt used
# learning_starts=20000 through the default 8-env host pool — ~35 min of
# warmup PER GAME over the tunneled device. The second attempt cut the
# warmup to 4096 but kept the HOST replay plane, so every K-update
# dispatch shipped ~40 MB/batch host->device through the tunnel: the
# learner crawled at ~0.4 updates/s with the host pegged at 100% iowait
# (observed mid-game-1, 2026-08-02), i.e. ~80 min/game — still
# unaffordable. The artifact's purpose is driving the sweep CLI for
# real (BASELINE config 3's driver), not a learning claim, so this
# launch puts each game on the framework's native data plane
# (collector=device + replay_plane=device: collection, replay, and the
# K-dispatch learner all stay in HBM; the tunnel carries scalars), with
# the 4096-transition warmup, K=16 update dispatches (the threaded
# trainer was dispatch-latency-bound at K=1 over the tunnel: ~3
# updates/s observed), and unthrottled learner pacing — and still
# exercises the full path (env factory -> threaded trainer ->
# checkpoints -> summary.jsonl). Partial earlier dirs removed.
rm -rf runs/sweep_r5
python -m r2d2_tpu.sweep --games catch memory_catch memory_catch:60 \
  --allow-any-env --preset atari --root runs/sweep_r5/catch_family \
  --steps 2000 --set learning_starts=4096 --set num_actors=64 \
  --set buffer_capacity=80000 \
  --set collector=device --set replay_plane=device \
  --set updates_per_dispatch=16 \
  --set samples_per_insert=100000 --set save_interval=1000
echo "=== SWEEP_CATCH EXIT: $? ==="
python -m r2d2_tpu.sweep --games procmaze_shaped procmaze_shaped:8 \
  --allow-any-env --preset procgen_impala --root runs/sweep_r5/procmaze \
  --steps 2000 --set learning_starts=4096 --set num_actors=64 \
  --set buffer_capacity=80000 \
  --set collector=device --set replay_plane=device \
  --set updates_per_dispatch=16 \
  --set samples_per_insert=100000 --set save_interval=1000
echo "=== SWEEP_PROCMAZE EXIT: $? ==="

mkdir -p runs/procmaze16_flat
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
  --mode fused --steps 120000 --updates-per-dispatch 16 \
  --set checkpoint_dir=runs/procmaze16_flat/ckpt \
  --set metrics_path=runs/procmaze16_flat/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=7500 \
  --set target_net_update_interval=500 --set forward_steps=20 \
  --set num_actors=16 --set eps_alpha=3.0
echo "=== PROCMAZE16_FLAT TRAIN EXIT: $? ==="
python runs/eval_stats.py --preset procgen_impala --env procmaze_shaped:16 \
  --ckpt runs/procmaze16_flat/ckpt --episodes 512 --null-episodes 2048 \
  --set forward_steps=20 --set num_actors=16 \
  --out runs/procmaze16_flat/eval_stats.jsonl
echo "=== PROCMAZE16_FLAT STATS EXIT: $? ==="

echo R5D_CHAIN_ALL_DONE
