"""Shared host-side replay control plane.

Both replay buffers — host data plane (replay_buffer.ReplayBuffer) and HBM
data plane (device_store.DeviceReplayBuffer) — run the SAME control logic:
sum-tree priorities, circular block pointer, eviction/size accounting,
clamped stratified sampling of sequence coordinates, and the stale-priority
pointer-window rejection of reference worker.py:290-307. It lives here once
so a fix to any of the subtle parts (wrap-around masking, zero-leaf clamp)
cannot diverge between the two data planes.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.sum_tree import SumTree


def shard_config(cfg: R2D2Config, dp: int) -> R2D2Config:
    """The per-shard (1/dp) view of a config, for dp-sharded replay planes:
    each shard's control plane sees its slice of capacity/batch and knows
    nothing of the mesh."""
    return cfg.replace(
        buffer_capacity=cfg.buffer_capacity // dp,
        learning_starts=max(cfg.learning_starts // dp, 1),
        batch_size=cfg.batch_size // dp,
        dp_size=1,
        tp_size=1,
        replay_plane="host",
        collector="host",  # collection is the PARENT plane's concern
        updates_per_dispatch=1,
        # the PARENT plane owns device-tree residency and the superstep;
        # each shard's control plane is plain host bookkeeping (its device
        # tree, when any, is attached by the parent)
        priority_plane="host",
        superstep_dispatches=1,
    )


class ReplayControlPlane:
    def __init__(self, cfg: R2D2Config, native: Optional[object] = None):
        self.cfg = cfg
        if native is None and cfg.use_native_replay:
            from r2d2_tpu._native import load_native

            native = load_native()  # None if the toolchain is unavailable
        self.native = native
        self.tree = SumTree(
            cfg.num_sequences, cfg.prio_exponent, cfg.is_exponent, native=native
        )
        self.block_ptr = 0
        # monotone count of ring-pointer advances (writes + retirement
        # jumps): lap detection for the staleness mask. The wrapped pointer
        # alone cannot distinguish "nothing happened" from "exactly one
        # full lap" (ptr == old_ptr either way) — after a lap EVERY slot
        # was overwritten and all in-flight priorities must be dropped.
        self.ptr_advances = 0
        self.size = 0
        self.env_steps = 0
        self.num_episodes = 0
        self.episode_reward_sum = 0.0
        # run-lifetime totals (never reset by pop_episode_stats)
        self.total_episodes = 0
        self.total_reward_sum = 0.0
        self.learning_sum = np.zeros(cfg.num_blocks, np.int64)
        self.occupied = np.zeros(cfg.num_blocks, bool)
        self.num_seq_store = np.zeros(cfg.num_blocks, np.int32)
        # Disk-tier mode only (TieredReplayBuffer allocates it, sized
        # host+disk blocks): per-slot last-mutation stamp in ptr_advances
        # clock units. The pointer-window staleness mask below assumes
        # slots are overwritten in ring order; priority-aware demotion
        # moves block contents between ARBITRARY slots, so in disk mode
        # every mutation (write, demote, retire) stamps its slot and
        # update_priorities compares stamps instead of windows. None on
        # every non-disk plane — the window mask and its exact byte
        # behavior are untouched.
        self.slot_stamp = None
        # priority_plane="device": an HBM float32 mirror of the tree
        # (replay/device_sum_tree.DeviceSumTree) attached by the owning
        # data plane. Every host-side tree write goes through _tree_write,
        # which keeps the mirror in sync. All mirror writes happen under
        # self.lock — the same lock the data plane holds while dispatching
        # a learner superstep and installing its output tree — so device
        # tree mutations enqueue in lock-acquisition order and the device
        # stream serializes them exactly like the host tree: ingestion
        # dispatched after a superstep lands ON TOP of its write-backs,
        # which is precisely the verdict the host pointer-window mask
        # reaches for slots overwritten during a round trip.
        self.dtree = None
        self.lock = threading.Lock()

    def attach_device_tree(self, dtree) -> None:
        self.dtree = dtree

    def _tree_write(self, idxes: np.ndarray, td_errors: np.ndarray) -> None:
        """The single funnel for host-initiated tree writes (ingestion,
        retirement, drained priorities). Caller holds the lock."""
        self.tree.update(idxes, td_errors)
        if self.dtree is not None:
            self.dtree.update(idxes, td_errors)

    def __len__(self) -> int:
        return self.size

    def can_sample(self) -> bool:
        return self.size >= self.cfg.learning_starts

    # --- accounting (call with self.lock held) ----------------------------

    # r2d2: guarded-by(lock)
    def _account_block_at(
        self, slot: int, num_sequences: int, learning_total: int,
        priorities: np.ndarray, episode_reward: Optional[float],
    ) -> None:
        """Tree + counter bookkeeping for a block at an explicit slot; does
        NOT move the ring pointer (the caller owns pointer protocol — either
        _account_add's advance-after or _reserve_advance's advance-before).
        Caller holds the lock."""
        S = self.cfg.seqs_per_block
        idxes = np.arange(slot * S, (slot + 1) * S, dtype=np.int64)
        self._tree_write(idxes, priorities)
        if self.occupied[slot]:
            self.size -= int(self.learning_sum[slot])
        self.learning_sum[slot] = learning_total
        self.occupied[slot] = True
        self.num_seq_store[slot] = num_sequences
        self.size += learning_total
        self.env_steps += learning_total
        if episode_reward is not None:
            self.episode_reward_sum += episode_reward
            self.num_episodes += 1
            self.total_episodes += 1
            self.total_reward_sum += episode_reward

    def _account_add(
        self, num_sequences: int, learning_total: int, priorities: np.ndarray,
        episode_reward: Optional[float],
    ) -> int:
        """Update tree + counters for a block landing at block_ptr; returns
        the slot index written. Caller holds the lock and writes the data
        plane for the same slot."""
        ptr = self.block_ptr
        self._account_block_at(
            ptr, num_sequences, learning_total, priorities, episode_reward
        )
        self.block_ptr = (ptr + 1) % self.cfg.num_blocks
        self.ptr_advances += 1
        if self.slot_stamp is not None:
            self.slot_stamp[ptr] = self.ptr_advances
        return ptr

    def _account_blocks(
        self,
        num_seq: np.ndarray,
        learning_totals: np.ndarray,
        priorities: np.ndarray,
        episode_rewards: np.ndarray,
        dones: np.ndarray,
    ) -> None:
        """Account a batch of blocks written at consecutive ring slots
        (shared by every batched-write path: the one place that knows a
        truncated chunk is not a finished episode). Caller holds the lock
        and has already written the data plane."""
        for i in range(len(num_seq)):
            self._account_add(
                int(num_seq[i]),
                int(learning_totals[i]),
                priorities[i],
                float(episode_rewards[i]) if dones[i] else None,
            )

    def _retire_slots(self, slots: np.ndarray) -> None:
        """Evict the blocks at `slots` from the tree and the size
        accounting (priorities zeroed: they can never be sampled again).
        Caller holds the lock."""
        occ = slots[self.occupied[slots]]
        if occ.size:
            S = self.cfg.seqs_per_block
            idxes = (occ[:, None] * S + np.arange(S)[None, :]).ravel()
            self._tree_write(idxes, np.zeros(idxes.size, np.float32))
            self.size -= int(self.learning_sum[occ].sum())
            self.learning_sum[occ] = 0
            self.occupied[occ] = False
            self.num_seq_store[occ] = 0
        if self.slot_stamp is not None and slots.size:
            # disk mode: retirement is a mutation like any other — bump
            # the clock once and stamp so in-flight priority write-backs
            # for these slots are rejected by the stamp comparison
            self.ptr_advances += 1
            self.slot_stamp[slots] = self.ptr_advances

    def _reserve_contiguous(self, n: int) -> int:
        """Wrap the ring pointer to 0 if fewer than n slots remain before
        the end, and return the pointer: the caller writes slots
        [ptr, ptr+n) as ONE contiguous slab (a dynamic_update_slice — a
        ring-crossing scatter is ~20x slower on TPU). The skipped tail
        slots are RETIRED: with a steady E-batch writer the pointer cycle
        repeats every lap, so the tail would otherwise hold frozen,
        never-evicted blocks — instead their priorities are zeroed and
        their transitions leave the size accounting, shrinking effective
        capacity to floor(num_blocks/n)*n for batch writers. The
        pointer-window staleness mask treats the whole tail as overwritten
        — over-rejection, never wrong. Caller holds the lock."""
        nb = self.cfg.num_blocks
        if self.block_ptr + n > nb:
            self._retire_slots(np.arange(self.block_ptr, nb))
            # the jump traverses the tail: it counts toward lap detection
            self.ptr_advances += nb - self.block_ptr
            self.block_ptr = 0
        return self.block_ptr

    def _reserve_advance(self, n: int) -> int:
        """Reserve n contiguous slots AND advance the ring pointer past
        them, retiring the slots' previous blocks immediately. For writers
        that defer the new blocks' accounting (FusedSystemRunner's
        one-dispatch-lag chunk readback): after this returns, (a) draws
        cannot target the reserved slots (leaves are zero), and (b) the
        pointer-window staleness mask already treats them as overwritten —
        so priority rows and the chunk's own accounting can land in any
        order later, via _account_blocks_at. Caller holds the lock."""
        ptr0 = self._reserve_contiguous(n)
        self._retire_slots(np.arange(ptr0, ptr0 + n))
        self.block_ptr = (ptr0 + n) % self.cfg.num_blocks
        self.ptr_advances += n
        return ptr0

    def _account_blocks_at(
        self,
        ptr0: int,
        num_seq: np.ndarray,
        learning_totals: np.ndarray,
        priorities: np.ndarray,
        episode_rewards: np.ndarray,
        dones: np.ndarray,
    ) -> None:
        """Deferred accounting for blocks written at slots [ptr0, ptr0+E)
        previously reserved via _reserve_advance (pointer already past
        them). Caller holds the lock; the data plane was written by the
        dispatch that the reservation preceded."""
        for i in range(len(num_seq)):
            self._account_block_at(
                ptr0 + i,
                int(num_seq[i]),
                int(learning_totals[i]),
                priorities[i],
                float(episode_rewards[i]) if dones[i] else None,
            )

    def _draw(self, rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Stratified draw of batch_size sequence coordinates (with the
        zero-leaf clamp reflected into the returned global idxes). Caller
        holds the lock. Returns (b, s, idxes, is_weights)."""
        S = self.cfg.seqs_per_block
        idxes, is_weights = self.tree.sample(self.cfg.batch_size, rng)
        b = idxes // S
        s = np.minimum(idxes % S, np.maximum(self.num_seq_store[b] - 1, 0))
        return b, s, b * S + s, is_weights

    # --- priorities -------------------------------------------------------

    def update_priorities(
        self,
        idxes: np.ndarray,
        td_errors: np.ndarray,
        old_ptr: int,
        old_advances: Optional[int] = None,
    ) -> None:
        """Apply learner priorities, discarding any index overwritten during
        the sample->train round trip (worker.py:290-307 invariant).

        old_advances: the draw-time ptr_advances stamp. When provided, a
        FULL ring lap between draw and apply (every slot overwritten, the
        wrapped pointer back at old_ptr — invisible to the window mask)
        rejects the whole batch. Callers without the stamp keep the
        window-mask-only behavior (the reference's own guarantee)."""
        S = self.cfg.seqs_per_block
        with self.lock:
            if self.slot_stamp is not None and old_advances is not None:
                # Disk mode: demotion moves blocks between arbitrary slots,
                # so ring-window reasoning is void. A per-slot stamp gives
                # the EXACT verdict: keep an index iff its slot has not
                # mutated since the draw. (The full-lap check below would
                # also misfire here — demotions bump ptr_advances without
                # overwriting every slot.)
                mask = self.slot_stamp[idxes // S] <= old_advances
                self._tree_write(idxes[mask], td_errors[mask])
                return
            if (
                old_advances is not None
                and self.ptr_advances - old_advances >= self.cfg.num_blocks
            ):
                return
            ptr = self.block_ptr
            if ptr > old_ptr:
                mask = (idxes < old_ptr * S) | (idxes >= ptr * S)
            elif ptr < old_ptr:
                mask = (idxes < old_ptr * S) & (idxes >= ptr * S)
            else:
                mask = np.ones_like(idxes, dtype=bool)
            self._tree_write(idxes[mask], td_errors[mask])

    def pop_episode_stats(self):
        with self.lock:
            n, r = self.num_episodes, self.episode_reward_sum
            self.num_episodes = 0
            self.episode_reward_sum = 0.0
        return n, r

    def episode_totals(self):
        """Run-lifetime (episodes, reward_sum) — unaffected by the
        pop-and-reset logging stream."""
        with self.lock:
            return self.total_episodes, self.total_reward_sum
