"""KeyDoor — a partially-observable keyed-door corridor (pure JAX).

The memory probe of the multi-task family (ROADMAP item 2): a key color is
rendered for only the first `cue_steps` observations of the episode; the
agent then walks a corridor and, at the door cell on the far end, must pick
the open-action matching the remembered color. The cue-to-door gap is the
whole corridor, so the recurrent carry — not the frame — has to transport
the color. This is the same stress as catch's memory variant (envs/catch.py
cue_steps) but with a DISCRETE recall decision at the end instead of a
continuous tracking one, which makes partial credit impossible: a policy
that forgets the color caps at 1/num_colors of the achievable return.

Same functional protocol as envs/catch.py (reset/step/render + NUM_ACTIONS),
so the host pool, vectorized actor, on-device collector, and evaluator all
compose unchanged. Action space: 0 NOOP, 1 left, 2 right, 3+c open-with-
color-c at the door (opens elsewhere are NOOPs — out-of-range actions from
a padded multi-task union action space degrade to NOOP, never crash).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

KEYDOOR_DEFAULTS = dict(length=6, num_colors=2, cue_steps=1)


def keydoor_params(name: str) -> dict:
    """Variant parameters encoded in an env name: 'keydoor[:L[:C[:CUE]]]'
    (corridor length, key colors, cue steps). Raises on non-keydoor names
    (gate on is_keydoor_name) and on degenerate values."""
    n = name.lower()
    base, _, suffix = n.partition(":")
    if base != "keydoor":
        raise ValueError(f"not a keydoor family env name: {name!r}")
    out = dict(KEYDOOR_DEFAULTS)
    if suffix:
        parts = suffix.split(":")
        if len(parts) > 3:
            raise ValueError(f"keydoor takes at most :L:C:CUE, got {name!r}")
        keys = ("length", "num_colors", "cue_steps")
        for k, v in zip(keys, parts):
            out[k] = int(v)
    if out["length"] < 2:
        raise ValueError(f"keydoor length must be >= 2, got {out['length']}")
    if out["num_colors"] < 2:
        raise ValueError(
            f"keydoor num_colors must be >= 2 (1 color has no memory "
            f"demand), got {out['num_colors']}"
        )
    if out["cue_steps"] < 1:
        raise ValueError(f"keydoor cue_steps must be >= 1, got {out['cue_steps']}")
    return out


def is_keydoor_name(name: str) -> bool:
    return name.lower().partition(":")[0] == "keydoor"


def build_keydoor_env(obs_shape, max_episode_steps: int, name: str) -> "KeyDoorEnv":
    """ONE factory for every 'keydoor[:L[:C[:CUE]]]' name (the same
    single-factory rule as envs/procmaze.build_procmaze_env). The episode
    horizon is 4*length + 4 (enough slack for an exploring policy to reach
    the door) capped by the config's episode budget."""
    p = keydoor_params(name)
    h, w, c = obs_shape
    horizon = min(max_episode_steps, 4 * p["length"] + 4)
    return KeyDoorEnv(height=h, width=w, horizon=horizon, **p)


class KeyDoorState(NamedTuple):
    pos: jnp.ndarray    # int32 corridor cell in [0, length)
    color: jnp.ndarray  # int32 key color in [0, num_colors)
    t: jnp.ndarray      # int32 step counter (drives the cue window)
    key: jnp.ndarray    # PRNG key (auto-reset contract, envs/functional.py)


class KeyDoorEnv:
    """Functional single-env core; every method is jit/vmap-safe."""

    # 0 NOOP, 1 left, 2 right, then one open-action per color
    NUM_ACTIONS = 3 + KEYDOOR_DEFAULTS["num_colors"]

    def __init__(
        self,
        height: int = 8,
        width: int = 8,
        length: int = 6,
        num_colors: int = 2,
        cue_steps: int = 1,
        horizon: int = 28,
    ):
        if length < 2 or num_colors < 2 or cue_steps < 1:
            raise ValueError(
                f"degenerate keydoor geometry: length={length}, "
                f"num_colors={num_colors}, cue_steps={cue_steps}"
            )
        if width < max(length, num_colors):
            raise ValueError(
                f"keydoor width {width} cannot render the corridor "
                f"(length {length}) and the cue row ({num_colors} colors)"
            )
        if height < 3:
            raise ValueError(f"keydoor needs height >= 3, got {height}")
        if horizon < length:
            raise ValueError(
                f"keydoor horizon {horizon} ends before the door "
                f"(corridor length {length}) is reachable: every episode "
                "would end reward-free"
            )
        self.h, self.w = height, width
        self.length = length
        self.colors = num_colors
        self.cue = cue_steps
        self.horizon = horizon
        # instance attr (not the class default) so the union action space
        # of a multi-color variant is visible to the adapters
        self.NUM_ACTIONS = 3 + num_colors

    def reset(self, key: jax.Array) -> KeyDoorState:
        key, kc = jax.random.split(key)
        color = jax.random.randint(kc, (), 0, self.colors)
        zero = jnp.zeros((), jnp.int32)
        return KeyDoorState(zero, color, zero, key)

    def render(self, s: KeyDoorState) -> jnp.ndarray:
        """(H, W, 1) uint8: row 0 flashes the key color (column = color
        index, only while t < cue_steps); row 1 is the agent's corridor
        position; the bottom row marks the door cell — a static landmark
        so 'where is the door' never needs memory, only 'which color'."""
        ys = jnp.arange(self.h)[:, None]
        xs = jnp.arange(self.w)[None, :]
        cue = (ys == 0) & (xs == s.color) & (s.t < self.cue)
        agent = (ys == 1) & (xs == s.pos)
        door = (ys == self.h - 1) & (xs == self.length - 1)
        frame = jnp.where(cue | agent | door, 255, 0).astype(jnp.uint8)
        return frame[:, :, None]

    def step(self, s: KeyDoorState, action: jnp.ndarray):
        """Returns (state', reward, done). Terminal on any open-action at
        the door (+1 iff the color matches) or at the horizon."""
        dx = jnp.where(action == 1, -1, jnp.where(action == 2, 1, 0))
        pos = jnp.clip(s.pos + dx, 0, self.length - 1)
        t = s.t + 1
        at_door = s.pos == self.length - 1
        opening = at_door & (action >= 3)
        matched = opening & (action - 3 == s.color)
        done = opening | (t >= self.horizon)
        reward = jnp.where(matched, 1.0, 0.0)
        return KeyDoorState(pos, s.color, t, s.key), reward, done
