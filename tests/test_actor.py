"""VectorizedActor tests: block production, terminal/truncation handling,
carry resets, obs-aliasing regression, param refresh."""

import jax
import numpy as np

from r2d2_tpu.actor import HostEnvPool, ParamStore, VectorizedActor
from r2d2_tpu.config import tiny_test
from r2d2_tpu.envs.fake import ScriptedEnv
from r2d2_tpu.learner import init_train_state
from r2d2_tpu.ops.epsilon import epsilon_ladder


def build_actor(cfg, episode_len=9, push=None, num_envs=2):
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    store = ParamStore(state.params)
    pool = HostEnvPool(
        [ScriptedEnv(obs_shape=cfg.obs_shape, action_dim=cfg.action_dim, episode_len=episode_len)
         for _ in range(num_envs)]
    )
    pushed = []
    actor = VectorizedActor(
        cfg, net, store, pool,
        epsilon_ladder(num_envs, cfg.base_eps, cfg.eps_alpha),
        push or (lambda b, p, r: pushed.append((b, p, r))),
        seed=0,
    )
    return actor, pushed, store, state


def test_terminal_blocks_produced():
    cfg = tiny_test()
    actor, pushed, _, _ = build_actor(cfg, episode_len=9)
    actor.run_steps(9)
    # both envs terminate at step 9 -> one terminal block each
    assert len(pushed) == 2
    for block, prios, ep_reward in pushed:
        assert ep_reward is not None  # terminal episodes report reward
        np.testing.assert_allclose(block.gamma[-1], 0.0)  # terminal encoding
        assert block.action.shape[0] == 9


def test_block_cut_bootstraps_next_step():
    cfg = tiny_test()  # block_length 16
    actor, pushed, _, _ = build_actor(cfg, episode_len=100)
    actor.run_steps(16)
    assert len(pushed) == 0  # cut is deferred to the next policy call
    actor.run_steps(1)
    assert len(pushed) == 2
    for block, prios, ep_reward in pushed:
        assert ep_reward is None  # episode still running
        assert block.gamma[-1] > 0.0  # bootstrapped, not terminal


def test_truncation_resets_carry_and_episode():
    cfg = tiny_test().replace(max_episode_steps=6)
    actor, pushed, _, _ = build_actor(cfg, episode_len=100)
    actor.run_steps(6)
    assert len(pushed) == 0
    actor.run_steps(1)  # truncation tick: finish(q) + fresh episode, NOOP absorbed
    assert len(pushed) == 2
    for block, prios, ep_reward in pushed:
        assert ep_reward is None
        assert block.gamma[-1] > 0.0  # truncation bootstraps
    # carry must be zeroed for the fresh episodes
    h, c = actor.carry
    np.testing.assert_allclose(np.asarray(h), 0.0)
    np.testing.assert_allclose(np.asarray(c), 0.0)
    assert (actor.episode_steps == 0).all()
    assert (actor.last_action == 0).all() and (actor.last_reward == 0).all()
    # the fresh accumulators were seeded (1 entry, no steps yet)
    assert all(len(acc.obs_buf) == 1 and acc.size == 0 for acc in actor.accs)


def test_obs_aliasing_regression():
    """The accumulator must snapshot observations: the actor mutates its
    obs buffer in place every step, and the episode-seed entry must keep
    the FIRST frame (pixel value 0 for ScriptedEnv), not the latest."""
    cfg = tiny_test()
    actor, pushed, _, _ = build_actor(cfg, episode_len=9)
    actor.run_steps(9)
    block, _, _ = pushed[0]
    # ScriptedEnv pixels encode the timestep: first stored obs must be t=0
    assert (block.obs[0] == 0).all()
    assert (block.obs[1] == 1).all()


def test_param_refresh_uses_published_version():
    cfg = tiny_test().replace(actor_update_interval=4)
    actor, pushed, store, state = build_actor(cfg, episode_len=100)
    assert actor.param_version == 0
    new_params = jax.tree.map(lambda x: x + 1.0, state.params)
    store.publish(new_params)
    actor.run_steps(2)  # 2 steps x 2 envs = 4 >= interval -> refresh
    assert actor.param_version == 1
