"""Deterministic fault-injection plane + shared retry/backoff helpers.

Long preemptible-TPU runs fail by interruption, not by bug: SIGTERM with a
grace window, a wedged host->device transfer, a checkpoint write into a
flaky shared fs. The framework's failure handling (supervision restarts,
replay snapshots, atomic checkpoints, preemption-safe resume) is only
trustworthy if those paths are EXERCISED — so every host-side subsystem
registers named fault sites via `fault_point("site")`, a hook that costs
one global read + one `is None` branch when no plane is installed, and a
test (or an operator, via the R2D2_FAULTS env var) installs a seeded
schedule that fires crashes, stalls, torn transfers, or a real delivered
SIGTERM at exact call counts.

Determinism contract: a FaultPlane fires as a pure function of
(seed, site, per-site call number) — never of wall clock or thread
interleaving on the SAME call sequence — so a chaos test that kills the
trainer at site X call N reproduces bit-for-bit, and a failure seen in CI
replays locally from the spec string alone.

The second half is the shared transient-I/O policy: `with_retries` wraps
the flaky boundaries (host<->device transfers, checkpoint I/O, the serve
checkpoint watcher) in bounded exponential backoff, and every retry is
counted per-site in `retry_stats()` so the Trainer/serve metrics streams
carry the flake rate instead of silently absorbing it.

Registered sites (KNOWN_SITES below):
- trainer.update      — top of every learner update (SIGTERM injection)
- actor.step          — top of every host collection step
- host_plane.h2d      — host replay batch lift to device (train.py)
- tiered.stage_h2d    — staged-chunk device_put (replay/tiered_store.py)
- checkpoint.save     — orbax write (utils/checkpoint.py)
- checkpoint.restore  — orbax read (utils/checkpoint.py)
- snapshot.write      — replay snapshot npz write (replay/snapshot.py)
- serve.reload        — serve-plane checkpoint hot-reload (serve/server.py)
- serve.replica_stall — top of every serve-loop iteration: a "stall:S"
                        action wedges ONE replica's serve loop for S
                        seconds, the straggler-replica drill
                        (serve/server.py)
- serve.replica_kill  — the scenario engine's chaos tick: an "error"
                        action at call N triggers a replica kill +
                        session migration at exactly the N-th scenario
                        event (serve/scenarios.py)
- serve.slow_client   — the scenario engine's slow-client dispatch: a
                        "stall:S" action adds straggler delay on top of
                        the scenario's own (serve/scenarios.py)
- reshard.gather      — elastic-resume slab regather (replay/reshard.py)
- reshard.scatter     — elastic-resume re-deal/scatter (replay/reshard.py)
- liveloop.tap        — top of every liveloop-tap iteration: served batch
                        records -> per-session accumulators; an "error"
                        exercises supervised restart with the bounded
                        record queue as the crash boundary
                        (liveloop/loop.py)
- liveloop.ingest     — top of every liveloop-ingest iteration AND the
                        retry site for the replay add itself: finished
                        Blocks -> replay plane (liveloop/loop.py,
                        liveloop/bridge.py)
- autoscale.evaluate  — top of every autoscaler evaluation tick: an
                        "error" exercises the supervised-restart drill on
                        the control loop itself (serve/autoscale.py)
- autoscale.scale_up  — fires at the exact decision to grow the fleet,
                        before add_replica runs: scheduled chaos fails a
                        scale-up mid-pressure (serve/autoscale.py)
- autoscale.scale_down — fires at the exact decision to drain a replica,
                        before the victim is chosen (serve/autoscale.py)
- transport.connect   — the block-stream publisher's connect+handshake to
                        the learner's ingest service; retried with
                        jittered backoff, the reconnect drill
                        (transport/publisher.py)
- transport.send      — one framed send on the publisher's socket: a
                        mid-stream "error" drops the connection and the
                        unacked spool tail is resent after the reconnect
                        handshake (transport/publisher.py)
- transport.recv      — one framed receive (ACK/CKPT/HEARTBEAT) on the
                        publisher's socket (transport/publisher.py)
- transport.spool     — the publisher's per-block spool write (the
                        at-least-once persistence point; on-disk when
                        transport_spool_dir is set)
                        (transport/publisher.py)
- ingest.accept       — the learner-side service's accept/handshake of
                        one host connection (transport/ingest.py)
- ingest.dedup        — the per-host sequence-number admission check on
                        every received BLOCK frame — the exactly-once
                        delivery seam (transport/ingest.py)
- disk.write          — one demoted block's segment-record write in the
                        replay disk tier (data-first: fires BEFORE the
                        mmap write, so a kill here leaves the control
                        plane untouched) (replay/disk_tier.py)
- disk.promote        — one disk-resident block's page-in + decode back
                        to host arrays (the staging-thread read path and
                        the snapshot/reshard promote path)
                        (replay/disk_tier.py)
- codec.decode        — one encoded field's decode (inflate + un-delta),
                        shared by disk page-in, spool load, and BLOCK
                        frame ingest (replay/codec.py)
"""

from __future__ import annotations

import os
import signal
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple, Type

# Every site wired into the codebase, for chaos suites that want to sweep
# "kill at every registered site". Adding a fault_point at a new boundary
# should add its name here — the analysis plane enforces it statically:
# the unknown-fault-site lint (r2d2_tpu/analysis/ast_rules.py) flags any
# fault_point("...") literal missing from this tuple, so a typo'd or
# unregistered site fails the tier-1 analysis gate instead of silently
# dropping out of sweeps.
KNOWN_SITES = (
    "trainer.update",
    "actor.step",
    "host_plane.h2d",
    "tiered.stage_h2d",
    "checkpoint.save",
    "checkpoint.restore",
    "snapshot.write",
    "serve.reload",
    "serve.client",
    "serve.replica_stall",
    "serve.replica_kill",
    "serve.slow_client",
    "reshard.gather",
    "reshard.scatter",
    "liveloop.tap",
    "liveloop.ingest",
    "autoscale.evaluate",
    "autoscale.scale_up",
    "autoscale.scale_down",
    "transport.connect",
    "transport.send",
    "transport.recv",
    "transport.spool",
    "ingest.accept",
    "ingest.dedup",
    "disk.write",
    "disk.promote",
    "codec.decode",
)


class InjectedFault(RuntimeError):
    """A fault_point fired an 'error' action. Classified as TRANSIENT by
    with_retries — the injected stand-in for a flaky transfer or fs — so
    retry-wrapped boundaries absorb it up to their attempt budget."""


class FaultPlane:
    """A seeded schedule of named fault sites.

    Two trigger forms, combinable:
    - `schedule={site: {n: action}}` — fire `action` on the site's n-th
      call (1-based, counted per site since install);
    - `rates={site: (p, action)}` — fire on calls where a crc32 hash of
      (seed, site, n) maps below p. Same seed => same firing calls, on
      any host, in any thread interleaving.

    Actions:
    - "error"       raise InjectedFault (transient-classified)
    - "sigterm"     os.kill(self, SIGTERM) — the preemption drill; the
                    call itself returns normally, exactly like a real
                    grace-window delivery mid-step
    - "stall:S"     sleep S seconds (heartbeat/watchdog drill)
    - "exit:C"      os._exit(C) — hard crash, no unwind

    `max_fires` bounds total firings (a rate-based plane in a long run
    should degrade to a no-op once it has made its point). Thread-safe;
    counters are per-site."""

    def __init__(
        self,
        schedule: Optional[Dict[str, Dict[int, str]]] = None,
        rates: Optional[Dict[str, Tuple[float, str]]] = None,
        seed: int = 0,
        max_fires: Optional[int] = None,
    ):
        self.schedule = {s: dict(m) for s, m in (schedule or {}).items()}
        self.rates = dict(rates or {})
        self.seed = seed
        self.max_fires = max_fires
        self.calls: Dict[str, int] = {}
        self.fired: List[Tuple[str, int, str]] = []  # (site, call_n, action)
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlane":
        """Parse the R2D2_FAULTS wire format: comma/semicolon-separated
        clauses of `site@N=action` (exact call) or `site%P=action` (seeded
        rate P in [0,1]), plus `seed=K` / `max_fires=K` settings. Example:

            R2D2_FAULTS="trainer.update@5=sigterm,tiered.stage_h2d%0.05=error,seed=7"
        """
        schedule: Dict[str, Dict[int, str]] = {}
        rates: Dict[str, Tuple[float, str]] = {}
        max_fires = None
        for clause in spec.replace(";", ",").split(","):
            clause = clause.strip()
            if not clause:
                continue
            key, _, action = clause.partition("=")
            if not action:
                raise ValueError(f"fault spec clause {clause!r} needs '=action'")
            key = key.strip()
            action = action.strip()
            if key == "seed":
                seed = int(action)
            elif key == "max_fires":
                max_fires = int(action)
            elif "@" in key:
                site, _, n = key.partition("@")
                schedule.setdefault(site, {})[int(n)] = action
            elif "%" in key:
                site, _, p = key.partition("%")
                rates[site] = (float(p), action)
            else:
                raise ValueError(
                    f"fault spec clause {clause!r}: expected site@N=action, "
                    "site%P=action, seed=K, or max_fires=K"
                )
        return cls(schedule=schedule, rates=rates, seed=seed, max_fires=max_fires)

    def _decide(self, site: str) -> Optional[Tuple[int, str]]:
        with self._lock:
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            if self.max_fires is not None and len(self.fired) >= self.max_fires:
                return None
            action = self.schedule.get(site, {}).get(n)
            if action is None and site in self.rates:
                p, rate_action = self.rates[site]
                h = zlib.crc32(f"{self.seed}:{site}:{n}".encode())
                if h / 2**32 < p:
                    action = rate_action
            if action is None:
                return None
            self.fired.append((site, n, action))
            return n, action

    def hit(self, site: str) -> None:
        decided = self._decide(site)
        if decided is None:
            return
        n, action = decided
        if action == "error":
            raise InjectedFault(f"injected fault at {site!r} (call {n})")
        if action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if action.startswith("stall:"):
            time.sleep(float(action[6:]))
            return
        if action.startswith("exit:"):
            os._exit(int(action[5:]))
        raise ValueError(f"unknown fault action {action!r} at {site!r}")


# the installed plane; None (the default) keeps fault_point at one global
# read + one branch — zero-cost in production hot loops
_PLANE: Optional[FaultPlane] = None


def fault_point(site: str) -> None:
    """Named fault site. No-op unless a FaultPlane is installed."""
    plane = _PLANE
    if plane is not None:
        plane.hit(site)


def install(plane: FaultPlane) -> FaultPlane:
    global _PLANE
    _PLANE = plane
    return plane


def uninstall() -> None:
    global _PLANE
    _PLANE = None


def active() -> Optional[FaultPlane]:
    return _PLANE


def install_from_env(var: str = "R2D2_FAULTS") -> Optional[FaultPlane]:
    """Entry-point hook (train.main and chaos subprocesses): install a
    plane from the env var's spec string, if set."""
    spec = os.environ.get(var)
    if not spec:
        return None
    return install(FaultPlane.from_spec(spec))


# ------------------------------------------------------------------ retries

# The transient class: injected faults plus the OS-level errors a flaky
# shared fs or interconnect surfaces. Deliberately NOT a bare Exception —
# a logic bug must never be silently retried into "success".
TRANSIENT_ERRORS: Tuple[Type[BaseException], ...] = (
    InjectedFault,
    OSError,
    ConnectionError,
)

_retry_lock = threading.Lock()
_retry_counts: Dict[str, int] = {}


def with_retries(
    fn: Callable,
    site: str,
    attempts: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = TRANSIENT_ERRORS,
    sleep: Callable[[float], None] = time.sleep,
    max_elapsed: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
):
    """Run `fn` with bounded exponential backoff on transient errors.

    Every retry increments the site's counter in retry_stats() — the
    Trainer and serve metrics merge these, so a flaky boundary shows up
    as a rate in the metrics stream instead of vanishing into latency.
    The final attempt's error propagates: retries bound tail latency,
    they do not convert persistent failures into hangs.

    `max_elapsed` (seconds) is a second, wall-clock budget on top of the
    attempt count: once `clock()` has advanced past it — attempt time
    included, not just backoff sleeps — the next failure propagates even
    with attempts remaining. Supervised worker bodies wrap transport I/O
    with max_elapsed below their heartbeat timeout so a wedged peer
    surfaces as a (restartable) crash, never as a stale heartbeat that
    escalates to a process-fatal stall."""
    delay = base_delay
    t0 = clock() if max_elapsed is not None else 0.0
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on:
            if attempt == attempts - 1:
                raise
            if max_elapsed is not None and clock() - t0 >= max_elapsed:
                raise
            with _retry_lock:
                _retry_counts[site] = _retry_counts.get(site, 0) + 1
            sleep(min(delay, max_delay))
            delay *= 2.0


def retry_stats() -> Dict[str, int]:
    """Per-site retry counts since process start (or the last reset)."""
    with _retry_lock:
        return dict(_retry_counts)


def total_retries() -> int:
    with _retry_lock:
        return sum(_retry_counts.values())


def reset_retry_stats() -> None:
    with _retry_lock:
        _retry_counts.clear()


class Backoff:
    """Tiny backoff state machine for poll loops (the serve checkpoint
    watcher): fail() escalates and returns the next delay, reset() on
    success. Keeps the loop's one-bounded-unit-of-work-per-call contract —
    the DELAY is returned, not slept, so callers wait on their own stop
    event and stay responsive to shutdown.

    `jitter` in (0, 1] de-synchronizes a fleet: after a replica kill,
    every client/watcher that failed on the same event would otherwise
    retry on the SAME escalation schedule and thundering-herd the
    survivors. Jitter pulls each delay down by up to `jitter` of its
    headroom above `base`, deterministically per (seed, failure number) —
    the same crc32 derivation the FaultPlane rates use — so every delay
    stays within [base, max_delay], a given seed reproduces its exact
    delay sequence, and different seeds spread. jitter=0 (default) keeps
    the exact legacy schedule."""

    def __init__(self, base: float = 0.1, factor: float = 2.0,
                 max_delay: float = 30.0, jitter: float = 0.0,
                 seed: int = 0):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.seed = seed
        self.failures = 0

    def fail(self) -> float:
        delay = min(self.base * (self.factor ** self.failures), self.max_delay)
        if self.jitter > 0.0:
            u = zlib.crc32(f"{self.seed}:{self.failures}".encode()) / 2**32
            delay -= self.jitter * u * (delay - self.base)
        self.failures += 1
        return delay

    def reset(self) -> None:
        self.failures = 0
