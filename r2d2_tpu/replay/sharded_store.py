"""dp-sharded device-resident replay: HBM capacity scales with the mesh.

The single-chip DeviceReplayBuffer (replay/device_store.py) caps replay at
one chip's HBM (~2M transitions of 84x84 obs fills 16 GB). This variant
shards every store's block axis over the mesh's dp axis, so a v4-8 holds
dp x that — the reference's full 2e6-transition capacity
(reference config.py:16) fits in HBM on a 4-way mesh with room to spare.

Design (mirrors the scaling-book recipe: pick a mesh, annotate shardings,
let collectives ride ICI):

- CONTROL PLANE: one host-side ReplayControlPlane PER SHARD (sum tree over
  that shard's sequence slots, its own circular pointer + staleness
  window). Blocks round-robin across shards, so every shard stays
  statistically identical to a 1/dp-sized uniform slice of the stream.
- DATA PLANE: one global jnp array per field with the block axis sharded
  NamedSharding(mesh, P("dp")). A block write is a donated
  dynamic_update_index_in_dim at the owning shard's global slot — XLA
  resolves it to a local update on the owning device.
- SAMPLING: each shard draws batch_size/dp sequences from its own tree;
  IS weights are renormalized across shards to the BATCH-global minimum
  priority, so weights match what a single global tree would produce for
  the same draws (min is over the sampled batch, replay/sum_tree.py).
- TRAINING: learner.make_sharded_fused_train_step runs under shard_map —
  each device gathers its sub-batch from its LOCAL shard (zero cross-device
  data-plane traffic) and gradients pmean over dp.

Priority round trip: update_priorities applies each shard's slice under
that shard's own pointer-window staleness mask (reference worker.py:290-307
invariant, per shard).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay.block import Block, store_field_specs
from r2d2_tpu.replay.control_plane import ReplayControlPlane, shard_config
from r2d2_tpu.replay.device_store import DeviceReplayBuffer


@dataclasses.dataclass
class ShardedSampleIdx:
    """Per-shard stacked sample coordinates (host side)."""

    b: np.ndarray           # (dp, B/dp) block slot LOCAL to each shard
    s: np.ndarray           # (dp, B/dp) sequence-in-block
    is_weights: np.ndarray  # (dp, B/dp) float32, batch-globally normalized
    idxes: np.ndarray       # (dp, B/dp) sequence slots LOCAL to each shard
    old_ptrs: List[int]     # per-shard block pointer at sample time
    env_steps: int


class ShardedDeviceReplay:
    def __init__(self, cfg: R2D2Config, mesh: Mesh):
        dp = mesh.shape["dp"]
        if cfg.num_blocks % dp != 0:
            raise ValueError(f"num_blocks {cfg.num_blocks} not divisible by dp {dp}")
        if cfg.batch_size % dp != 0:
            raise ValueError(f"batch_size {cfg.batch_size} not divisible by dp {dp}")
        self.cfg = cfg
        self.mesh = mesh
        self.dp = dp
        self.blocks_per_shard = cfg.num_blocks // dp
        # per-shard view: 1/dp of capacity and batch; the shard config is
        # single-plane (its own control plane knows nothing of the mesh)
        shard_cfg = shard_config(cfg, dp)
        self.shards = [ReplayControlPlane(shard_cfg) for _ in range(dp)]
        self._rr = 0  # round-robin write cursor over shards

        nb = cfg.num_blocks
        shd = NamedSharding(mesh, P("dp"))
        self.stores: Dict[str, jnp.ndarray] = {
            k: jnp.zeros((nb, *shape), dt, device=shd)
            for k, (shape, dt) in store_field_specs(cfg).items()
        }

        def _write(stores, ptr, vals):
            return {
                k: jax.lax.dynamic_update_index_in_dim(arr, vals[k], ptr, axis=0)
                for k, arr in stores.items()
            }

        self._write = jax.jit(
            _write,
            donate_argnums=(0,),
            out_shardings={k: shd for k in self.stores},
        )

        # batched scatter for the on-device collector: E global slots in
        # one donated dispatch (XLA reshards the collector's output onto
        # the owning shards)
        def _write_batch(stores, ptrs, vals):
            return {k: arr.at[ptrs].set(vals[k]) for k, arr in stores.items()}

        self._write_batch = jax.jit(
            _write_batch,
            donate_argnums=(0,),
            out_shardings={k: shd for k in self.stores},
        )
        self.lock = threading.Lock()

    # ---------------------------------------------------------------- state

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    @property
    def env_steps(self) -> int:
        return sum(s.env_steps for s in self.shards)

    def can_sample(self) -> bool:
        return (
            len(self) >= self.cfg.learning_starts
            and all(s.tree.total > 0 for s in self.shards)
        )

    def pop_episode_stats(self):
        n = r = 0
        for sh in self.shards:
            ni, ri = sh.pop_episode_stats()
            n += ni
            r += ri
        return n, r

    def episode_totals(self):
        n = r = 0
        for sh in self.shards:
            ni, ri = sh.episode_totals()
            n += ni
            r += ri
        return n, r

    # ------------------------------------------------------------------ add

    def add_block(
        self, block: Block, priorities: np.ndarray, episode_reward: Optional[float]
    ) -> None:
        cfg = self.cfg
        vals = DeviceReplayBuffer.pad_block_fields(cfg, block)
        with self.lock:
            shard_id = self._rr
            shard = self.shards[shard_id]
            with shard.lock:
                # write first, account last (see replay_buffer.add_block)
                global_ptr = shard_id * self.blocks_per_shard + shard.block_ptr
                self.stores = self._write(self.stores, global_ptr, vals)
                shard._account_add(
                    block.num_sequences,
                    int(block.learning_steps.sum()),
                    priorities,
                    episode_reward,
                )
            self._rr = (self._rr + 1) % self.dp

    def add_blocks_batch(
        self,
        fields: Dict[str, jnp.ndarray],
        num_seq: np.ndarray,
        learning_totals: np.ndarray,
        priorities: np.ndarray,
        episode_rewards: np.ndarray,
        dones: np.ndarray,
    ) -> None:
        """Write E collector-packed blocks round-robin across shards in one
        scatter (collect.DeviceCollector contract, mirroring
        DeviceReplayBuffer.add_blocks_batch). Fields stay on device end to
        end; only the per-block accounting scalars are host-side."""
        E = len(num_seq)
        bps = self.blocks_per_shard
        if E > self.dp * bps:
            raise ValueError(f"{E} blocks per batch exceeds {self.dp * bps} slots")
        with self.lock:
            shard_ids = [(self._rr + i) % self.dp for i in range(E)]
            # hold EVERY affected shard's lock across write + account
            # (ascending order; other paths only ever hold one at a time):
            # a sampler draw between the scatter and the accounting would
            # pair new slot data with the evicted blocks' tree state —
            # add_block's single-shard lock gives the same guarantee
            locks = [self.shards[sid].lock for sid in sorted(set(shard_ids))]
            for lk in locks:
                lk.acquire()
            try:
                # destination slots BEFORE accounting mutates the pointers
                # (write first, account last — same contract as add_block)
                sim = {sid: self.shards[sid].block_ptr for sid in set(shard_ids)}
                ptrs = np.empty(E, np.int64)
                for i, sid in enumerate(shard_ids):
                    ptrs[i] = sid * bps + sim[sid]
                    sim[sid] = (sim[sid] + 1) % bps
                self.stores = self._write_batch(
                    self.stores, jnp.asarray(ptrs, jnp.int32), fields
                )
                for i, sid in enumerate(shard_ids):
                    self.shards[sid]._account_add(
                        int(num_seq[i]),
                        int(learning_totals[i]),
                        priorities[i],
                        float(episode_rewards[i]) if dones[i] else None,
                    )
                self._rr = (self._rr + E) % self.dp
            finally:
                for lk in reversed(locks):
                    lk.release()

    # --------------------------------------------------------------- sample

    def sample_indices(self, rng: np.random.Generator) -> ShardedSampleIdx:
        """Each shard draws B/dp sequences; IS weights renormalized to the
        batch-global minimum priority so the sharded draw matches the
        single-tree semantics."""
        bs, ss, idxs, prios = [], [], [], []
        old_ptrs = []
        for shard in self.shards:
            with shard.lock:
                b, s, idxes, _w = shard._draw(rng)
                old_ptrs.append(shard.block_ptr)
                # read priorities under the SAME lock as the draw — an
                # interleaved add_block would rewrite these leaves and the
                # weights would no longer describe the drawn sample
                p = shard.tree.priorities_of(idxes)
            bs.append(b)
            ss.append(s)
            idxs.append(idxes)
            prios.append(p)
        p = np.stack(prios)  # (dp, B/dp) raw tree priorities
        positive = p[p > 0.0]
        min_p = positive.min() if positive.size else 1.0
        w = np.power(np.maximum(p, min_p) / min_p, -self.cfg.is_exponent)
        return ShardedSampleIdx(
            b=np.stack(bs).astype(np.int32),
            s=np.stack(ss).astype(np.int32),
            is_weights=w.astype(np.float32),
            idxes=np.stack(idxs),
            old_ptrs=old_ptrs,
            env_steps=self.env_steps,
        )

    # ------------------------------------------------------------ round trip

    def update_priorities(
        self, idxes: np.ndarray, td_errors: np.ndarray, old_ptrs: List[int]
    ) -> None:
        """idxes/td_errors: (dp, B/dp) as returned by sample/train."""
        for shard, idx_row, td_row, old_ptr in zip(
            self.shards, idxes, np.asarray(td_errors), old_ptrs
        ):
            shard.update_priorities(idx_row, td_row, old_ptr)

    # ------------------------------------------------------------- dispatch

    def run_with_stores(self, fn: Callable):
        """Dispatch fn(stores) under the buffer lock (same contract as
        DeviceReplayBuffer.run_with_stores: the donated write invalidates
        prior store references)."""
        with self.lock:
            return fn(self.stores)
