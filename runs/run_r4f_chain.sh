#!/bin/bash
# Round-4 chain F: the MFU measurement, after chain E drains.
# measure_mfu wedged twice when sharing the tunneled chip with another
# client; it runs here with the device to itself (progress prints added
# so any further wedge localizes).
cd /root/repo
while ! grep -q R4E_CHAIN_ALL_DONE runs/r4e_chain.log 2>/dev/null; do sleep 60; done

timeout 1200 python runs/measure_mfu.py --out runs/mfu.json
echo "=== MFU EXIT: $? ==="

echo R4F_CHAIN_ALL_DONE
