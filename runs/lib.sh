# Shared helpers for runs/run_*.sh chain scripts. Source from a chain:
#   . runs/lib.sh
# Historical chains (r3*/r4*/r5a-e) carry inlined copies from before this
# file existed; they are provenance artifacts and are not rewritten.

# Assert a checkpoint dir's replay-snapshot topology manifests before
# trusting --resume (the replay-side twin of run_r5h2_chain.sh's
# stale-ckpt guard): prints every manifest as json; fails on incoherent
# shard coverage, pre-manifest snapshot files, or an expectation
# mismatch. Usage: assert_snapshot_topology CKPT_DIR [DP [TP [NPROC]]]
assert_snapshot_topology() {
  local dir=$1 dp=$2 tp=$3 nproc=$4
  local args=("$dir")
  [ -n "$dp" ] && args+=(--expect-dp "$dp")
  [ -n "$tp" ] && args+=(--expect-tp "$tp")
  [ -n "$nproc" ] && args+=(--expect-process-count "$nproc")
  python -m r2d2_tpu.replay.reshard "${args[@]}"
}

# Retry a training command on the watchdog's stall exit code (86 =
# STALL_EXIT_CODE, r2d2_tpu/utils/supervision.py) by appending --resume,
# up to 3 resumes. Set RETRY_CKPT_DIR (plus optional RETRY_EXPECT, e.g.
# "1 1 1" for dp/tp/nproc) to assert the replay snapshots' topology
# manifests before every resume attempt — a stale snapshot from an
# earlier layout aborts the chain instead of being silently regathered.
run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    if [ -n "$RETRY_CKPT_DIR" ] && \
       ! assert_snapshot_topology "$RETRY_CKPT_DIR" $RETRY_EXPECT; then
      echo "=== ABORT resume: snapshot topology assert failed for $RETRY_CKPT_DIR ==="
      return 2
    fi
    "$@" --resume; rc=$?
  done
  return $rc
}

# Print the final mean_reward from an eval.jsonl, or -9 when the file is
# missing/empty (a crashed run never writes eval.jsonl — the sentinel makes
# the chain's >= threshold gates read a crash as a clean negative instead
# of feeding float('') a blank).
last_eval() { python - "$1" <<'PY'
import json, os, sys
path = sys.argv[1]
rows = []
if os.path.exists(path):
    rows = [json.loads(l) for l in open(path) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}
