"""CLI for the analysis plane.

    python -m r2d2_tpu.analysis [--format text|json|sarif] [--changed-only]
                                [--jaxpr] [--concurrency] [--determinism]
                                [paths...]

Default paths: the installed r2d2_tpu package tree. Exit status 1 when any
unsuppressed finding remains (suppressed ones are counted in text mode but
never gate). `--changed-only` narrows to files reported by
`git diff --name-only HEAD` plus untracked .py files — the fast local
loop. `--jaxpr` additionally traces the canonical entry points at both
precisions (slower: pulls in jax and the model stack); combined with
`--changed-only` the jaxpr results are served from a cache keyed on a
hash of the traced entry-point sources, so unchanged traces cost nothing.
`--concurrency` runs the interprocedural thread/lock pass (concurrency.py)
over the same paths. `--determinism` runs the resume-completeness /
nondeterminism-taint / chaos-coverage pass (determinism.py) — like the
concurrency pass it is interprocedural, so it always scans the full
requested tree. `--format sarif` emits SARIF 2.1.0 for CI annotation
(runs/run_analyze_ci.sh); rule indices are stable because the driver's
rule table is the sorted set of rule ids present.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List

from r2d2_tpu.analysis import ast_rules
from r2d2_tpu.analysis.findings import render_json, render_sarif, render_text

# --changed-only --jaxpr result cache, relative to the repo root (see
# scan_entry_points_cached); untracked, cheap to delete
_JAXPR_CACHE = ".r2d2_jaxpr_cache.json"


def _changed_files(repo_root: str) -> List[str]:
    """Tracked-modified plus untracked .py files, absolute paths."""
    out: List[str] = []
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, cwd=repo_root, capture_output=True, text=True, check=True
            )
        except (OSError, subprocess.CalledProcessError):
            continue
        out.extend(
            os.path.join(repo_root, line)
            for line in res.stdout.splitlines()
            if line.endswith(".py")
        )
    return sorted(dict.fromkeys(out))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="r2d2-analyze",
        description="JAX-aware static analysis: dtype/recompile/host-sync/"
        "donation/fault-site lints, jaxpr gates, and the interprocedural "
        "concurrency and determinism passes",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the r2d2_tpu package)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text"
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="lint only git-changed/untracked .py files (fast local loop)",
    )
    parser.add_argument(
        "--determinism", action="store_true",
        help="also run the interprocedural determinism pass: resume-"
        "completeness of carry/restore state, wall-clock/unsorted-scan/"
        "unseeded-RNG taint into deterministic sinks, and chaos-site "
        "coverage",
    )
    parser.add_argument(
        "--jaxpr", action="store_true",
        help="also trace the canonical train/act/serve entry points at both "
        "precisions and run the jaxpr checkers (slow: imports jax; cached "
        "under --changed-only)",
    )
    parser.add_argument(
        "--concurrency", action="store_true",
        help="also run the interprocedural concurrency pass: thread-root "
        "inventory, lock-order cycles, cross-thread write guards, and "
        "blocking-under-lock",
    )
    args = parser.parse_args(argv)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    if args.changed_only:
        paths = _changed_files(repo_root)
    elif args.paths:
        paths = args.paths
    else:
        paths = [pkg_root]

    findings, suppressed = ast_rules.analyze_paths(paths)
    if args.concurrency:
        # the pass is interprocedural: a changed file's hazards can live in
        # its callers, so it always runs over the full requested tree (the
        # default package root under --changed-only)
        from r2d2_tpu.analysis import concurrency

        conc_paths = args.paths if args.paths else [pkg_root]
        cf, cs = concurrency.analyze_paths(conc_paths)
        findings = findings + cf
        suppressed = suppressed + cs
    if args.determinism:
        # interprocedural like the concurrency pass: a missing carry field
        # or a tainted helper shows up at its callers, so the pass always
        # covers the full requested tree
        from r2d2_tpu.analysis import determinism

        det_paths = args.paths if args.paths else [pkg_root]
        df, ds = determinism.analyze_paths(det_paths)
        findings = findings + df
        suppressed = suppressed + ds
    if args.jaxpr:
        from r2d2_tpu.analysis import jaxpr_rules

        if args.changed_only:
            cache_path = os.path.join(repo_root, _JAXPR_CACHE)
            findings = findings + jaxpr_rules.scan_entry_points_cached(cache_path)
        else:
            findings = findings + jaxpr_rules.scan_entry_points()

    if args.format == "json":
        print(render_json(findings))
    elif args.format == "sarif":
        print(render_sarif(findings))
    else:
        print(render_text(findings))
        if suppressed:
            print(f"({len(suppressed)} suppressed)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
