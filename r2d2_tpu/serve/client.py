"""Clients for the serving plane.

Two transports over the same PolicyServer:

- `LocalClient` — in-process blocking wrapper over `PolicyServer.submit`;
  what tests, bench.py's load generator, and embedded callers use. One
  client instance is safe to share across session threads (the batcher
  queue is the synchronization point).
- `serve_tcp` + `PolicyClient` — a stdlib JSON-lines TCP frontend for
  out-of-process callers (`python -m r2d2_tpu.serve`). One request per
  line: ``{"session": id, "obs": [...], "reward": r, "reset": bool}`` ->
  ``{"action": a, "ckpt_step": s, "params_version": v}`` (add
  ``"want_q": true`` for the full Q row; ``{"session": id, "cmd":
  "evict"}`` frees the session's cache slot on disconnect).

The wire format is deliberately boring — the serving plane's substance is
the batcher/cache/hot-reload machinery behind it, and the bit-parity tests
run through LocalClient where numbers survive untouched.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from r2d2_tpu.serve.batcher import QueueFullError
from r2d2_tpu.serve.server import ServeResult
from r2d2_tpu.utils.faults import (
    TRANSIENT_ERRORS,
    Backoff,
    fault_point,
    with_retries,
)


class LocalClient:
    """Works against a PolicyServer or a MultiDeviceServer — both expose
    the same submit/reset_session/evict surface."""

    def __init__(self, server, timeout: float = 30.0):
        self.server = server
        self.timeout = timeout

    def act(self, session_id: str, obs, reward: float = 0.0,
            reset: bool = False, epsilon: Optional[float] = None,
            task: int = 0) -> ServeResult:
        """Submit one request and block for its result. Raises what the
        server failed the future with (QueueFullError on overload,
        RuntimeError on a crashed iteration). `epsilon` overrides the
        session's exploration for THIS request (None = server default);
        `task` is the session's task id under multi-task serving."""
        fut = self.server.submit(
            session_id, obs, reward=reward, reset=reset, epsilon=epsilon,
            task=task,
        )
        return fut.result(timeout=self.timeout)

    def reset(self, session_id: str) -> None:
        self.server.reset_session(session_id)

    def evict(self, session_id: str) -> None:
        self.server.evict(session_id)


class _RequestHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server = self.server.policy_server  # type: ignore[attr-defined]
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                if req.get("cmd") == "evict":
                    server.evict(str(req["session"]))
                    resp = {"ok": True}
                else:
                    # host-side JSON decode, no device values in sight
                    obs = np.asarray(req["obs"], np.uint8)  # r2d2: disable=blocking-host-sync-in-serve-step
                    eps = req.get("epsilon")
                    # epsilon only when the request carries one: requests
                    # without the field make the exact pre-override call,
                    # so servers exposing the old submit surface still work
                    kwargs = {} if eps is None else {"epsilon": float(eps)}  # r2d2: disable=blocking-host-sync-in-serve-step
                    fut = server.submit(
                        str(req["session"]), obs,
                        reward=float(req.get("reward", 0.0)),  # r2d2: disable=blocking-host-sync-in-serve-step
                        reset=bool(req.get("reset", False)),  # r2d2: disable=blocking-host-sync-in-serve-step
                        **kwargs,
                    )
                    result = fut.result(timeout=30.0)
                    resp = {
                        "action": result.action,
                        "ckpt_step": result.ckpt_step,
                        "params_version": result.params_version,
                    }
                    if req.get("want_q"):
                        # result.q is already host numpy (server reads it back)
                        resp["q"] = np.asarray(result.q).tolist()  # r2d2: disable=blocking-host-sync-in-serve-step
            except Exception as e:  # answer in-band; keep the stream alive
                resp = {"error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve_tcp(server, host: str = "127.0.0.1",
              port: int = 0) -> Tuple[_TCPServer, threading.Thread]:
    """Start the JSON-lines frontend on (host, port); port 0 picks a free
    one (read it back from ``tcp.server_address``). Returns the live
    socketserver and its acceptor thread; call ``tcp.shutdown()`` then
    ``tcp.server_close()`` to stop."""
    tcp = _TCPServer((host, port), _RequestHandler)
    tcp.policy_server = server  # type: ignore[attr-defined]
    thread = threading.Thread(target=tcp.serve_forever, name="serve-tcp", daemon=True)
    thread.start()
    return tcp, thread


class PolicyClient:
    """Blocking JSON-lines TCP client; one socket, one session stream at a
    time per instance (open one client per concurrent session).

    Transient trouble is retried in the client, not surfaced: socket-level
    errors (reset/refused/closed connections — reconnected between
    attempts) go through the shared `utils/faults.with_retries` backoff
    policy under the `serve.client` fault site, so each retry shows up in
    `retry_stats()` like every other retried boundary. Overload is a
    SEPARATE budget: a full serve queue (`QueueFullError` answered
    in-band) retries up to `queue_retries` times with SEEDED JITTERED
    backoff — a fleet of clients rejected by the same overloaded (or
    freshly killed) replica spreads its retries instead of
    thundering-herding the survivors — then gives up and raises. The
    final error of either budget propagates — retries bound tail latency,
    they do not hide a down or drowning server. `retries=1` /
    `queue_retries=1` restore fail-fast behavior.

    Every give-up is classified in `error_counts` (`rejected` — queue
    budget exhausted; `timeout` — the socket deadline; `transport` —
    every other connection/server failure) so bench rows report WHY
    requests failed, not one lumped count."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 30.0, retries: int = 3,
                 queue_retries: int = 3, seed: int = 0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retries = max(int(retries), 1)
        self.queue_retries = max(int(queue_retries), 1)
        self.seed = seed
        self.error_counts: Dict[str, int] = {
            "rejected": 0, "timeout": 0, "transport": 0,
        }
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self._rfile = self._sock.makefile("rb")

    def _disconnect(self) -> None:
        try:
            if self._rfile is not None:
                self._rfile.close()
        except OSError:
            pass
        finally:
            self._rfile = None
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        finally:
            self._sock = None

    def _attempt(self, payload: dict) -> dict:
        fault_point("serve.client")
        if self._sock is None:
            self._connect()
        try:
            self._sock.sendall((json.dumps(payload) + "\n").encode())
            line = self._rfile.readline()
        except OSError:
            # dead socket: drop it so the next attempt reconnects
            self._disconnect()
            raise
        if not line:
            self._disconnect()
            raise ConnectionError("server closed the connection")
        resp = json.loads(line)
        err = resp.get("error")
        if err is not None:
            # errors travel in-band; re-raise overload as the typed error
            # so the retry policy can tell it from a permanent failure
            if err.startswith("QueueFullError"):
                raise QueueFullError(err)
            raise RuntimeError(err)
        return resp

    def _round_trip(self, payload: dict) -> dict:
        # two nested budgets: the INNER with_retries absorbs transport
        # transients (counted per-site in retry_stats); the OUTER loop is
        # the overload budget — QueueFullError means the server is ALIVE
        # and shedding, so wait a jittered backoff and re-offer, at most
        # queue_retries times. Jitter is seeded per client: a rejected
        # fleet de-synchronizes instead of re-offering in lockstep.
        backoff = Backoff(base=0.01, factor=2.0, max_delay=0.5,
                          jitter=0.5, seed=self.seed)
        for attempt in range(self.queue_retries):
            try:
                return with_retries(
                    lambda: self._attempt(payload),
                    "serve.client",
                    attempts=self.retries,
                    retry_on=TRANSIENT_ERRORS,
                )
            except QueueFullError:
                if attempt == self.queue_retries - 1:
                    self.error_counts["rejected"] += 1
                    raise
                time.sleep(backoff.fail())
            except socket.timeout:
                self.error_counts["timeout"] += 1
                raise
            except TRANSIENT_ERRORS:
                self.error_counts["transport"] += 1
                raise
            except RuntimeError:
                # in-band server-side failure (non-overload)
                self.error_counts["transport"] += 1
                raise

    def act(self, session_id: str, obs, reward: float = 0.0,
            reset: bool = False, want_q: bool = False,
            epsilon: Optional[float] = None) -> dict:
        payload = {
            "session": session_id,
            "obs": np.asarray(obs).tolist(),
            "reward": float(reward),
            "reset": bool(reset),
        }
        if want_q:
            payload["want_q"] = True
        if epsilon is not None:
            payload["epsilon"] = float(epsilon)
        return self._round_trip(payload)

    def evict(self, session_id: str) -> None:
        self._round_trip({"session": session_id, "cmd": "evict"})

    def close(self) -> None:
        self._disconnect()

    def __enter__(self) -> "PolicyClient":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
