"""Learner tests: target math vs an independent numpy recomputation, learning
on a fixed batch, in-jit target sync, and single-vs-8-device dp equivalence
(the SURVEY.md section 4 'distributed-without-a-cluster' strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.learner import DeviceBatch, init_train_state, make_train_step
from r2d2_tpu.ops.priority import mixed_td_priorities_np
from r2d2_tpu.ops.value_rescale import inverse_value_rescale_np, value_rescale_np
from r2d2_tpu.parallel.mesh import make_mesh, shard_batch


@pytest.fixture(scope="module")
def cfg():
    return tiny_test()


@pytest.fixture(scope="module")
def setup(cfg):
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    return net, state


def random_batch(cfg, seed=0):
    rng = np.random.default_rng(seed)
    B, T, L = cfg.batch_size, cfg.seq_len, cfg.learning_steps
    learn = np.full(B, L, np.int32)
    learn[-1] = L - 1  # one ragged row
    fwd = np.full(B, cfg.forward_steps, np.int32)
    fwd[-1] = 1
    return DeviceBatch(
        obs=jnp.asarray(rng.integers(0, 255, size=(B, T, *cfg.obs_shape), dtype=np.uint8)),
        last_action=jnp.asarray(rng.integers(0, cfg.action_dim, size=(B, T)), jnp.int32),
        last_reward=jnp.asarray(rng.normal(size=(B, T)).astype(np.float32)),
        hidden=jnp.asarray(rng.normal(size=(B, 2, cfg.hidden_dim)).astype(np.float32)),
        action=jnp.asarray(rng.integers(0, cfg.action_dim, size=(B, L)), jnp.int32),
        n_step_reward=jnp.asarray(rng.normal(size=(B, L)).astype(np.float32)),
        gamma=jnp.asarray(np.full((B, L), cfg.gamma**cfg.forward_steps, np.float32)),
        burn_in_steps=jnp.asarray(np.full(B, cfg.burn_in_steps, np.int32)),
        learning_steps=jnp.asarray(learn),
        forward_steps=jnp.asarray(fwd),
        is_weights=jnp.asarray(rng.uniform(0.3, 1.0, size=B).astype(np.float32)),
    )


def test_step_runs_and_metrics_finite(cfg, setup):
    net, state = setup
    step = make_train_step(cfg, net, donate=False)
    batch = random_batch(cfg)
    new_state, metrics, priorities = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert priorities.shape == (cfg.batch_size,)
    assert np.isfinite(np.asarray(priorities)).all()
    assert int(new_state.step) == 1


def test_target_math_matches_numpy(cfg, setup):
    """Recompute y, loss, priorities in numpy from the net's own Q outputs
    and compare to the jitted step's metrics (SURVEY.md section 2.6 target
    invariant)."""
    net, state = setup
    batch = random_batch(cfg, seed=1)

    q_learn, q_boot_online, mask = net.apply(
        state.params, batch.obs, batch.last_action, batch.last_reward, batch.hidden,
        batch.burn_in_steps, batch.learning_steps, batch.forward_steps,
    )
    _, q_boot_target, _ = net.apply(
        state.target_params, batch.obs, batch.last_action, batch.last_reward, batch.hidden,
        batch.burn_in_steps, batch.learning_steps, batch.forward_steps,
    )
    q_learn, q_boot_online, q_boot_target, mask = map(
        np.asarray, (q_learn, q_boot_online, q_boot_target, mask)
    )
    a_star = q_boot_online.argmax(-1)
    q_tgt = np.take_along_axis(q_boot_target, a_star[..., None], -1)[..., 0]
    y = value_rescale_np(
        np.asarray(batch.n_step_reward) + np.asarray(batch.gamma) * inverse_value_rescale_np(q_tgt)
    )
    q_taken = np.take_along_axis(q_learn, np.asarray(batch.action)[..., None], -1)[..., 0]
    td = y - q_taken
    w = np.asarray(batch.is_weights)[:, None]
    want_loss = (w * td**2 * mask).sum() / mask.sum()
    want_prios = mixed_td_priorities_np(np.abs(td) * mask, mask, cfg.td_mix_eta)

    step = make_train_step(cfg, net, donate=False)
    _, metrics, priorities = step(state, batch)
    np.testing.assert_allclose(float(metrics["loss"]), want_loss, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(priorities), want_prios, rtol=1e-3, atol=1e-5)


def test_loss_decreases_on_fixed_batch(cfg):
    fast_cfg = cfg.replace(lr=5e-3)
    net, state = init_train_state(fast_cfg, jax.random.PRNGKey(1))
    step = make_train_step(fast_cfg, net, donate=False)
    batch = random_batch(fast_cfg, seed=2)
    losses = []
    for _ in range(30):
        state, metrics, _ = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses


def test_target_sync_inside_jit(cfg):
    net, state = init_train_state(cfg, jax.random.PRNGKey(2))
    step = make_train_step(cfg, net, donate=False)
    batch = random_batch(cfg, seed=3)
    interval = cfg.target_net_update_interval
    for i in range(interval):
        state, _, _ = step(state, batch)
        online = jax.tree.leaves(state.params)[0]
        target = jax.tree.leaves(state.target_params)[0]
        if i + 1 < interval:
            assert not np.allclose(np.asarray(online), np.asarray(target))
    # at step == interval the target must have snapped to the online params
    chex_equal = jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state.params, state.target_params,
    )
    del chex_equal


def test_dp8_equivalence(cfg):
    """Sharding the batch over an 8-device dp mesh must produce the same
    update as single-device (XLA psum == serial sum)."""
    assert len(jax.devices()) == 8, "conftest must force 8 cpu devices"
    net, state = init_train_state(cfg, jax.random.PRNGKey(3))
    step = make_train_step(cfg, net, donate=False)
    batch = random_batch(cfg, seed=4)

    single_state, single_metrics, single_prios = step(state, batch)

    mesh = make_mesh(dp=8, tp=1)
    sharded = DeviceBatch(*shard_batch(mesh, tuple(batch)))
    rep_state = jax.device_put(state, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    multi_state, multi_metrics, multi_prios = step(rep_state, sharded)

    np.testing.assert_allclose(
        float(single_metrics["loss"]), float(multi_metrics["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(np.asarray(single_prios), np.asarray(multi_prios), rtol=1e-4, atol=1e-6)
    a = jax.tree.leaves(single_state.params)
    b = jax.tree.leaves(multi_state.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-6)


def test_tensor_parallel_matches_single_device():
    """dp=2 x tp=2 with LSTM kernels sharded over tp must reproduce the
    single-device update exactly (GSPMD inserts the tp collectives from
    the param sharding annotations alone)."""
    from r2d2_tpu.parallel.mesh import shard_batch, train_state_shardings

    cfg = tiny_test().replace(lstm_backend="scan")
    net, state0 = init_train_state(cfg, jax.random.PRNGKey(0))
    batch = random_batch(cfg)  # includes a ragged row
    step = make_train_step(cfg, net, donate=False)

    ref_state, ref_m, ref_p = step(state0, batch)
    ref_state, ref_m, ref_p = step(ref_state, batch)

    mesh = make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    tp_state = jax.device_put(state0, train_state_shardings(state0, mesh))
    tp_batch = type(batch)(*shard_batch(mesh, tuple(batch)))
    # confirm the wide kernels really are tp-sharded
    wi = tp_state.params["params"]["core"]["wi"]
    assert len({sh.device for sh in wi.addressable_shards}) == 4
    tp_s, tp_m, tp_p = step(tp_state, tp_batch)
    tp_s, tp_m, tp_p = step(tp_s, tp_batch)

    np.testing.assert_allclose(float(tp_m["loss"]), float(ref_m["loss"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(tp_p), np.asarray(ref_p), atol=1e-5)
    for a, b in zip(jax.tree.leaves(tp_s.params), jax.tree.leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # compile-level partition check: GSPMD kept every annotated kernel
    # SHARDED through the whole update (a silently-gathered weight would
    # come back replicated) — column pairs on the output axis, row pairs
    # on the contraction axis, column biases on their output axis
    from jax.sharding import PartitionSpec as P

    p = tp_s.params["params"]
    assert p["core"]["wi"].sharding.spec == P(None, "tp")
    assert p["core"]["wh"].sharding.spec == P(None, "tp")
    assert p["core"]["b"].sharding.spec == P("tp")
    assert p["adv_hidden"]["kernel"].sharding.spec == P(None, "tp")
    assert p["val_hidden"]["kernel"].sharding.spec == P(None, "tp")
    assert p["adv_out"]["kernel"].sharding.spec in (P("tp"), P("tp", None))
    assert p["val_out"]["kernel"].sharding.spec in (P("tp"), P("tp", None))
    assert p["enc"]["Dense_0"]["kernel"].sharding.spec == P(None, "tp")
    assert p["enc"]["Dense_0"]["bias"].sharding.spec == P("tp")
    # each tp shard holds HALF the annotated kernels' bytes (true
    # partitioning, not replication with a sharded-looking spec)
    for kern in (p["adv_hidden"]["kernel"], p["adv_out"]["kernel"]):
        shard_elems = {s.data.size for s in kern.addressable_shards}
        assert shard_elems == {kern.size // 2}


def test_zero_state_replay_ablation_matches_manual_zeroing(cfg):
    """cfg.zero_state_replay must equal running the normal step on a batch
    whose stored hidden was zeroed by hand — one flag, same math."""
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    b = random_batch(cfg, seed=13)
    zeroed = b._replace(hidden=jnp.zeros_like(b.hidden))

    cfg_abl = cfg.replace(zero_state_replay=True)
    net_a, state_a = init_train_state(cfg_abl, jax.random.PRNGKey(0))
    s1, m1, p1 = make_train_step(cfg_abl, net_a, donate=False)(state_a, b)
    s2, m2, p2 = make_train_step(cfg, net, donate=False)(state, zeroed)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(m1["loss"]), np.asarray(m2["loss"]))
    # and it differs from the stored-state step (the flag is load-bearing)
    _, m3, _ = make_train_step(cfg, net, donate=False)(state, b)
    assert float(m3["loss"]) != float(m1["loss"])


def test_cosine_lr_schedule_decays_updates():
    """lr_schedule='cosine': the SAME gradient produces a much smaller
    param step near training_steps than at step 0 (lr_final_frac=0 floors
    at zero), while the default constant schedule does not; the schedule
    position rides the checkpointed opt_state count."""
    import pytest

    from r2d2_tpu.config import tiny_test

    base = tiny_test().replace(training_steps=10, lr_final_frac=0.0)
    batch = random_batch(base, seed=3)

    def step_sizes(cfg):
        net, state = init_train_state(cfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, net, donate=False)
        sizes = []
        for _ in range(10):
            prev = state.params
            state, _, _ = step(state, batch)
            sizes.append(
                float(
                    sum(
                        np.abs(np.asarray(a) - np.asarray(b)).sum()
                        for a, b in zip(
                            jax.tree.leaves(state.params), jax.tree.leaves(prev)
                        )
                    )
                )
            )
        return sizes

    cos = step_sizes(base.replace(lr_schedule="cosine"))
    const = step_sizes(base)
    # cosine: final step ~cos^2(pi/2 * 9.5/10) of the first; constant: flat
    assert cos[-1] < 0.05 * cos[0], (cos[0], cos[-1])
    assert const[-1] > 0.3 * const[0], (const[0], const[-1])

    with pytest.raises(ValueError, match="lr_schedule"):
        tiny_test().replace(lr_schedule="warmup")
