"""Ape-X epsilon ladder.

epsilon_i = base ** (1 + i / (N - 1) * alpha)  for actor i in [0, N)
(invariant from reference train.py:15-26). For N=8, base=0.4, alpha=7 this
yields [0.4, 0.16, 0.064, 0.0256, 0.01024, 0.0041, 0.00164, 0.00066]
(SURVEY.md component 18, verified numerically).

Returned as a vector so the actor service can hold one epsilon per
vectorized environment — the TPU-native generalization of the reference's
one-process-per-epsilon fleet.
"""

from __future__ import annotations

import numpy as np


def epsilon_ladder(
    num_actors: int, base_eps: float = 0.4, alpha: float = 7.0
) -> np.ndarray:
    """One vectorized expression for any N >= 1.

    The N=1 rung falls out of the same formula (i=0 gives exponent 1, so
    the sole actor gets base_eps exactly); the max() only guards the 0/0.
    Exponentiation runs in float64 once and lands in float32 — the ladder
    spans ~5 decades for the default alpha=7, and float32 pow would wobble
    the smallest rungs' last bits across platforms.
    """
    if num_actors < 1:
        raise ValueError(f"num_actors must be >= 1, got {num_actors}")
    i = np.arange(num_actors, dtype=np.float64)
    exponent = 1.0 + i / max(num_actors - 1, 1) * alpha
    return (float(base_eps) ** exponent).astype(np.float32)


def multitask_epsilon_ladders(
    num_tasks: int,
    actors_per_task: int,
    base_eps: float = 0.4,
    alpha: float = 7.0,
) -> np.ndarray:
    """(num_tasks, actors_per_task) ε matrix: EACH task gets its own full
    Ape-X ladder rather than slicing one ladder across tasks.

    Rationale (Agent57, PAPERS.md): exploration needs are per-task — a
    task whose replay is young still wants its greedy rungs, and a task
    whose rewards are dense still wants its exploratory rungs. Slicing one
    N*T ladder would give task 0 only the noisy top and task T-1 only the
    near-greedy bottom.
    """
    if num_tasks < 1:
        raise ValueError(f"num_tasks must be >= 1, got {num_tasks}")
    ladder = epsilon_ladder(actors_per_task, base_eps, alpha)
    return np.tile(ladder[None, :], (num_tasks, 1))


def multitask_gamma_ladder(
    num_tasks: int, gamma_min: float = 0.97, gamma_max: float = 0.997
) -> np.ndarray:
    """(num_tasks,) per-task discount ladder, interpolated UNIFORMLY IN
    log(1 - gamma) space (Agent57 section 3.1's horizon-spacing trick):
    linear interpolation in gamma-space would crowd every rung against
    gamma_max because effective horizon 1/(1-gamma) is convex in gamma.

    Task 0 gets gamma_max (the longest horizon — by convention the primary
    task); the single-task rung is gamma_max exactly.
    """
    if num_tasks < 1:
        raise ValueError(f"num_tasks must be >= 1, got {num_tasks}")
    if not (0.0 < gamma_min <= gamma_max < 1.0):
        raise ValueError(f"need 0 < gamma_min <= gamma_max < 1, got [{gamma_min}, {gamma_max}]")
    i = np.arange(num_tasks, dtype=np.float64)
    frac = i / max(num_tasks - 1, 1)
    log_span = np.log(1.0 - gamma_max) + frac * (
        np.log(1.0 - gamma_min) - np.log(1.0 - gamma_max)
    )
    return (1.0 - np.exp(log_span)).astype(np.float64).astype(np.float32)
