"""ProcMaze: procedural layout generation, mechanics, rendering, and the
generic functional-env adapters + collector integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.envs.procmaze import ProcMazeEnv


def _bfs_reachable(walls, start, goal):
    """Host-side BFS ground truth for solvability."""
    g = walls.shape[0]
    seen = np.zeros_like(walls, bool)
    frontier = [tuple(start)]
    seen[start[0], start[1]] = True
    while frontier:
        r, c = frontier.pop()
        if (r, c) == tuple(goal):
            return True
        for dr, dc in ((1, 0), (-1, 0), (0, 1), (0, -1)):
            nr, nc = r + dr, c + dc
            if 0 <= nr < g and 0 <= nc < g and not walls[nr, nc] and not seen[nr, nc]:
                seen[nr, nc] = True
                frontier.append((nr, nc))
    return False


def test_every_level_is_solvable_and_diverse():
    env = ProcMazeEnv()
    layouts = []
    for seed in range(50):
        s = env.reset(jax.random.PRNGKey(seed))
        walls = np.asarray(s.walls)
        agent, goal = np.asarray(s.agent), np.asarray(s.goal)
        assert not walls[agent[0], agent[1]] and not walls[goal[0], goal[1]]
        assert tuple(agent) != tuple(goal)
        assert _bfs_reachable(walls, agent, goal), f"unsolvable level seed={seed}"
        layouts.append(walls.tobytes())
    # procedural diversity: essentially every level is distinct
    assert len(set(layouts)) >= 45


def test_step_mechanics_walls_block_and_goal_pays():
    env = ProcMazeEnv(horizon=96)
    s = env.reset(jax.random.PRNGKey(3))
    # drive the agent along the carved corridor toward the goal greedily:
    # BFS on host to get a shortest path, then replay it through step()
    walls = np.asarray(s.walls)
    start, goal = tuple(np.asarray(s.agent)), tuple(np.asarray(s.goal))
    from collections import deque

    prev = {start: None}
    q = deque([start])
    while q:
        cur = q.popleft()
        if cur == goal:
            break
        for a, (dr, dc) in ((1, (-1, 0)), (2, (1, 0)), (3, (0, -1)), (4, (0, 1))):
            nxt = (cur[0] + dr, cur[1] + dc)
            if (
                0 <= nxt[0] < env.g and 0 <= nxt[1] < env.g
                and not walls[nxt] and nxt not in prev
            ):
                prev[nxt] = (cur, a)
                q.append(nxt)
    assert goal in prev
    path = []
    node = goal
    while prev[node] is not None:
        node, a = prev[node]
        path.append(a)
    path.reverse()
    total = 0.0
    done = False
    for a in path:
        assert not done
        s, r, done = env.step(s, jnp.int32(a))
        total += float(r)
    assert done and total == 1.0

    # walls block: stepping into a wall leaves the agent in place
    s2 = env.reset(jax.random.PRNGKey(7))
    walls2 = np.asarray(s2.walls)
    agent = np.asarray(s2.agent)
    for a, (dr, dc) in ((1, (-1, 0)), (2, (1, 0)), (3, (0, -1)), (4, (0, 1))):
        tr, tc = agent[0] + dr, agent[1] + dc
        if 0 <= tr < env.g and 0 <= tc < env.g and walls2[tr, tc]:
            s3, _, _ = env.step(s2, jnp.int32(a))
            np.testing.assert_array_equal(np.asarray(s3.agent), agent)
            break


def test_horizon_truncates_with_zero_reward():
    env = ProcMazeEnv(horizon=5)
    s = env.reset(jax.random.PRNGKey(0))
    done = False
    steps, total = 0, 0.0
    while not done:
        s, r, done = env.step(s, jnp.int32(0))  # NOOP forever
        total += float(r)
        steps += 1
    assert steps == 5 and total == 0.0


def test_render_shape_and_colors():
    env = ProcMazeEnv()
    s = env.reset(jax.random.PRNGKey(1))
    img = np.asarray(env.render(s))
    assert img.shape == (64, 64, 3) and img.dtype == np.uint8
    # agent cell pure red, goal cell pure green, at 4px cell granularity
    ar, ac = np.asarray(s.agent) * env.cell
    gr, gc = np.asarray(s.goal) * env.cell
    np.testing.assert_array_equal(img[ar, ac], [255, 0, 0])
    np.testing.assert_array_equal(img[gr, gc], [0, 255, 0])


def test_functional_adapters_and_factories():
    from r2d2_tpu.config import procgen_impala
    from r2d2_tpu.envs import make_env
    from r2d2_tpu.train import build_fn_env, build_vec_env

    cfg = procgen_impala().replace(num_actors=3)
    host = make_env(cfg, seed=0)
    assert host.action_dim == 5 and host.obs_shape == (64, 64, 3)
    obs = host.reset()
    assert obs.shape == (64, 64, 3) and obs.dtype == np.uint8
    obs2, r, done, _ = host.step(0)
    assert obs2.shape == (64, 64, 3)

    vec = build_vec_env(cfg, seed=0)
    assert vec.num_envs == 3 and vec.obs_shape == (64, 64, 3)
    obs = vec.reset_all()
    assert obs.shape == (3, 64, 64, 3)
    term, r, d, nxt = vec.step(np.zeros(3, np.int64))
    assert term.shape == (3, 64, 64, 3) and nxt.shape == (3, 64, 64, 3)

    fn_env = build_fn_env(cfg)
    assert fn_env.NUM_ACTIONS == 5


def test_vec_autoreset_draws_new_level():
    from r2d2_tpu.envs.functional import FnVecEnv

    env = ProcMazeEnv(horizon=3)
    vec = FnVecEnv(env, num_envs=2, seed=5)
    vec.reset_all()
    walls0 = np.asarray(vec._state.walls).copy()
    done_seen = False
    for _ in range(4):
        _, _, done, _ = vec.step(np.zeros(2, np.int64))
        done_seen = done_seen or done.any()
    assert done_seen
    # after auto-reset the layouts changed (fresh levels)
    assert not np.array_equal(np.asarray(vec._state.walls), walls0)


def test_device_collector_runs_on_procmaze():
    """The on-device collector composes with procmaze unchanged (fn_env
    protocol) — chunk collection fills the HBM replay."""
    from r2d2_tpu.collect import DeviceCollector
    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.learner import init_train_state
    from r2d2_tpu.replay.device_store import DeviceReplayBuffer

    env = ProcMazeEnv(grid=6, cell=2, horizon=12)
    cfg = tiny_test().replace(
        env_name="procmaze",
        obs_shape=(12, 12, 3),
        action_dim=5,
        encoder="mlp",
        num_actors=4,
        max_episode_steps=12,
        collector="device",
        replay_plane="device",
    )
    net, state = init_train_state(cfg, jax.random.PRNGKey(0))

    class _P:
        def latest(self):
            return state.params, 0

    replay = DeviceReplayBuffer(cfg)
    col = DeviceCollector(cfg, net, _P(), env, replay, seed=3)
    for _ in range(4):
        col.step()
    assert replay.env_steps > 0 and len(replay) > 0


def test_procmaze_name_parsing():
    from r2d2_tpu.envs.procmaze import (
        PROCMAZE_SHAPING_COEF,
        is_procmaze_name,
        procmaze_params,
    )

    assert is_procmaze_name("procmaze") and is_procmaze_name("procmaze_shaped:8")
    assert not is_procmaze_name("catch") and not is_procmaze_name("procmazes")
    assert procmaze_params("procmaze") == {}
    assert procmaze_params("procmaze_shaped") == {"shaping_coef": PROCMAZE_SHAPING_COEF}
    assert procmaze_params("procmaze:8") == {"grid": 8}
    assert procmaze_params("procmaze_shaped:8") == {
        "shaping_coef": PROCMAZE_SHAPING_COEF, "grid": 8,
    }
    with pytest.raises(ValueError):
        procmaze_params("procmaze:1")


def test_procmaze_shaped_rewards_telescope():
    """Shaped variant: a step toward the goal pays +coef, away -coef,
    blocked/NOOP 0, reaching still pays the full +1 — so the shaping sum
    telescopes to coef * initial distance and cannot outweigh the goal."""
    import jax
    import numpy as np

    from r2d2_tpu.envs.procmaze import PROCMAZE_SHAPING_COEF as C
    from r2d2_tpu.envs.procmaze import ProcMazeEnv, ProcMazeState

    env = ProcMazeEnv(grid=8, cell=8, horizon=96, shaping_coef=C)
    walls = jnp.zeros((8, 8), bool)
    s = ProcMazeState(
        walls,
        jnp.asarray([4, 2], jnp.int32),
        jnp.asarray([4, 5], jnp.int32),
        jnp.zeros((), jnp.int32),
        jax.random.PRNGKey(0),
    )
    s1, r_toward, d = env.step(s, jnp.int32(4))   # right, toward goal
    assert float(r_toward) == pytest.approx(C) and not bool(d)
    _, r_away, _ = env.step(s, jnp.int32(3))      # left, away
    assert float(r_away) == pytest.approx(-C)
    _, r_noop, _ = env.step(s, jnp.int32(0))
    assert float(r_noop) == 0.0
    s2, _, _ = env.step(s1, jnp.int32(4))
    s3, r_goal, done = env.step(s2, jnp.int32(4))  # lands on goal
    assert float(r_goal) == 1.0 and bool(done)

    # sparse variant unchanged: same path pays 0 until the goal
    sparse = ProcMazeEnv(grid=8, cell=8, horizon=96)
    _, r0, _ = sparse.step(s, jnp.int32(4))
    assert float(r0) == 0.0


def test_procmaze_grid_variant_through_trainer_envs():
    """'procmaze_shaped:8' builds an 8x8 maze at the same 64x64x3 obs via
    both the functional and vec construction paths."""
    from r2d2_tpu.config import procgen_impala
    from r2d2_tpu.train import build_fn_env, build_vec_env

    cfg = procgen_impala("procmaze_shaped:8").replace(num_actors=2)
    fn_env = build_fn_env(cfg)
    assert fn_env.g == 8 and fn_env.cell == 8 and fn_env.shaping > 0
    import jax

    s = fn_env.reset(jax.random.PRNGKey(0))
    assert fn_env.render(s).shape == (64, 64, 3)
    vec = build_vec_env(cfg, seed=1)
    assert vec.obs_shape == (64, 64, 3) and vec.action_dim == 5
