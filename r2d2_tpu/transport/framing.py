"""Wire format of the block-stream transport: length-prefixed CRC frames.

One frame = a fixed 13-byte header + payload:

    magic   4 bytes  b"R2DB" — stream-resync sentinel; a mismatch means
                     the peer is not speaking this protocol (or the
                     stream tore mid-frame) and the connection is dead
    type    1 byte   frame type (HELLO .. CKPT below)
    length  4 bytes  big-endian u32 payload byte count
    crc     4 bytes  big-endian u32 crc32 of the payload

The CRC is an end-to-end integrity check on the PAYLOAD (the header is
covered by the magic + the length bound): a flipped bit anywhere in a
spooled-then-streamed Block surfaces as a FrameError at the receiver
instead of a silently corrupted replay write. FrameError subclasses
ConnectionError on purpose — every framing violation means the stream
state is unrecoverable mid-connection, so the shared retry policy
(`with_retries`, TRANSIENT_ERRORS) treats it exactly like a torn socket:
drop the connection, reconnect, resume from the handshake.

Handshake (versioned): the publisher opens with HELLO
`{"proto": PROTO_VERSION, "host": <host-id>, "next_seq": N}` and the
service answers HELLO_ACK `{"proto": ..., "last_seq": M}` — M being the
highest contiguous sequence number it has already ingested from that
host. The publisher then resends ONLY seq > M, which is what turns
at-least-once spooling into exactly-once delivery on the happy path: a
reconnecting (or SIGKILL-restarted) host never re-sends what the learner
already owns, and the service's per-frame seq admission check
(`ingest.dedup`) stays a belt-and-suspenders counter that reads 0.

Control payloads (HELLO/HELLO_ACK/ACK/HEARTBEAT) are canonical JSON;
BLOCK and CKPT payloads are npz archives (numpy's own portable binary
container, loaded with allow_pickle=False) — see encode_block /
encode_ckpt below.

Wire codec (PR 19): HELLO may carry `"codec": <name>` (replay/codec.py
CODECS); the service answers HELLO_ACK with the codec it accepts —
`"none"` when it does not recognize the request, and an old service
simply omits the key (JSON ignores unknown keys both ways), which the
publisher reads as `"none"`. Under a negotiated codec, BLOCK payloads
swap the raw `obs` npz entry for `obs_enc` (a codec.encode_field byte
vector); decode_block is self-describing either way, so a spool written
under one negotiation can be transcoded at send time for a peer that
negotiated another (transcode_raw).
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.replay import codec as blockcodec
from r2d2_tpu.replay.block import Block

MAGIC = b"R2DB"
PROTO_VERSION = 1

# frame types
HELLO = 1       # publisher -> service: {proto, host, next_seq}
HELLO_ACK = 2   # service -> publisher: {proto, last_seq}
BLOCK = 3       # publisher -> service: npz (one Block + stream metadata)
ACK = 4         # service -> publisher: {seq}: highest contiguous ingested
HEARTBEAT = 5   # either direction: {t} liveness proof on idle streams
CKPT = 6        # service -> publisher: npz (flattened param leaves)

_HEADER = struct.Struct(">4sBII")

# hard bound on a single frame; a length field past this is treated as a
# torn/garbage header rather than an allocation request (a real CKPT of
# the presets is a few MB; tiny_test Blocks are KBs)
MAX_FRAME_BYTES = 256 * 1024 * 1024


class FrameError(ConnectionError):
    """Framing violation: bad magic, CRC mismatch, absurd length, or a
    protocol-version mismatch. The stream cannot be re-synchronized
    mid-connection; classified transient so retry wrappers reconnect."""


def encode_frame(ftype: int, payload: bytes) -> bytes:
    return _HEADER.pack(MAGIC, ftype, len(payload), zlib.crc32(payload)) + payload


def send_frame(sock, ftype: int, payload: bytes) -> None:
    sock.sendall(encode_frame(ftype, payload))


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock) -> Tuple[int, bytes]:
    """Read one complete frame; raises FrameError on any violation and
    ConnectionError on EOF (both transient-classified)."""
    magic, ftype, length, crc = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds {MAX_FRAME_BYTES}")
    payload = _recv_exact(sock, length) if length else b""
    if zlib.crc32(payload) != crc:
        raise FrameError(f"payload crc mismatch on frame type {ftype}")
    return ftype, payload


# ------------------------------------------------------------- JSON control


def encode_json(obj: Dict) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def decode_json(payload: bytes) -> Dict:
    try:
        obj = json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"malformed control payload: {e}") from e
    if not isinstance(obj, dict):
        raise FrameError("control payload must be a JSON object")
    return obj


# ------------------------------------------------------------- block codec

# scalar/metadata keys ride in the same npz as the arrays so one archive
# is the whole frame (no second framing layer inside the payload)
_BLOCK_ARRAYS = (
    "obs", "last_action", "last_reward", "action", "n_step_reward",
    "gamma", "hidden", "burn_in_steps", "learning_steps", "forward_steps",
)


def encode_block(
    block: Block,
    priorities: np.ndarray,
    episode_reward: Optional[float],
    seq: int,
    t_serve: float,
    eps_stamps: Optional[np.ndarray] = None,
    ver_stamps: Optional[np.ndarray] = None,
    codec: str = "none",
    stats_out: Optional[Dict] = None,
) -> bytes:
    """One finished Block + its replay-add arguments + stream metadata as
    an npz payload. `t_serve` (sender wall clock at spool time) is the
    ingest-lag measurement anchor; `eps_stamps`/`ver_stamps` are the
    block's per-transition off-policy audit stamps (the tap's audit-tail
    entry), shipped so the learner side can stamp (host, ε, version) skew
    without trusting the sender's aggregation.

    `codec` (default "none" = byte-identical to the pre-codec wire): a
    replay/codec.py name; under a compressing codec the uint8 obs plane —
    the payload's dominant field — ships as an `obs_enc` encoded byte
    vector instead of the raw `obs` entry. `stats_out`, when given, gets
    `obs_raw_bytes`/`obs_enc_bytes` so callers can account the codec win
    without re-measuring."""
    arrays = {k: np.asarray(getattr(block, k)) for k in _BLOCK_ARRAYS}
    if stats_out is not None:
        stats_out["obs_raw_bytes"] = int(arrays["obs"].nbytes)
        stats_out["obs_enc_bytes"] = int(arrays["obs"].nbytes)
    if codec != "none":
        enc = blockcodec.encode_field(arrays.pop("obs"), codec)
        arrays["obs_enc"] = np.frombuffer(enc, np.uint8)
        if stats_out is not None:
            stats_out["obs_enc_bytes"] = len(enc)
    arrays["num_sequences"] = np.asarray(block.num_sequences, np.int64)
    arrays["task"] = np.asarray(block.task, np.int64)
    arrays["priorities"] = np.asarray(priorities)
    arrays["has_episode_reward"] = np.asarray(
        int(episode_reward is not None), np.int64
    )
    arrays["episode_reward"] = np.asarray(
        0.0 if episode_reward is None else float(episode_reward), np.float64
    )
    arrays["seq"] = np.asarray(int(seq), np.int64)
    arrays["t_serve"] = np.asarray(float(t_serve), np.float64)
    arrays["eps_stamps"] = np.asarray(
        [] if eps_stamps is None else eps_stamps, np.float32
    )
    arrays["ver_stamps"] = np.asarray(
        [] if ver_stamps is None else ver_stamps, np.int64
    )
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_block(payload: bytes, stats_out: Optional[Dict] = None) -> Dict:
    """Inverse of encode_block. Returns {block, priorities,
    episode_reward, seq, t_serve, eps_stamps, ver_stamps}.

    When `stats_out` is given it receives `obs_enc_bytes` (obs bytes as
    carried by this payload) and `obs_raw_bytes` (after decode) so the
    receiver can account wire savings without re-parsing the npz."""
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as d:
            arrays = {k: np.asarray(d[k]) for k in d.files}
    except (ValueError, OSError, KeyError, zlib.error,
            zipfile.BadZipFile) as e:
        raise FrameError(f"malformed BLOCK payload: {e}") from e
    if "obs_enc" in arrays:
        # codec-negotiated payload: the obs plane rides encoded. Decode on
        # THIS (ingest/staging) thread — codec damage is payload damage,
        # classified like a CRC miss
        enc = arrays.pop("obs_enc").tobytes()
        try:
            arrays["obs"], _ = blockcodec.decode_field(enc)
        except blockcodec.CodecError as e:
            raise FrameError(f"BLOCK obs codec damage: {e}") from e
        if stats_out is not None:
            stats_out["obs_enc_bytes"] = len(enc)
            stats_out["obs_raw_bytes"] = int(arrays["obs"].nbytes)
    elif stats_out is not None and "obs" in arrays:
        stats_out["obs_enc_bytes"] = int(arrays["obs"].nbytes)
        stats_out["obs_raw_bytes"] = int(arrays["obs"].nbytes)
    try:
        block = Block(
            **{k: arrays[k] for k in _BLOCK_ARRAYS},
            num_sequences=int(arrays["num_sequences"][()]),
            task=int(arrays["task"][()]),
        )
        return {
            "block": block,
            "priorities": arrays["priorities"],
            "episode_reward": (
                float(arrays["episode_reward"][()])
                if int(arrays["has_episode_reward"][()]) else None
            ),
            "seq": int(arrays["seq"][()]),
            "t_serve": float(arrays["t_serve"][()]),
            "eps_stamps": arrays["eps_stamps"],
            "ver_stamps": arrays["ver_stamps"],
        }
    except KeyError as e:
        raise FrameError(f"BLOCK payload missing field {e}") from e


def obs_crc(payload: bytes) -> int:
    """crc32 of the DECODED obs bytes of a BLOCK payload — the spool
    header's integrity check. Computed over decoded bytes on purpose: it
    pins the round trip (a spool written by a binary whose codec decodes
    differently fails the check on load instead of misdecoding into
    replay), which a CRC over the encoded bytes could never catch."""
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as d:
            if "obs_enc" in d.files:
                obs, _ = blockcodec.decode_field(
                    np.asarray(d["obs_enc"]).tobytes()
                )
            else:
                obs = np.asarray(d["obs"])
    except (ValueError, OSError, KeyError, zlib.error,
            zipfile.BadZipFile) as e:
        raise FrameError(f"malformed BLOCK payload: {e}") from e
    except blockcodec.CodecError as e:
        raise FrameError(f"BLOCK obs codec damage: {e}") from e
    return zlib.crc32(np.ascontiguousarray(obs).tobytes())


def transcode_raw(payload: bytes) -> bytes:
    """A BLOCK payload with any codec undone: `obs_enc` decoded back to a
    raw `obs` npz entry. The publisher calls this at SEND time when its
    spool was written under a codec but the connected peer negotiated
    "none" (mixed old/new fleets) — the on-disk spool stays encoded; only
    the wire copy is raw. Already-raw payloads pass through untouched."""
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as d:
            if "obs_enc" not in d.files:
                return payload
            arrays = {k: np.asarray(d[k]) for k in d.files}
    except (ValueError, OSError, KeyError, zlib.error,
            zipfile.BadZipFile) as e:
        raise FrameError(f"malformed BLOCK payload: {e}") from e
    try:
        arrays["obs"], _ = blockcodec.decode_field(arrays.pop("obs_enc").tobytes())
    except blockcodec.CodecError as e:
        raise FrameError(f"BLOCK obs codec damage: {e}") from e
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


# -------------------------------------------------------- checkpoint codec


def encode_ckpt(leaves: List[np.ndarray], step: int, version: int) -> bytes:
    """Flattened param leaves + provenance as one npz payload. The
    receiver reconstructs against its OWN template treedef (both ends
    build the same network from the same config), so only leaf order —
    jax.tree flattening order, deterministic for a fixed structure —
    crosses the wire, never pickled tree structure."""
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["n_leaves"] = np.asarray(len(leaves), np.int64)
    arrays["step"] = np.asarray(int(step), np.int64)
    arrays["version"] = np.asarray(int(version), np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_ckpt(payload: bytes) -> Tuple[List[np.ndarray], int, int]:
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as d:
            n = int(np.asarray(d["n_leaves"])[()])
            leaves = [np.asarray(d[f"leaf_{i}"]) for i in range(n)]
            return (
                leaves,
                int(np.asarray(d["step"])[()]),
                int(np.asarray(d["version"])[()]),
            )
    except (ValueError, OSError, KeyError, zlib.error) as e:
        raise FrameError(f"malformed CKPT payload: {e}") from e
