"""BlockStreamPublisher — the serve host's end of the pod-loop stream.

The publisher exposes the replay-sink surface the liveloop bridge drains
into (`add_blocks_batch` / `add_block`), so a serve process upgrades from
single-process liveloop to pod-loop by passing THIS object as
`LiveLoopPlane(cfg, server, replay=publisher)` — the tap, the bridge, its
bounded queue, and its fault sites all keep working unchanged; only the
final hop changes from "write the local replay store" to "spool and
stream to the learner".

Delivery contract (at-least-once spool, exactly-once effect):

- every offered Block is assigned the next monotonic per-host sequence
  number and spooled BEFORE it is eligible to send (`transport.spool`;
  on disk under `transport_spool_dir` so a SIGKILL'd host resumes its
  numbering and unacked tail from disk);
- the spool is bounded (`transport_spool_depth`): when full the OLDEST
  unacked block is shed and counted — the same fresh-beats-stale policy
  as every liveloop queue. The ingest service tolerates the resulting
  seq gap (it acks highest-ingested, not strictly-contiguous);
- a supervised worker ("transport-publish") owns the socket: it
  connects with jittered exponential backoff (`transport.connect`,
  single attempts wrapped in `with_retries` with a `max_elapsed` budget
  below the supervision heartbeat), replays the HELLO handshake, and
  learns from HELLO_ACK the highest seq the learner already ingested —
  resending ONLY past it, so reconnects deliver zero duplicates;
- acks prune the spool; a torn connection (any TRANSIENT_ERRORS out of
  `transport.send`/`transport.recv`) just marks the stream disconnected
  and the next iteration reconnects — the worker's restart budget is
  reserved for real bugs, not network weather;
- CKPT frames arriving on the same socket (the learner's hot-reload
  broadcast) are decoded and handed to `on_checkpoint(leaves, step,
  version)` on the worker thread.

Single-writer discipline: only the worker thread touches the socket;
producer threads (the bridge's ingest worker) and the worker share the
spool and counters under one lock, with no blocking call inside it.
"""

from __future__ import annotations

import os
import select
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay import codec as blockcodec
from r2d2_tpu.transport import framing
from r2d2_tpu.utils.faults import (
    TRANSIENT_ERRORS,
    Backoff,
    fault_point,
    with_retries,
)
from r2d2_tpu.utils.supervision import Supervisor

# bound on blocks sent per worker iteration: keeps one body call's work
# bounded (the supervision contract) while still draining bursts fast
_SEND_BATCH = 64

# Versioned on-disk spool entry (PR 19): header + BLOCK payload.
#
#     magic    4 bytes  b"R2SP"
#     version  1 byte   spool format version (this is v1)
#     codec    1 byte   index into replay/codec.CODECS the payload was
#                       written under
#     obs_crc  4 bytes  crc32 of the DECODED obs bytes — the
#                       upgrade-then-SIGKILL-resume guard: a binary whose
#                       codec would misdecode this payload fails the CRC
#                       on load and DROPS the entry instead of feeding
#                       garbage into replay
#     length   4 bytes  payload byte count
#
# A file without the magic is either an old binary's spool (raw npz
# starting b"PK" — still a valid payload, loaded and counted legacy) or
# damage (dropped and counted).
_SPOOL_MAGIC = b"R2SP"
_SPOOL_VERSION = 1
_SPOOL_HEADER = struct.Struct(">4sBBII")


class BlockStreamPublisher:
    def __init__(
        self,
        cfg: R2D2Config,
        address: Tuple[str, int],
        host_id: str,
        audit_source: Optional[Callable[[], Optional[dict]]] = None,
        on_checkpoint: Optional[Callable] = None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.address = address
        self.host_id = host_id
        # called (on the producer thread) right after each block is
        # offered; returns the tap's freshest audit-tail entry — by the
        # tap's emit ordering, exactly this block's (epsilon,
        # params_version) stamps — or None when no tap is wired
        self.audit_source = audit_source
        self.on_checkpoint = on_checkpoint
        self._lock = threading.Lock()
        # spool of (seq, payload) awaiting ack, oldest first
        self._spool: Deque[Tuple[int, bytes]] = deque()
        self._next_seq = 1
        self._sent_up_to = 0  # highest seq handed to sendall this session
        self._acked = 0       # highest seq the service has acknowledged
        self._sock: Optional[socket.socket] = None
        self._last_send = 0.0
        self._backoff = Backoff(
            base=0.05, factor=2.0, max_delay=2.0, jitter=0.5, seed=seed
        )
        self.supervisor: Optional[Supervisor] = None
        # wire codec negotiated with the CURRENT peer (worker thread only;
        # "none" until a HELLO_ACK accepts our cfg.block_codec)
        self._wire_codec = "none"
        # counters, guarded by _lock
        self.spooled_blocks = 0
        self.sent_blocks = 0
        self.acked_blocks = 0
        self.spool_dropped = 0
        self.reconnects = 0
        self.connect_failures = 0
        self.ckpts_applied = 0
        self.bytes_pre_codec = 0   # what the spooled payloads would be raw
        self.bytes_post_codec = 0  # spooled payload bytes as encoded
        self.bytes_on_wire = 0     # frame bytes actually sent (post-transcode)
        self.spool_legacy = 0          # pre-header spool files adopted
        self.spool_corrupt_dropped = 0 # spool files failing header/CRC checks
        self._spool_path = None
        if cfg.transport_spool_dir:
            self._spool_path = os.path.join(cfg.transport_spool_dir, host_id)
            os.makedirs(self._spool_path, exist_ok=True)
            self._load_spool()

    # ------------------------------------------------------------ spool disk

    def _parse_spool_entry(self, raw: bytes) -> Optional[bytes]:
        """One on-disk spool file -> BLOCK payload, or None when the entry
        must be dropped. Handles all three generations: v1 headered
        (verified against the decoded-obs CRC), legacy headerless raw npz
        (an old binary's spool adopted across an upgrade), damage."""
        if raw[:4] == _SPOOL_MAGIC:
            try:
                _, version, codec_id, crc, length = _SPOOL_HEADER.unpack_from(raw)
            except struct.error:
                return None
            payload = raw[_SPOOL_HEADER.size:]
            if (
                version != _SPOOL_VERSION
                or codec_id >= len(blockcodec.CODECS)
                or len(payload) != length
            ):
                return None
            try:
                if framing.obs_crc(payload) != crc:
                    return None
            except framing.FrameError:
                return None
            return payload
        if raw[:2] == b"PK":  # headerless npz: an old binary wrote this
            # r2d2: disable=lock-discipline — __init__-only (no worker yet)
            self.spool_legacy += 1
            return raw
        return None

    def _load_spool(self) -> None:
        """Crash resume: reload the unacked tail and continue the sequence
        numbering past everything ever spooled here. Entries that fail the
        v1 header checks (an upgrade-then-SIGKILL resume onto a spool this
        binary would misdecode, or plain damage) are dropped and counted —
        a dropped block is an at-least-once gap the ingest side already
        tolerates; a misdecoded block would be silent replay corruption."""
        entries = []
        max_seq = 0  # over EVERY file, dropped ones included: a dropped
        # entry's number must never be reissued (the ingest high-water
        # dedup would discard its reuse as a duplicate)
        # sorted: names are zero-padded seqs, so lexicographic IS replay
        # order — the drop/unlink side effects and max_seq accounting run
        # identically on every host and resume
        for name in sorted(os.listdir(self._spool_path)):
            if not name.endswith(".blk"):
                continue
            seq = int(name[:-4])
            max_seq = max(max_seq, seq)
            path = os.path.join(self._spool_path, name)
            with open(path, "rb") as f:
                payload = self._parse_spool_entry(f.read())
            if payload is None:
                # r2d2: disable=lock-discipline — __init__-only
                self.spool_corrupt_dropped += 1
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            entries.append((seq, payload))
        entries.sort()
        # __init__-only (no worker exists yet)
        # r2d2: disable=cross-thread-unguarded-write
        self._spool.extend(entries)
        if max_seq:
            # __init__-only (no worker exists yet)
            self._next_seq = max_seq + 1  # r2d2: disable=lock-discipline

    def _spool_file(self, seq: int) -> str:
        return os.path.join(self._spool_path, f"{seq:012d}.blk")

    # --------------------------------------------------------- replay surface

    def add_block(self, block, priorities, episode_reward) -> None:
        """The bridge's per-block sink: assign a seq, encode, persist,
        enqueue. Never blocks on the network — the worker streams the
        spool independently."""
        audit = self.audit_source() if self.audit_source is not None else None
        eps = audit.get("epsilon") if audit else None
        ver = audit.get("params_version") if audit else None
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        cstats: dict = {}
        payload = framing.encode_block(
            block, priorities, episode_reward, seq=seq, t_serve=time.time(),
            eps_stamps=eps, ver_stamps=ver, codec=self.cfg.block_codec,
            stats_out=cstats,
        )
        fault_point("transport.spool")
        if self._spool_path is not None:
            # persist-then-enqueue: a crash between the two re-sends a
            # spooled block (at-least-once), never invents a seq gap. The
            # v1 header's decoded-obs CRC comes straight from the block —
            # the load side recomputes it through the decode path, closing
            # the round trip
            crc = zlib.crc32(np.ascontiguousarray(block.obs).tobytes())
            header = _SPOOL_HEADER.pack(
                _SPOOL_MAGIC, _SPOOL_VERSION,
                blockcodec.CODECS.index(self.cfg.block_codec),
                crc, len(payload),
            )
            tmp = self._spool_file(seq) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(header + payload)
            os.replace(tmp, self._spool_file(seq))
        with self._lock:
            if len(self._spool) >= self.cfg.transport_spool_depth:
                old_seq, _ = self._spool.popleft()
                self.spool_dropped += 1
                self._drop_spool_file(old_seq)
            self._spool.append((seq, payload))
            self.spooled_blocks += 1
            # pre-codec = the payload as it would have spooled raw: only
            # the obs entry differs between the two encodings
            self.bytes_post_codec += len(payload)
            self.bytes_pre_codec += (
                len(payload) - cstats["obs_enc_bytes"] + cstats["obs_raw_bytes"]
            )

    def add_blocks_batch(self, items) -> None:
        for block, priorities, episode_reward in items:
            self.add_block(block, priorities, episode_reward)

    def _drop_spool_file(self, seq: int) -> None:
        if self._spool_path is None:
            return
        try:
            os.unlink(self._spool_file(seq))
        except OSError:
            pass  # already pruned (or the dir is gone at teardown)

    # ------------------------------------------------------------- connection

    def _connect_once(self) -> socket.socket:
        fault_point("transport.connect")
        sock = socket.create_connection(
            self.address, timeout=self.cfg.transport_connect_timeout_s
        )
        try:
            sock.settimeout(self.cfg.transport_connect_timeout_s)
            with self._lock:
                next_seq = self._next_seq
            framing.send_frame(sock, framing.HELLO, framing.encode_json({
                "proto": framing.PROTO_VERSION,
                "host": self.host_id,
                "next_seq": next_seq,
                "codec": self.cfg.block_codec,
            }))
            ftype, payload = framing.recv_frame(sock)
            if ftype != framing.HELLO_ACK:
                raise framing.FrameError(
                    f"expected HELLO_ACK, got frame type {ftype}"
                )
            hello = framing.decode_json(payload)
            if hello.get("proto") != framing.PROTO_VERSION:
                raise framing.FrameError(
                    f"protocol version mismatch: peer speaks "
                    f"{hello.get('proto')}, we speak {framing.PROTO_VERSION}"
                )
            last_seq = int(hello.get("last_seq", 0))
            # codec negotiation: the service echoes what it accepts; an
            # OLD service omits the key entirely (unknown JSON keys are
            # ignored both directions), which reads as "none" — spooled
            # payloads are then transcoded raw at send time, so mixed
            # old/new fleets interop on the raw wire format
            self._wire_codec = str(hello.get("codec", "none"))
        except BaseException:
            sock.close()
            raise
        sock.settimeout(self.cfg.transport_connect_timeout_s)
        self._on_resume(last_seq)
        return sock

    def _on_resume(self, last_seq: int) -> None:
        """HELLO_ACK told us what the learner already owns: prune it from
        the spool and resume sending strictly past it — the zero-duplicate
        reconnect contract."""
        dropped: List[int] = []
        with self._lock:
            while self._spool and self._spool[0][0] <= last_seq:
                dropped.append(self._spool.popleft()[0])
            self._acked = max(self._acked, last_seq)
            self._sent_up_to = last_seq
            self.acked_blocks += len(dropped)
        for seq in dropped:
            self._drop_spool_file(seq)

    def connected(self) -> bool:
        return self._sock is not None

    def _disconnect(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # ---------------------------------------------------------------- pumping

    def pump(self, timeout: float = 0.25) -> None:
        """One bounded unit of publisher work: ensure a live connection,
        drain inbound control frames, stream the unsent spool tail, prove
        liveness. The supervised worker body; also callable synchronously
        (tests, the stop-path flush)."""
        if self._sock is None:
            try:
                # two fast attempts per iteration, wall-clock-bounded so a
                # black-holed connect can never starve the heartbeat; the
                # across-iteration escalation is the jittered Backoff
                sock = with_retries(
                    self._connect_once, "transport.connect", attempts=2,
                    base_delay=0.05, max_elapsed=
                    2 * self.cfg.transport_connect_timeout_s,
                )
            except TRANSIENT_ERRORS:
                with self._lock:
                    self.connect_failures += 1
                wait = self._backoff.fail()
                stop = self.supervisor.stop if self.supervisor else None
                if stop is not None:
                    stop.wait(wait)
                else:
                    time.sleep(wait)
                return
            self._backoff.reset()
            with self._lock:
                self._sock = sock
                self.reconnects += 1
                self._last_send = time.monotonic()
        try:
            self._drain_inbound(timeout)
            self._send_tail()
            self._maybe_heartbeat()
        except TRANSIENT_ERRORS:
            # torn stream (real or injected at transport.send/recv): the
            # next iteration reconnects and the handshake resumes the seq
            self._disconnect()

    def _drain_inbound(self, timeout: float) -> None:
        while True:
            ready, _, _ = select.select([self._sock], [], [], timeout)
            if not ready:
                return
            timeout = 0.0  # only the first wait blocks; then drain dry
            fault_point("transport.recv")
            ftype, payload = framing.recv_frame(self._sock)
            if ftype == framing.ACK:
                self._on_ack(int(framing.decode_json(payload)["seq"]))
            elif ftype == framing.CKPT:
                leaves, step, version = framing.decode_ckpt(payload)
                with self._lock:
                    self.ckpts_applied += 1
                if self.on_checkpoint is not None:
                    self.on_checkpoint(leaves, step, version)
            elif ftype == framing.HEARTBEAT:
                pass  # liveness only
            else:
                raise framing.FrameError(
                    f"unexpected frame type {ftype} on publisher stream"
                )

    def _on_ack(self, seq: int) -> None:
        dropped: List[int] = []
        with self._lock:
            while self._spool and self._spool[0][0] <= seq:
                dropped.append(self._spool.popleft()[0])
            self._acked = max(self._acked, seq)
            self.acked_blocks += len(dropped)
        for s in dropped:
            self._drop_spool_file(s)

    def _send_tail(self) -> None:
        with self._lock:
            tail = [
                (seq, payload) for seq, payload in self._spool
                if seq > self._sent_up_to
            ][:_SEND_BATCH]
        for seq, payload in tail:
            fault_point("transport.send")
            if self._wire_codec == "none" and self.cfg.block_codec != "none":
                # the peer did not negotiate our codec: undo it for the
                # wire copy only (the spool stays encoded on disk)
                payload = framing.transcode_raw(payload)
            framing.send_frame(self._sock, framing.BLOCK, payload)
            with self._lock:
                self._last_send = time.monotonic()
                self._sent_up_to = max(self._sent_up_to, seq)
                self.sent_blocks += 1
                self.bytes_on_wire += len(payload) + framing._HEADER.size

    def _maybe_heartbeat(self) -> None:
        now = time.monotonic()
        if now - self._last_send >= self.cfg.transport_heartbeat_s:
            fault_point("transport.send")
            framing.send_frame(
                self._sock, framing.HEARTBEAT,
                framing.encode_json({"t": time.time()}),
            )
            with self._lock:
                self._last_send = now

    # --------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.supervisor = Supervisor()
        self.supervisor.spawn("transport-publish", lambda: self.pump(0.25))

    def check(self) -> dict:
        return self.supervisor.check() if self.supervisor is not None else {}

    def flush(self, deadline_s: float = 5.0) -> bool:
        """Best-effort final drain (stop path): pump synchronously until
        the spool is fully acked or the deadline passes. Returns True when
        everything offered was delivered AND acknowledged."""
        limit = time.monotonic() + deadline_s
        while time.monotonic() < limit:
            with self._lock:
                if not self._spool:
                    return True
            self.pump(timeout=0.05)
        with self._lock:
            return not self._spool

    def stop(self, flush_deadline_s: float = 5.0) -> None:
        if self.supervisor is not None:
            self.supervisor.shutdown(timeout=5.0)
            self.supervisor = None
        self.flush(flush_deadline_s)
        self._disconnect()

    def stats(self) -> dict:
        with self._lock:
            return {
                "transport_spooled_blocks": self.spooled_blocks,
                "transport_sent_blocks": self.sent_blocks,
                "transport_acked_blocks": self.acked_blocks,
                "transport_spool_dropped": self.spool_dropped,
                "transport_spool_depth": len(self._spool),
                "transport_reconnects": self.reconnects,
                "transport_connect_failures": self.connect_failures,
                "transport_ckpts_applied": self.ckpts_applied,
                "transport_acked_seq": self._acked,
                "transport_next_seq": self._next_seq,
                "transport_connected": self._sock is not None,
                "transport_bytes_pre_codec": self.bytes_pre_codec,
                "transport_bytes_post_codec": self.bytes_post_codec,
                "transport_bytes_on_wire": self.bytes_on_wire,
                "transport_codec_ratio": (
                    self.bytes_pre_codec / self.bytes_post_codec
                    if self.bytes_post_codec else 0.0
                ),
                "transport_spool_legacy": self.spool_legacy,
                "transport_spool_corrupt_dropped": self.spool_corrupt_dropped,
                "transport_wire_codec": self._wire_codec,
            }
