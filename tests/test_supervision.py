"""Failure detection (SURVEY.md section 5.3 — absent in the reference).

Unit tests for the Supervisor plus a fault-injection integration test: an
env slot raises mid-run, the actor worker is restarted by the supervisor,
and threaded training still reaches its step target.
"""

import threading
import time

import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.envs.catch import CatchVecEnv
from r2d2_tpu.train import Trainer
from r2d2_tpu.utils.supervision import Supervisor, WorkerFatalError


def test_supervisor_restarts_crashing_worker():
    sup = Supervisor()
    calls = []

    def body():
        calls.append(1)
        if len(calls) == 2:
            raise RuntimeError("injected")
        if len(calls) > 5:
            sup.stop.set()
        time.sleep(0.01)

    w = sup.spawn("w", body, max_restarts=3)
    deadline = time.monotonic() + 10
    while not sup.stop.is_set() and time.monotonic() < deadline:
        sup.check()
        time.sleep(0.02)
    sup.shutdown()
    assert len(calls) > 5  # kept running after the injected crash
    assert w.restarts == 1
    assert "injected" in w.last_error


def test_supervisor_fatal_after_restart_budget():
    sup = Supervisor()

    def body():
        raise RuntimeError("always broken")

    sup.spawn("bad", body, max_restarts=2)
    deadline = time.monotonic() + 10
    with pytest.raises(WorkerFatalError, match="always broken"):
        while time.monotonic() < deadline:
            sup.check()
            time.sleep(0.02)
    sup.shutdown()


def test_on_restart_hook_failure_goes_fatal():
    """A failing recovery hook means the worker cannot be restored to a
    known-good state: the supervisor must go fatal immediately instead of
    restarting into corruption — and check() must surface BOTH tracebacks
    (the crash and the failed hook)."""
    sup = Supervisor()
    bodies = []

    def body():
        bodies.append(1)
        raise RuntimeError("worker crashed")

    def bad_hook():
        raise RuntimeError("hook is broken too")

    w = sup.spawn("w", body, max_restarts=5, on_restart=bad_hook)
    deadline = time.monotonic() + 10
    with pytest.raises(WorkerFatalError):
        while time.monotonic() < deadline:
            sup.check()
            time.sleep(0.02)
    assert w.fatal
    assert len(bodies) == 1  # never restarted after the hook failed
    assert any("hook is broken too" in e for e in w.errors)
    assert any("worker crashed" in e for e in w.errors)
    sup.shutdown()


def test_exit_codes_are_distinct():
    """The CLI contract's three-way exit distinction: clean (0), preempted
    (state CURRENT, restart with --resume), stalled (state possibly STALE,
    backend suspect). Supervisors key recovery policy off these."""
    from r2d2_tpu.utils.supervision import PREEMPT_EXIT_CODE, STALL_EXIT_CODE

    assert len({0, PREEMPT_EXIT_CODE, STALL_EXIT_CODE}) == 3
    # both fit in a POSIX exit byte and stay clear of shell/signal codes
    assert 1 <= PREEMPT_EXIT_CODE <= 125
    assert 1 <= STALL_EXIT_CODE <= 125


def test_supervisor_reports_stall():
    sup = Supervisor(heartbeat_timeout=0.05)
    release = threading.Event()

    def body():
        release.wait(5.0)

    sup.spawn("slow", body)
    time.sleep(0.2)
    stats = sup.check()
    assert stats["worker_stalls"] == 1
    release.set()
    sup.shutdown()


class FaultyCatchVecEnv(CatchVecEnv):
    """Raises once, after `fault_after` steps — a transient actor fault."""

    def __init__(self, *a, fault_after: int = 30, **kw):
        super().__init__(*a, **kw)
        self._steps = 0
        self._fault_after = fault_after
        self._fired = False

    def step(self, actions):
        self._steps += 1
        if not self._fired and self._steps >= self._fault_after:
            self._fired = True
            raise RuntimeError("injected env fault")
        return super().step(actions)


def test_fault_injected_actor_recovers():
    cfg = tiny_test().replace(
        env_name="catch",
        training_steps=12,
        learning_starts=48,
        save_interval=1000,
        checkpoint_dir="/tmp/sup_test_ckpt_unused",
    )
    vec_env = FaultyCatchVecEnv(
        num_envs=cfg.num_actors, height=12, width=12, seed=0, fault_after=40
    )
    trainer = Trainer(cfg, vec_env=vec_env)
    trainer.run_threaded()
    assert int(trainer.state.step) == cfg.training_steps
    assert vec_env._fired  # the fault actually triggered mid-run


def test_stalled_worker_escalates_to_fatal():
    """A thread wedged inside an unkillable call (observed: a tunneled-
    backend device readback) must fail the run loudly past
    stall_fatal_timeout instead of letting it limp forever."""
    sup = Supervisor(heartbeat_timeout=0.2, stall_fatal_timeout=3.0)
    release = threading.Event()
    sup.spawn("wedged", release.wait)  # blocks indefinitely, no heartbeat
    time.sleep(0.5)
    stats = sup.check()  # stale but below fatal: surfaced, not raised
    assert stats["worker_stalls"] == 1
    time.sleep(3.0)
    with pytest.raises(WorkerFatalError, match="stalled"):
        sup.check()
    release.set()
    sup.shutdown()


def test_stall_escalation_disabled_with_zero_timeout():
    sup = Supervisor(heartbeat_timeout=0.05, stall_fatal_timeout=0.0)
    release = threading.Event()
    sup.spawn("wedged", release.wait)
    time.sleep(0.4)
    stats = sup.check()  # never escalates, only reports
    assert stats["worker_stalls"] == 1
    release.set()
    sup.shutdown()


class WedgingCatchVecEnv(CatchVecEnv):
    """Blocks forever inside step() once `wedge_now` is set — models a
    thread stuck in a device readback that never returns."""

    wedge_now = False

    def step(self, actions):
        if self.wedge_now:
            threading.Event().wait()  # never set: unkillable from Python
        return super().step(actions)


def test_run_threaded_exits_on_wedged_actor(tmp_path):
    from r2d2_tpu.utils.supervision import WorkerStalledError

    cfg = tiny_test().replace(
        env_name="catch",
        training_steps=10_000,  # far more than the wedge allows
        learning_starts=48,
        heartbeat_timeout=0.2,
        stall_fatal_timeout=1.5,
        save_interval=100_000,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    vec_env = WedgingCatchVecEnv(num_envs=cfg.num_actors, height=12, width=12, seed=0)
    trainer = Trainer(cfg, vec_env=vec_env)
    trainer.warmup()  # wedge only after sampling opens
    vec_env.wedge_now = True
    t0 = time.time()
    try:
        with pytest.raises(WorkerStalledError, match="stalled"):
            trainer.run_threaded()
        # exit skipped device-blocking cleanup: it must be prompt, not hung
        assert time.time() - t0 < 30.0
    finally:
        # the watchdog deliberately stays armed through the unwind (it
        # guards against atexit hangs); a caller keeping the process alive
        # must disarm — else it would hard-exit pytest minutes later
        trainer.disarm_watchdog()


def test_main_watchdog_hard_exits_wedged_process(tmp_path):
    """A wedge on the MAIN thread (e.g. the learner's own device readback)
    can't reach sup.check() — the watchdog must hard-exit the process with
    STALL_EXIT_CODE so an external restart can recover."""
    import subprocess
    import sys as _sys

    from r2d2_tpu.utils.supervision import STALL_EXIT_CODE

    script = """
import threading, time
from r2d2_tpu.utils.supervision import Supervisor
sup = Supervisor(heartbeat_timeout=0.2, stall_fatal_timeout=1.0,
                 main_stall_headroom=0.0)
sup.start_main_watchdog()
sup.main_beat()
threading.Event().wait()  # main thread wedges: no further beats
"""
    t0 = time.time()
    proc = subprocess.run(
        [_sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=60,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == STALL_EXIT_CODE, proc.stderr
    assert "MAIN thread stalled" in proc.stderr
    assert time.time() - t0 < 60
