"""IngestService — the learner's end of the pod-loop block stream.

One supervised worker ("transport-ingest") owns a listening socket and
every accepted host connection, in a single select loop — accepts, frame
reads, dead-peer reaping, and the checkpoint broadcast all happen on the
one thread, so the peer table needs no locking and the ingest order for
any single host is its sequence order (which is what makes the chaos
sweep's replay-store fingerprints bit-reproducible).

Per host connection:

- HELLO/HELLO_ACK handshake (`ingest.accept`): the service answers with
  the highest sequence number it has EVER ingested from that host id —
  state that survives reconnects, so a SIGKILL-restarted publisher
  resumes exactly past what the learner already owns;
- every BLOCK frame passes the seq admission check (`ingest.dedup`):
  seq <= last-ingested is acknowledged but dropped (counted in
  `duplicate_blocks` — 0 on the happy path, because the handshake
  already de-duplicated the stream), anything newer is ingested and
  advances the host's high-water mark (gaps are tolerated: a publisher
  that shed spool under backpressure counted the loss on its side);
- ingested blocks within one select pass fan into the replay plane in a
  single `add_blocks_batch` call (one store-lock acquisition per burst,
  the same discipline as the in-process bridge);
- the learner-side skew stamp is recorded per block into a bounded
  audit tail: (host, ε stamps, params_version stamps, version skew vs
  the learner's current version, ingest lag). **Ingest lag** — sender
  spool time to trainable time, measured when `add_blocks_batch`
  returns — is the pod-loop's first-class health metric (BENCH column);
- a host silent past `transport_dead_peer_s` (heartbeats count) is
  reaped; its seq high-water mark is kept for its next reconnect.

Checkpoints flow the OTHER way on the same sockets: the learner calls
`broadcast_checkpoint(leaves, step, version)` (any thread — the payload
is queued under a lock), and the worker ships the CKPT frame to every
connected host on its next pass. Hot-reload therefore needs no shared
filesystem: the fleet-of-fleets broadcast is the transport itself.
"""

from __future__ import annotations

import select
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay import codec as blockcodec
from r2d2_tpu.transport import framing
from r2d2_tpu.utils.faults import TRANSIENT_ERRORS, fault_point
from r2d2_tpu.utils.supervision import Supervisor


class _Peer:
    __slots__ = ("sock", "host", "last_heard")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.host: Optional[str] = None  # set by HELLO
        self.last_heard = time.monotonic()


class IngestService:
    def __init__(
        self,
        cfg: R2D2Config,
        replay,
        host: str = "127.0.0.1",
        port: int = 0,
        version_source=None,
        audit_tail_len: int = 256,
    ):
        self.cfg = cfg
        self.replay = replay
        # callable returning the learner's current params_version (for
        # the per-block version-skew stamp); None stamps skew as 0
        self.version_source = version_source
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._listener.setblocking(False)
        # worker-thread-only state (single-writer: the select loop)
        self._peers: List[_Peer] = []
        self._host_seq: Dict[str, int] = {}  # per-host high-water mark
        self.supervisor: Optional[Supervisor] = None
        self._lock = threading.Lock()
        # counters + cross-thread hand-offs, guarded by _lock
        self.ingested_blocks = 0
        self.duplicate_blocks = 0
        self.accepted_conns = 0
        self.dead_peers = 0
        self.frame_errors = 0
        self.ckpts_broadcast = 0
        self.bytes_on_wire = 0  # BLOCK frame bytes as received (post-codec)
        self.bytes_decoded = 0  # same blocks re-encoded raw (pre-codec cost)
        self._pending_ckpt: Optional[bytes] = None
        self._lag_samples: deque = deque(maxlen=512)  # seconds
        self.audit_tail: deque = deque(maxlen=audit_tail_len)

    @property
    def address(self):
        return self._listener.getsockname()

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    # ----------------------------------------------------------- select loop

    def poll_once(self, timeout: float = 0.25) -> int:
        """One bounded pass: accept, read every ready peer, batch-ingest,
        ack, reap, broadcast a queued checkpoint. Returns blocks ingested
        this pass. The supervised worker body; also driven synchronously
        by tests."""
        socks = [self._listener] + [p.sock for p in self._peers]
        try:
            ready, _, _ = select.select(socks, [], [], timeout)
        except OSError:
            # a peer socket died between passes; reap and retry next call
            self._reap(force_dead=True)
            return 0
        ingested = 0
        batch = []  # (host, decoded) pairs admitted this pass
        for sock in ready:
            if sock is self._listener:
                self._accept()
                continue
            peer = next((p for p in self._peers if p.sock is sock), None)
            if peer is None:
                continue
            try:
                self._read_peer(peer, batch)
            except TRANSIENT_ERRORS:
                self._drop_peer(peer, dead=False)
        if batch:
            ingested = self._ingest(batch)
        self._reap()
        self._broadcast_pending()
        return ingested

    def _accept(self) -> None:
        try:
            fault_point("ingest.accept")
            sock, _ = self._listener.accept()
        except BlockingIOError:
            return
        sock.settimeout(self.cfg.transport_connect_timeout_s)
        with self._lock:
            self._peers.append(_Peer(sock))
            self.accepted_conns += 1

    def _read_peer(self, peer: _Peer, batch: List) -> None:
        """Drain every complete frame the peer has ready (the first read
        blocks only for an already-signaled socket)."""
        first = True
        while True:
            if not first:
                ready, _, _ = select.select([peer.sock], [], [], 0.0)
                if not ready:
                    return
            first = False
            ftype, payload = framing.recv_frame(peer.sock)
            peer.last_heard = time.monotonic()
            if ftype == framing.HELLO:
                hello = framing.decode_json(payload)
                if hello.get("proto") != framing.PROTO_VERSION:
                    raise framing.FrameError(
                        f"protocol version mismatch from {hello.get('host')}"
                    )
                peer.host = str(hello.get("host"))
                last = self._host_seq.get(peer.host, 0)
                # Codec negotiation: echo the publisher's requested wire
                # codec iff this binary knows it; an old publisher omits
                # the key and an old learner omits it from the ACK, so
                # both directions degrade to raw frames ("none").
                req = str(hello.get("codec", "none"))
                framing.send_frame(
                    peer.sock, framing.HELLO_ACK,
                    framing.encode_json({
                        "proto": framing.PROTO_VERSION,
                        "last_seq": last,
                        "codec": req if req in blockcodec.CODECS else "none",
                    }),
                )
            elif ftype == framing.BLOCK:
                if peer.host is None:
                    raise framing.FrameError("BLOCK before HELLO")
                cstats: Dict = {}
                decoded = framing.decode_block(payload, stats_out=cstats)
                fault_point("ingest.dedup")
                with self._lock:
                    self.bytes_on_wire += len(payload) + framing._HEADER.size
                    self.bytes_decoded += (
                        len(payload)
                        + cstats.get("obs_raw_bytes", 0)
                        - cstats.get("obs_enc_bytes", 0)
                    )
                    if decoded["seq"] <= self._host_seq.get(peer.host, 0):
                        self.duplicate_blocks += 1
                        decoded = None
                    else:
                        self._host_seq[peer.host] = decoded["seq"]
                if decoded is not None:
                    batch.append((peer, decoded))
            elif ftype == framing.HEARTBEAT:
                pass  # last_heard already refreshed
            else:
                raise framing.FrameError(
                    f"unexpected frame type {ftype} on ingest stream"
                )

    def _ingest(self, batch: List) -> int:
        """Fan one pass's admitted blocks into replay (one lock
        acquisition), then stamp skew/lag and ack every source host at
        its new high-water mark."""
        self.replay.add_blocks_batch(
            [(d["block"], d["priorities"], d["episode_reward"])
             for _, d in batch]
        )
        t_trainable = time.time()
        version = (
            int(self.version_source())
            if self.version_source is not None else 0
        )
        ack_to: Dict[str, _Peer] = {}
        with self._lock:
            for peer, d in batch:
                self.ingested_blocks += 1
                lag = max(t_trainable - d["t_serve"], 0.0)
                self._lag_samples.append(lag)
                vers = d["ver_stamps"]
                self.audit_tail.append({
                    "host": peer.host,
                    "seq": d["seq"],
                    "epsilon": d["eps_stamps"],
                    "params_version": vers,
                    "version_skew": (
                        version - int(vers.max()) if len(vers) else 0
                    ),
                    "ingest_lag_s": lag,
                })
                ack_to[peer.host] = (peer, self._host_seq[peer.host])
        for host, (peer, seq) in ack_to.items():
            try:
                framing.send_frame(
                    peer.sock, framing.ACK,
                    framing.encode_json({"seq": seq}),
                )
            except TRANSIENT_ERRORS:
                self._drop_peer(peer, dead=False)
        return len(batch)

    def _drop_peer(self, peer: _Peer, dead: bool) -> None:
        try:
            peer.sock.close()
        except OSError:
            pass
        with self._lock:
            if peer in self._peers:
                self._peers.remove(peer)
            if dead:
                self.dead_peers += 1

    def _reap(self, force_dead: bool = False) -> None:
        now = time.monotonic()
        limit = self.cfg.transport_dead_peer_s
        for peer in list(self._peers):
            broken = False
            if force_dead:
                # select refused the set: find the closed socket(s)
                try:
                    peer.sock.fileno()
                    select.select([peer.sock], [], [], 0.0)
                except OSError:
                    broken = True
            if broken or now - peer.last_heard > limit:
                self._drop_peer(peer, dead=True)

    # ---------------------------------------------------- checkpoint broadcast

    def broadcast_checkpoint(self, leaves, step: int, version: int) -> None:
        """Queue a CKPT frame for every connected host (any thread); the
        select loop ships it on its next pass. Only the newest queued
        checkpoint survives — a slow pass coalesces broadcasts, it never
        builds a backlog of stale params."""
        payload = framing.encode_ckpt(
            [np.asarray(x) for x in leaves], step, version
        )
        with self._lock:
            self._pending_ckpt = payload

    def _broadcast_pending(self) -> None:
        with self._lock:
            payload, self._pending_ckpt = self._pending_ckpt, None
        if payload is None:
            return
        for peer in list(self._peers):
            if peer.host is None:
                continue
            try:
                framing.send_frame(peer.sock, framing.CKPT, payload)
            except TRANSIENT_ERRORS:
                self._drop_peer(peer, dead=False)
        with self._lock:
            self.ckpts_broadcast += 1

    # --------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.supervisor = Supervisor()
        self.supervisor.spawn("transport-ingest", lambda: self.poll_once(0.25))

    def check(self) -> dict:
        return self.supervisor.check() if self.supervisor is not None else {}

    def stop(self) -> None:
        if self.supervisor is not None:
            self.supervisor.shutdown(timeout=5.0)
            self.supervisor = None
        for peer in list(self._peers):
            self._drop_peer(peer, dead=False)
        try:
            self._listener.close()
        except OSError:
            pass

    # ----------------------------------------------------------------- stats

    def lag_quantiles_ms(self) -> Dict[str, Optional[float]]:
        with self._lock:
            samples = np.asarray(self._lag_samples, np.float64)
        if samples.size == 0:
            return {"ingest_lag_p50_ms": None, "ingest_lag_p95_ms": None,
                    "ingest_lag_max_ms": None}
        ms = samples * 1e3
        return {
            "ingest_lag_p50_ms": round(float(np.percentile(ms, 50)), 3),
            "ingest_lag_p95_ms": round(float(np.percentile(ms, 95)), 3),
            "ingest_lag_max_ms": round(float(ms.max()), 3),
        }

    def stats(self) -> dict:
        with self._lock:
            out = {
                "ingest_blocks": self.ingested_blocks,
                "ingest_duplicate_blocks": self.duplicate_blocks,
                "ingest_accepted_conns": self.accepted_conns,
                "ingest_connected_hosts": sum(
                    1 for p in self._peers if p.host is not None
                ),
                "ingest_dead_peers": self.dead_peers,
                "ingest_ckpts_broadcast": self.ckpts_broadcast,
                "ingest_bytes_on_wire": self.bytes_on_wire,
                "ingest_bytes_decoded": self.bytes_decoded,
                "ingest_codec_ratio": round(
                    self.bytes_decoded / self.bytes_on_wire, 3
                ) if self.bytes_on_wire else 0.0,
                "ingest_host_seq": dict(self._host_seq),
            }
        out.update(self.lag_quantiles_ms())
        return out
