"""Task registry: env name -> dense task id + union geometry.

One shared network serves every task, so the registry computes the UNION
action space (max native action_dim; the model's per-task mask floors the
padding, models/r2d2.py) and requires a shared obs_shape — the functional
env families render at whatever geometry the config asks for (each
build_*_env factory takes obs_shape), so no padding plane is needed for
training. Per-task discounts come from the Agent57-style gamma ladder
(ops/epsilon.multitask_gamma_ladder) unless pinned explicitly.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.ops.epsilon import multitask_gamma_ladder

# launcher shorthand (sweep.py --multitask maze,drift,bandit) -> env names
TASK_ALIASES = {
    "maze": "keydoor",
    "keydoor": "keydoor",
    "drift": "drift",
    "bandit": "banditgrid",
    "banditgrid": "banditgrid",
    "catch": "catch",
}


class TaskSpec(NamedTuple):
    task_id: int
    name: str         # launcher alias ("maze") or the env name itself
    env_name: str     # full env name the factories parse
    action_dim: int   # NATIVE action count (<= union cfg.action_dim)
    gamma: float      # per-task discount (stored into replayed returns)


def resolve_task_names(spec: str) -> List[str]:
    """"maze,drift,bandit" -> env names, aliases resolved, order kept.
    Unknown names pass through verbatim (full env names like
    "keydoor:4:2" are legal task entries)."""
    names = [s.strip() for s in spec.split(",") if s.strip()]
    if not names:
        raise ValueError(f"no task names in {spec!r}")
    return [TASK_ALIASES.get(n.lower(), n) for n in names]


def _native_action_dim(cfg: R2D2Config, env_name: str) -> int:
    """The env's own action count at this config's geometry — read off the
    functional core so the registry can never drift from the factories."""
    from r2d2_tpu.train import build_fn_env

    return build_fn_env(cfg.replace(env_name=env_name)).NUM_ACTIONS


def build_registry(
    cfg: R2D2Config,
    names: Sequence[str],
    gammas: Optional[Sequence[float]] = None,
    gamma_min: float = 0.97,
) -> Tuple[R2D2Config, List[TaskSpec]]:
    """Resolve task names into (multi-task config, specs).

    The returned config carries num_tasks / multitask_envs /
    task_action_dims / task_gammas and the UNION action_dim; it has been
    validate()d, so every task's env geometry passed the per-family
    sanity checks (config._validate_env_geometry).
    """
    env_names = resolve_task_names(",".join(names)) if isinstance(names, str) else [
        TASK_ALIASES.get(n.lower(), n) for n in names
    ]
    T = len(env_names)
    if T < 1:
        raise ValueError("need at least one task")
    if len(set(env_names)) != T:
        raise ValueError(f"duplicate task envs in {env_names}")

    dims = [_native_action_dim(cfg, n) for n in env_names]
    union_a = max(dims)

    if gammas is None:
        # task 0 keeps the config's own horizon; later tasks step down the
        # log(1-gamma) ladder (Agent57's horizon spacing)
        g_max = cfg.gamma
        g_min = min(gamma_min, g_max)
        gammas = [float(g) for g in multitask_gamma_ladder(T, g_min, g_max)]
    else:
        gammas = [float(g) for g in gammas]
        if len(gammas) != T:
            raise ValueError(f"{len(gammas)} gammas for {T} tasks")

    out = cfg.replace(
        env_name=env_names[0],
        action_dim=union_a,
        num_tasks=T,
        multitask_envs=tuple(env_names),
        task_action_dims=tuple(dims),
        task_gammas=tuple(gammas),
    )
    out.validate()

    specs = [
        TaskSpec(task_id=t, name=env_names[t], env_name=env_names[t],
                 action_dim=dims[t], gamma=gammas[t])
        for t in range(T)
    ]
    return out, specs
