"""The serve loop: supervised batched acting with checkpoint hot-reload.

The inference-side counterpart of the training workers (SEED RL's
centralized inference, Espeholt et al. 2020): ONE thread owns the device
and the session cache, pulling micro-batches from the batcher, advancing
all sessions in a single jitted `net.act` step, and resolving each
request's Future with the chosen action. The supervised workers run under
`utils/supervision.Supervisor` exactly like the training-side actor loops:

- ``serve-loop``   — batch formation + STAGE (host assembly into the
  batcher's preallocated staging buffers, RNG draws in arrival order) +
  DISPATCH (the async jitted step and the donated in-place carry
  commit); a raising iteration fails only the in-flight batches' futures
  (recovery hook) and the loop restarts with the session cache intact;
- ``serve-complete`` — (cfg.serve_pipeline, the default) materializes
  each dispatched batch's q/action in dispatch order, resolves client
  futures, and feeds the tap, the degrade window, and metrics — so the
  serve thread stages and dispatches batch k+1 while the device still
  runs batch k. A depth-2 semaphore bounds how far staging runs ahead:
  same-session ordering and the staging buffers' double-buffer reuse
  both rely on batch k being complete before batch k+2 stages. With
  cfg.serve_pipeline=False there is no completion worker and the serve
  loop completes each batch inline — the strictly serial pre-pipeline
  path, bit-identical because both modes share one stage/dispatch body
  and the completion order is FIFO either way;
- ``ckpt-watcher`` — polls the orbax series (utils/checkpoint.py) and
  atomically publishes new params.

Hot reload is a single-attribute swap: params travel as one
``(params, ckpt_step, version, arm)`` tuple, read ONCE per batch, so every
request in a batch is answered by exactly one checkpoint — a reload
mid-traffic can never tear a batch across two param sets. In-flight
requests complete under the params they were batched with. The fourth
element is the degradation-ladder ARM ("full" | "bf16" | "int8",
serve/degrade.py): the same atomic cell that makes reloads tearless makes
arm fallback tearless — a batch runs entirely on one (params, arm) pair,
and the step function is selected per batch from the arm it read.

Bucketed shapes bound compilation: the jitted step retraces only when the
(bucket,) batch shape is new, and `trace_count` counts the retraces so
tests can pin traces <= len(buckets).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.learner import init_train_state
from r2d2_tpu.models.r2d2 import R2D2Network
from r2d2_tpu.serve.batcher import BucketStaging, MicroBatcher, ServeRequest, StagedBatch
from r2d2_tpu.serve.degrade import DegradeConfig, DegradeController
from r2d2_tpu.serve.state_cache import RecurrentStateCache
from r2d2_tpu.utils.checkpoint import latest_checkpoint_step, restore_checkpoint
from r2d2_tpu.utils.faults import Backoff, InjectedFault, fault_point, total_retries
from r2d2_tpu.utils.metrics import MetricsLogger
from r2d2_tpu.utils.supervision import Supervisor


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-plane knobs (the model/network config stays R2D2Config)."""

    buckets: Tuple[int, ...] = (2, 4, 8, 16, 32)
    max_wait_ms: float = 2.0
    queue_depth: int = 1024
    cache_capacity: int = 4096
    poll_interval_s: float = 0.5  # checkpoint watcher cadence
    epsilon: float = 0.0  # serving default: greedy
    max_restarts: int = 3
    seed: int = 0


class ServeResult:
    """One answered request: the action plus enough provenance (checkpoint
    step, params version, batch bucket) to audit which params produced it.
    `bucket` is the batch shape the request was actually served at — a
    reference replay that pads to the same bucket runs the very program
    shape the server compiled, which makes bit-parity structural instead
    of leaning on XLA's batch-size canonicalization."""

    __slots__ = ("action", "q", "ckpt_step", "params_version", "bucket")

    def __init__(self, action: int, q: np.ndarray, ckpt_step: int,
                 params_version: int, bucket: int = 0):
        self.action = action
        self.q = q
        self.ckpt_step = ckpt_step
        self.params_version = params_version
        self.bucket = bucket

    def __repr__(self) -> str:
        return (
            f"ServeResult(action={self.action}, ckpt_step={self.ckpt_step}, "
            f"params_version={self.params_version})"
        )


@dataclasses.dataclass
class _PipelineRecord:
    """One dispatched batch in flight between DISPATCH and COMPLETE.

    `q`/`action` are device arrays (futures under JAX async dispatch —
    `copy_to_host_async` was already started); `staged` pins the staging
    buffer set the batch was assembled in so the double-buffer flip
    cannot hand it back out before this record completes (the depth-2
    semaphore releases only after completion); `tap_rows` are the
    batch rows' committed carries, gathered at dispatch time on the
    serve thread so completion never touches stores a later donated
    step may already have consumed."""

    batch: List[ServeRequest]
    n: int
    bucket: int
    ckpt_step: int
    version: int
    arm: str
    q: object
    action: object
    staged: StagedBatch
    tap_rows: Optional[tuple]


_REF_JITS: Dict[R2D2Network, object] = {}


def _pad_obs(obs: np.ndarray, target: Tuple[int, ...]) -> np.ndarray:
    """Zero-pad one request's obs up to the serving geometry (mixed-shape
    multi-task families: a smaller task's rendering rides in the top-left
    corner of the union canvas, exactly where the training-side factories
    put it when asked to render AT the union shape)."""
    target = tuple(target)
    if obs.shape == target:
        return obs
    if obs.ndim != len(target) or any(s > t for s, t in zip(obs.shape, target)):
        raise ValueError(
            f"request obs shape {obs.shape} does not fit the serve "
            f"obs_shape {target}"
        )
    return np.pad(obs, [(0, t - s) for s, t in zip(obs.shape, target)])


def reference_act(net: R2D2Network, params, obs, last_action, last_reward, carry,
                  min_batch: int = 2, task=None):
    """The direct (unbatched-service) acting path tests compare against:
    one jitted `net.act` on exactly the given sessions, padded to
    `min_batch` rows. The pad matters twice over: XLA lowers batch-1
    acting through a matrix-vector path whose reduction order differs
    bitwise from the batched matmul path, and at aggressive-enough (or
    low-enough) backend optimization levels even two matmul batch shapes
    may lower with different reduction orders. Rows are independent and
    pad-content blind at ANY level, so padding to the EXACT bucket the
    server answered at (`ServeResult.bucket`) replays the same program
    shape the server compiled and makes bit-parity structural. The
    min_batch=2 default remains the canonical standalone reference at
    XLA's default optimization level.

    `task` ((B,) int32, multi-task serving only) conditions the head the
    same way the served path does; None is the single-task golden path.

    Returns (q (B, A), (h, c)) for the B real rows.
    """
    fn = _REF_JITS.get(net)
    if fn is None:
        fn = jax.jit(
            lambda p, o, la, lr, c, t: net.apply(
                p, o, la, lr, c, task=t, method=net.act
            )
        )
        _REF_JITS[net] = fn
    obs = jnp.asarray(obs)
    la = jnp.asarray(last_action, jnp.int32)
    lr = jnp.asarray(last_reward, jnp.float32)
    if task is not None:
        task = jnp.asarray(task, jnp.int32)
    h, c = carry
    B = obs.shape[0]
    pad = max(min_batch - B, 0)
    if pad:
        obs = jnp.concatenate([obs, jnp.zeros((pad, *obs.shape[1:]), obs.dtype)])
        la = jnp.concatenate([la, jnp.zeros((pad,), jnp.int32)])
        lr = jnp.concatenate([lr, jnp.zeros((pad,), jnp.float32)])
        h = jnp.concatenate([h, jnp.zeros((pad, h.shape[1]), h.dtype)])
        c = jnp.concatenate([c, jnp.zeros((pad, c.shape[1]), c.dtype)])
        if task is not None:
            task = jnp.concatenate([task, jnp.zeros((pad,), jnp.int32)])
    q, (h_out, c_out) = fn(params, obs, la, lr, (h, c), task)
    return q[:B], (h_out[:B], c_out[:B])


class PolicyServer:
    """Session-stateful batched policy service over a trained checkpoint.

    Lifecycle: construct (params explicit, or restored from the latest
    checkpoint under `checkpoint_dir`), `start()`, submit requests (or use
    a serve.client wrapper), `stop()`. `check()` surfaces supervisor
    restart/stall counters and raises if a worker died for good — call it
    from the owning loop exactly like Trainer does.
    """

    def __init__(
        self,
        cfg: R2D2Config,
        serve_cfg: ServeConfig = ServeConfig(),
        params=None,
        checkpoint_dir: Optional[str] = None,
        metrics: Optional[MetricsLogger] = None,
        device=None,
        mesh=None,
        name: str = "",
        step_cache: Optional[Dict[bool, object]] = None,
        net=None,
        template=None,
    ):
        self.cfg = cfg
        self.serve_cfg = serve_cfg
        self.checkpoint_dir = checkpoint_dir
        self.metrics = metrics
        # replica placement (serve/multi.py): params + session rows live on
        # exactly this device; None keeps jax's default (single-device)
        self.device = device
        # sharded placement: a Mesh routes every publish — including the
        # int8-quantized tree, whose q8/scale leaves inherit the kernel
        # rules — through parallel/sharding_map.serve_param_shardings, the
        # SAME wildcard table the learner shards from. Mutually exclusive
        # with `device` (one replica is either pinned or mesh-spread).
        if mesh is not None and device is not None:
            raise ValueError("pass device= or mesh=, not both")
        self.mesh = mesh
        # worker-name suffix so multi-device supervisors tell replicas apart
        self.name = name

        # `net`/`template` (serve/multi.py passes the fleet's) skip the
        # jitted model init: the net is stateless (params are call
        # arguments) and every replica of a fleet initializes an
        # identical one from the same seed anyway — re-running init in a
        # replica forked mid-traffic would stall the serving core on the
        # init compile for nothing
        if net is not None and template is not None:
            self.net, self._template = net, template
        else:
            self.net, self._template = init_train_state(
                cfg, jax.random.PRNGKey(serve_cfg.seed)
            )
        ckpt_step = -1
        if params is None:
            if checkpoint_dir is not None and latest_checkpoint_step(checkpoint_dir) is not None:
                state, _, _ = restore_checkpoint(checkpoint_dir, self._template)
                params, ckpt_step = state.params, int(state.step)
            else:
                params = self._template.params  # fresh init (smoke serving)
        # serve_quantization="int8": per-channel symmetric weight-only
        # quantization of the encoder/head kernels (ops/quantize.py),
        # applied ONCE per publish (here and at every hot reload) so the
        # jitted step dequantizes int8 weights in-jit instead of fetching
        # f32 kernels from HBM. Default "none" publishes params as-is.
        self.quantized_leaves = 0
        # guards the mutable serve-plane state shared between the serve
        # loop, the checkpoint watcher, the fleet reload path, and
        # stop()-from-main: the publish cell + its version counter, the
        # reload counters, and the in-flight batch handoff. The slow parts
        # of a publish (quantize, device_put) stay OUTSIDE this lock —
        # only the O(1) swap happens under it (prepare_for_publish /
        # install_prepared).
        self._state_lock = threading.Lock()
        # the atomic hot-reload cell: ONE attribute holding ONE tuple, read
        # once per batch — Python attribute reads are atomic, so a batch
        # sees exactly one (params, step, version, arm), never a mix. The
        # arm rides in the same cell so a degrade-ladder fallback is as
        # tearless as a reload (indices 0-2 are unchanged for readers that
        # predate the arm, e.g. analysis/jaxpr_rules.py).
        self._published: Tuple[object, int, int, str] = (None, ckpt_step, -1, "full")
        # raw (pre-quantize, host-or-wherever) params the arms re-prepare
        # from: a bf16->int8 fallback must not re-round already-cast leaves
        self._params_raw = params
        self.arm_switches = 0
        self.publish(params, ckpt_step, version=0)

        if serve_cfg.cache_capacity < max(serve_cfg.buckets):
            # a batch's own admissions must never evict a co-batched
            # session (two rows sharing a slot): with capacity >= max
            # bucket, the LRU front is always a non-batch session
            raise ValueError(
                f"cache_capacity ({serve_cfg.cache_capacity}) must be >= the "
                f"largest batch bucket ({max(serve_cfg.buckets)})"
            )
        # carries cache at cfg.state_dtype (bf16 under precision="bf16"):
        # half the per-session HBM and gather/scatter bytes per batch.
        # cfg.serve_spill > 0 adds the host spill tier: evicted sessions
        # demote to a host-RAM slab and promote back carry-intact.
        self.cache = RecurrentStateCache(
            serve_cfg.cache_capacity, cfg.hidden_dim, dtype=cfg.state_dtype,
            spill_capacity=cfg.serve_spill, device=device,
        )
        self.batcher = MicroBatcher(
            buckets=serve_cfg.buckets,
            max_wait_s=serve_cfg.max_wait_ms / 1000.0,
            queue_depth=serve_cfg.queue_depth,
        )
        self._rng = np.random.default_rng(serve_cfg.seed)
        # preallocated per-bucket staging buffers (serve/batcher.py): batch
        # assembly writes into these instead of allocating per batch. Two
        # sets per bucket, flipped per staging — with the depth-2 pipeline
        # bound, a set is never re-staged before the batch that used it
        # fully completed.
        self._staging = BucketStaging(serve_cfg.buckets, num_tasks=cfg.num_tasks)
        # the pipeline depth bound: acquired before a batch stages,
        # released after it completes. Depth 2 = one batch on the device +
        # one staged/dispatched behind it.
        self._depth_sem = threading.Semaphore(2)
        # stage/dispatch -> complete handoff (FIFO preserves dispatch
        # order, which is completion order)
        self._complete_q: "queue.Queue[_PipelineRecord]" = queue.Queue()
        self._complete_worker = None
        self.completed_batches = 0
        # deferred serve metrics (cfg.serve_log_interval > 0): batches that
        # skipped the metrics row, so rates stay computable from the rows
        # that did log
        self.metrics_skipped = 0
        self._metrics_last_t = float("-inf")
        self._metrics_last_arm: Optional[str] = None
        self._metrics_last_version: Optional[int] = None
        # hoisted once: per-task action dims for native exploration draws
        self._task_dims = (
            np.asarray(cfg.task_action_dims, np.int64)
            if cfg.task_action_dims else None
        )
        # live-loop capture hooks (liveloop/loop.py installs both; None —
        # the default — keeps _run_batch byte-for-byte the pre-liveloop
        # path): tap records served batches, eps_assigner maps sessions
        # to sticky exploration epsilons
        self.tap = None
        self.eps_assigner = None
        self.trace_count = 0  # python-body counter: +1 per jit trace
        self.reloads = 0
        self.reload_errors = 0
        # watcher poll escalation on transient reload failures (checkpoint
        # dir not mounted yet, step pruned between list and restore): back
        # off instead of hammering the fs at poll_interval_s
        self._watch_backoff = Backoff(
            base=serve_cfg.poll_interval_s, factor=2.0,
            max_delay=max(30.0, serve_cfg.poll_interval_s),
        )
        self._inflight: List[ServeRequest] = []
        # jitted steps by their one trace-relevant switch (in-jit dequant
        # or not); built lazily so the default config compiles exactly the
        # steps it always did. self._step tracks the last-selected one.
        # `step_cache` (serve/multi.py passes a fleet-level dict) SHARES
        # this cache across a fleet's replicas: replicas are structural
        # clones — same config, same net architecture, and every piece of
        # per-replica state (params, session stores, staging) enters the
        # step as a call argument, never closure state — so a replica the
        # autoscaler forks mid-traffic warms against the fleet's already
        # traced + compiled executables instead of stealing the serving
        # cores for a fresh trace/compile of identical programs.
        self._steps: Dict[bool, object] = (
            step_cache if step_cache is not None else {}
        )
        self._step = self._step_for(self._published[3])

        # degradation ladder (serve/degrade.py): default OFF — no
        # controller object, no admission watermark, no observe() calls,
        # the serve plane byte-for-byte as before. A fleet overrides
        # .degrade with ONE shared controller and owns its worker.
        self.degrade: Optional[DegradeController] = None
        self._degrade_owner = False
        # extra per-request latency observers (objects with .observe(s)) —
        # the autoscaler installs its own SignalWindow here when it runs
        # without a degrade ladder to share one with
        self._latency_sinks: tuple = ()
        if cfg.serve_degrade:
            self.degrade = DegradeController(
                self, DegradeConfig(slo_ms=cfg.serve_degrade_slo_ms)
            )
            self._degrade_owner = True

        self.supervisor: Optional[Supervisor] = None
        self._serve_worker = None
        self._watch_worker = None

    # ------------------------------------------------------------ jit step

    def prepare_for_publish(self, params, arm: Optional[str] = None):
        """The slow half of a publish, safe to run with NO lock held:
        the arm's weight transform (int8 quantization / weight-only bf16
        cast) plus the H2D placement onto this replica's device. Returns
        an opaque staged triple for install_prepared. The fleet reload
        path stages every replica with this before touching its reload
        lock so serving never stalls behind a device transfer.

        `arm` is the degradation-ladder rung's weight format (None keeps
        the currently published arm): "full" is the config's own behavior
        (int8 under serve_quantization="int8", verbatim otherwise);
        "bf16" casts float leaves to bfloat16 — the model's own dtype
        promotion upcasts at compute, so only weight rounding drifts;
        "int8" quantizes regardless of config."""
        if arm is None:
            arm = self._published[3]
        leaves = 0
        if arm == "int8" or (arm == "full" and self.cfg.serve_quantization == "int8"):
            from r2d2_tpu.ops.quantize import quantize_tree

            params, leaves = quantize_tree(params)
        elif arm == "bf16":
            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
                params,
            )
        elif arm != "full":
            raise ValueError(f"unknown serve arm {arm!r}")
        if self.mesh is not None:
            from r2d2_tpu.parallel.sharding_map import serve_param_shardings

            params = jax.device_put(
                params, serve_param_shardings(params, self.mesh))
        elif self.device is not None:
            params = jax.device_put(params, self.device)
        return params, leaves, arm

    def install_prepared(self, prepared, ckpt_step: int,
                         version: Optional[int] = None,
                         raw_params=None) -> None:
        """The O(1) lock-held tail of a publish: swap the publish cell
        (one tuple write) and bump the version. No device work, no I/O.
        `raw_params` (the fleet reload path) refreshes the pre-transform
        params the arms re-prepare from."""
        prepared_params, leaves, arm = prepared
        with self._state_lock:
            self.quantized_leaves = leaves
            if raw_params is not None:
                self._params_raw = raw_params
            if version is None:
                version = self._published[2] + 1
            self._published = (prepared_params, int(ckpt_step), version, arm)

    def publish(self, params, ckpt_step: int, version: Optional[int] = None,
                arm: Optional[str] = None) -> None:
        """Atomically publish a param set to this server/replica: prepare
        (the arm's weight transform), place on this replica's device —
        both outside the state lock — then swap the publish cell in ONE
        guarded write. The multi-device server stages all replicas via
        prepare_for_publish and installs with an explicit shared version
        so the fleet advances in lockstep."""
        self.install_prepared(
            self.prepare_for_publish(params, arm), ckpt_step, version,
            raw_params=params,
        )

    def set_arm(self, arm: str, params=None) -> bool:
        """Switch the degradation-ladder arm: re-prepare the RAW params
        under the new arm (outside all locks — quantize/cast + H2D) and
        swap the publish cell, preserving ckpt_step and bumping the
        version. No-op (False) when the arm is already live. Called by
        the degrade controller and the bench matrix; safe against a
        concurrent reload — whichever swap lands second wins the cell,
        and both are internally consistent (params, arm) pairs."""
        if arm == self._published[3]:
            return False
        raw = self._params_raw if params is None else params
        prepared = self.prepare_for_publish(raw, arm)
        with self._state_lock:
            ckpt_step = self._published[1]
        self.install_prepared(prepared, ckpt_step)
        with self._state_lock:
            self.arm_switches += 1
        return True

    # -------------------------------------------------- degrade surface
    # (serve/degrade.py drives these; MultiDeviceServer mirrors them)

    @property
    def queue_bound(self) -> int:
        return self.serve_cfg.queue_depth

    def queue_depth(self) -> int:
        return self.batcher.qsize()

    def set_admission(self, limit: Optional[int], budget: int = 0) -> None:
        self.batcher.set_admission(limit, budget=budget)

    def shed_spill(self, keep_fraction: float) -> int:
        return self.cache.shed_spill(keep_fraction)

    def _step_for(self, arm: str):
        """The jitted step matching an arm's published weight format.
        Only ONE switch is trace-relevant — whether the step dequantizes
        in-jit — so "full" and "bf16" share a step (bf16 leaves flow
        through the same graph at their own dtype) and the default config
        never builds more than it used to. Also updates self._step so
        external introspection (analysis/jaxpr_rules.py) always sees the
        step that last served traffic."""
        quantized = arm == "int8" or (
            arm == "full" and self.cfg.serve_quantization == "int8"
        )
        # warmup (main) and the serve loop both reach this cache; building
        # a step is cheap (jit wrapping is lazy — compilation happens at
        # the first call, outside the lock)
        with self._state_lock:
            fn = self._steps.get(quantized)
            if fn is None:
                fn = self._steps[quantized] = self._build_step(quantized)
            self._step = fn
        return fn

    def _build_step(self, quantized: bool):
        net = self.net

        def step(params, h_store, c_store, la_store, lr_store,
                 obs, rewards, slots, reset_mask, explore_mask, random_actions,
                 task=None):
            # runs once per TRACE (new bucket shape), not per call; a
            # metrics counter bumped at trace time — a lock can't live in
            # a traced function, and a lost increment under a concurrent
            # warmup/serve trace only undercounts a gauge
            self.trace_count += 1  # r2d2: disable=cross-thread-unguarded-write
            if quantized:
                # in-jit dequant: XLA fuses the i8->f32 convert + scale
                # multiply into the consuming matmuls (ops/quantize.py)
                from r2d2_tpu.ops.quantize import dequantize_tree

                params = dequantize_tree(params)
            h = h_store[slots]
            c = c_store[slots]
            la = la_store[slots]
            zero = reset_mask[:, None]
            h = jnp.where(zero, 0.0, h)
            c = jnp.where(zero, 0.0, c)
            la = jnp.where(reset_mask, 0, la)
            lr = jnp.where(reset_mask, 0.0, rewards)
            # fused act tail: dueling combine + ε-mask + argmax in one op
            # with the core step (models/r2d2.py act_select)
            q, action, (h_new, c_new) = net.apply(
                params, obs, la, lr, (h, c), explore_mask, random_actions,
                task=task, method=net.act_select,
            )
            # scatter back: pad rows all target the scratch slot (their
            # writes collide there harmlessly; real slots are unique by the
            # batcher's one-session-per-batch rule)
            # explicit downcast to the cache dtype (act may compute at a
            # wider dtype than the bf16 store holds)
            h_store = h_store.at[slots].set(h_new.astype(h_store.dtype))
            c_store = c_store.at[slots].set(c_new.astype(c_store.dtype))
            la_store = la_store.at[slots].set(action)
            lr_store = lr_store.at[slots].set(lr)
            return q, action, h_store, c_store, la_store, lr_store

        # donating the session stores lets XLA update them in place; on CPU
        # the donation is unsupported (warning noise) so it is gated off
        donate = () if jax.default_backend() == "cpu" else (1, 2, 3, 4)
        return jax.jit(step, donate_argnums=donate)

    # ------------------------------------------------------------- serving

    def submit(self, session_id: str, obs, reward: float = 0.0,
               reset: bool = False, epsilon: Optional[float] = None,
               task: int = 0) -> Future:
        return self.batcher.submit(
            session_id, obs, reward=reward, reset=reset, epsilon=epsilon,
            task=task,
        )

    def reset_session(self, session_id: str) -> None:
        self.cache.reset(session_id)

    def evict(self, session_id: str) -> None:
        """Disconnect: free the session's HBM slot and any spill row.
        Same surface as MultiDeviceServer.evict so clients (LocalClient,
        the TCP handler) work against either server unchanged."""
        self.cache.evict(session_id)
        if self.eps_assigner is not None:
            self.eps_assigner.forget(session_id)
        if self.tap is not None:
            self.tap.observe_evict(session_id)

    def _run_batch(self, batch: List[ServeRequest]) -> None:
        # the batch joins the in-flight set BEFORE any work: a crash
        # anywhere past this line reaches _serve_recover, which fails these
        # futures so no client blocks forever
        with self._state_lock:
            self._inflight = self._inflight + list(batch)
        if self.cfg.serve_pipeline and self._complete_worker is not None:
            # depth bound: at most 2 batches between stage and complete.
            # Bounded waits so a wedged completion worker cannot pin this
            # thread through a shutdown.
            while not self._depth_sem.acquire(timeout=0.25):
                if self.supervisor is not None and self.supervisor.stop.is_set():
                    raise RuntimeError("server stopping; batch not staged")
            try:
                rec = self._stage_and_dispatch(batch)
            except BaseException:
                self._depth_sem.release()
                raise
            self._complete_q.put(rec)
        else:
            # serial path (cfg.serve_pipeline=False, or a bare _run_batch
            # with no completion worker running): same stage/dispatch body,
            # completed inline — the strictly serial pre-pipeline loop
            rec = self._stage_and_dispatch(batch)
            self._complete(rec)

    def _stage_and_dispatch(self, batch: List[ServeRequest]) -> _PipelineRecord:
        """STAGE + DISPATCH, on the serve thread: assemble the batch into
        the preallocated staging buffers (RNG draws at stage time in
        arrival order — the exact stream the serial path consumes), then
        dispatch the async jitted step and commit the donated carry
        stores. Host-blocking materialization is banned here (the
        `blocking-host-sync-in-serve-step` lint enforces it); everything
        that must wait on the device lives in _complete."""
        # single read of the publish cell: the whole batch — and the
        # results' provenance — come from one (params, arm) pair; a reload
        # landing between stage and complete changes NOTHING for this
        # batch (mid-pipeline provenance invariant)
        params, ckpt_step, version, arm = self._published
        step_fn = self._step_for(arm)
        n = len(batch)
        bucket = self.batcher.bucket_for(n)
        slots, fresh = self.cache.assign([r.session_id for r in batch])

        obs_rows = [r.obs for r in batch]
        target = tuple(self.cfg.obs_shape)
        if any(o.shape != target for o in obs_rows):
            # mixed-shape task interleaving (multi-task serving): pad every
            # row to the union geometry the compiled step expects, so one
            # bucket serves the whole family without per-shape retraces
            obs_rows = [_pad_obs(o, target) for o in obs_rows]
        # zero-copy assembly: single vectorized writes into this bucket's
        # staging set (obs stack, rewards, reset|fresh, slots, task) —
        # no per-batch np.stack/np.concatenate allocs, no per-row loops
        staged = self._staging.stage(batch, bucket, obs_rows, self.serve_cfg.epsilon)
        # a row starts from zero state when the client asked for a reset OR
        # the cache admitted it fresh (new session, or evicted + returned);
        # pad rows were pre-set to reset so the scratch row never compounds
        staged.reset_mask[:n] |= fresh
        staged.slots[:n] = slots
        staged.slots[n:] = self.cache.pad_slot
        # per-row exploration: request override > per-session assignment
        # (liveloop's ladder) > the ServeConfig.epsilon fleet default.
        # RNG discipline keeps the legacy stream bit-exact: the coin and
        # random-action draws happen iff ANY row explores, in the same
        # order and count as the old scalar path — all-zero rows (the
        # default config) draw nothing, a uniform fleet epsilon draws
        # exactly what it used to. epsilon_for runs in arrival order
        # (sticky ladder rungs assign on first call).
        assigner = self.eps_assigner
        if assigner is not None:
            staged.eps[:n] = [
                r.epsilon if r.epsilon is not None
                else assigner.epsilon_for(r.session_id)
                for r in batch
            ]
        elif any(r.epsilon is not None for r in batch):
            staged.eps[:n] = [
                self.serve_cfg.epsilon if r.epsilon is None else r.epsilon
                for r in batch
            ]
        if float(staged.eps.max()) > 0.0:
            staged.explore[:] = self._rng.random(bucket) < staged.eps
            if staged.task is not None and self._task_dims is not None:
                # exploration stays NATIVE per row: a drawn action must be
                # legal for the row's task, not just the union head
                staged.randoms[:] = self._rng.integers(
                    0, self._task_dims[staged.task]
                )
            else:
                staged.randoms[:] = self._rng.integers(
                    0, self.cfg.action_dim, bucket
                )

        h, c, la, lr = self.cache.arrays()
        step_args = [
            params, h, c, la, lr,
            jnp.asarray(staged.obs), jnp.asarray(staged.rewards),
            jnp.asarray(staged.slots), jnp.asarray(staged.reset_mask),
            jnp.asarray(staged.explore),
            jnp.asarray(staged.randoms, jnp.int32),
        ]
        if staged.task is not None:
            step_args.append(jnp.asarray(staged.task))
        q, action, h, c, la, lr = step_fn(*step_args)
        # JAX async dispatch: q/action come back as futures. Start the D2H
        # copy NOW so it overlaps the remaining dispatch work and the next
        # batch's staging; _complete's materialization then finds the
        # bytes already on host (or waits the residue).
        if hasattr(q, "copy_to_host_async"):
            q.copy_to_host_async()
            action.copy_to_host_async()
        # stores commit at DISPATCH time, before the next batch can stage:
        # a same-session follow-up (only admissible in a later batch)
        # gathers from these arrays, and the device stream orders the
        # donated in-place update ahead of any later step that reads it
        self.cache.commit(h, c, la, lr)
        tap_rows = None
        if self.tap is not None:
            # gather the batch rows' committed carries HERE, on the serve
            # thread: on donating backends batch k's stores are consumed
            # by step k+1, so a completion-time gather could read freed
            # buffers. The gather is itself async — dispatch-ordered after
            # the commit, materialized by the tap/completion side.
            tap_rows = self.tap.gather_rows(h, c, staged.slots[:n])
        return _PipelineRecord(
            batch=batch, n=n, bucket=bucket, ckpt_step=ckpt_step,
            version=version, arm=arm, q=q, action=action, staged=staged,
            tap_rows=tap_rows,
        )

    def _complete(self, rec: _PipelineRecord) -> None:
        """COMPLETE: materialize q/action (the only host-blocking reads in
        the serve path), resolve client futures, retire the batch from the
        in-flight set, and feed the tap, the degrade window, and metrics.
        Runs on the serve-complete worker (pipelined), or inline on the
        serve thread (serial); records arrive in dispatch order either
        way."""
        q_np = np.asarray(rec.q)
        act_np = np.asarray(rec.action)
        t_done = time.monotonic()
        for i, r in enumerate(rec.batch):
            # .done() guard: _serve_recover may have failed these futures
            # after a serve-loop crash while this record was still queued
            if not r.future.done():
                r.future.set_result(
                    ServeResult(int(act_np[i]), q_np[i], rec.ckpt_step,
                                rec.version, bucket=rec.bucket)
                )
        with self._state_lock:
            done = set(map(id, rec.batch))
            self._inflight = [r for r in self._inflight if id(r) not in done]
            self.completed_batches += 1
        n = rec.n
        if self.tap is not None:
            # live-loop capture, after the clients have their answers. The
            # staging buffers are REUSED (double-buffered), so the tap gets
            # copies of the buffer-backed rows — its records must survive
            # the next staging of this bucket — plus the carry rows
            # pre-gathered at dispatch time
            staged = rec.staged
            self.tap.observe_batch(
                [r.session_id for r in rec.batch],
                staged.obs[:n].copy(), act_np[:n], q_np[:n],
                staged.rewards[:n].copy(), staged.reset_mask[:n].copy(),
                staged.eps[:n].copy(), rec.ckpt_step, rec.version,
                None, None, staged.slots[:n].copy(), rows=rec.tap_rows,
            )
        if self.degrade is not None or self._latency_sinks:
            # feed the ladder's latency window and any extra sinks (per
            # answered request, the same queue-to-resolve latency clients
            # experience)
            sinks = self._latency_sinks
            for r in rec.batch:
                lat = t_done - r.t_enqueue
                if self.degrade is not None:
                    self.degrade.observe(lat)
                for s in sinks:
                    s.observe(lat)
        if self.metrics is not None:
            self._log_serve_metrics(rec, t_done)

    def _log_serve_metrics(self, rec: _PipelineRecord, t_done: float) -> None:
        """Deferred serve metrics: the full stats dict (queue probe +
        cache.stats()) is built only when a row is due —
        cfg.serve_log_interval=0.0 (default) logs every batch, the
        pre-pipeline behavior; a positive interval logs on that cadence
        plus forced rows on every arm change and reload (version bump) so
        provenance edges are never silent. Skipped batches are counted so
        rates stay computable between rows."""
        interval = self.cfg.serve_log_interval
        with self._state_lock:
            force = (
                rec.arm != self._metrics_last_arm
                or rec.version != self._metrics_last_version
            )
            due = interval <= 0.0 or (t_done - self._metrics_last_t) >= interval
            if not (due or force):
                self.metrics_skipped += 1
                return
            self._metrics_last_t = t_done
            self._metrics_last_arm = rec.arm
            self._metrics_last_version = rec.version
            completed = self.completed_batches
            skipped = self.metrics_skipped
        # the dict build (batcher/cache probes take their own locks) stays
        # OUTSIDE the state lock
        self.metrics.log(
            {
                "plane": "serve",
                "batch_occupancy": rec.n,
                "bucket": rec.bucket,
                "queue_depth": self.batcher.qsize(),
                "latency_s_oldest": t_done - rec.batch[0].t_enqueue,
                "ckpt_step": rec.ckpt_step,
                "params_version": rec.version,
                "serve_arm": rec.arm,
                "reloads": self.reloads,
                "trace_count": self.trace_count,
                "completed_batches": completed,
                "metrics_skipped": skipped,
                **self.cache.stats(),
            }
        )

    def _fail_record(self, rec: _PipelineRecord) -> None:
        """Completion-side recovery: retire a record whose completion
        raised, failing any still-unresolved futures so clients retry.
        Session state is safe — the carry committed at dispatch."""
        with self._state_lock:
            dead = set(map(id, rec.batch))
            self._inflight = [r for r in self._inflight if id(r) not in dead]
        for r in rec.batch:
            if not r.future.done():
                r.future.set_exception(
                    RuntimeError("serve completion failed; retry the request")
                )

    def _complete_iteration(self) -> None:
        """Supervised serve-complete worker body: complete one dispatched
        batch (bounded wait so shutdown never blocks). The depth slot is
        released in ALL cases — a record either completes or is failed,
        never left holding pipeline depth."""
        try:
            rec = self._complete_q.get(timeout=0.25)
        except queue.Empty:
            return
        try:
            self._complete(rec)
        except BaseException:
            self._fail_record(rec)
            raise
        finally:
            self._depth_sem.release()

    def _serve_iteration(self) -> None:
        # straggler-replica drill: a "stall:S" schedule here wedges THIS
        # replica's serve loop (queue backs up, co-replicas keep serving);
        # an "error" exercises the supervised-restart path
        fault_point("serve.replica_stall")
        batch = self.batcher.next_batch(timeout=0.25)
        if batch:
            self._run_batch(batch)

    def _degrade_iteration(self) -> None:
        """Supervised degrade-controller body: one bounded evaluation
        tick, then wait out the cadence on the stop event."""
        self.degrade.evaluate_once()
        if self.supervisor is not None:
            self.supervisor.stop.wait(self.degrade.cfg.eval_interval_s)
        else:
            time.sleep(self.degrade.cfg.eval_interval_s)

    def _serve_recover(self) -> None:
        """Restart hook: fail the in-flight batch's futures so no client
        blocks forever on a crashed iteration. The session cache needs no
        repair — stores only commit after a fully successful step, so a
        crash leaves every session at its last committed state and a
        client retry re-runs from exactly there."""
        with self._state_lock:
            inflight, self._inflight = self._inflight, []
        for r in inflight:
            if not r.future.done():
                r.future.set_exception(
                    RuntimeError("serve iteration failed; retry the request")
                )

    # ----------------------------------------------------------- hot reload

    def _watch_iteration(self) -> None:
        # bounded work per call (supervision contract): one poll, then wait
        try:
            self.reload_now()
        except (OSError, InjectedFault):
            # transient fs trouble: the step vanished between listing and
            # restore (series advanced, retention pruned it —
            # FileNotFoundError), or the checkpoint dir itself is briefly
            # unreachable (remount, NFS hiccup). Count it and re-poll with
            # exponential backoff; the next successful reload resets the
            # cadence.
            with self._state_lock:
                self.reload_errors += 1
            wait = self._watch_backoff.fail()
        else:
            self._watch_backoff.reset()
            wait = self.serve_cfg.poll_interval_s
        if self.supervisor is not None:
            self.supervisor.stop.wait(wait)
        else:
            time.sleep(wait)

    def reload_now(self) -> bool:
        """One synchronous reload check (the watcher body; also usable
        directly by tests and watcher-less servers). Returns True if new
        params were published."""
        fault_point("serve.reload")
        step = latest_checkpoint_step(self.checkpoint_dir)
        if step is None or step == self._published[1]:
            return False
        state, _, _ = restore_checkpoint(self.checkpoint_dir, self._template, step)
        self.publish(state.params, int(state.step))
        with self._state_lock:
            self.reloads += 1
        return True

    # ------------------------------------------------------------ lifecycle

    def warmup(self) -> None:
        """Pre-trace every bucket shape with pad-only batches so live
        traffic never waits on a compile. Writes touch only the scratch
        row, so session state is untouched. The staging buffers warm
        alongside the compiles: a replica the autoscaler adds mid-traffic
        enters the rotation with no first-batch allocations left to pay.

        With a degrade ladder attached, the quality arms' executables
        warm too — bf16 is a new dtype signature, int8 a new (in-jit
        dequant) step — because an arm switch fires UNDER overload by
        definition: a switch that stalls the serving core on a fresh
        trace+compile mid-crest is a worse latency cliff than the
        pressure it answers. The trace budget is then arms x buckets
        (analysis/jaxpr_rules.check_trace_budget's `arms`); the warm
        params are staged copies, dropped after warmup — the publish
        cell never moves."""
        self._staging.warm(self.cfg.obs_shape, np.uint8)
        params, _, _, arm = self._published
        warm_arms = [(arm, params)]
        if self.degrade is not None:
            for rung_arm in ("bf16", "int8"):
                if rung_arm != arm:
                    p, _, _ = self.prepare_for_publish(
                        self._params_raw, rung_arm
                    )
                    warm_arms.append((rung_arm, p))
        for warm_arm, warm_params in warm_arms:
            step_fn = self._step_for(warm_arm)
            for bucket in self.batcher.buckets:
                obs = np.zeros((bucket, *self.cfg.obs_shape), np.uint8)
                h, c, la, lr = self.cache.arrays()
                warm_args = [
                    warm_params, h, c, la, lr,
                    jnp.asarray(obs), jnp.zeros(bucket, jnp.float32),
                    jnp.full(bucket, self.cache.pad_slot, jnp.int32),
                    jnp.ones(bucket, bool), jnp.zeros(bucket, bool),
                    jnp.zeros(bucket, jnp.int32),
                ]
                if self.cfg.num_tasks > 1:
                    warm_args.append(jnp.zeros(bucket, jnp.int32))
                out = step_fn(*warm_args)
                q, action, h, c, la, lr = out
                jax.block_until_ready(q)
                # commit: on donating backends the old stores were consumed
                self.cache.commit(h, c, la, lr)
        # leave the published arm as the last-selected step (analysis
        # introspection reads self._step)
        self._step_for(arm)

    def start(self, watch_checkpoints: Optional[bool] = None) -> None:
        if self.supervisor is not None:
            raise RuntimeError("server already started")
        if watch_checkpoints is None:
            watch_checkpoints = self.checkpoint_dir is not None
        self.supervisor = Supervisor()
        # lambda indirection so tests can monkeypatch _serve_iteration and
        # exercise the restart path on the live worker
        suffix = f"-{self.name}" if self.name else ""
        if self.cfg.serve_pipeline:
            # spawned BEFORE the serve loop so the first batch already
            # sees a completion worker and takes the pipelined path
            self._complete_worker = self.supervisor.spawn(
                "serve-complete" + suffix,
                lambda: self._complete_iteration(),
                max_restarts=self.serve_cfg.max_restarts,
            )
        self._serve_worker = self.supervisor.spawn(
            "serve-loop" + suffix,
            lambda: self._serve_iteration(),
            max_restarts=self.serve_cfg.max_restarts,
            on_restart=self._serve_recover,
        )
        if watch_checkpoints:
            self._watch_worker = self.supervisor.spawn(
                "ckpt-watcher" + suffix,
                lambda: self._watch_iteration(),
                max_restarts=self.serve_cfg.max_restarts,
            )
        if self.degrade is not None and self._degrade_owner:
            # only the controller's OWNER spawns its worker: fleet
            # replicas share the fleet's controller and must not run
            # N competing evaluation loops against it
            self.supervisor.spawn(
                "degrade-controller" + suffix,
                lambda: self._degrade_iteration(),
                max_restarts=self.serve_cfg.max_restarts,
            )

    def check(self) -> Dict[str, int]:
        """Supervisor passthrough: restart/stall counters for the metrics
        stream; raises WorkerFatalError when a worker is out of restarts."""
        if self.supervisor is None:
            return {"worker_restarts": 0, "worker_stalls": 0}
        return self.supervisor.check()

    def stop(self, timeout: float = 5.0) -> None:
        if self.supervisor is not None:
            self.supervisor.shutdown(timeout)
            self.supervisor = None
        self._complete_worker = None
        # drain the pipeline: records the completion worker never reached
        # are completed inline — their steps already dispatched, so their
        # clients still deserve answers (falling back to _fail_record only
        # if completion itself raises)
        while True:
            try:
                rec = self._complete_q.get_nowait()
            except queue.Empty:
                break
            try:
                self._complete(rec)
            except Exception:
                self._fail_record(rec)
            finally:
                self._depth_sem.release()
        for r in self.batcher.drain():
            if not r.future.done():
                r.future.set_exception(RuntimeError("server stopped"))
        self._serve_recover()  # anything mid-batch when the loop stopped

    def stats(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "reloads": self.reloads,
            "reload_errors": self.reload_errors,
            "io_retries": total_retries(),
            "trace_count": self.trace_count,
            "ckpt_step": self._published[1],
            "params_version": self._published[2],
            "serve_arm": self._published[3],
            "arm_switches": self.arm_switches,
            "serve_quantization": self.cfg.serve_quantization,
            "quantized_leaves": self.quantized_leaves,
            "completed_batches": self.completed_batches,
            "metrics_skipped": self.metrics_skipped,
            # dispatched-not-yet-completed requests: with the queue depth
            # and last_request_age_s (batcher stats) this is the idle
            # signal triplet the autoscaler's drain decision reads
            "inflight_depth": len(self._inflight),
        }
        out.update(self.batcher.stats())
        out.update(self.cache.stats())
        if self.eps_assigner is not None:
            out.update(self.eps_assigner.stats())
        if self.tap is not None:
            out.update(self.tap.stats())
        if self.degrade is not None and self._degrade_owner:
            out.update(self.degrade.stats())
        return out
