#!/bin/bash
# Round-4 chain C: measurement leftovers, after chain B drains.
# - measure_mfu.py was wedged in chain B by the tunneled backend's AOT
#   compile/cost RPC; the fixed script reads the pre-compile cost model
#   in a CPU-pinned child and times the dispatch via the plain jit path.
# - bench_core_unroll re-run gains the lru-c128 chunked-MXU row (the
#   in-flight chain B script predated the insertion; bash reads scripts
#   lazily, so the edit was skipped — never edit a running script).
cd /root/repo
while ! grep -q R4B_CHAIN_ALL_DONE runs/r4b_chain.log 2>/dev/null; do sleep 60; done

python runs/measure_mfu.py --out runs/mfu.json
echo "=== MFU EXIT: $? ==="
python runs/bench_core_unroll.py --out runs/core_unroll_r4.jsonl
echo "=== CORE_UNROLL_R4 EXIT: $? ==="

echo R4C_CHAIN_ALL_DONE
