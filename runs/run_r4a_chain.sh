#!/bin/bash
# Round-4 chain A: the two runs the round-3 verdict ranked first.
#
# 1) r3j, unblocked: long_context_mid with recurrent_core=lru. The LSTM
#    run peaked clearly above chance (-0.19 at 9k vs random ~-0.9,
#    runs/long_context_mid) then regressed; the LRU core solved both the
#    mid-scale memory task (7x fewer updates than LSTM) and the 84x84
#    wall, so it is the designed retry. Config identical to chain F's
#    LSTM run minus scan_chunk (the LRU core is a single associative
#    scan; chunked remat is an LSTM-path knob).
# 2) The flagship-NET memory run: memory_catch:60 at 84x84 with the
#    FULL Nature/512 network (the reference's net class, README.md:16-18
#    + model.py:47-59 evidence class) and recurrent_core=lru — the one
#    cell of the frontier table never tried (LSTM+Nature failed at every
#    budget; LRU+mid-net solved it). Mid-scale-proven hyperparameters
#    (gamma .99, sync 250, L=B=20) as in mc84_cue60. Learns => run the
#    zero-state ablation arm at the SAME scale/budget to complete the
#    controlled pair.
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid_lru \
  --env memory_catch:10:12 --steps 36000 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=288 \
  --set learning_steps=256 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru
echo "=== LONG_CONTEXT_MID_LRU EXIT: $? ==="

run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru \
  --env memory_catch:60 --full --mode fused --steps 100000 \
  --set recurrent_core=lru --set gamma=0.99 \
  --set target_net_update_interval=250 \
  --set learning_steps=20 --set burn_in_steps=20 --set save_interval=12500
echo "=== MC84_FULL_LRU EXIT: $? ==="
EV=$(last_eval runs/mc84_full_lru/eval.jsonl)
echo "=== MC84_FULL_LRU EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_zerostate \
    --env memory_catch:60 --full --mode fused --steps 100000 \
    --set recurrent_core=lru --set gamma=0.99 \
    --set target_net_update_interval=250 \
    --set learning_steps=20 --set burn_in_steps=20 --set save_interval=12500 \
    --ablate-zero-state
  echo "=== MC84_FULL_LRU_ZEROSTATE EXIT: $? ==="
fi

echo R4A_CHAIN_ALL_DONE
