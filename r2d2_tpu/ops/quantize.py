"""Per-channel symmetric int8 weight quantization for the serve plane.

Weight-only quantization of the WIDE feed-forward matmuls (encoder convs
/ dense layers, dueling-head kernels): each selected kernel leaf is
replaced by an int8 tensor plus a float32 per-output-channel scale

    scale_j = max_i |w_ij| / 127        (symmetric, zero-point free)
    q_ij    = round(w_ij / scale_j)     clipped to [-127, 127]

and dequantized in-jit (`q.astype(f32) * scale`) right before the matmul
— XLA fuses the convert+multiply into the weight fetch, so the kernel
ships to the device at a quarter of the fp32 bytes and nothing else in
the program changes.

What is NOT quantized, deliberately:

- the recurrent core subtree (wi/wh/b): the T-step sequential carry is
  the drift amplifier — per-step error compounds through the gates — and
  its (H, 4H) kernels are a small fraction of total weight bytes anyway;
- biases and every other rank-<2 leaf (norm scales, LRU ring params):
  negligible bytes, disproportionate drift.

This module is pytree surgery on host at PUBLISH time (checkpoint
hot-reload in serve/server.py), never in the train/learner path. The
quantized tree keeps the exact container structure of the input with
selected leaves swapped for {"q8", "scale"} dicts, so it threads through
jit boundaries as an ordinary pytree; `dequantize_tree` restores the
original structure (values within quantization error).

Bounded-parity class, like precision="bf16" (ARCHITECTURE.md): Q-values
drift by a bounded amount vs the fp32 arm; actions may flip only where
Q-gaps are inside that bound. Tests pin the drift (tests/test_serve.py),
BENCH serve rows report it (`q_drift_vs_fp32`).
"""

from __future__ import annotations

from typing import Mapping, Tuple

import jax
import jax.numpy as jnp

# container keys whose whole subtree stays full precision
_SKIP_SUBTREES = ("core",)
_Q8_KEYS = frozenset(("q8", "scale"))


def _is_qleaf(node) -> bool:
    return isinstance(node, Mapping) and set(node.keys()) == _Q8_KEYS


def _quantize_leaf(w: jnp.ndarray):
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=tuple(range(w32.ndim - 1)), keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q, "scale": scale}


def quantize_tree(params) -> Tuple[dict, int]:
    """Quantize eligible kernels in a flax param tree.

    Returns (quantized tree, number of leaves quantized). Eligible:
    float leaves with ndim >= 2 outside the `core` subtree. Everything
    else passes through untouched.
    """
    count = 0

    def rec(node, skip):
        nonlocal count
        if isinstance(node, Mapping):
            return {k: rec(v, skip or k in _SKIP_SUBTREES) for k, v in node.items()}
        if (
            not skip
            and hasattr(node, "ndim")
            and node.ndim >= 2
            and jnp.issubdtype(node.dtype, jnp.floating)
        ):
            count += 1
            return _quantize_leaf(node)
        return node

    return rec(params, False), count


def dequantize_tree(params, dtype=jnp.float32):
    """Inverse of quantize_tree (values within quantization error).

    Safe to call inside jit — it is a handful of convert+mul ops that XLA
    fuses into the consuming matmuls. A tree with no quantized leaves
    passes through unchanged.
    """

    def rec(node):
        if _is_qleaf(node):
            return (node["q8"].astype(dtype) * node["scale"].astype(dtype)).astype(dtype)
        if isinstance(node, Mapping):
            return {k: rec(v) for k, v in node.items()}
        return node

    return rec(params)


def quantized_bytes_saved(params) -> int:
    """HBM bytes saved by the int8 leaves of a quantized tree."""
    saved = 0
    for leaf in jax.tree.leaves(
        params, is_leaf=_is_qleaf
    ):
        if _is_qleaf(leaf):
            saved += 3 * leaf["q8"].size  # f32 (4B) -> i8 (1B)
    return saved
