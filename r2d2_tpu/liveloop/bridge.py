"""IngestBridge — bounded hand-off from tap-emitted Blocks to replay.

The tap emits finished (block, priorities, episode_reward) triples on the
liveloop-tap thread; the replay plane's add path takes the store lock and
may contend with the learner's sample path. This bridge decouples them:
`offer` is a lock-guarded bounded-deque append (drop-oldest, counted) so
block production can never block on replay, and the supervised
"liveloop-ingest" thread drains the queue into the store — in one
`add_blocks_batch` call (one lock acquisition) when the plane supports
it, else an `add_block` loop.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from r2d2_tpu.utils.faults import with_retries


class IngestBridge:
    def __init__(self, replay, depth: int = 64):
        self.replay = replay
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._q: deque = deque()
        self._wake = threading.Event()
        # counters guarded by _lock
        self.offered_blocks = 0
        self.dropped_blocks = 0
        self.ingested_blocks = 0
        # drop visibility at DRAIN granularity: how many offers were shed
        # since the previous drain_once (the backpressure signal a metrics
        # row can alarm on — a nonzero value means the ingest thread is
        # not keeping up RIGHT NOW, where the cumulative counter can't
        # distinguish an old burst from an ongoing one)
        self.dropped_last_drain = 0
        self._dropped_at_drain = 0

    def offer(self, block, priorities, episode_reward: Optional[float]) -> None:
        """Enqueue one finished block; sheds the OLDEST queued block when
        full (fresh experience beats stale under backpressure)."""
        with self._lock:
            self.offered_blocks += 1
            if len(self._q) >= self.depth:
                self._q.popleft()
                self.dropped_blocks += 1
            self._q.append((block, priorities, episode_reward))
        self._wake.set()

    def drain_once(self, timeout: float = 0.0) -> int:
        """Move every queued block into the replay plane; returns blocks
        ingested. The ingest thread body calls this with a small timeout;
        tests and the stop path call it with timeout=0."""
        if timeout > 0.0 and not self._wake.wait(timeout):
            return 0
        with self._lock:
            items = list(self._q)
            self._q.clear()
            self._wake.clear()
            self.dropped_last_drain = self.dropped_blocks - self._dropped_at_drain
            self._dropped_at_drain = self.dropped_blocks
        if not items:
            return 0

        def push():
            add_batch = getattr(self.replay, "add_blocks_batch", None)
            if add_batch is not None:
                add_batch(items)
            else:
                for block, priorities, episode_reward in items:
                    self.replay.add_block(block, priorities, episode_reward)

        # a flaky add re-pushes the same already-drained items: retries
        # never touch the tap or the queue, so nothing is double-counted
        with_retries(push, "liveloop.ingest")
        with self._lock:
            self.ingested_blocks += len(items)
        return len(items)

    def qsize(self) -> int:
        with self._lock:
            return len(self._q)

    def stats(self) -> dict:
        with self._lock:
            return {
                "bridge_offered_blocks": self.offered_blocks,
                "bridge_dropped_blocks": self.dropped_blocks,
                "bridge_dropped_last_drain": self.dropped_last_drain,
                "bridge_ingested_blocks": self.ingested_blocks,
                "bridge_queue_depth": len(self._q),
            }
