"""Microbench: recurrent-core unroll wall time vs sequence length.

The LRU core's claim is architectural: a diagonal linear recurrence
unrolls as ONE associative_scan (O(log T) dependent steps), while the
LSTM's nonlinear recurrence is inherently sequential (O(T)), Pallas
kernel or not. This measures exactly that on the real chip: forward
unroll time for the full R2D2Network (encoder + core + heads) at growing
T, one line of JSON per (core, T).

    python runs/bench_core_unroll.py --out runs/core_unroll.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench_one(cfg, B, T, iters=50):
    from r2d2_tpu.models.r2d2 import init_params

    net, params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.integers(0, 255, (B, T, *cfg.obs_shape), dtype=np.uint8))
    la = jnp.asarray(rng.integers(0, cfg.action_dim, (B, T)), jnp.int32)
    lr = jnp.asarray(rng.normal(size=(B, T)).astype(np.float32))
    hid = jnp.zeros((B, 2, cfg.hidden_dim), jnp.float32)
    burn = jnp.zeros(B, jnp.int32)
    learn = jnp.full(B, cfg.learning_steps, jnp.int32)
    fwd = jnp.full(B, cfg.forward_steps, jnp.int32)

    @jax.jit
    def fn(params, obs, la, lr, hid, burn, learn, fwd):
        q, _, _ = net.apply(params, obs, la, lr, hid, burn, learn, fwd)
        # scalar output: the end-of-window sync is one float readback
        # (np.asarray-style host sync is the only reliable barrier on the
        # tunneled backend — block_until_ready returns at enqueue there)
        return jnp.sum(q.astype(jnp.float32))

    args = (params, obs, la, lr, hid, burn, learn, fwd)
    float(fn(*args))  # compile + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(out)  # host readback = device sync
    return (time.perf_counter() - t0) / iters


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--lens", default="128,256,512,1024")
    args = p.parse_args()

    from r2d2_tpu.config import R2D2Config

    rows = []
    for T in [int(x) for x in args.lens.split(",")]:
        # learning/forward fill the window; burn_in=0 keeps T the whole story
        base = dict(
            obs_shape=(84, 84, 1), action_dim=9, encoder="nature",
            hidden_dim=args.hidden, compute_dtype="bfloat16",
            burn_in_steps=0, learning_steps=T - 1, forward_steps=1,
            block_length=T - 1, buffer_capacity=(T - 1) * 4,
        )
        for core, extra in (
            ("lstm-pallas", dict(recurrent_core="lstm", lstm_backend="pallas")),
            ("lstm-scan", dict(recurrent_core="lstm", lstm_backend="scan")),
            ("lru", dict(recurrent_core="lru")),
            ("lru-c128", dict(recurrent_core="lru", lru_chunk=128)),
        ):
            cfg = R2D2Config(**base, **extra).validate()
            try:
                dt = bench_one(cfg, args.batch, T)
            except Exception as e:  # e.g. pallas unavailable off-TPU
                print(f"# skip {core} T={T}: {type(e).__name__}: {e}", file=sys.stderr)
                continue
            row = {
                "core": core, "T": T, "B": args.batch, "hidden": args.hidden,
                "ms_per_unroll": round(dt * 1e3, 3),
                "us_per_step_per_seq": round(dt * 1e6 / T / args.batch, 3),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            for row in rows:
                fh.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
