#!/bin/bash
# Round-4 chain B: after chain A drains.
#  1. MFU + LRU-breakdown measurements (verdict items 6 & 8) while the
#     chip is otherwise idle — minutes each.
#  2. The long-context stabilization attack (verdict item 1 follow-up):
#     BOTH round-3 long-context runs (LSTM chain F, LRU chain A) climbed
#     clearly above chance (~-0.19 vs random ~-0.9) then REGRESSED under
#     constant lr. Retry the LRU run with lr_schedule=cosine (decay to
#     0.1x by 36k) — the single-variable change aimed at the late-run
#     instability; n=64 eval for tighter error bars. If the final
#     checkpoints still regress below -0.35, a second arm adds the
#     slower target sync (500).
#  3. The 8x8 procmaze confirmation eval at n=256 (verdict item 5).
#  4. The procmaze ladder with transfer (verdict item 4): measure the
#     12x12 random baseline, warm-start from the solved 8x8 policy
#     (runs/procmaze_small step_30000, the curriculum pattern that
#     cracked memory catch), train 30k more, eval the series. If the
#     final eval clears the measured baseline, climb to 16x16 the same
#     way.
cd /root/repo
while ! grep -q R4A_CHAIN_ALL_DONE runs/r4a_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

python runs/measure_mfu.py --out runs/mfu.json
echo "=== MFU EXIT: $? ==="
python runs/bench_lru_breakdown.py --out runs/lru_breakdown.jsonl
echo "=== LRU_BREAKDOWN EXIT: $? ==="
python runs/bench_core_unroll.py --out runs/core_unroll_r4.jsonl
echo "=== CORE_UNROLL_R4 EXIT: $? ==="

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid_lru2 \
  --env memory_catch:10:12 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=288 \
  --set learning_steps=256 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID_LRU2 EXIT: $? ==="
EV=$(last_eval runs/long_context_mid_lru2/eval.jsonl)
echo "=== LONG_CONTEXT_MID_LRU2 EVAL: $EV ==="
if ! python -c "import sys; sys.exit(0 if float('$EV') >= -0.35 else 1)"; then
  run_with_retry python examples/long_context_demo.py --out runs/long_context_mid_lru3 \
    --env memory_catch:10:12 --steps 36000 --eval-episodes 4 \
    --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
    --set hidden_dim=128 --set max_episode_steps=288 \
    --set learning_steps=256 --set block_length=512 \
    --set buffer_capacity=102400 --set learning_starts=40000 \
    --set recurrent_core=lru --set lr_schedule=cosine \
    --set target_net_update_interval=500
  echo "=== LONG_CONTEXT_MID_LRU3 EXIT: $? ==="
fi

python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:8 --episodes 16 \
  --out runs/procmaze_small/eval_n256.jsonl --plot runs/procmaze_small/curve_n256.jpg \
  --set checkpoint_dir=runs/procmaze_small/ckpt
echo "=== PROCMAZE8_N256 EXIT: $? ==="

mkdir -p runs/procmaze12_warm/ckpt
python runs/measure_random_baseline.py --env procmaze_shaped:12 --episodes 2048 \
  --out runs/procmaze12_warm/baseline.json
echo "=== PROCMAZE12_BASELINE EXIT: $? ==="
if [ ! -d runs/procmaze12_warm/ckpt/step_30000 ]; then
  cp -r runs/procmaze_small/ckpt/step_30000 runs/procmaze12_warm/ckpt/step_30000
fi
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:12 \
  --mode fused --steps 60000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze12_warm/ckpt \
  --set metrics_path=runs/procmaze12_warm/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE12 TRAIN EXIT: $? ==="
python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:12 --episodes 4 \
  --out runs/procmaze12_warm/eval.jsonl --plot runs/procmaze12_warm/curve.jpg \
  --set checkpoint_dir=runs/procmaze12_warm/ckpt
echo "=== PROCMAZE12 EVAL EXIT: $? ==="

EV12=$(last_eval runs/procmaze12_warm/eval.jsonl)
BASE12=$(python -c "import json; print(json.load(open('runs/procmaze12_warm/baseline.json'))['random_mean_reward'])" 2>/dev/null || echo 9)
echo "=== PROCMAZE12 EVAL: $EV12 BASELINE: $BASE12 ==="
if python -c "import sys; sys.exit(0 if float('$EV12') > float('$BASE12') + 0.05 else 1)"; then
  mkdir -p runs/procmaze16_warm/ckpt
  python runs/measure_random_baseline.py --env procmaze_shaped:16 --episodes 2048 \
    --out runs/procmaze16_warm/baseline.json
  if [ ! -d runs/procmaze16_warm/ckpt/step_60000 ]; then
    cp -r runs/procmaze12_warm/ckpt/step_60000 runs/procmaze16_warm/ckpt/step_60000
  fi
  run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
    --mode fused --steps 90000 --updates-per-dispatch 16 --resume \
    --set checkpoint_dir=runs/procmaze16_warm/ckpt \
    --set metrics_path=runs/procmaze16_warm/metrics.jsonl \
    --set buffer_capacity=200000 --set learning_starts=30000 \
    --set samples_per_insert=15.0 --set save_interval=3750 \
    --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
  echo "=== PROCMAZE16 TRAIN EXIT: $? ==="
  python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:16 --episodes 4 \
    --out runs/procmaze16_warm/eval.jsonl --plot runs/procmaze16_warm/curve.jpg \
    --set checkpoint_dir=runs/procmaze16_warm/ckpt
  echo "=== PROCMAZE16 EVAL EXIT: $? ==="
fi

echo R4B_CHAIN_ALL_DONE
