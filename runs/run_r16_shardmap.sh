#!/bin/bash
# Round-16 manual-partitioning chain: the measurement side of the
# shard_map tp x fsdp PR. Four rungs, each one JSON line appended to
# runs/bench_shardmap_r16.jsonl:
#
#   1. shardmap gate — the manual-partition parity suites (tp2 x fsdp2 x
#      dp2 step vs the unsharded reference at fp32 AND bf16; ZeRO-2
#      moment shards + update equality vs replicated Adam; resume across
#      a CHANGED tp x fsdp layout), the backward-arm auto-selection
#      tests, and the static analysis CLI (the shard_mapped step and
#      both auto arms are traced at fp32+bf16; raw shard_map imports
#      outside parallel/jax_compat.py are an AST error). A parity
#      regression aborts the chain: a wrong collective's speedup is
#      noise.
#   2. breakdown (auto arm) — per-phase step timing with the vs_r14
#      column (per-phase deltas against BENCH_r14.json), the
#      backward_arm/backward_arm_mode stamps, and the
#      largest-model-that-fits table per mesh shape (model_fits).
#   3. breakdown (grown presets) — the same timing at --model-preset
#      wide/deep: the "grow the brain" rung. TPU-gated: on CPU the
#      grown shapes crawl and the timings say nothing (rung 2's
#      model_fits rows already size every preset analytically on any
#      host).
#   4. tp x fsdp smoke — one short train.py run on the dp2 x tp2 x
#      fsdp2 cell over faked host devices (the exact mesh shape PR 14's
#      validate() used to block), then resume under a DIFFERENT
#      tp x fsdp layout: orbax restores onto the new layout's shardings
#      through the sharded restore template.
#
# PRE-REGISTERED read: rung 2's model_fits.largest_fit growing
# monotonically with tp x fsdp (more shards -> bigger largest model),
# the auto backward_arm stamp matching resolve_backward_arm at the
# benched shapes, and rung 4's resume crossing the layout change with
# training continuing from the saved step — the BENCH_r16 headline.
cd /root/repo

. runs/lib.sh

OUT=runs/bench_shardmap_r16.jsonl
: > "$OUT"

echo "=== RUNG 1: shardmap + auto-arm gate ==="
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m pytest tests/test_sharding_map.py tests/test_pallas_lstm.py \
  tests/test_analysis.py -q -p no:cacheprovider
RC=$?
echo "=== SHARDMAP_PYTEST EXIT: $RC ==="
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m r2d2_tpu.analysis.cli --jaxpr
RCA=$?
echo "=== ANALYSIS EXIT: $RCA ==="
if [ $RC -ne 0 ] || [ $RCA -ne 0 ]; then
  echo "=== ABORT: shardmap gate failed; bench rows would be noise ==="
  exit 1
fi

echo "=== RUNG 2: breakdown, auto arm (vs_r14 + model_fits) ==="
python bench.py --mode breakdown --batch 8 | tee -a "$OUT"
echo "=== BREAKDOWN_AUTO EXIT: $? ==="

if python -c 'import jax, sys; sys.exit(0 if jax.default_backend() == "tpu" else 1)'; then
  echo "=== RUNG 3: breakdown, grown model presets ==="
  python bench.py --mode breakdown --batch 8 --model-preset wide | tee -a "$OUT"
  echo "=== BREAKDOWN_WIDE EXIT: $? ==="
  python bench.py --mode breakdown --batch 8 --model-preset deep | tee -a "$OUT"
  echo "=== BREAKDOWN_DEEP EXIT: $? ==="
else
  echo "=== RUNG 3 SKIPPED: no TPU (grown presets crawl on CPU) ==="
fi

echo "=== RUNG 4: tp x fsdp smoke (save/resume across the layout) ==="
CKPT=runs/r16_shardmap_smoke
rm -rf "$CKPT"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m r2d2_tpu.train --preset tiny_test --env catch --mode inline \
  --dp 2 --tp 2 --fsdp 2 --steps 30 \
  --set checkpoint_dir="$CKPT" --set save_interval=15
echo "=== TPFSDP_TRAIN EXIT: $? ==="
# resume under a DIFFERENT tp x fsdp layout: the sharded restore
# template places every leaf per the NEW mesh, so the step count
# continues and no TopologyMismatch fires
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m r2d2_tpu.train --preset tiny_test --env catch --mode inline \
  --dp 4 --tp 1 --fsdp 2 --steps 60 --resume \
  --set checkpoint_dir="$CKPT" --set save_interval=15
echo "=== TPFSDP_RESUME EXIT: $? ==="

echo R16_SHARDMAP_ALL_DONE
