"""Shared driver for the multi-host replay tests.

`build_and_run(mesh)` fills a MultiHostShardedReplay with per-shard
deterministic blocks and runs 3 collective train steps — called BOTH by the
in-process single-host reference (4 fake devices, all shards local) and by
the real 2-process children this file spawns as `python multihost_child.py
<pid> <nprocs> <port>`. Identical per-shard content + layout-independent
draw seeds mean the two topologies must produce the same losses.
"""

import json
import sys


def build_and_run(mesh):
    import jax
    import numpy as np

    from bench import synth_block
    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.learner import init_train_state, make_sharded_fused_train_step
    from r2d2_tpu.parallel.mesh import replicated_sharding
    from r2d2_tpu.replay.multihost_store import MultiHostShardedReplay

    cfg = tiny_test().replace(batch_size=8)
    replay = MultiHostShardedReplay(cfg, mesh, seed=5)
    # per-GLOBAL-shard content streams: the same blocks land in the same
    # shards regardless of how shards are spread over processes
    rngs = {g: np.random.default_rng(100 + g) for g in replay.local_ids}
    for _ in range(2):
        for g in replay.local_ids:
            block = synth_block(cfg, rngs[g])
            prios = np.full(cfg.seqs_per_block, 1.0, np.float32)  # equal ->
            replay.add_block(block, prios, None)  # IS weights exactly 1.0
    assert replay.can_sample()

    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_sharded_fused_train_step(
        cfg, net, mesh, donate=False, is_from_priorities=True
    )
    losses = []
    for _ in range(3):
        state, metrics = replay.run_step(step_fn, state)
        losses.append(float(metrics["loss"]))
    # K-dispatch phase: two K=2 collective scan dispatches (the second
    # also drains the first's deferred priorities), then the final drain —
    # the full run_step_k lifecycle on both process topologies
    from r2d2_tpu.learner import make_sharded_fused_multi_train_step

    multi_fn = make_sharded_fused_multi_train_step(
        cfg, net, mesh, 2, donate=False, is_from_priorities=True
    )
    for _ in range(2):
        state, metrics = replay.run_step_k(multi_fn, state, 2)
        losses.append(float(metrics["loss"]))
    replay.drain_pending()
    checksum = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(state.params))
    )
    # the trees saw every drained priority batch: fold the GLOBAL tree
    # mass into the cross-topology comparison too (each process only
    # holds its local shards' trees)
    local_tree = np.float64(sum(replay.shards[g].tree.total for g in replay.local_ids))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        local_tree = multihost_utils.process_allgather(local_tree).sum()
    checksum += float(local_tree)
    return losses, checksum


def main():
    import os

    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", nprocs, pid)
    assert jax.process_count() == nprocs, jax.process_count()

    from r2d2_tpu.parallel.multihost import make_global_mesh

    mesh = make_global_mesh(tp=1)
    losses, checksum = build_and_run(mesh)
    print(
        "CHILD_RESULT "
        + json.dumps({"pid": pid, "losses": losses, "checksum": checksum}),
        flush=True,
    )


if __name__ == "__main__":
    main()
