"""Multi-host (multi-process) support: DCN + ICI spanning meshes.

The reference is strictly single-host (SURVEY.md section 5.8: mp.Queues and
shared memory; no NCCL/MPI). The TPU-native scale-out story is standard JAX
SPMD: every host process runs the SAME program, `jax.devices()` is the
GLOBAL device list, and one Mesh spans all of them — collectives ride ICI
within a slice and DCN between slices, inserted by XLA from the same
shardings that the single-host tests exercise on the 8-fake-device CPU mesh.

Division of labor per host (mirrors the single-host design 1:1):

- learner step: the shard_map/psum train step (learner.py) is already
  multi-host-correct — each process feeds its ADDRESSABLE shards and XLA
  runs the global program. Params/opt state replicated; gradient psum over
  the global dp axis.
- replay + collection: each host owns the control planes (sum trees,
  pointers) for the dp shards whose devices it hosts, and its collector
  writes blocks only into those local shards (`local_axis_indices` below
  tells it which). No cross-host replay traffic exists by construction —
  the same zero-copy locality argument as the single-host sharded plane
  (replay/sharded_store.py), now with hosts as the unit.
- weight publish to actors is host-local (each host's actors read its own
  ParamStore snapshot of the replicated params).

This module provides the three pieces a launcher needs; everything else is
the same code the tests run single-host.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from r2d2_tpu.parallel.mesh import make_mesh


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed for a multi-process run; returns True if
    a multi-process runtime was set up.

    Arguments fall back to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) and, on TPU pods, to the TPU
    metadata autodetection built into jax.distributed.initialize().
    Single-process (no coordinator configured) is a no-op — the rest of
    the framework behaves identically either way."""
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None:
        return False  # single-process run
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def make_global_mesh(
    dp: Optional[int] = None, tp: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    """A dp x tp mesh over the GLOBAL device list (all processes).

    dp defaults to global_device_count / tp. Device order follows
    jax.devices(), which groups by process — so consecutive dp indices map
    to one host's devices first, keeping each host's replay shards on its
    own chips (ICI-local gathers, DCN only for the gradient psum legs that
    cross hosts)."""
    # make_mesh already defaults dp to len(devices)//tp and validates the
    # factorization; this wrapper only supplies the GLOBAL device list
    return make_mesh(dp=dp, tp=tp, devices=devices if devices is not None else jax.devices())


def local_slab_ranges(mesh: Mesh, num_blocks: int, axis: str = "dp"):
    """The rows of mesh.slab_partition_map owned by THIS process: global
    block ranges [start, end) per local shard id. Snapshot topology
    manifests embed these per host, so an elastic resume can place every
    saved slab in logical order without knowing the saving layout."""
    from r2d2_tpu.parallel.mesh import slab_partition_map

    pmap = slab_partition_map(mesh, num_blocks, axis)
    return {g: pmap[g] for g in local_axis_indices(mesh, axis)}


def local_axis_indices(mesh: Mesh, axis: str = "dp") -> List[int]:
    """Indices along `axis` whose devices are addressable from THIS process.

    The multi-host replay layout hangs off this: a host constructs control
    planes and runs collectors only for its local shard indices; remote
    shards are other hosts' responsibility. An axis index counts as local
    when every device in its slice is addressable (with process-grouped
    device order and tp <= devices-per-host this is all-or-nothing; a
    partially-addressable slice raises, because splitting one shard's
    control plane across hosts is not a supported layout)."""
    pid = jax.process_index()
    local = []
    arr = mesh.devices  # ndarray shaped by mesh axis order
    axis_num = list(mesh.axis_names).index(axis)
    for i in range(arr.shape[axis_num]):
        devs = np.take(arr, i, axis=axis_num).ravel()
        owned = [d.process_index == pid for d in devs]
        if all(owned):
            local.append(i)
        elif any(owned):
            raise ValueError(
                f"{axis} index {i} is split across processes; choose mesh "
                "factors so each shard's devices live on one host"
            )
    return local
