#!/bin/bash
# Round-5 chain A, resumed after a driver restart killed the original
# run_r5a_chain.sh mid-chain. Arm 1 (mc84_full_lru_cue40) COMPLETED
# before the restart: final eval -0.78 at 100k updates (n=64) — the
# full Nature/512+LRU net does NOT solve the cue-40 geometry (blind
# span 42 >> L=20), so per the chain's pre-registered branch the
# fallback geometry runs: cue 60 (the KNOWN-solvable task, blind 22)
# with L=B=10 windows, attacking the window-carry confound from the
# window side (blind 22 >> L=10). Both arms. See run_r5a_chain.sh for
# the full design rationale.
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

last_eval() { python - "$1" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
print(rows[-1]["mean_reward"] if rows else -9)
PY
}

echo "=== MC84_FULL_LRU_CUE40 EVAL (pre-restart): $(last_eval runs/mc84_full_lru_cue40/eval.jsonl) (NEGATIVE => fallback) ==="

run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_L10 \
  --env memory_catch:60 --full --mode fused --steps 100000 \
  --set recurrent_core=lru --set gamma=0.99 \
  --set target_net_update_interval=250 \
  --set learning_steps=10 --set burn_in_steps=10 --set save_interval=12500
echo "=== MC84_FULL_LRU_L10 EXIT: $? ==="
EV=$(last_eval runs/mc84_full_lru_L10/eval.jsonl)
echo "=== MC84_FULL_LRU_L10 EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_L10_zs \
    --env memory_catch:60 --full --mode fused --steps 100000 \
    --set recurrent_core=lru --set gamma=0.99 \
    --set target_net_update_interval=250 \
    --set learning_steps=10 --set burn_in_steps=10 --set save_interval=12500 \
    --ablate-zero-state
  echo "=== MC84_FULL_LRU_L10_ZS EXIT: $? ==="
fi

echo R5A_CHAIN_ALL_DONE
