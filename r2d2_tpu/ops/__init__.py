"""Pure functional math shared across the framework.

Everything here is a small, heavily unit-tested function implementing one of
the behavioral invariants in SURVEY.md section 2.6.
"""

from r2d2_tpu.ops.value_rescale import value_rescale, inverse_value_rescale
from r2d2_tpu.ops.returns import n_step_returns, n_step_gammas
from r2d2_tpu.ops.epsilon import epsilon_ladder
from r2d2_tpu.ops.priority import mixed_td_priorities, mixed_td_priorities_np

__all__ = [
    "value_rescale",
    "inverse_value_rescale",
    "n_step_returns",
    "n_step_gammas",
    "epsilon_ladder",
    "mixed_td_priorities",
    "mixed_td_priorities_np",
]
