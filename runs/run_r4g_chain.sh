#!/bin/bash
# Round-4 chain G: the TEMPORAL frontier's intermediate rung.
# The spatial frontier (PARITY table) was charted by holding the recipe
# and growing resolution; this charts the time axis the same way. The
# solved fast task (fall_every=1: 24-step episodes, blind 14) and the
# open slow task (fall_every=12: 288 steps, blind ~270) differ 12x in
# blind span; fall_every=6 (144-step episodes, blind ~126) sits halfway
# (log scale) with an almost identical measured random null (-0.516 vs
# -0.504 — diffusion saturates the 24-column board by ~126 steps). Best
# known recipe: lru core + cosine lr; window geometry scaled to the
# episode (two 128-step learning windows per 256-block, window 1 from
# stored state; seq 212).
cd /root/repo

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

run_with_retry python examples/long_context_demo.py --out runs/long_context_mid6 \
  --env memory_catch:10:6 --steps 36000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=144 \
  --set learning_steps=128 --set block_length=256 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== LONG_CONTEXT_MID6 EXIT: $? ==="

echo R4G_CHAIN_ALL_DONE
