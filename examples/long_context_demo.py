"""Long-context learning demo: seq-581 stored-state burn-in, end to end.

The long_context preset (BASELINE.json config 5) trains 512-step learning
windows with 64-step burn-in on the MULTI-BALL slow-fall flashing-cue
catch (envs/catch.py, 'memory_catch:10:8:4', the round-5 re-target):
768-step episodes at 26x26 of four balls, each visible only during its
own 10-step cue before a ~170-step blind fall. Each replay block holds
TWO learning windows, so window 1 replays from a STORED recurrent state —
the R2D2 stored-state + burn-in machinery exercised at ~6x the
reference's sequence length (85 -> 581, reference config.py:27-30). The
round-4 84x84 single-ball stretch task remains available:
--env memory_catch:8:12 --set obs_shape=84,84,4 --set
max_episode_steps=984 (and nature/512 net overrides) works the open
problem beyond the measured temporal frontier.

Defaults are sized for one chip (~1 GB HBM replay, batch 16, K=2 fused
dispatches). Artifacts match catch_demo: {out}/metrics.jsonl, eval.jsonl,
curve.jpg, checkpoints under {out}/ckpt.

    python examples/long_context_demo.py --out runs/long_context --steps 12000
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="runs/long_context")
    p.add_argument("--steps", type=int, default=12000)
    p.add_argument("--actors", type=int, default=8)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--env", default=None,
                   help="catch-family env overriding the preset's "
                        "memory_catch:10:8:4 — e.g. memory_catch:10:8 "
                        "(single ball, 192-step episodes: ONE 512-step "
                        "window covers the episode; the training seq "
                        "stays 581). Episode caps follow the preset's "
                        "26x26 obs_shape")
    p.add_argument("--eval-episodes", type=int, default=2,
                   help="episodes per eval slot per checkpoint (16 slots)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--ablate-zero-state", action="store_true",
                   help="zero-state replay ablation (burn_in=0): window 1 "
                        "of every block loses the stored state that carries "
                        "the cue")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="override any R2D2Config field on top of the demo "
                        "config (repeatable, typed by the field)")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from r2d2_tpu.config import long_context
    from r2d2_tpu.envs.catch import CatchEnv, catch_params
    from r2d2_tpu.evaluate import evaluate_params_device, evaluate_series, make_eval_collect_fn, plot_series
    from r2d2_tpu.train import Trainer
    from r2d2_tpu.utils.supervision import WorkerStalledError, exit_for_stall

    K = 2
    steps = max(args.steps // K, 1) * K
    cfg = long_context(args.env) if args.env else long_context()
    cfg = cfg.replace(
        num_actors=args.actors,
        batch_size=args.batch,
        # one-chip demo budget: 200 block slots ~= 1.5 GB obs store; each
        # episode-aligned block holds ~984 steps
        buffer_capacity=1024 * 200,
        learning_starts=60_000,
        collector="device",
        replay_plane="device",
        updates_per_dispatch=K,
        # n-step 20: the terminal-only reward must propagate ~900 steps
        # through bootstrap chains; at the default n=5 that takes ~4x the
        # target syncs (config 5's seq shape keeps n=5 for parity — this
        # is the learning-demo knob, stated here openly)
        forward_steps=20,
        target_net_update_interval=250,
        samples_per_insert=30.0,
        training_steps=steps,
        save_interval=max(steps // 8, K),
        checkpoint_dir=os.path.join(args.out, "ckpt"),
        metrics_path=os.path.join(args.out, "metrics.jsonl"),
    )
    from r2d2_tpu.config import apply_cli_overrides

    cfg = apply_cli_overrides(cfg, args.set, args.ablate_zero_state)

    trainer = Trainer(cfg, resume=args.resume)
    try:
        trainer.run_fused()
    except WorkerStalledError as e:
        exit_for_stall(e)

    h = cfg.obs_shape[0]
    fn_env = CatchEnv(height=h, width=h, **catch_params(cfg.env_name))
    collect_fn = make_eval_collect_fn(cfg, trainer.net, fn_env, num_envs=16)
    reward_fn = lambda net, p: evaluate_params_device(
        cfg, net, p, fn_env, num_envs=16, seed=1234, collect_fn=collect_fn,
        episodes_per_slot=args.eval_episodes,
    )
    rows = evaluate_series(
        cfg, None, out_path=os.path.join(args.out, "eval.jsonl"), reward_fn=reward_fn,
        episodes_per_checkpoint=16 * args.eval_episodes,
        evaluator_label="device",
    )
    if rows:
        plot_series(rows, os.path.join(args.out, "curve.jpg"))
        print(f"final mean reward: {rows[-1]['mean_reward']:.3f}")


if __name__ == "__main__":
    main()
