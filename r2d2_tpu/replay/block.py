"""Block — the unit of replay storage.

Mirrors the reference Block (reference worker.py:23-66) with two TPU-first
changes:

- `last_action` is stored as a scalar uint8 index, not a bool one-hot
  (reference worker.py:31,498). One-hot expansion happens on device inside
  the jitted step (jax.nn.one_hot) — an A-fold replay-RAM saving and less
  host->device traffic.
- Per-sequence step counters are int32, not uint8, so block/burn-in/learning
  spans > 255 (the long-context preset) don't silently wrap (SURVEY.md
  quirk 12).

Observations keep the reference's uint8 storage; normalization to [0, 1]
happens exactly once, on device (SURVEY.md quirk 15).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Block:
    # (stored_steps, *obs_shape) uint8; stored_steps = burn_in_steps[0] +
    # sum(learning_steps) + 1 (trailing seed entry for the next window)
    obs: np.ndarray
    # (stored_steps,) uint8 — action that *led to* the aligned obs
    last_action: np.ndarray
    # (stored_steps,) float32 — reward that came with the aligned obs
    last_reward: np.ndarray
    # (T,) uint8 — action taken at each learning step
    action: np.ndarray
    # (T,) float32 — n-step return R_t
    n_step_reward: np.ndarray
    # (T,) float32 — bootstrap discount gamma_n(t); 0 past a terminal
    gamma: np.ndarray
    # (num_sequences, 2, hidden_dim) — LSTM (h, c) at the TRUE replay-
    # window start of each sequence (fixes SURVEY.md quirk 1). Packed
    # float32 by the accumulator; the stores downcast to cfg.state_dtype
    # (bfloat16 under precision="bf16") at write time.
    hidden: np.ndarray
    num_sequences: int
    # (num_sequences,) int32 each
    burn_in_steps: np.ndarray
    learning_steps: np.ndarray
    forward_steps: np.ndarray
    # multi-task plane: the task id the producing actor was collecting
    # (multitask/registry.py). Scalar per block — one actor serves one
    # task — broadcast per-sequence by the stores. 0 on single-task runs.
    task: int = 0

    @property
    def stored_steps(self) -> int:
        return len(self.obs)


def store_field_specs(cfg):
    """Per-slot (shape, dtype) of every replay-store field, WITHOUT the
    leading block axis — the single source of truth shared by all device
    store planes (device_store / sharded_store / multihost_store). Adding a
    Block field means extending this map and pad_block_fields once."""
    S, slot, bl = cfg.seqs_per_block, cfg.block_slot_len, cfg.block_length
    return {
        "obs": ((slot, *cfg.obs_shape), np.uint8),
        "last_action": ((slot,), np.int32),
        "last_reward": ((slot,), np.float32),
        "action": ((bl,), np.int32),
        "n_step_reward": ((bl,), np.float32),
        "gamma": ((bl,), np.float32),
        # carries store at cfg.state_dtype: float32 on the golden path,
        # bfloat16 under precision="bf16" (half the HBM/H2D bytes; the
        # model cores cast back to their compute dtype on use)
        "hidden": ((S, 2, cfg.hidden_dim), cfg.state_dtype),
        "burn_in": ((S,), np.int32),
        "learning": ((S,), np.int32),
        "forward": ((S,), np.int32),
    } | (
        # per-sequence task ids, present ONLY on multi-task configs so the
        # single-task store layout (and every golden-path jaxpr/donation
        # contract over it) is byte-identical to before
        {"task": ((S,), np.int32)} if cfg.num_tasks > 1 else {}
    )


# The per-step fields a demoted block carries in its disk-segment record,
# in record order (replay/disk_tier.py walks them to size and parse the
# fixed-geometry slots). The small per-sequence metadata (hidden carries,
# burn_in/learning/forward spans, task id) stays RAM-resident for disk
# slots — the control plane needs it to keep demoted sequences sampleable
# without touching the segment, and it is a rounding error next to the
# per-step planes the record actually holds.
DISK_FIELDS = (
    "obs", "last_action", "last_reward", "action", "n_step_reward", "gamma",
)


def disk_field_specs(cfg):
    """Per-slot (shape, dtype) of every disk-segment record field, in
    DISK_FIELDS order. Dtypes mirror the HOST slab (uint8 scalar actions,
    replay_buffer.py), not the device-store int32 layout above — the disk
    tier spills host rows and must round-trip them bit-exactly."""
    slot, bl = cfg.block_slot_len, cfg.block_length
    return {
        "obs": ((slot, *cfg.obs_shape), np.uint8),
        "last_action": ((slot,), np.uint8),
        "last_reward": ((slot,), np.float32),
        "action": ((bl,), np.uint8),
        "n_step_reward": ((bl,), np.float32),
        "gamma": ((bl,), np.float32),
    }
