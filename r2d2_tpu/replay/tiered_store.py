"""Tiered replay plane, L3 half: full-capacity host store + HBM staging.

The capacity/throughput dilemma this closes (VERDICT round 5): the HBM
plane (replay/device_store.py) serves 1M+ env-frames/s but only at
capacities that fit on-chip (~100k transitions of 84x84 obs), while the
host plane holds the paper's full 2x10^6 transitions but is tunnel-bound
at 0.4-3 updates/s — every batch pays a blocking host->device copy plus
per-field transfer latency, serialized ahead of its update.

Tiering splits the difference:

- The RESIDENT tier is the host-RAM slab store, unchanged from
  ReplayBuffer (same preallocated per-field arrays, same add_block, same
  shared control plane) — np.zeros allocation is lazy on Linux, so a 2M
  config costs physical pages only for the filled prefix.
- The STAGING tier is a pair of HBM slabs holding K sample-batches'
  gathered windows each. `sample_window_stack` draws K batches under ONE
  control-plane lock hold and gathers ALL their sequence windows in one
  vectorized pass: the (K, B) coordinates are flattened and each field
  GROUP crosses into the native core once (gather_windows_multi,
  _native/replay_core.cpp) — host assembly is memcpy-bound, not
  Python-loop-bound. `stage_chunk` then starts one async `device_put` of
  the whole stacked pytree; TieredPrefetchPipeline runs that on a staging
  thread so the transfer of chunk k+1 executes while the learner's fused
  K-update scan (learner.make_stacked_batch_train_step) consumes chunk k.

Staleness is applied AT STAGE TIME: the gather copies bytes out of the
resident tier under the lock, so a staged chunk can never be invalidated
by a concurrent block write — there is nothing pointer-like left in it.
The old_ptr/old_advances stamps captured in the same lock hold ride along
so the deferred priority write-back still passes through the standard
pointer-window mask (control_plane.update_priorities): rows whose slots
were overwritten between stage and write-back are dropped, never
mis-applied.
"""

from __future__ import annotations

import contextlib
import dataclasses
import queue
import threading
import time
from typing import Optional

import numpy as np

from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.utils.faults import fault_point, with_retries


@dataclasses.dataclass
class StagedWindows:
    """K sample-batches' windows, stacked (K, B, ...) on host — the field
    set of SampledBatch with a leading K axis, plus the stage-time stamps
    shared by the whole chunk (all K draws happen under one lock hold)."""

    obs: np.ndarray            # (K, B, seq_len, *obs_shape) uint8
    last_action: np.ndarray    # (K, B, seq_len) uint8
    last_reward: np.ndarray    # (K, B, seq_len) float32
    hidden: np.ndarray         # (K, B, 2, H) float32
    action: np.ndarray         # (K, B, L) int32
    n_step_reward: np.ndarray  # (K, B, L) float32
    gamma: np.ndarray          # (K, B, L) float32
    burn_in_steps: np.ndarray  # (K, B) int32
    learning_steps: np.ndarray # (K, B) int32
    forward_steps: np.ndarray  # (K, B) int32
    is_weights: np.ndarray     # (K, B) float32
    idxes: np.ndarray          # (K, B) int64 — for the priority write-back
    old_ptr: int
    env_steps: int
    old_advances: int

    def nbytes(self) -> int:
        return sum(
            getattr(self, f.name).nbytes
            for f in dataclasses.fields(self)
            if f.name not in ("old_ptr", "env_steps", "old_advances")
        )


@dataclasses.dataclass
class StagedChunk:
    """A StagedWindows after lift-off: `batch` is a stacked
    learner.DeviceBatch (leaves (K, B, ...)) whose device_put has been
    started; the stamps stay host-side for the priority write-back."""

    batch: object
    idxes: np.ndarray
    old_ptr: int
    old_advances: int
    env_steps: int
    # the sampling RNG's bit-generator state captured BEFORE this chunk's
    # draws — the rewind point if the chunk is discarded at preemption
    # (TieredPrefetchPipeline.stop(rewind=True))
    rng_state: Optional[dict] = None


class TieredReplayBuffer(ReplayBuffer):
    """ReplayBuffer (full-capacity host data plane, shared control plane)
    plus the vectorized K-batch window gather the staging tier feeds on.

    The single-batch `sample_batch` path is inherited untouched — it is the
    executable spec `sample_window_stack` must match bit-for-bit (pinned by
    tests/test_tiered_store.py): same RNG stream consumption (K stratified
    tree draws in order), same clamp semantics, same dtypes, same stamps."""

    def sample_window_stack(self, rng: np.random.Generator, k: int) -> StagedWindows:
        cfg = self.cfg
        L, T, B = cfg.learning_steps, cfg.seq_len, cfg.batch_size
        with self.lock:
            draws = [self._draw(rng) for _ in range(k)]
            # flattened (K*B,) coordinates: one gather per field group
            b = np.concatenate([d[0] for d in draws])
            s = np.concatenate([d[1] for d in draws])
            idxes = np.stack([d[2] for d in draws])
            is_weights = np.stack([d[3] for d in draws])

            burn = self.burn_in_store[b, s]
            learn = self.learning_store[b, s]
            fwd = self.forward_store[b, s]
            first_burn = self.burn_in_store[b, 0]
            win_start = first_burn + s * L - burn
            lstart = s * L

            if self.native is not None:
                obs, last_action, last_reward = self.native.gather_windows_multi(
                    [self.obs_store, self.last_action_store, self.last_reward_store],
                    b, win_start, T,
                )
                action, n_step_reward, gamma = self.native.gather_windows_multi(
                    [self.action_store, self.n_step_reward_store, self.gamma_store],
                    b, lstart, L,
                )
                action = action.astype(np.int32)
            else:
                t = np.arange(T)
                rows = win_start[:, None] + t[None, :]
                np.clip(rows, 0, cfg.block_slot_len - 1, out=rows)
                bcol = b[:, None]
                obs = self.obs_store[bcol, rows]
                last_action = self.last_action_store[bcol, rows]
                last_reward = self.last_reward_store[bcol, rows]
                tl = np.arange(L)
                lrows = lstart[:, None] + tl[None, :]
                np.clip(lrows, 0, cfg.block_length - 1, out=lrows)
                action = self.action_store[bcol, lrows].astype(np.int32)
                n_step_reward = self.n_step_reward_store[bcol, lrows]
                gamma = self.gamma_store[bcol, lrows]

            hidden = self.hidden_store[b, s]
            old_ptr = self.block_ptr
            env_steps = self.env_steps
            old_advances = self.ptr_advances

        def kb(x):
            return x.reshape(k, B, *x.shape[1:])

        return StagedWindows(
            obs=kb(obs),
            last_action=kb(last_action),
            last_reward=kb(last_reward),
            hidden=kb(hidden),
            action=kb(action),
            n_step_reward=kb(n_step_reward),
            gamma=kb(gamma),
            burn_in_steps=kb(burn.astype(np.int32)),
            learning_steps=kb(learn.astype(np.int32)),
            forward_steps=kb(fwd.astype(np.int32)),
            is_weights=is_weights,
            idxes=idxes,
            old_ptr=old_ptr,
            env_steps=env_steps,
            old_advances=old_advances,
        )


def stage_chunk(replay: TieredReplayBuffer, rng: np.random.Generator, k: int,
                timer=None) -> StagedChunk:
    """Draw + host-gather + lift one K-batch chunk into HBM.

    The device_put covers the whole stacked pytree in one call (one
    transfer program, not 11 per update like the inline host plane), and
    the trailing block_until_ready makes the h2d span measure true
    transfer completion — callers run this off the critical path (staging
    thread), so blocking here costs the consumer nothing. `timer` is a
    utils.profiling.TransferTimer or None."""
    import jax

    from r2d2_tpu.learner import DeviceBatch

    pre_state = rng.bit_generator.state
    sw = replay.sample_window_stack(rng, k)

    def lift():
        fault_point("tiered.stage_h2d")
        batch = jax.device_put(DeviceBatch(
            obs=sw.obs,
            last_action=sw.last_action.astype(np.int32),
            last_reward=sw.last_reward,
            hidden=sw.hidden,
            action=sw.action,
            n_step_reward=sw.n_step_reward,
            gamma=sw.gamma,
            burn_in_steps=sw.burn_in_steps,
            learning_steps=sw.learning_steps,
            forward_steps=sw.forward_steps,
            is_weights=sw.is_weights,
        ))
        jax.block_until_ready(batch)
        return batch

    cm = timer.h2d(sw.nbytes()) if timer is not None else contextlib.nullcontext()
    with cm:
        # a torn/failed transfer re-lifts from the already-gathered host
        # windows: the retry never re-draws, so the sampling stream is
        # unaffected by transfer flakes
        batch = with_retries(lift, "tiered.stage_h2d")
    return StagedChunk(
        batch=batch,
        idxes=sw.idxes,
        old_ptr=sw.old_ptr,
        old_advances=sw.old_advances,
        env_steps=sw.env_steps,
        rng_state=pre_state,
    )


class TieredPrefetchPipeline:
    """Double-buffered staging: a daemon thread stages chunk k+1 (host
    gather + async device_put) while the consumer's fused K-update scan
    executes chunk k.

    depth=1 (the default) is the double buffer: one chunk ready in the
    queue + one being consumed; the thread starts gathering the next only
    after the queued one is taken, so steady-state HBM holds two staging
    slabs — and the consumed slab's buffers are donated back by
    make_stacked_batch_train_step, which is what makes the pair a ring
    rather than a leak. The bounded queue IS the backpressure: a slow
    consumer (compiling, checkpointing) simply stalls staging; a slow
    stager surfaces as TransferTimer wait time (overlap fraction < 1).

    A crash on the staging thread (malformed store, OOM) is re-raised from
    get() instead of starving the consumer silently."""

    def __init__(self, replay: TieredReplayBuffer, rng: np.random.Generator,
                 k: int, timer=None, depth: int = 1):
        self.replay = replay
        self.rng = rng
        self.k = k
        self.timer = timer
        self.q: "queue.Queue[StagedChunk]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        # RNG state before the draw of a chunk staged but NOT yet queued —
        # the rewind point when stop(rewind=True) catches a stage in flight
        self._inflight_state: Optional[dict] = None
        self._thread = threading.Thread(
            target=self._run, name="tiered-stage", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                if not self.replay.can_sample():
                    # constructed pre-warmup (bench convenience): idle until
                    # the sampling gate opens instead of crashing on an
                    # all-zero tree
                    time.sleep(0.01)
                    continue
                self._inflight_state = self.rng.bit_generator.state
                chunk = stage_chunk(self.replay, self.rng, self.k, self.timer)
                while not self._stop.is_set():
                    try:
                        self.q.put(chunk, timeout=0.1)
                        self._inflight_state = None
                        break
                    except queue.Full:
                        pass
        except BaseException as e:  # noqa: BLE001 — re-raised from get()
            self._err = e

    def get(self) -> StagedChunk:
        """Next staged chunk; the block time (the un-hidden part of the
        tunnel) is recorded as TransferTimer wait."""
        cm = self.timer.wait() if self.timer is not None else contextlib.nullcontext()
        with cm:
            while True:
                if self._err is not None:
                    raise RuntimeError("tiered staging thread died") from self._err
                try:
                    return self.q.get(timeout=0.5)
                except queue.Empty:
                    if not self._thread.is_alive() and self._err is None:
                        raise RuntimeError("tiered staging thread exited")

    def stop(self, rewind: bool = False) -> None:
        """Stop the staging thread. With rewind=True (the preemption path),
        also rewind the sampling RNG to the state before the EARLIEST
        unconsumed draw — queued chunks are discarded, and a resumed run
        re-draws them identically, keeping the sampling stream bit-exact
        across the preempt instead of skipping the prefetched batches."""
        self._stop.set()
        self._thread.join(timeout=10.0)
        if not rewind:
            return
        states = []
        while True:  # drain in FIFO (= draw) order
            try:
                states.append(self.q.get_nowait().rng_state)
            except queue.Empty:
                break
        states.append(self._inflight_state)
        for st in states:
            if st is not None:
                self.rng.bit_generator.state = st
                break
