#!/bin/bash
# Round-5 chain F (queued behind chain E's idle-chip measurements):
#
# 1) Component wall-clock decomposition of the headline update
#    (runs/measure_update_breakdown.py) — four rounds argued encoder
#    granularity vs LSTM serialization from FLOP ledgers; this measures
#    the actual parts at the actual shapes on the idle chip.
#
# 2) The cue-50 middle rung of the full-scale (84x84, Nature/512+LRU)
#    memory frontier: chain A measured cue-60 (blind 22) solving and
#    cue-40 (blind 42) failing. Cue 50 => blind 32: (a) brackets the
#    full-scale memory break to one rung, and (b) is PARTIALLY
#    deconfounded — L=20 windows that contain any cue frame end >= 12
#    steps before landing, so the whole final positioning phase is
#    cue-blind in-window. If stored-state solves, the zero-state arm
#    (true burn_in=0 after the round-5 ordering fix) completes a
#    controlled pair at a geometry where within-window cue carry cannot
#    cover the decision steps.
cd /root/repo
while ! grep -q R5D_CHAIN_ALL_DONE runs/r5d_chain.log 2>/dev/null; do sleep 60; done

. runs/lib.sh

python runs/measure_update_breakdown.py --iters 30 \
  --out runs/update_breakdown_r5.jsonl > runs/update_breakdown_r5.log 2>&1
echo "=== UPDATE_BREAKDOWN EXIT: $? ==="
tail -12 runs/update_breakdown_r5.log

run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_cue50 \
  --env memory_catch:50 --full --mode fused --steps 100000 \
  --set recurrent_core=lru --set gamma=0.99 \
  --set target_net_update_interval=250 \
  --set learning_steps=20 --set burn_in_steps=20 --set save_interval=12500
echo "=== MC84_FULL_LRU_CUE50 EXIT: $? ==="
EV=$(last_eval runs/mc84_full_lru_cue50/eval.jsonl)
echo "=== MC84_FULL_LRU_CUE50 EVAL: $EV ==="
if python -c "import sys; sys.exit(0 if float('$EV') >= 0.5 else 1)"; then
  run_with_retry python examples/catch_demo.py --out runs/mc84_full_lru_cue50_zs \
    --env memory_catch:50 --full --mode fused --steps 100000 \
    --set recurrent_core=lru --set gamma=0.99 \
    --set target_net_update_interval=250 \
    --set learning_steps=20 --set save_interval=12500 \
    --ablate-zero-state
  echo "=== MC84_FULL_LRU_CUE50_ZS EXIT: $? ==="
fi

# Blind-243 budget extension: chain B left mid11 climbing monotonically
# (0.47 -> 0.72) at its 36k budget end — double the budget to 72k to
# settle whether the 243 rung SOLVES (sharpening the frontier to "break
# strictly inside 243..270") or stalls short.
#
# SESSION-RESTART REWRITE: the original plan resumed the 36k checkpoint,
# but checkpoint dirs were cleaned at the session boundary (and --resume
# on an empty dir silently starts fresh), so this is an honestly FRESH
# 72k run into its own directory. That is the cleaner experiment anyway:
# the cosine lr horizon matches the full 72k from step 0 — a
# schedule-pure budget doubling with no SGDR warm-restart confound. The
# 36k chain-B run stands untouched in runs/long_context_mid11/.
run_with_retry python examples/long_context_demo.py --out runs/long_context_mid11_72k \
  --env memory_catch:10:11 --steps 72000 --eval-episodes 4 \
  --set obs_shape=26,26,1 --set encoder=impala --set impala_channels=8,16 \
  --set hidden_dim=128 --set max_episode_steps=264 \
  --set learning_steps=128 --set block_length=512 \
  --set buffer_capacity=102400 --set learning_starts=40000 \
  --set recurrent_core=lru --set lr_schedule=cosine
echo "=== MID11_EXTENSION EXIT: $? ==="
python runs/plot_temporal_frontier.py --out runs/temporal_frontier.jpg
echo "=== FRONTIER_REPLOT EXIT: $? ==="

echo R5F_CHAIN_ALL_DONE
