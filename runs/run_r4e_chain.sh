#!/bin/bash
# Round-4 chain E: the corrected procmaze ladder, after chain D.
# Chain B's 12x12 rung was structurally impossible: ProcMaze renders its
# grid into the fixed 64x64 obs and 64 % 12 != 0 (envs/procmaze.py
# raises at construction). The ladder's real rungs are 8 -> 16
# (64 = 8*8 = 16*4). So: re-run the 8x8 confirmation eval at n=256
# through the device evaluator (chain B's host-driven attempt was cut),
# then warm-start 16x16 from the solved 8x8 policy (the transfer pattern
# the round-3 verdict prescribed), 30k fresh updates, eval at n=64
# against the 16x16 random baseline measured in round 3
# (runs/procmaze_shaped/baseline.json: 0.137 mean shaped reward).
cd /root/repo
while ! grep -q R4D_CHAIN_ALL_DONE runs/r4d_chain.log 2>/dev/null; do sleep 60; done

run_with_retry() {
  local tries=0
  "$@"
  local rc=$?
  while [ $rc -eq 86 ] && [ $tries -lt 3 ]; do
    tries=$((tries+1)); echo "=== stall 86; resume (try $tries) ==="
    "$@" --resume; rc=$?
  done
  return $rc
}

python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:8 \
  --episodes 16 --evaluator device \
  --out runs/procmaze_small/eval_n256.jsonl \
  --plot runs/procmaze_small/curve_n256.jpg \
  --set checkpoint_dir=runs/procmaze_small/ckpt
echo "=== PROCMAZE8_N256 EXIT: $? ==="

mkdir -p runs/procmaze16_warm/ckpt
python runs/measure_random_baseline.py --env procmaze_shaped:16 --episodes 2048 \
  --out runs/procmaze16_warm/baseline.json
echo "=== PROCMAZE16_BASELINE EXIT: $? ==="
if [ ! -d runs/procmaze16_warm/ckpt/step_30000 ]; then
  cp -r runs/procmaze_small/ckpt/step_30000 runs/procmaze16_warm/ckpt/step_30000
fi
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
  --mode fused --steps 60000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze16_warm/ckpt \
  --set metrics_path=runs/procmaze16_warm/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE16_WARM TRAIN EXIT: $? ==="
python -m r2d2_tpu.evaluate --preset procgen_impala --env procmaze_shaped:16 \
  --episodes 4 --evaluator device \
  --out runs/procmaze16_warm/eval.jsonl --plot runs/procmaze16_warm/curve.jpg \
  --set checkpoint_dir=runs/procmaze16_warm/ckpt
echo "=== PROCMAZE16_WARM EVAL EXIT: $? ==="

echo R4E_CHAIN_ALL_DONE
