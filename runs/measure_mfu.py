"""MFU: what fraction of the chip the learner dispatch actually uses.

Round-3 verdict item 6: BENCH proves the system is fast vs the
reference's implied rate (17.2x), but never states utilization vs the
HARDWARE. This measures it for the exact dispatch bench.py's headline
times — make_fused_multi_train_step (K prioritized double-Q updates in
one jitted scan) against a synthetically filled HBM replay:

- FLOPs per dispatch from XLA's own cost model: the script re-invokes
  itself with --cost-only, which pins the CPU platform and reads
  `jitted.lower(...).cost_analysis()["flops"]` PRE-compile — a
  client-side analytic pass over the same HLO (shape-determined, so
  platform-independent), avoiding the tunneled backend's wedging
  compile/cost RPCs observed when AOT-compiling on the axon device;
- wall time per dispatch with the readback sync bench.py uses
  (block_until_ready returns at enqueue on the tunneled backend);
- MFU = achieved FLOP/s / peak. Peak defaults to 197e12 (TPU v5e
  bf16 per chip, public spec); override with --peak-tflops.

Also prints an ANALYTIC per-component forward-FLOP table (Nature conv
trunk layer by layer, recurrent core, dueling heads) so the dominant
kernel is named, not guessed — the conv trunk's share decides whether
chasing the encoder (verdict item 7) has headroom.

    python runs/measure_mfu.py --out runs/mfu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def nature_encoder_flops_per_frame(obs_hw=(84, 84), latent=512):
    """Analytic MACs*2 for the Nature trunk at VALID padding (the exact
    geometry of models/encoders.py NatureEncoder; reference model.py:47-57).
    Returns (total, rows) with one row per layer."""
    H, W = obs_hw
    rows = []
    cin = 1
    total = 0
    for name, k, s, cout in (("conv1", 8, 4, 32), ("conv2", 4, 2, 64), ("conv3", 3, 1, 64)):
        H = (H - k) // s + 1
        W = (W - k) // s + 1
        f = H * W * cout * (k * k * cin) * 2
        rows.append({"layer": name, "out": f"{H}x{W}x{cout}", "mflops_per_frame": round(f / 1e6, 2)})
        total += f
        cin = cout
    dense = H * W * cin * latent * 2
    rows.append({"layer": "enc_dense", "out": f"{latent}", "mflops_per_frame": round(dense / 1e6, 2)})
    total += dense
    return total, rows


def core_flops_per_step(cfg):
    """Matmul MACs*2 per sequence step for the configured recurrent core
    (elementwise recurrence work excluded — it is bandwidth, not MXU)."""
    H = cfg.hidden_dim
    D = H + cfg.action_dim + 1  # concat(latent, one-hot action, reward)
    if cfg.recurrent_core == "lru":
        # in_re/in_im (D,H) + out_re/out_im (H,H) + skip (D,H)
        f = 2 * (2 * D * H + 2 * H * H + D * H)
        if cfg.lru_chunk > 0:
            # chunked formulation: 4 causal (C,C,H) einsums per chunk =
            # 4*C*H MACs per step amortized (counting the masked zeros XLA
            # actually multiplies)
            f += 2 * 4 * cfg.lru_chunk * H
        return f
    # LSTM: wi (D,4H) + wh (H,4H)
    return 2 * (D + H) * 4 * H


def heads_flops_per_step(cfg):
    H, A = cfg.hidden_dim, cfg.action_dim
    return 2 * (H * H + H * H + H * A + H)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--K", type=int, default=16)
    p.add_argument("--seconds", type=float, default=15.0)
    p.add_argument("--peak-tflops", type=float, default=197.0,
                   help="chip peak dense TFLOP/s for the MFU denominator "
                        "(197 = TPU v5e bf16)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes + 2s window: plumbing check "
                        "(the MFU number itself is meaningless off-chip)")
    p.add_argument("--cost-only", action="store_true",
                   help="internal: pin CPU, print the per-dispatch FLOP "
                        "count from the pre-compile cost model, exit")
    p.add_argument("--core", default="lstm", choices=["lstm", "lru"],
                   help="recurrent core of the measured dispatch")
    p.add_argument("--lru-chunk", type=int, default=0,
                   help="LRU formulation: 0 = scan, N > 0 = chunked MXU")
    p.add_argument("--batch", type=int, default=0,
                   help="override batch_size (0 = preset default)")
    args = p.parse_args()

    if args.cost_only:
        jax.config.update("jax_platforms", "cpu")

    from bench import synth_block
    from r2d2_tpu.config import default_atari
    from r2d2_tpu.learner import init_train_state, make_fused_multi_train_step
    from r2d2_tpu.replay.device_store import DeviceReplayBuffer

    cfg = default_atari().replace(
        compute_dtype="bfloat16", buffer_capacity=100_000,
        recurrent_core=args.core,
        lru_chunk=args.lru_chunk if args.core == "lru" else 0,
    )
    if args.batch:
        cfg = cfg.replace(batch_size=args.batch)
    if args.smoke:
        cfg = cfg.replace(
            obs_shape=(84, 84, 1), batch_size=4, buffer_capacity=8_000,
            learning_starts=2_000, num_actors=2,
        )
        args.K = min(args.K, 2)
        args.seconds = min(args.seconds, 2.0)
    if args.cost_only:
        # FLOP totals depend on batch/seq/net shapes, not store capacity;
        # a small store keeps this pass light
        cfg = cfg.replace(buffer_capacity=8_000, learning_starts=2_000)
    K = args.K
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", file=sys.stderr)

    print("filling replay...", file=sys.stderr, flush=True)
    replay = DeviceReplayBuffer(cfg)
    for _ in range(cfg.learning_starts // cfg.block_length + 5):
        replay.add_block(
            synth_block(cfg, rng),
            rng.uniform(0.5, 2.0, size=cfg.seqs_per_block).astype(np.float32),
            None,
        )
    assert replay.can_sample()
    print("replay filled", file=sys.stderr, flush=True)

    net, state = init_train_state(cfg, jax.random.PRNGKey(0))
    multi_step = make_fused_multi_train_step(cfg, net, K, donate=False)
    sample_rng = np.random.default_rng(1)
    draws = [replay.sample_indices(sample_rng) for _ in range(K)]
    b = jax.device_put(np.stack([d.b for d in draws]))
    s = jax.device_put(np.stack([d.s for d in draws]))
    w = jax.device_put(np.stack([d.is_weights for d in draws]))

    if args.cost_only:
        # K is forced to 1 here: the pre-compile cost model counts a
        # lax.scan BODY once regardless of trip count (verified: K=16
        # lowering reports ~1 update's FLOPs), so the parent scales the
        # single-update count by its K explicitly.
        ca = multi_step.lower(state, replay.stores, b, s, w).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        print(f"COST_FLOPS {float(ca.get('flops', float('nan')))}")
        return

    # per-UPDATE FLOP count via the CPU-pinned child (same shapes, same
    # HLO pass), scaled by this run's K
    import subprocess

    xla_flops_per_update = float("nan")
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cost-only",
             "--K", "1", "--core", args.core,
             "--lru-chunk", str(args.lru_chunk),
             "--batch", str(args.batch)] + (["--smoke"] if args.smoke else []),
            capture_output=True, text=True, timeout=900,
        )
        for line in child.stdout.splitlines():
            if line.startswith("COST_FLOPS "):
                xla_flops_per_update = float(line.split()[1])
        if not np.isfinite(xla_flops_per_update):
            print(
                f"cost-only child failed:\n{child.stdout}\n{child.stderr[-2000:]}",
                file=sys.stderr,
            )
    except subprocess.TimeoutExpired:
        # fall through: the timing window below needs no child data
        print("cost-only child timed out after 900s", file=sys.stderr)
    xla_flops_per_dispatch = xla_flops_per_update * K

    # timed window (state NOT donated so the same args re-dispatch).
    # FIXED dispatch count, synced at the end: a wall-clock-bounded loop
    # without backpressure enqueues free (dispatch returns at enqueue on
    # this backend) and then drains for minutes — the wedge chains A-C
    # hit. n is sized from a 3-dispatch calibration to fill ~args.seconds.
    print("compiling timed dispatch...", file=sys.stderr, flush=True)
    out = multi_step(state, replay.stores, b, s, w)
    _ = int(np.asarray(out[0].step))  # compile+sync
    t0 = time.perf_counter()
    for _ in range(3):
        out = multi_step(state, replay.stores, b, s, w)
    _ = int(np.asarray(out[0].step))
    per = (time.perf_counter() - t0) / 3
    n = max(int(args.seconds / per), 5)
    print(f"calibrated {per*1e3:.0f} ms/dispatch; timing {n}...",
          file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    for _ in range(n):
        out = multi_step(state, replay.stores, b, s, w)
    _ = int(np.asarray(out[0].step))
    elapsed = time.perf_counter() - t0

    dispatches_per_s = n / elapsed
    updates_per_s = dispatches_per_s * K
    achieved = xla_flops_per_dispatch * dispatches_per_s
    peak = args.peak_tflops * 1e12
    mfu = achieved / peak

    # analytic forward breakdown: where the FLOPs are, per net evaluation
    enc_total, enc_rows = nature_encoder_flops_per_frame(
        cfg.obs_shape[:2], cfg.hidden_dim
    )
    core = core_flops_per_step(cfg)
    heads = heads_flops_per_step(cfg)
    per_step = enc_total + core + heads
    breakdown = enc_rows + [
        {"layer": f"core_{cfg.recurrent_core}", "mflops_per_frame": round(core / 1e6, 2)},
        {"layer": "dueling_heads", "mflops_per_frame": round(heads / 1e6, 2)},
    ]
    for r in breakdown:
        r["share"] = round(float(r["mflops_per_frame"]) * 1e6 / per_step, 3)
    dominant = max(breakdown, key=lambda r: r["share"])
    # 2 full-sequence evals per update (online w/ grad + target fwd-only):
    # fwd_target + fwd_online + bwd_online(~2x fwd) = 4x one forward
    analytic_per_update = 4 * cfg.batch_size * cfg.seq_len * per_step

    ok = np.isfinite(xla_flops_per_dispatch)
    row = {
        "metric": "learner_mfu",
        "updates_per_sec": round(updates_per_s, 2),
        # null (valid strict JSON), never NaN, when the child failed
        "xla_flops_per_dispatch": xla_flops_per_dispatch if ok else None,
        "achieved_tflops": round(achieved / 1e12, 2) if ok else None,
        "peak_tflops": args.peak_tflops,
        "mfu": round(mfu, 4) if ok else None,
        "analytic_flops_per_update": analytic_per_update,
        "analytic_vs_xla": round(
            analytic_per_update * K / xla_flops_per_dispatch, 3
        ) if ok else None,
        "dominant_component": dominant["layer"],
        "forward_breakdown": breakdown,
        "core": cfg.recurrent_core + (f"_c{cfg.lru_chunk}" if cfg.lru_chunk else ""),
        "K": K,
        "batch": cfg.batch_size,
        "seq_len": cfg.seq_len,
        "device": f"{dev.device_kind} ({dev.platform})",
    }
    print(json.dumps(row, allow_nan=False))
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(json.dumps(row, allow_nan=False) + "\n")
    if not ok:
        sys.exit(3)  # timing printed above; the chain must see the failure


if __name__ == "__main__":
    main()
