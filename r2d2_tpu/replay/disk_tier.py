"""Memory-mapped disk tier below the host replay slab.

The third storage tier (HBM staging / host slab / THIS): fixed-geometry
segment files holding demoted blocks' per-step fields, encoded by
replay/codec.py. TieredReplayBuffer owns the policy — priority-aware victim
choice, control-plane accounting, decode caching — this module owns only
the bytes-on-disk mechanism, mirroring how tiered_store.py splits staging
policy from the host slab.

Geometry
--------
A record is one demoted block:

    directory   len(DISK_FIELDS) x u32   encoded byte length per field
    fields      concatenated encode_field outputs, DISK_FIELDS order
    slack       up to record_size, untouched

Every record slot is `record_size` bytes = directory + the codec's
worst-case bound per field (codec.encoded_max_len — encode_field output can
NEVER exceed it, so any encoding fits any slot and a record rewrite never
shifts its neighbors). Records pack `seg_blocks` to a segment file
`seg_{k:06d}.dat`; segments are created lazily on first write (np.memmap
"w+") so a mostly-empty disk tier costs only the slots actually demoted —
the same lazy-page discipline tiered_store uses for HBM staging slabs.

Crash ordering: `fault_point("disk.write")` fires BEFORE the record bytes
land, so a kill there leaves a slot whose directory still describes the
PREVIOUS record — and the caller's retire-then-write-then-account protocol
guarantees nothing references the slot yet. Page-in passes
`fault_point("disk.promote")` then decodes on the staging thread.
"""

from __future__ import annotations

import os
import struct
from typing import Dict

import numpy as np

from r2d2_tpu.config import R2D2Config
from r2d2_tpu.replay import codec
from r2d2_tpu.replay.block import DISK_FIELDS, disk_field_specs
from r2d2_tpu.utils.faults import fault_point

# records per segment file: small enough that a lazily-created segment is
# cheap, large enough that a populated tier is a handful of mmaps
SEG_BLOCKS = 64


class DiskTier:
    def __init__(self, cfg: R2D2Config):
        self.cfg = cfg
        self.dir = cfg.replay_disk_dir
        self.disk_blocks = cfg.replay_disk_capacity // cfg.block_length
        self.codec = cfg.block_codec
        self.specs = disk_field_specs(cfg)
        self._dir_struct = struct.Struct(f">{len(DISK_FIELDS)}I")
        self._field_max = {
            name: codec.encoded_max_len(shape, dt)
            for name, (shape, dt) in self.specs.items()
        }
        self.record_size = self._dir_struct.size + sum(self._field_max.values())
        self.seg_blocks = min(self.disk_blocks, SEG_BLOCKS)
        self._maps: Dict[int, np.memmap] = {}
        os.makedirs(self.dir, exist_ok=True)
        # counters (read under the owning buffer's lock via stats())
        self.writes = 0
        self.reads = 0
        self.bytes_raw = 0   # pre-codec bytes of every record written
        self.bytes_enc = 0   # encoded bytes actually written

    # -------------------------------------------------------------- segments

    def _segment_path(self, k: int) -> str:
        return os.path.join(self.dir, f"seg_{k:06d}.dat")

    def _segment(self, k: int) -> np.memmap:
        mm = self._maps.get(k)
        if mm is None:
            path = self._segment_path(k)
            size = self.seg_blocks * self.record_size
            mode = "r+" if (
                os.path.exists(path) and os.path.getsize(path) == size
            ) else "w+"
            mm = np.memmap(path, dtype=np.uint8, mode=mode, shape=(size,))
            self._maps[k] = mm
        return mm

    def _locate(self, slot: int):
        if not (0 <= slot < self.disk_blocks):
            raise IndexError(f"disk slot {slot} out of range")
        return self._segment(slot // self.seg_blocks), (
            slot % self.seg_blocks
        ) * self.record_size

    # --------------------------------------------------------------- records

    def write_block(self, slot: int, fields: Dict[str, np.ndarray]) -> None:
        """Encode and write one demoted block's per-step fields into record
        slot `slot`. Fields must match disk_field_specs geometry (the host
        slab rows do by construction)."""
        lengths, payloads, raw = [], [], 0
        for name in DISK_FIELDS:
            shape, dt = self.specs[name]
            arr = np.ascontiguousarray(fields[name], dtype=dt).reshape(shape)
            enc = codec.encode_field(arr, self.codec)
            if len(enc) > self._field_max[name]:  # encode_field guarantees not
                raise codec.CodecError(f"{name} encoding exceeds record slot")
            lengths.append(len(enc))
            payloads.append(enc)
            raw += arr.nbytes
        buf = self._dir_struct.pack(*lengths) + b"".join(payloads)
        # a kill here (or mid-mmap-write) must leave replay consistent: the
        # caller has already retired whatever this slot held, and accounts
        # the new occupant only after we return
        fault_point("disk.write")
        mm, off = self._locate(slot)
        mm[off : off + len(buf)] = np.frombuffer(buf, np.uint8)
        self.writes += 1
        self.bytes_raw += raw
        self.bytes_enc += len(buf)

    def read_block(self, slot: int) -> Dict[str, np.ndarray]:
        """Page in and decode record slot `slot`. Staging/ingest threads
        only — never the learner hot loop (codec-decode-in-hot-loop lint)."""
        fault_point("disk.promote")
        mm, off = self._locate(slot)
        lengths = self._dir_struct.unpack(
            bytes(mm[off : off + self._dir_struct.size])
        )
        pos = off + self._dir_struct.size
        out = {}
        view = memoryview(mm)
        for name, ln in zip(DISK_FIELDS, lengths):
            arr, end = codec.decode_field(view, pos)
            if end - pos != ln:
                raise codec.CodecError(
                    f"{name} record length {end - pos} != directory {ln}"
                )
            out[name] = arr
            pos = end
        self.reads += 1
        return out

    # ------------------------------------------------- snapshot raw transfer

    def record_bytes(self, slot: int) -> np.ndarray:
        """The used bytes of record `slot` (directory + encoded fields),
        verbatim — snapshots embed these so --resume rewrites segments
        bit-exactly without a decode/re-encode round trip."""
        mm, off = self._locate(slot)
        lengths = self._dir_struct.unpack(
            bytes(mm[off : off + self._dir_struct.size])
        )
        used = self._dir_struct.size + sum(lengths)
        return np.array(mm[off : off + used])

    def write_record_bytes(self, slot: int, buf: np.ndarray) -> None:
        """Inverse of record_bytes: restore a record's raw bytes."""
        buf = np.asarray(buf, dtype=np.uint8)
        if len(buf) > self.record_size:
            raise codec.CodecError("record bytes exceed slot geometry")
        mm, off = self._locate(slot)
        mm[off : off + len(buf)] = buf

    def flush(self) -> None:
        for mm in self._maps.values():
            mm.flush()

    def stats(self) -> Dict[str, float]:
        return {
            "disk_blocks": self.disk_blocks,
            "disk_writes": self.writes,
            "disk_reads": self.reads,
            "disk_bytes_raw": self.bytes_raw,
            "disk_bytes_enc": self.bytes_enc,
            "disk_codec_ratio": (
                self.bytes_raw / self.bytes_enc if self.bytes_enc else 0.0
            ),
        }
