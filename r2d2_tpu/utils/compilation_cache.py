"""Persistent XLA compilation cache (SURVEY.md 5.1 adjacent; VERDICT r2
item 8).

The flagship program set (fused megastep + eval collector + acting
forward) costs ~27-110 s to compile cold on the tunneled TPU backend —
BENCH_r01 measured 26.7 s, BENCH_r02 109.7 s for the same programs, the
spread being backend/tunnel noise, not repo changes. Every fresh process
(each curriculum stage, each bench run, each eval pass) repaid it.

jax's persistent compilation cache works on this backend (verified:
2.26 s cold -> 0.13 s warm across processes for a 2048^2 bf16 matmul
program). Enabling it makes multi-process drivers (runs/
run_mc_curriculum.py replays 7+ stages) pay compilation once per
distinct program, not once per process.

Opt-out: set R2D2_TPU_NO_COMPILE_CACHE=1 (e.g. when measuring true cold
compile times — bench.py does this for its compile-time metric).

Directory selection (first match wins):
  1. explicit `cache_dir` argument (the CLIs' --compile-cache flag)
  2. R2D2_COMPILE_CACHE env var
  3. the repo-local .jax_cache default

Hit/miss accounting: enable_compilation_cache registers a
jax.monitoring listener counting the persistent-cache events jax's
compiler emits; log_compile_cache_stats() prints one
`[compile-cache] dir=... hits=H misses=M` line (the CLIs call it after
warmup/run so a driver log shows whether the cache actually served)."""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)

# persistent-cache event counters (jax._src.compiler emits these names)
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_REQ_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
_counts = {_HIT_EVENT: 0, _REQ_EVENT: 0}
_listener_installed = False


def _count_event(event: str, **kwargs) -> None:
    if event in _counts:
        _counts[event] += 1


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    import jax

    jax.monitoring.register_event_listener(_count_event)
    _listener_installed = True


def compile_cache_stats() -> dict:
    """(hits, misses) observed by this process so far. A `miss` is a
    compile request that consulted the cache and fell through to XLA —
    cold programs that get WRITTEN for the next process to hit."""
    hits = _counts[_HIT_EVENT]
    return {"hits": hits, "misses": max(_counts[_REQ_EVENT] - hits, 0)}


def log_compile_cache_stats(prefix: str = "compile-cache") -> str:
    """Print and return the one-line cache report the CLIs emit."""
    import jax

    d = jax.config.jax_compilation_cache_dir or "<disabled>"
    s = compile_cache_stats()
    line = f"[{prefix}] dir={d} hits={s['hits']} misses={s['misses']}"
    print(line, flush=True)
    return line


def enable_compilation_cache(cache_dir: str | None = None) -> bool:
    """Idempotently point jax at a persistent compilation cache directory.

    Returns True when the cache is (already) enabled, False when opted
    out. Safe to call before or after backend init; an explicit
    JAX_COMPILATION_CACHE_DIR env var or earlier jax.config setting
    wins. cache_dir (or R2D2_COMPILE_CACHE) also enables the cache on
    the CPU backend — an explicit ask beats the SIGILL-warning caution
    below, and it is what the tests use."""
    if os.environ.get("R2D2_TPU_NO_COMPILE_CACHE"):
        return False
    import jax

    _install_listener()
    if jax.config.jax_compilation_cache_dir:  # env var or earlier caller
        return True
    cache_dir = cache_dir or os.environ.get("R2D2_COMPILE_CACHE")
    if jax.default_backend() == "cpu" and not cache_dir:
        # XLA:CPU AOT cache loads warn about machine-feature mismatches
        # ("could lead to SIGILL") and CPU compiles are cheap — the cache
        # earns its keep only on the accelerator backend
        return False
    jax.config.update("jax_compilation_cache_dir", cache_dir or _DEFAULT_DIR)
    # the default 1 s floor would skip many of the small eval/acting
    # programs whose compiles still dominate short runs in aggregate
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return True
