"""`python -m r2d2_tpu.serve` — run the policy service on a TCP port.

Quickstart (after a training run wrote checkpoints):

    python -m r2d2_tpu.serve --preset tiny_test --ckpt /tmp/run/ckpt \\
        --port 9955 --metrics /tmp/serve_metrics.jsonl

Then from any process:

    from r2d2_tpu.serve import PolicyClient
    c = PolicyClient(port=9955)
    c.act("session-1", obs, reward=0.0, reset=True)["action"]

The checkpoint watcher keeps polling `--ckpt`, so a concurrently training
run's new saves go live without a restart.
"""

from __future__ import annotations

import argparse
import sys
import time

from r2d2_tpu.config import PRESETS, parse_overrides
from r2d2_tpu.serve.client import serve_tcp
from r2d2_tpu.serve.multi import MultiDeviceServer
from r2d2_tpu.serve.server import PolicyServer, ServeConfig
from r2d2_tpu.utils.metrics import MetricsLogger


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m r2d2_tpu.serve",
        description="session-stateful batched policy serving",
    )
    p.add_argument("--preset", default="tiny_test", choices=sorted(PRESETS))
    p.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE",
                   help="R2D2Config overrides, e.g. --set hidden_dim=256")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint series dir; latest step is served and "
                        "new steps hot-reload. Omitted: fresh-init params "
                        "(smoke serving)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9955)
    p.add_argument("--buckets", type=int, nargs="+", default=[2, 4, 8, 16, 32],
                   help="padded batch shapes (min 2: batch-1 breaks bitwise "
                        "parity with batched acting)")
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--queue-depth", type=int, default=1024)
    p.add_argument("--cache-capacity", type=int, default=4096,
                   help="resident sessions before LRU eviction")
    p.add_argument("--poll-interval", type=float, default=0.5,
                   help="checkpoint watcher poll cadence (seconds)")
    p.add_argument("--epsilon", type=float, default=0.0)
    p.add_argument("--metrics", default=None, help="jsonl metrics path")
    p.add_argument("--devices", type=int, default=None,
                   help="serve replicas over local devices with session-"
                        "affinity routing (serve/multi.py); default "
                        "cfg.serve_devices (1 = single-device server)")
    p.add_argument("--spill", type=int, default=None,
                   help="host-RAM spill slab capacity in sessions "
                        "(default cfg.serve_spill; 0 disables — evicted "
                        "sessions restart fresh)")
    p.add_argument("--autoscale", action="store_true",
                   help="elastic fleet (serve/autoscale.py): grow replicas "
                        "under sustained SLO pressure, drain idle ones "
                        "through session migration. Bounds and dwells via "
                        "--set autoscale_min_replicas=1 "
                        "autoscale_max_replicas=4 ... (config.py)")
    p.add_argument("--dryrun", type=int, default=0, metavar="N",
                   help="serve N synthetic requests in-process (no TCP) "
                        "and exit 0 — the multi-device smoke path")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(R2D2_COMPILE_CACHE env var is the same knob) — "
                        "amortizes the bucket-warmup compiles across "
                        "server restarts")
    args = p.parse_args(argv)

    from r2d2_tpu.utils.compilation_cache import (
        enable_compilation_cache,
        log_compile_cache_stats,
    )

    enable_compilation_cache(args.compile_cache)
    cfg = PRESETS[args.preset]()
    if args.set:
        cfg = cfg.replace(**parse_overrides(args.set))
    if args.devices is not None:
        cfg = cfg.replace(serve_devices=args.devices)
    if args.spill is not None:
        cfg = cfg.replace(serve_spill=args.spill)
    if args.autoscale:
        cfg = cfg.replace(serve_autoscale=True)
    cfg = cfg.validate()
    serve_cfg = ServeConfig(
        buckets=tuple(args.buckets),
        max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth,
        cache_capacity=args.cache_capacity,
        poll_interval_s=args.poll_interval,
        epsilon=args.epsilon,
    )
    metrics = MetricsLogger(args.metrics) if args.metrics else None
    if cfg.serve_devices > 1 or cfg.serve_autoscale:
        # an elastic fleet of 1 is still a fleet: add_replica/kill_replica
        # and the router only exist on the multi-device server
        server = MultiDeviceServer(cfg, serve_cfg, checkpoint_dir=args.ckpt,
                                   metrics=metrics)
        print(f"[serve] {cfg.serve_devices} replicas"
              + (" (elastic, "
                 f"{cfg.autoscale_min_replicas}.."
                 f"{cfg.autoscale_max_replicas})" if cfg.serve_autoscale
                 else "")
              + f": {[str(d) for d in server.devices]}", file=sys.stderr)
    else:
        server = PolicyServer(cfg, serve_cfg, checkpoint_dir=args.ckpt,
                              metrics=metrics)
    print(f"[serve] warming up {len(serve_cfg.buckets)} bucket shapes", file=sys.stderr)
    server.warmup()
    log_compile_cache_stats("serve compile-cache")
    server.start()
    if args.dryrun:
        import numpy as np

        from r2d2_tpu.serve.client import LocalClient

        try:
            client = LocalClient(server)
            rng = np.random.default_rng(0)
            for i in range(args.dryrun):
                sid = f"dry-{i % max(args.dryrun // 2, 1)}"
                obs = rng.integers(0, 255, cfg.obs_shape, np.uint8)
                client.act(sid, obs, reward=0.0, reset=False)
            server.check()
            st = server.stats()
            print(f"[serve] dryrun ok: {args.dryrun} requests, "
                  f"ckpt_step={st['ckpt_step']} "
                  f"devices={st.get('serve_devices', 1)}", file=sys.stderr)
            return 0
        finally:
            server.stop()
            if metrics is not None:
                metrics.close()
    tcp, _ = serve_tcp(server, host=args.host, port=args.port)
    host, port = tcp.server_address[:2]
    print(
        f"[serve] listening on {host}:{port} "
        f"(ckpt_step={server.stats()['ckpt_step']})",
        file=sys.stderr,
    )
    try:
        while True:
            time.sleep(5.0)
            server.check()  # raises WorkerFatalError when a worker dies
    except KeyboardInterrupt:
        return 0
    finally:
        tcp.shutdown()
        tcp.server_close()
        server.stop()
        if metrics is not None:
            metrics.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
