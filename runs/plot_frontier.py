"""Render the memory scale-frontier comparison figure.

One panel, four eval series: the SAME mid-scale recipe (IMPALA-small,
128-LSTM, stored-state + burn-in, blind fraction ~0.58) at 26/40/52/84
resolution. 26 solves; everything wider sits at chance — the PARITY.md
frontier table, as a picture.

  python runs/plot_frontier.py --out runs/memory_scale_frontier.jpg
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

HERE = os.path.dirname(os.path.abspath(__file__))

SERIES = [
    # the n=64-episode re-run supersedes the round-2 n=8 series when present
    ("26x26 (solved)", ("mc_mid_main_n64/eval.jsonl", "mc_mid_main/eval.jsonl"),
     "tab:green"),
    ("40x40", ("mc_frontier40/eval.jsonl",), "tab:orange"),
    ("52x52", ("mc_frontier52/eval.jsonl",), "tab:red"),
    ("84x84 (cue 60)", ("mc84_small_cue60/eval.jsonl",), "tab:purple"),
    # the round-3 coda: same 84x84 task, LRU core — solved
    ("84x84 LRU core (solved)", ("mc84_lru/eval.jsonl",), "tab:blue"),
]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(HERE, "memory_scale_frontier.jpg"))
    args = p.parse_args()

    fig, ax = plt.subplots(figsize=(7, 4.2))
    for label, rels, color in SERIES:
        path = next(
            (p for rel in rels if os.path.exists(p := os.path.join(HERE, rel))),
            None,
        )
        if path is None:
            print(f"skip {label}: {rels} missing", file=sys.stderr)
            continue
        rows = [json.loads(l) for l in open(path) if l.strip()]
        ax.plot(
            [r["step"] / 1e3 for r in rows],
            [r["mean_reward"] for r in rows],
            marker="o", ms=3, color=color, label=label,
        )
    ax.axhline(1.0, color="gray", lw=0.6, ls="--")
    ax.axhline(-1.0, color="gray", lw=0.6, ls="--")
    ax.set_xlabel("updates (thousands)")
    ax.set_ylabel("eval mean reward (ε=0.001)")
    ax.set_title("Memory catch: LSTM recipe vs spatial scale; LRU coda")
    ax.legend(loc="center right", fontsize=8)
    fig.tight_layout()
    fig.savefig(args.out, dpi=130)
    print(args.out)


if __name__ == "__main__":
    main()
