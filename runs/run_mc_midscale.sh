#!/bin/bash
cd /root/repo
python examples/catch_demo.py --out runs/mc_mid_main --env memory_catch:10 --steps 48000 --mode fused
echo "=== MID MAIN EXIT: $? ==="
python examples/catch_demo.py --out runs/mc_mid_zerostate --env memory_catch:10 --steps 48000 --mode fused --ablate-zero-state
echo "=== MID ABLATION EXIT: $? ==="
echo MID_ALL_DONE
