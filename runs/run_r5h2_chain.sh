#!/bin/bash
# Round-5 chain H2: the corrected warm rung (replaces chain H's rungs
# 2-3). Chain H's rung 2 tried procmaze_shaped:12 — geometrically
# invalid (obs 64 not divisible into a 12-cell grid), the SAME wall
# round 4 hit before correcting its ladder to 8->16 directly
# (runs/README.md procmaze16_warm row: "64 % 12 != 0, so 8->16 is the
# real next rung"). This replicates the corrected round-4 protocol:
# 16x16 warm-started from the solved 8x8 policy (step_30000 copied in,
# --resume), 30k fresh updates, then the n=1024 z-instrument series.
cd /root/repo
. runs/lib.sh

if [ ! -d runs/procmaze8_r5/ckpt/step_30000 ]; then
  echo "=== ABORT: 8x8 seed checkpoint missing ==="
  echo R5H2_CHAIN_ALL_DONE
  exit 1
fi
mkdir -p runs/procmaze16_warm2/ckpt
if [ ! -d runs/procmaze16_warm2/ckpt/step_30000 ]; then
  cp -r runs/procmaze8_r5/ckpt/step_30000 runs/procmaze16_warm2/ckpt/step_30000
fi
# --resume restores the LATEST step in the dir: a stale step_33750+ from an
# earlier aborted attempt would silently override the freshly copied 8x8
# warm start. Assert the dir holds ONLY step_30000 before training.
stale=$(ls runs/procmaze16_warm2/ckpt | grep -v '^step_30000$' || true)
if [ -n "$stale" ]; then
  echo "=== ABORT: stale checkpoints in procmaze16_warm2/ckpt: $stale ==="
  echo "=== clear them (or the whole dir) so --resume starts from the 8x8 seed ==="
  echo R5H2_CHAIN_ALL_DONE
  exit 1
fi
# The replay-side twin of the stale-ckpt guard: an aborted attempt under a
# different device/host layout would leave replay snapshots whose slabs
# --resume would regather wrong. Assert the manifests match this chain's
# single-host dp=1 tp=1 layout (no snapshot at all is fine — --resume
# refills replay from scratch).
if ! assert_snapshot_topology runs/procmaze16_warm2/ckpt 1 1 1; then
  echo "=== ABORT: replay snapshot topology mismatch in procmaze16_warm2/ckpt ==="
  echo "=== resume there with --reshard, or clear the stale snapshots ==="
  echo R5H2_CHAIN_ALL_DONE
  exit 1
fi
RETRY_CKPT_DIR=runs/procmaze16_warm2/ckpt RETRY_EXPECT="1 1 1" \
run_with_retry python -m r2d2_tpu.train --preset procgen_impala --env procmaze_shaped:16 \
  --mode fused --steps 60000 --updates-per-dispatch 16 --resume \
  --set checkpoint_dir=runs/procmaze16_warm2/ckpt \
  --set metrics_path=runs/procmaze16_warm2/metrics.jsonl \
  --set buffer_capacity=200000 --set learning_starts=30000 \
  --set samples_per_insert=15.0 --set save_interval=3750 \
  --set target_net_update_interval=500 --set forward_steps=20 --set num_actors=16
echo "=== PROCMAZE16_WARM2 TRAIN EXIT: $? ==="

python runs/eval_stats.py --preset procgen_impala --env procmaze_shaped:16 \
  --ckpt runs/procmaze16_warm2/ckpt --episodes 1024 --null-episodes 2048 \
  --set forward_steps=20 --set num_actors=16 \
  --out runs/procmaze16_warm2/eval_stats.jsonl
echo "=== PROCMAZE16_WARM2 STATS EXIT: $? ==="

echo R5H2_CHAIN_ALL_DONE
