"""Profiler hooks (utils/profiling.py): spans are free when idle, and a
bounded trainer trace actually lands on disk."""

import glob
import os

import jax.numpy as jnp

from r2d2_tpu.utils.profiling import span, step_span, trace_to


def test_spans_are_noops_when_idle():
    with span("replay/sample"):
        x = jnp.ones(4) + 1
    with step_span("learner_update", 3):
        y = x * 2
    assert float(y.sum()) == 16.0


def test_trace_to_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    with trace_to(d):
        jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace artifacts written"


def test_trace_to_none_is_disabled(tmp_path):
    with trace_to(None):
        jnp.ones(2).block_until_ready()


def test_trainer_profile_dir(tmp_path):
    from r2d2_tpu.config import tiny_test
    from r2d2_tpu.train import Trainer

    d = str(tmp_path / "prof")
    cfg = tiny_test().replace(
        env_name="catch",
        training_steps=4,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    tr = Trainer(cfg, profile_dir=d, profile_steps=2)
    tr.run_inline()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "trainer wrote no trace"
