"""Deterministic scripted environment for exact-math tests.

Emits a fixed reward script and obs whose pixel value encodes the timestep,
so n-step returns, terminal encoding, and replay window contents have
closed-form expected values (SURVEY.md section 4 'fake backends').
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class ScriptedEnv:
    def __init__(
        self,
        obs_shape: Tuple[int, ...] = (12, 12, 1),
        action_dim: int = 4,
        episode_len: int = 9,
        rewards: Optional[Sequence[float]] = None,
    ):
        self.obs_shape = obs_shape
        self.action_dim = action_dim
        self.episode_len = episode_len
        self.rewards = list(rewards) if rewards is not None else [float(i % 3) for i in range(episode_len)]
        self.t = 0

    def _obs(self) -> np.ndarray:
        return np.full(self.obs_shape, self.t % 256, dtype=np.uint8)

    def reset(self) -> np.ndarray:
        self.t = 0
        return self._obs()

    def step(self, action: int):
        reward = self.rewards[self.t % len(self.rewards)]
        self.t += 1
        done = self.t >= self.episode_len
        return self._obs(), float(reward), bool(done), {}


class ScriptedFnState(NamedTuple):
    t: jnp.ndarray    # int32 timestep
    key: jnp.ndarray  # PRNG key (unused by the deterministic dynamics)


class ScriptedFnEnv:
    """Functional (jit/vmap-safe) twin of ScriptedEnv, for the on-device
    collector: same reward script, same timestep-encoded obs, same fixed
    episode length — so the device collection path can be compared
    field-by-field against the host actor path on identical trajectories."""

    def __init__(
        self,
        obs_shape: Tuple[int, ...] = (12, 12, 1),
        action_dim: int = 4,
        episode_len: int = 9,
        rewards: Optional[Sequence[float]] = None,
    ):
        self.obs_shape = obs_shape
        self.action_dim = self.NUM_ACTIONS = action_dim
        self.episode_len = episode_len
        script = list(rewards) if rewards is not None else [float(i % 3) for i in range(episode_len)]
        self._rewards = jnp.asarray(script, jnp.float32)

    def reset(self, key: jax.Array) -> ScriptedFnState:
        return ScriptedFnState(jnp.zeros((), jnp.int32), key)

    def render(self, s: ScriptedFnState) -> jnp.ndarray:
        return jnp.full(self.obs_shape, (s.t % 256).astype(jnp.uint8), jnp.uint8)

    def step(self, s: ScriptedFnState, action: jnp.ndarray):
        reward = self._rewards[s.t % len(self._rewards)]
        t2 = s.t + 1
        done = t2 >= self.episode_len
        return ScriptedFnState(t2, s.key), reward, done
