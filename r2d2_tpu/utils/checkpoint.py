"""Orbax checkpointing with a full resume path.

The reference half-has this subsystem: it pickles (state_dict, num_updates,
env_steps, wall_minutes) every 500 updates but can never RESUME — optimizer
state, target net, and RNG state are never saved (reference worker.py:450-452;
SURVEY.md section 5.4). Here a checkpoint carries the complete TrainState
(params, target params, opt state, step) plus env_steps/wall_minutes, and
`restore_checkpoint` reconstructs the LEARNER exactly. Collection state
(replay contents, actor/sampler RNG streams) is not persisted: a resumed run
continues optimization from the identical learner state but refills replay
with freshly collected experience.

Layout: {dir}/step_{N}/ orbax trees — the evaluator walks the same series
the reference's test.py walks (test.py:26-30).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from r2d2_tpu.learner import TrainState


def _payload(state: TrainState, env_steps: int, wall_minutes: float) -> Dict[str, Any]:
    return {
        "params": state.params,
        "target_params": state.target_params,
        "opt_state": state.opt_state,
        "step": state.step,
        "env_steps": np.asarray(env_steps),
        "wall_minutes": np.asarray(wall_minutes),
    }


def save_checkpoint(
    ckpt_dir: str, state: TrainState, env_steps: int, wall_minutes: float
) -> str:
    step = int(state.step)
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _payload(state, env_steps, wall_minutes), force=True)
    ckptr.wait_until_finished()
    return path


def list_checkpoint_steps(ckpt_dir: str) -> List[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                pass
    return sorted(steps)


def latest_checkpoint_step(ckpt_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(ckpt_dir: str, template_state: TrainState, step: Optional[int] = None):
    """Returns (TrainState, env_steps, wall_minutes). `template_state` is an
    uninitialized state of the right structure (from init_train_state)."""
    if step is None:
        step = latest_checkpoint_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    abstract = jax.tree.map(
        ocp.utils.to_shape_dtype_struct, _payload(template_state, 0, 0.0)
    )
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, abstract)
    state = TrainState(
        params=restored["params"],
        target_params=restored["target_params"],
        opt_state=restored["opt_state"],
        step=jnp.asarray(restored["step"], jnp.int32),
    )
    return state, int(restored["env_steps"]), float(restored["wall_minutes"])
