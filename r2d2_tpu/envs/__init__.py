"""Environment layer (L1).

Three env families, one host-facing protocol (reset/step over numpy):

- `atari`: gymnasium+ALE wrappers reproducing the reference's preprocessing
  exactly (reference environment.py). Import-gated: ALE is optional.
- `catch`: a pure-JAX, fully vectorizable control env rendered at the same
  84x84x1 uint8 resolution as Atari, so the full Nature-CNN compute path is
  exercised end-to-end on TPU with no emulator on the host.
- `fake`: a deterministic scripted env giving exact expected values for
  n-step/terminal math in tests (SURVEY.md section 4 'fake backends').

The multi-task family (ROADMAP item 2) adds three more pure-JAX cores with
deliberately different structure — `keydoor` (partially observable memory
probe), `drift` (continuing, no terminals), `banditgrid` (high-variance
stochastic rewards) — all through the same functional protocol.
"""

from r2d2_tpu.envs.fake import ScriptedEnv
from r2d2_tpu.envs.catch import (
    CatchEnv,
    CatchHostEnv,
    CatchVecEnv,
    catch_cue_steps,
    catch_params,
    is_catch_name,
)
from r2d2_tpu.envs.banditgrid import banditgrid_params, is_banditgrid_name
from r2d2_tpu.envs.drift import drift_params, is_drift_name
from r2d2_tpu.envs.keydoor import is_keydoor_name, keydoor_params
from r2d2_tpu.envs.procmaze import is_procmaze_name, procmaze_params

__all__ = ["ScriptedEnv", "CatchEnv", "CatchHostEnv", "CatchVecEnv", "make_env"]


def is_multitask_family_name(name: str) -> bool:
    """True for the pure-JAX multi-task family cores added by ROADMAP
    item 2 (keydoor/drift/banditgrid) — the names routed through
    envs/functional.FnHostEnv below and build_fn_env's functional path."""
    return is_keydoor_name(name) or is_drift_name(name) or is_banditgrid_name(name)


def make_env(cfg, seed: int = 0):
    """Host-protocol (reset()/step(int)) env factory by cfg.env_name.

    For vectorized on-device Catch use envs.catch.CatchVecEnv directly
    (train.build_vec_env does)."""
    name = cfg.env_name.lower()
    if is_catch_name(name):
        return CatchHostEnv(
            height=cfg.obs_shape[0], width=cfg.obs_shape[1], seed=seed,
            **catch_params(name),
        )
    if is_procmaze_name(name):
        from r2d2_tpu.envs.functional import FnHostEnv
        from r2d2_tpu.envs.procmaze import (
            ProcMazeEnv,
            procmaze_geometry,
            procmaze_params,
        )

        # same construction as procmaze.build_procmaze_env, but through
        # FnHostEnv's (class, args, kwargs) form so the jitted fns cache
        # across a pool of N host envs
        params = procmaze_params(name)
        grid, cell, horizon = procmaze_geometry(
            cfg.obs_shape, cfg.max_episode_steps, grid=params.pop("grid", None)
        )
        return FnHostEnv(ProcMazeEnv, (grid, cell, horizon), seed=seed, kwargs=params)
    if is_multitask_family_name(name):
        from r2d2_tpu.envs.banditgrid import BanditGridEnv
        from r2d2_tpu.envs.drift import DriftEnv
        from r2d2_tpu.envs.functional import FnHostEnv
        from r2d2_tpu.envs.keydoor import KeyDoorEnv

        # FnHostEnv's (class, args, kwargs) form so the jitted fns cache
        # across a pool of N host envs (same reason as procmaze above);
        # kwargs mirror each family's build_*_env factory exactly
        h, w = cfg.obs_shape[0], cfg.obs_shape[1]
        if is_keydoor_name(name):
            p = keydoor_params(name)
            p["horizon"] = min(cfg.max_episode_steps, 4 * p["length"] + 4)
            return FnHostEnv(KeyDoorEnv, (h, w), seed=seed, kwargs=p)
        if is_drift_name(name):
            return FnHostEnv(DriftEnv, (h, w), seed=seed, kwargs=drift_params(name))
        p = banditgrid_params(name)
        return FnHostEnv(
            BanditGridEnv, (h, w), seed=seed,
            kwargs=dict(
                grid=p["grid"], horizon=min(cfg.max_episode_steps, p["horizon"])
            ),
        )
    if name == "scripted" or name.startswith("scripted:"):
        # "scripted:A" pins the action space independently of cfg — gives
        # the sweep tests per-game action_dim diversity without ALE
        adim = int(name.split(":", 1)[1]) if ":" in name else cfg.action_dim
        return ScriptedEnv(obs_shape=cfg.obs_shape, action_dim=adim)
    from r2d2_tpu.envs.atari import create_atari_env  # gated import

    return create_atari_env(cfg.env_name, noop_start=True, noop_max=cfg.noop_max, seed=seed)
