// Native replay core: the host-side data-plane hot ops as C++.
//
// The reference's replay machinery rides on native code it inherits from its
// dependencies — numpy's vectorized sum-tree math (reference
// priority_tree.py:16-46) and torch's C++ slicing/pad_sequence batch
// assembly (reference worker.py:210-288). This library is the framework's
// own native equivalent: the sum-tree update/sample and the window-gather
// batch assembly as first-class C++, loaded via ctypes
// (r2d2_tpu/_native/__init__.py) and used by replay/sum_tree.py and
// replay/replay_buffer.py when config.use_native_replay is set.
//
// Layout contract (matches replay/sum_tree.py): a complete binary tree in
// one double array; num_layers layers; node 0 is the root; node i's
// children are 2i+1, 2i+2; leaf k lives at k + 2^(num_layers-1) - 1.
//
// Build: g++ -O3 -shared -fPIC (see Makefile / __init__.py auto-build).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <cmath>
#include <vector>

extern "C" {

// Set leaf priorities to |td|^alpha and resum ancestors bottom-up.
// Duplicate idxes are fine: parents are recomputed from child values, so
// the last write per leaf wins and every touched ancestor is exact.
void tree_update(double* tree, int64_t num_layers, const int64_t* idxes,
                 const double* td, int64_t n, double alpha) {
  if (n <= 0) return;
  const int64_t leaf_offset = (int64_t{1} << (num_layers - 1)) - 1;
  std::vector<int64_t> nodes(n);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t node = idxes[i] + leaf_offset;
    tree[node] = std::pow(td[i], alpha);
    nodes[i] = node;
  }
  // layer-by-layer parent resummation over the deduplicated frontier
  for (int64_t layer = 0; layer < num_layers - 1; ++layer) {
    for (auto& node : nodes) node = (node - 1) / 2;
    std::sort(nodes.begin(), nodes.end());
    nodes.erase(std::unique(nodes.begin(), nodes.end()), nodes.end());
    for (const int64_t node : nodes)
      tree[node] = tree[2 * node + 1] + tree[2 * node + 2];
  }
}

// Stratified descent: for each prefix sum, walk root->leaf. Writes the
// absolute node index (caller subtracts leaf_offset).
void tree_sample(const double* tree, int64_t num_layers, const double* prefix,
                 int64_t n, int64_t* out_nodes) {
  for (int64_t i = 0; i < n; ++i) {
    double p = prefix[i];
    int64_t node = 0;
    for (int64_t layer = 0; layer < num_layers - 1; ++layer) {
      const int64_t left = 2 * node + 1;
      const double left_sum = tree[left];
      if (p < left_sum) {
        node = left;
      } else {
        node = left + 1;
        p -= left_sum;
      }
    }
    out_nodes[i] = node;
  }
}

// Batch assembly: gather B windows of T rows each from a (num_blocks, slot)
// row-major store of row_bytes-sized rows into a contiguous (B, T,
// row_bytes) output. Row index win_start[i] + t is clamped to [0, slot-1]
// (the fixed-shape replacement for the reference's ragged pad_sequence
// slicing, worker.py:224-260). Works for any dtype: the caller passes raw
// bytes.
void gather_windows(const uint8_t* store, int64_t slot, int64_t row_bytes,
                    const int64_t* b, const int64_t* win_start, int64_t B,
                    int64_t T, uint8_t* out) {
  const int64_t block_bytes = slot * row_bytes;
  const int64_t out_window = T * row_bytes;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < B; ++i) {
    const uint8_t* block = store + b[i] * block_bytes;
    uint8_t* dst = out + i * out_window;
    const int64_t start = win_start[i];
    // contiguous fast path: whole window in range -> one memcpy
    if (start >= 0 && start + T <= slot) {
      std::memcpy(dst, block + start * row_bytes, out_window);
      continue;
    }
    for (int64_t t = 0; t < T; ++t) {
      int64_t row = start + t;
      row = row < 0 ? 0 : (row >= slot ? slot - 1 : row);
      std::memcpy(dst + t * row_bytes, block + row * row_bytes, row_bytes);
    }
  }
}

// Multi-field window gather: one call gathers the SAME (b, win_start)
// windows from num_fields stores that share the slot axis (e.g. the
// obs/last_action/last_reward group, or the action/reward/gamma learning
// group). The tiered plane's K-batch staging path flattens its (K, B)
// coordinates and crosses ctypes ONCE per field group instead of once per
// (field, batch); the single OMP region load-balances the whole slab
// (fields have wildly different row sizes — obs rows are ~7 KB, scalar
// rows 1-4 bytes — so collapsing fields x windows into one schedule keeps
// every thread busy). Field f is a (num_blocks, slot, ...) store of
// row_bytes[f]-sized rows; clamp semantics identical to gather_windows.
void gather_windows_multi(const uint8_t* const* stores,
                          const int64_t* row_bytes, int64_t num_fields,
                          int64_t slot, const int64_t* b,
                          const int64_t* win_start, int64_t B, int64_t T,
                          uint8_t* const* outs) {
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t f = 0; f < num_fields; ++f) {
    for (int64_t i = 0; i < B; ++i) {
      const int64_t rb = row_bytes[f];
      const uint8_t* block = stores[f] + b[i] * slot * rb;
      uint8_t* dst = outs[f] + i * T * rb;
      const int64_t start = win_start[i];
      if (start >= 0 && start + T <= slot) {
        std::memcpy(dst, block + start * rb, T * rb);
        continue;
      }
      for (int64_t t = 0; t < T; ++t) {
        int64_t row = start + t;
        row = row < 0 ? 0 : (row >= slot ? slot - 1 : row);
        std::memcpy(dst + t * rb, block + row * rb, rb);
      }
    }
  }
}

// Priority-of-leaves lookup plus IS-weight computation in one pass:
// w_i = (max(p_i, min_positive_p) / min_positive_p)^-beta
// (reference priority_tree.py:40-42 with the zero-leaf clamp of
// replay/sum_tree.py). Returns the number of positive-priority leaves.
int64_t is_weights(const double* tree, int64_t num_layers,
                   const int64_t* nodes, int64_t n, double beta,
                   float* out_w) {
  double min_p = 0.0;
  int64_t positive = 0;
  for (int64_t i = 0; i < n; ++i) {
    const double p = tree[nodes[i]];
    if (p > 0.0 && (positive == 0 || p < min_p)) min_p = p;
    if (p > 0.0) ++positive;
  }
  if (positive == 0) min_p = 1.0;
  for (int64_t i = 0; i < n; ++i) {
    const double p = std::max(tree[nodes[i]], min_p);
    out_w[i] = static_cast<float>(std::pow(p / min_p, -beta));
  }
  return positive;
}

}  // extern "C"
