"""Parity of the fused Pallas LSTM unroll (ops/pallas_lstm.py) against the
lax.scan reference implementation (models/lstm.py), values AND gradients.

Runs in Pallas interpret mode on the CPU test backend — the same kernel
code path that compiles on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import tiny_test
from r2d2_tpu.models.lstm import LSTM
from r2d2_tpu.ops.pallas_lstm import lstm_unroll


def _scan_reference(proj_t, wh, h0, c0):
    """Plain-JAX unroll over time-major projections (the scan semantics)."""
    H = h0.shape[-1]

    def step(carry, p):
        h, c = carry
        z = p + h @ wh
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H : 2 * H])
        g = jnp.tanh(z[..., 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[..., 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (h, c), outs = jax.lax.scan(step, (h0, c0), proj_t)
    return outs, (h, c)


def _rand_inputs(rng, T=6, B=8, H=16):
    proj_t = jnp.asarray(rng.normal(size=(T, B, 4 * H)).astype(np.float32))
    wh = jnp.asarray((rng.normal(size=(H, 4 * H)) / np.sqrt(H)).astype(np.float32))
    h0 = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.3)
    c0 = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.3)
    return proj_t, wh, h0, c0


def test_forward_matches_scan():
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(0))
    outs_p, (hT_p, cT_p) = lstm_unroll(proj_t, wh, h0, c0)
    outs_s, (hT_s, cT_s) = _scan_reference(proj_t, wh, h0, c0)
    np.testing.assert_allclose(np.asarray(outs_p), np.asarray(outs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT_p), np.asarray(hT_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT_p), np.asarray(cT_s), atol=1e-5)


@pytest.mark.parametrize("wrt", [0, 1, 2, 3])  # proj, wh, h0, c0
def test_grads_match_scan(wrt):
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(1))
    rng = np.random.default_rng(2)
    # random cotangent over outputs only (the learner's real use: the final
    # carry is discarded by R2D2Network.unroll)
    ct = jnp.asarray(rng.normal(size=(6, 8, 16)).astype(np.float32))

    def loss_pallas(*args):
        outs, _ = lstm_unroll(*args)
        return jnp.sum(outs * ct)

    def loss_scan(*args):
        outs, _ = _scan_reference(*args)
        return jnp.sum(outs * ct)

    g_p = jax.grad(loss_pallas, argnums=wrt)(proj_t, wh, h0, c0)
    g_s = jax.grad(loss_scan, argnums=wrt)(proj_t, wh, h0, c0)
    np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_s), rtol=1e-4, atol=1e-5)


def test_final_carry_grads_match_scan():
    """Cotangents through (h_T, c_T) too — exercises the dcT seed path."""
    proj_t, wh, h0, c0 = _rand_inputs(np.random.default_rng(3))

    def loss(fn, *args):
        outs, (hT, cT) = fn(*args)
        return jnp.sum(outs) * 0.1 + jnp.sum(hT * cT)

    for wrt in range(4):
        g_p = jax.grad(lambda *a: loss(lstm_unroll, *a), argnums=wrt)(proj_t, wh, h0, c0)
        g_s = jax.grad(lambda *a: loss(_scan_reference, *a), argnums=wrt)(proj_t, wh, h0, c0)
        np.testing.assert_allclose(
            np.asarray(g_p), np.asarray(g_s), rtol=1e-4, atol=1e-5,
        )


def test_lstm_module_backend_parity():
    """The full flax LSTM module agrees between backend='scan' and
    backend='pallas' (same params), values and input grads."""
    cfg = tiny_test()
    B, T, D, H = 4, 6, 24, cfg.hidden_dim
    scan_mod = LSTM(hidden_dim=H, in_dim=D, backend="scan")
    pallas_mod = LSTM(hidden_dim=H, in_dim=D, backend="pallas")
    rng = np.random.default_rng(4)
    xs = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    carry = (
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32) * 0.2),
    )
    params = scan_mod.init(jax.random.PRNGKey(0), xs, carry)

    outs_s, carry_s = scan_mod.apply(params, xs, carry)
    outs_p, carry_p = pallas_mod.apply(params, xs, carry)
    np.testing.assert_allclose(np.asarray(outs_p), np.asarray(outs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(carry_p[0]), np.asarray(carry_s[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(carry_p[1]), np.asarray(carry_s[1]), atol=1e-5)

    def loss(mod, p, xs):
        outs, _ = mod.apply(p, xs, carry)
        return jnp.sum(jnp.tanh(outs))

    g_s = jax.grad(lambda p: loss(scan_mod, p, xs))(params)
    g_p = jax.grad(lambda p: loss(pallas_mod, p, xs))(params)
    flat_s = jax.tree.leaves(g_s)
    flat_p = jax.tree.leaves(g_p)
    for a, b in zip(flat_p, flat_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
