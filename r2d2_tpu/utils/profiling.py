"""Tracing / profiling hooks (SURVEY.md section 5.1 rebuild).

The reference has no profiler at all — its only timing is wall-clock
minutes stored in checkpoints (reference worker.py:378,452) and derived
rates printed every 10 s (worker.py:126,135). Here:

- `start_profiler_server(port)` exposes the live process to
  `xprof`/TensorBoard-profile capture at any time (device + host traces).
- `trace_to(dir)` context manager records a bounded trace programmatically
  (e.g. `--profile-dir` on the trainer CLI traces the first post-warmup
  updates, where the steady-state pipeline shape is visible).
- `span(name)` / `step_span(name, step)` annotate HOST-side phases (replay
  sample, block pack, priority update) so they line up against device
  activity in the trace viewer. They are no-ops costing one context-manager
  enter/exit when no trace is being captured, so the hot paths keep them
  permanently.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

_server = None


def start_profiler_server(port: int = 9012) -> None:
    """Idempotent: starts the jax.profiler server once per process."""
    global _server
    if _server is None:
        _server = jax.profiler.start_server(port)


@contextlib.contextmanager
def trace_to(log_dir: Optional[str]) -> Iterator[None]:
    """Record a profiler trace into `log_dir` for the duration of the
    context; None disables (zero overhead)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def span(name: str):
    """Named host-span annotation visible in the trace viewer."""
    return jax.profiler.TraceAnnotation(name)


def step_span(name: str, step: int):
    """Step-correlated span: groups device work under learner step N."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)
